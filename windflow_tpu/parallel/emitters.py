"""Emitters: the routing plane between operator stages.

Re-design of the reference emitter family (``/root/reference/wf/basic_emitter.hpp``,
``forward_emitter.hpp``, ``keyby_emitter.hpp``, ``broadcast_emitter.hpp``, and the
``*_emitter_gpu.hpp`` device variants):

* The reference emitter pushes pointers into lock-free thread queues
  (``ff_send_out_to``).  Here an emitter appends messages to destination
  replica inboxes; the host driver (graph/pipegraph.py) drains them.  Because
  JAX arrays are immutable, broadcast needs no reference-counted multicast
  (reference ``delete_counter``, ``single_t.hpp:54``) — sharing a DeviceBatch
  handle is free.

* The CPU→GPU staging emitters (``forward_emitter_gpu.hpp:254-300`` pinned
  double-buffering) become :class:`DeviceStageEmitter`: host records are
  accumulated and staged to TPU HBM as one SoA batch.  JAX dispatch is
  asynchronous, so consecutive staged batches overlap transfer/compute without
  explicit double buffering.

* The GPU→GPU keyby emitter's sort/unique machinery
  (``keyby_emitter_gpu.hpp:519-583``) is *not* reproduced at the emitter: keys
  ride the batch as a dense-id lane and key grouping happens inside the
  consuming operator with XLA sort/segment ops — the compiler fuses it with
  the operator body, which a standalone emitter kernel would prevent.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import numpy as np

from windflow_tpu import staging
from windflow_tpu.analysis.hotpath import hot_path
from windflow_tpu.basic import RoutingMode, WindFlowError, int32_key
from windflow_tpu.batch import (DeviceBatch, HostBatch, Punctuation, WM_NONE,
                                columns_to_device, host_to_device,
                                stage_packed, transfer_nbytes)
from windflow_tpu.monitoring import recorder as flightrec


_M64 = (1 << 64) - 1


def splitmix64_int(k: int) -> int:
    """Pure-Python splitmix64, bit-identical to the native ``wf_hash64`` /
    ``native.hash64`` (keyed routing placement must agree across the
    per-tuple, columnar-native, and on-device paths)."""
    x = (k + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


def _splitmix64_dev(k32):
    """splitmix64 as jnp ops over an int32 key lane (sign-extended to the
    same int64 the host paths hash) — keeps device-side keyby placement
    bit-identical to the host staging emitter's."""
    import jax.numpy as jnp
    x = k32.astype(jnp.int64).astype(jnp.uint64) \
        + jnp.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


# canonical definition lives in basic.py (pure-Python layers like the Kafka
# client need it without pulling in this module's numpy/jax imports);
# re-exported here because keyby placement is this layer's concern
from windflow_tpu.basic import stable_hash  # noqa: F401,E402


class KeyInterner:
    """Host-side mapping from arbitrary user keys to dense int slots.

    The TPU answer to per-key device state without pointer-chasing hash maps
    (SURVEY.md §7 "hard parts"): the host assigns each distinct key a dense id
    at the staging boundary; device state lives in dense ``[num_slots, ...]``
    tables indexed by that id.  Parity: the reference copies distinct keys to
    host at the keyby boundary anyway (``dist_keys_cpu``,
    ``keyby_emitter_gpu.hpp:519-583``)."""

    def __init__(self) -> None:
        self._ids = {}

    def intern(self, key: Any) -> int:
        i = self._ids.get(key)
        if i is None:
            i = len(self._ids)
            self._ids[key] = i
        return i

    def __len__(self) -> int:
        return len(self._ids)

    def keys_by_slot(self) -> list:
        out = [None] * len(self._ids)
        for k, i in self._ids.items():
            out[i] = k
        return out


class Emitter:
    """Base emitter: owns destination inboxes and per-destination channel ids
    (reference ``Basic_Emitter``, ``basic_emitter.hpp:62-121``)."""

    #: whether this emitter implements the host-tuple emit() interface;
    #: device-only emitters (pass-through, device keyby) override to False
    #: so callers can detect an impossible host fallback up front
    can_emit_host_items = True

    def __init__(self, dests: Sequence[Tuple[Any, int]],
                 output_batch_size: int) -> None:
        # dests: list of (replica, channel_id on that replica).
        self.dests = list(dests)
        self.output_batch_size = output_batch_size
        # observability plumbing, bound by PipeGraph._build through the
        # OWNING replica (the replica whose output this emitter routes):
        # `stats` is that replica's StatsRecord (transfer byte counters —
        # the reference credits H2D/D2H to the transferring replica,
        # stats_record.hpp:152-160), `ring` its flight-recorder span ring,
        # `flight` the graph's FlightRecorder (trace-id assignment at
        # batch-birth sites).  All None when observability is off.
        self.stats = None
        self.ring = None
        self.flight = None

    def bind_observability(self, stats, ring, flight) -> None:
        """Attach the owning replica's stats/ring and the graph recorder;
        compound emitters (keyed staging, device→host, splitting) override
        to propagate the binding to their inner emitters."""
        self.stats = stats
        self.ring = ring
        self.flight = flight

    def _new_trace(self, stage: int = flightrec.EMITTED):
        """Trace lane for a batch BORN at this emitter: the 1-in-N sampling
        decision plus the birth span event; None (and no work beyond one
        check) when the recorder is off or the batch is not sampled."""
        if self.flight is None:
            return None
        tr = self.flight.maybe_trace()
        if tr is not None and self.ring is not None:
            self.ring.record(tr[0], stage, tr[1])
        return tr

    # -- host-tuple interface ----------------------------------------------
    def emit(self, item: Any, ts: int, wm: int,
             shared: bool = False, tid=None) -> None:
        """``shared=True`` marks an item whose object is (or may be) also
        delivered elsewhere (split multicast); it taints the open batch so
        in-place consumers copy before mutating rather than paying an eager
        deepcopy per branch.  ``tid`` is the optional origin id relayed for
        DETERMINISTIC tie-breaking (HostBatch.ids)."""
        raise NotImplementedError

    # -- device-batch interface --------------------------------------------
    def emit_device_batch(self, batch: DeviceBatch) -> None:
        raise NotImplementedError

    # -- whole-host-batch interface (TPU→host boundary) ---------------------
    def emit_host_batch(self, hb: HostBatch) -> None:
        """Route a whole HostBatch (from a device transfer) downstream.
        Forward/broadcast emitters route at batch granularity — the
        reference GPU→CPU path also re-ships whole CPU batches
        (``keyby_emitter_gpu.hpp:594-638``); the default falls back to
        per-tuple emit for routings that need tuple granularity (keyby)."""
        for item, ts, tid in zip(hb.items, hb.tss, hb.ids_or_nones()):
            self.emit(item, ts, hb.watermark, hb.shared, tid=tid)

    # -- columnar interface (bulk sources, windflow_tpu/io) -----------------
    def emit_columns(self, cols, tss, wm: int, row_wms=None) -> None:
        """Emit a block of tuples given as SoA numpy columns.  ``wm`` is the
        frontier after the block's LAST row; ``row_wms`` (optional int64
        [n]) is the frontier after EACH row — sources that know it (e.g. a
        cumulative max of event timestamps) let the staging emitter stamp
        batches that split the block exactly instead of conservatively.
        The default explodes to per-tuple records (host destinations care
        about items, not layout); the device staging emitter overrides this
        with a zero-per-tuple path."""
        names = list(cols)
        arrs = [cols[n] for n in names]
        for i in range(len(tss)):
            item = {n: a[i].item() for n, a in zip(names, arrs)}
            self.emit(item, int(tss[i]),
                      int(row_wms[i]) if row_wms is not None else wm)

    def propagate_punctuation(self, wm: int) -> None:
        """Flush open batches, then multicast a watermark punctuation
        (reference ``forward_emitter.hpp:226-262``)."""
        self.flush(wm)
        for replica, ch in self.dests:
            replica.receive(ch, Punctuation(wm))

    def flush(self, wm: int) -> None:
        """Send any partially-filled batches downstream (EOS / cadence)."""

    # -- helpers ------------------------------------------------------------
    def _send(self, dest_idx: int, msg) -> None:
        replica, ch = self.dests[dest_idx]
        replica.receive(ch, msg)


def _concat(arrs):
    return arrs[0] if len(arrs) == 1 else np.concatenate(arrs)


def _log_fold(comb, rec: dict, m: int) -> dict:
    """Fold ``m`` records held as a dict of ``[m]`` numpy columns into
    one scalar record through an ASSOCIATIVE combiner, by repeated
    halving — the combiner runs log2(m) times over vectorized halves
    instead of m-1 times over scalars.  Only the grouping changes
    (associativity; float sums carry the same rounding tolerance as the
    dense reduce path)."""
    while m > 1:
        h = m // 2
        a = {k: v[:h] for k, v in rec.items()}
        b = {k: v[h:2 * h] for k, v in rec.items()}
        c = comb(a, b)
        if m - 2 * h:
            rec = {k: np.concatenate([np.atleast_1d(np.asarray(c[k])),
                                      np.asarray(v[2 * h:])])
                   for k, v in rec.items()}
        else:
            rec = {k: np.atleast_1d(np.asarray(c[k])) for k in rec}
        m = h + (m - 2 * h)
    return {k: v[0] for k, v in rec.items()}


# transfer byte accounting: the packed staging path counts its buffer's
# exact nbytes; every other path uses the shared whole-batch definition
_db_nbytes = transfer_nbytes


class _OpenBatch:
    """Accumulates tuples for one destination.

    The watermark folds the MINIMUM frontier, as the reference does
    (``Batch_CPU_t::addTuple``, ``batch_cpu_t.hpp:51-205``): a downstream
    host operator may unpack the batch and re-emit singles each carrying the
    batch stamp, and a max-fold would let the first single's watermark fire
    windows ahead of its batch-siblings still in flight on the same channel,
    silently dropping them as late.  The tighter newest frontier travels
    separately as ``DeviceBatch.frontier`` (see batch.py), valid only for
    the consuming operator's own place-then-fire step."""

    __slots__ = ("items", "tss", "wm", "shared", "tids", "any_tid")

    def __init__(self):
        self.items: list = []
        self.tss: list = []
        self.wm: int = WM_NONE
        self.shared: bool = False
        self.tids: list = []
        self.any_tid: bool = False

    @hot_path
    def add(self, item, ts, wm, shared=False, tid=None):
        self.items.append(item)
        self.tss.append(ts)
        self.tids.append(tid)
        self.any_tid |= tid is not None
        self.shared |= shared
        if wm != WM_NONE:
            self.wm = wm if self.wm == WM_NONE else min(self.wm, wm)

    def ids_or_none(self):
        return self.tids if self.any_tid else None


class ForwardEmitter(Emitter):
    """FORWARD / REBALANCING routing of host tuples: round-robin over
    destinations, accumulating per-destination batches of ``output_batch_size``
    (reference ``forward_emitter.hpp:49-285``)."""

    def __init__(self, dests, output_batch_size):
        super().__init__(dests, output_batch_size)
        self._open = [_OpenBatch() for _ in dests]
        self._next = 0

    @hot_path
    def emit(self, item, ts, wm, shared=False, tid=None):
        d = self._next
        self._next = (self._next + 1) % len(self.dests)
        ob = self._open[d]
        ob.add(item, ts, wm, shared, tid)
        if len(ob.items) >= max(1, self.output_batch_size):
            self._flush_dest(d)

    def _flush_dest(self, d):
        ob = self._open[d]
        if ob.items:
            self._send(d, HostBatch(ob.items, ob.tss, ob.wm,
                                    shared=ob.shared,
                                    ids=ob.ids_or_none(),
                                    trace=self._new_trace()))
            self._open[d] = _OpenBatch()

    def emit_host_batch(self, hb):
        # batch-granular round-robin; flush the destination's open batch
        # first so per-destination arrival order is preserved
        d = self._next
        self._next = (self._next + 1) % len(self.dests)
        self._flush_dest(d)
        self._send(d, hb)

    def flush(self, wm):
        for d in range(len(self.dests)):
            self._flush_dest(d)


class KeyByEmitter(Emitter):
    """KEYBY routing: ``hash(key) % num_dests`` per tuple with per-destination
    open batches (reference ``keyby_emitter.hpp:216-257``)."""

    def __init__(self, dests, output_batch_size,
                 key_extractor: Callable[[Any], Any]):
        super().__init__(dests, output_batch_size)
        self.key_extractor = key_extractor
        self._open = [_OpenBatch() for _ in dests]
        #: shard-plane sketch (monitoring/shard_ledger.py), attached by
        #: the ledger at graph build; None leaves one check per FLUSH —
        #: the per-tuple emit path carries no sketch work at all (the
        #: flush path samples one key per shipped batch instead)
        self._sketch = None
        #: reshard-executor key→shard override (windflow_tpu/serving):
        #: moved keys route to their assigned shard BEFORE the hash —
        #: the advisor's move_keys contract.  None leaves one check per
        #: tuple (a plain attribute read, no allocation)
        self._override = None

    def set_override(self, override) -> None:
        """Install/replace the key→destination override map (reshard
        executor moves; restore re-installs checkpointed maps)."""
        self._override = dict(override) if override else None

    @hot_path
    def emit(self, item, ts, wm, shared=False, tid=None):
        key = self.key_extractor(item)
        d = None
        if self._override is not None:
            d = self._override.get(key)
        if d is None:
            d = stable_hash(key) % len(self.dests)
        ob = self._open[d]
        ob.add(item, ts, wm, shared, tid)
        if len(ob.items) >= max(1, self.output_batch_size):
            self._flush_dest(d)

    def _flush_dest(self, d):
        ob = self._open[d]
        if ob.items:
            if self._sketch is not None:
                try:
                    key = self.key_extractor(ob.items[0])
                except Exception:  # lint: broad-except-ok (telemetry
                    # sampling of an arbitrary user key — a throwing
                    # extractor degrades the sketch, never routing)
                    key = None
                # exactly ONE note_flush per shipped batch (note_flush
                # itself never raises), so loads stay single-counted
                self._sketch.note_flush(d, len(ob.items), key)
            self._send(d, HostBatch(ob.items, ob.tss, ob.wm,
                                    shared=ob.shared,
                                    ids=ob.ids_or_none(),
                                    trace=self._new_trace()))
            self._open[d] = _OpenBatch()

    def flush(self, wm):
        for d in range(len(self.dests)):
            self._flush_dest(d)


class BroadcastEmitter(Emitter):
    """BROADCAST routing: every destination sees every tuple (reference
    ``broadcast_emitter.hpp``).  Batches are built once and the same immutable
    HostBatch object is delivered to all inboxes."""

    def __init__(self, dests, output_batch_size):
        super().__init__(dests, output_batch_size)
        self._ob = _OpenBatch()

    def emit(self, item, ts, wm, shared=False, tid=None):
        self._ob.add(item, ts, wm, shared, tid)
        if len(self._ob.items) >= max(1, self.output_batch_size):
            self.flush(wm)

    def flush(self, wm):
        if self._ob.items:
            # one immutable batch object multicast by handle; `shared` makes
            # in-place consumers copy before mutating (reference pairs the
            # delete_counter multicast with Map's copyOnWrite,
            # single_t.hpp:54, map.hpp:57-215)
            b = HostBatch(self._ob.items, self._ob.tss, self._ob.wm,
                          shared=len(self.dests) > 1 or self._ob.shared,
                          ids=self._ob.ids_or_none(),
                          trace=self._new_trace())
            for d in range(len(self.dests)):
                self._send(d, b)
            self._ob = _OpenBatch()

    def emit_host_batch(self, hb):
        self.flush(hb.watermark)
        if len(self.dests) > 1:
            hb = HostBatch(hb.items, hb.tss, hb.watermark, shared=True,
                           ids=hb.ids)
        for d in range(len(self.dests)):
            self._send(d, hb)


class _StagedPacket:
    """One finalized packed batch, pre-``stage_packed``: everything the
    per-batch ship stamps, captured at finalize time so the megastep
    plane (windflow_tpu/megastep.py) can queue K of them and either
    fold them into one scan dispatch or replay the verbatim per-batch
    ship (``_ship_packed``) in FIFO order.  ``nbytes`` is the WIRE
    buffer's size at finalize (the H2D ledger credit); ``wm_pane`` is
    filled in by the megastep edge for time-based window tails."""

    __slots__ = ("buf", "fmt", "wm", "frontier", "ts_min", "ts_max",
                 "n", "trace", "nbytes", "logical_nbytes", "pool",
                 "treedef", "dtypes", "capacity", "wm_pane")

    def __init__(self, buf, fmt, wm, frontier, ts_min, ts_max, n,
                 trace, logical_nbytes, pool, treedef, dtypes,
                 capacity):
        self.buf = buf
        self.fmt = fmt
        self.wm = wm
        self.frontier = frontier
        self.ts_min = ts_min
        self.ts_max = ts_max
        self.n = n
        self.trace = trace
        self.nbytes = buf.nbytes
        self.logical_nbytes = logical_nbytes
        self.pool = pool
        self.treedef = treedef
        self.dtypes = dtypes
        self.capacity = capacity
        self.wm_pane = None


class DeviceStageEmitter(Emitter):
    """Host→TPU boundary (reference CPU→GPU ``Forward_Emitter_GPU`` /
    ``KeyBy_Emitter_GPU`` staging paths): accumulates host records, stages one
    SoA DeviceBatch of fixed capacity ``output_batch_size``, and round-robins
    destination replicas.

    Keyed destinations need no work here: keyed TPU operators extract their
    key lane from the payload inside their own compiled program (see
    ``ops/tpu.py``), identically for staged and device-resident batches.  The
    fixed capacity keeps every staged batch the same shape, so the
    destination's compiled program never re-traces.
    """

    def __init__(self, dests, output_batch_size, mesh=None):
        if output_batch_size <= 0:
            # Parity: a device operator must be preceded by batching output
            # (reference multipipe.hpp:441-444).
            raise WindFlowError(
                "a TPU operator requires the upstream operator to set an "
                "output batch size > 0")
        super().__init__(dests, output_batch_size)
        self._ob = _OpenBatch()
        self._next = 0
        # Newest watermark seen by this emitter (monotone): staged batches
        # carry it as DeviceBatch.frontier so the consuming device operator
        # can fire time windows without the min-fold's one-batch lag — see
        # _OpenBatch and DeviceBatch.frontier for why the propagated
        # watermark stays min-folded.
        self._frontier = WM_NONE
        # Columnar accumulation: list of (cols dict, tss, per-row-wm)
        # chunks + row count.  A chunk-level watermark is only valid after
        # the chunk's LAST row — stamping a head batch of a split chunk
        # with it would let downstream time windows fire ahead of the
        # chunk's still-buffered tail rows and drop them as late.  So each
        # chunk is kept with a per-row frontier lane (given by the source,
        # or synthesized as last-row-only), and a staged batch is stamped
        # with the running max at ITS last row.
        self._col_chunks = []
        self._col_rows = 0
        # Streaming packed staging (windflow_tpu/staging): single-chip
        # packable columns bypass the chunk-accumulate/concatenate path
        # entirely — rows are written straight into a pooled staging
        # buffer at their final packed offsets, and a full buffer ships
        # as ONE fused host→device transfer.  State of the open builder
        # (the pool is looked up per batch, not captured: swapping the
        # process-wide pool via staging.set_default_pool must redirect
        # live emitters, or stats()["Staging_pool"] reports counters the
        # staging path no longer touches):
        self._builder = None
        self._b_dtypes = None
        self._b_treedef = None
        self._b_wm = WM_NONE            # running row-frontier max
        self._b_ts_min = None           # data-ts extrema of the OPEN batch
        self._b_ts_max = None
        # shard-plane key probe (monitoring/shard_ledger.HostKeyProbe):
        # attached by the ledger when this non-keyed staging edge feeds
        # a keyed device consumer whose key extraction runs in-program
        # (mesh FFAT / dense reduce / stateful) — the probe applies that
        # extractor host-side at batch granularity; None leaves one
        # check per columnar chunk / per shipped record batch
        self._shard_probe = None
        # wire plane (windflow_tpu/wire.py): enabled by wire.attach_wire
        # at graph build when the feeding edge has a declared/inferred
        # record spec — finished packed buffers are re-encoded lane by
        # lane (delta/dict/const/bit-pack) into a pooled wire buffer and
        # the inverse decode rides the SAME unpack dispatch on device.
        # Off/downgraded leaves exactly one flag check per finalize.
        self._wire_on = False
        self._wire_reseed = 64
        self._wire_encoders = {}
        # megastep plane (windflow_tpu/megastep.py): attached by
        # PipeGraph._build when this edge feeds an eligible device tail
        # and Config.megastep_sweeps resolves to K>1 — finalized packed
        # batches are OFFERED to the edge, which folds K of them into
        # one lax.scan dispatch.  None (the K=1 kill switch and every
        # ineligible edge) leaves exactly one check per finalize and
        # the verbatim per-batch ship below.
        self._megastep = None
        # Multi-chip: lay staged batch lanes out data-sharded over the mesh
        # so downstream sharded programs consume them without a reshard
        # (parallel/mesh.py batch_sharding).
        self._stage_target = None
        #: lanes THIS process contributes per staged batch: equals the
        #: batch capacity single-process; on a multi-host mesh each of the
        #: P processes stages capacity/P local lanes and the global batch
        #: is assembled shard-locally (batch.py _stage_soa; SURVEY §5.8)
        self._local_cap = output_batch_size
        if mesh is not None:
            from windflow_tpu.parallel.mesh import batch_sharding
            if output_batch_size % math.prod(mesh.devices.shape):
                raise WindFlowError(
                    f"output batch size {output_batch_size} not divisible "
                    f"by the mesh's {math.prod(mesh.devices.shape)} devices")
            self._stage_target = batch_sharding(mesh)
            if jax.process_count() > 1:
                # fully-sharded staging: each process's lanes land at its
                # own (data, key) blocks (batch.py _stage_soa); consumers
                # gather over both axes (mesh.py ingest="flat")
                from jax.sharding import (NamedSharding,
                                          PartitionSpec as _P)

                from windflow_tpu.parallel.mesh import DATA_AXIS, KEY_AXIS
                self._stage_target = NamedSharding(
                    mesh, _P((DATA_AXIS, KEY_AXIS)))
                self._local_cap = output_batch_size // jax.process_count()

    def _advance_frontier(self, wm):
        if wm != WM_NONE and wm > self._frontier:
            self._frontier = wm

    def _local_share(self, nbytes: int) -> int:
        """This PROCESS's share of a staged batch's bytes: on a
        multi-host mesh each host packs and ships only its local chips'
        shard (batch.py ``_stage_soa``), so crediting the GLOBAL batch
        size on every host would multiply the H2D ledger by the process
        count (the per-host attribution the sweep ledger's wire
        subsection surfaces)."""
        if self._stage_target is not None and jax.process_count() > 1:
            return nbytes // jax.process_count()
        return nbytes

    def enable_wire(self, reseed_every: int = 64) -> None:
        """Turn on columnar wire compression for this emitter's packed
        staging (called by ``wire.attach_wire`` at graph build — only
        for edges whose record spec is declared/inferred, the WF606
        contract).  Mesh-sharded targets ignore the flag: their
        transfers are assembled per shard, not packed."""
        self._wire_on = self._stage_target is None
        self._wire_reseed = max(1, reseed_every)

    def _wire_encoder(self, dtypes, capacity: int):
        key = (dtypes, capacity)
        enc = self._wire_encoders.get(key)
        if enc is None:
            from windflow_tpu.wire import WireEncoder
            enc = WireEncoder(dtypes, capacity,
                              reseed_every=self._wire_reseed)
            self._wire_encoders[key] = enc
        return enc

    def emit(self, item, ts, wm, shared=False, tid=None):
        # `shared` is irrelevant here: staging materializes new device
        # arrays from the record's values, never aliasing the host object;
        # `tid` is dropped — device edges are DEFAULT-mode only.
        self._advance_frontier(wm)
        self._ob.add(item, ts, wm)
        if len(self._ob.items) >= self._local_cap:
            # capacity flush: INTERNAL, so a megastep edge keeps
            # accumulating record-path batches (flush() below is the
            # external entry point that drains the megastep queue)
            self._flush_impl(wm)

    def emit_columns(self, cols, tss, wm, row_wms=None):
        """Columnar fast path.  Single-chip packable columns take the
        STREAMING packed route: rows are written directly into a pooled
        staging buffer at their final packed offsets
        (staging.PackedBatchBuilder) and a full buffer ships as ONE fused
        host→device transfer — no chunk concatenate, no per-batch numpy
        allocation, no per-lane device_put (the reference's recycled
        pinned staging, ``forward_emitter_gpu.hpp:254-300`` +
        ``recycling.hpp``).  Mesh-sharded targets and non-packable lanes
        fall back to the chunk-accumulate path below."""
        if self._shard_probe is not None:
            self._shard_probe.columns(cols, len(tss))
        if self._stage_target is None and not self._col_chunks:
            leaves, treedef = jax.tree.flatten(
                {nm: np.asarray(a) for nm, a in cols.items()})
            if all(l.ndim == 1 and staging.packable_dtype(l.dtype)
                   for l in leaves):
                self._emit_columns_packed(leaves, treedef, tss, wm, row_wms)
                return
        if self._builder is not None:
            # falling back mid-stream: ship the open packed rows first so
            # per-destination arrival order is preserved
            self._finalize_builder()
        self._emit_columns_chunked(cols, tss, wm, row_wms)

    def _emit_columns_packed(self, leaves, treedef, tss, wm, row_wms):
        """Streaming packed staging (see emit_columns).  Watermark lane
        contract matches the chunked path: a staged batch is stamped with
        the running row-frontier max at ITS last row; a chunk-level ``wm``
        is applied only once the chunk's last row is packed."""
        tss = np.ascontiguousarray(tss, np.int64)
        dtypes = tuple(str(l.dtype) for l in leaves)
        if self._builder is not None and (treedef != self._b_treedef
                                          or dtypes != self._b_dtypes):
            self._finalize_builder()    # lane structure changed mid-stream
        m = len(tss)
        pos = 0
        while pos < m:
            if self._builder is None:
                self._b_treedef = treedef
                self._b_dtypes = dtypes
                self._builder = staging.PackedBatchBuilder(
                    dtypes, self.output_batch_size)
                self._b_ts_min = None
                self._b_ts_max = None
            take = min(self._builder.room, m - pos)
            sl = slice(pos, pos + take)
            tsl = tss[sl]
            self._builder.append([l[sl] for l in leaves], tsl)
            lo, hi = int(tsl.min()), int(tsl.max())
            if self._b_ts_min is None or lo < self._b_ts_min:
                self._b_ts_min = lo
            if self._b_ts_max is None or hi > self._b_ts_max:
                self._b_ts_max = hi
            if row_wms is not None:
                w = int(np.max(row_wms[sl]))
                if w != WM_NONE and w > self._b_wm:
                    self._b_wm = w
            elif pos + take == m and wm != WM_NONE and wm > self._b_wm:
                # a chunk-level wm is valid only after the chunk's LAST row
                self._b_wm = wm
            pos += take
            if self._builder.room == 0:
                self._finalize_builder()

    def _finalize_builder(self, fallback_wm: int = WM_NONE) -> None:
        """Ship the open packed batch (padding derived on device from the
        fill count; the pooled buffer is recycled gated on the unpack —
        batch.stage_packed)."""
        b, self._builder = self._builder, None
        if b is None:
            return
        if b.n == 0:
            b.abandon()
            return
        wm = self._b_wm if self._b_wm != WM_NONE else fallback_wm
        self._advance_frontier(wm)
        buf = b.finish()
        logical_nbytes = buf.nbytes
        fmt = None
        if self._wire_on:
            # wire plane (windflow_tpu/wire.py): lane-wise re-encode of
            # the finished logical buffer; a batch compression cannot
            # shrink ships the logical buffer unchanged (fmt None)
            enc = self._wire_encoder(self._b_dtypes, b.capacity)
            buf, fmt = enc.encode(buf, pool=b.pool)
        if self.stats is not None:
            # the packed path's H2D transfer is exactly this buffer;
            # the logical counter keeps compression from silently
            # inflating bytes-derived ratios (wire-round honesty fix)
            self.stats.h2d_bytes += buf.nbytes
            self.stats.h2d_logical_bytes += logical_nbytes
        pkt = _StagedPacket(buf, fmt, wm, self._frontier,
                            self._b_ts_min, self._b_ts_max, b.n,
                            self._new_trace(flightrec.STAGED),
                            logical_nbytes, b.pool, self._b_treedef,
                            self._b_dtypes, b.capacity)
        ms = self._megastep
        if ms is not None and ms.offer(pkt):
            return
        self._ship_packed(pkt)

    def _ship_packed(self, pkt: "_StagedPacket") -> None:
        """The verbatim per-batch ship of one finalized packed batch —
        the K=1 path, the megastep warm-up/fallback path, and
        ``MegastepEdge.drain_remainder``'s partial-group path.  Stamps
        come from the PACKET (captured at finalize), not the emitter:
        a queued batch shipped later must not borrow a frontier that
        advanced past it."""
        db = stage_packed(pkt.buf, pkt.treedef, pkt.dtypes,
                          pkt.capacity, pkt.n, watermark=pkt.wm,
                          device=None, frontier=pkt.frontier,
                          ts_max=pkt.ts_max, ts_min=pkt.ts_min,
                          pool=pkt.pool, trace=pkt.trace,
                          wire=pkt.fmt,
                          logical_nbytes=pkt.logical_nbytes)
        d = self._next
        self._next = (self._next + 1) % len(self.dests)
        self._send(d, db)

    def _emit_columns_chunked(self, cols, tss, wm, row_wms=None):
        """Chunk-accumulate staging (mesh-sharded targets, non-packable
        lanes): stage full batches with one concatenate + one transfer.
        See the ``_col_chunks`` note for the watermark lane."""
        if row_wms is None:
            # chunk-level wm: valid only after the last row
            row_wms = np.full(len(tss), WM_NONE, np.int64)
            if len(tss) and wm != WM_NONE:
                row_wms[-1] = wm
        self._col_chunks.append((cols, tss, row_wms))
        self._col_rows += len(tss)
        cap = self._local_cap
        if self._col_rows < cap:
            return
        names = list(self._col_chunks[0][0])
        cat = {n: _concat([c[0][n] for c in self._col_chunks])
               for n in names}
        tcat = _concat([c[1] for c in self._col_chunks])
        wcat = np.maximum.accumulate(
            _concat([c[2] for c in self._col_chunks]))
        total = len(tcat)
        for lo in range(0, total - total % cap, cap):
            hi = lo + cap
            bwm = int(wcat[hi - 1])
            self._advance_frontier(bwm)
            self._stage_columns(
                {n: a[lo:lo + cap] for n, a in cat.items()},
                tcat[lo:lo + cap], bwm)
        rem = total % cap
        self._col_chunks = [] if rem == 0 else [
            ({n: a[total - rem:] for n, a in cat.items()},
             tcat[total - rem:], wcat[total - rem:])]
        self._col_rows = rem

    def _stage_columns(self, cols, tss, wm):
        db = columns_to_device(cols, tss, self.output_batch_size,
                               watermark=wm, device=self._stage_target,
                               frontier=self._frontier,
                               trace=self._new_trace(flightrec.STAGED))
        if self.stats is not None:
            self.stats.h2d_bytes += self._local_share(_db_nbytes(db))
            self.stats.h2d_logical_bytes += \
                self._local_share(_db_nbytes(db))
        d = self._next
        self._next = (self._next + 1) % len(self.dests)
        self._send(d, db)

    def flush(self, wm):
        """EXTERNAL flush (EOS, punctuation cadence, durability
        quiesce): ship everything open, then drain any megastep queue
        per-batch — a checkpoint or a propagated watermark must never
        overtake packed batches parked for a future megastep."""
        self._flush_impl(wm)
        ms = self._megastep
        if ms is not None:
            ms.drain_remainder()

    def _flush_impl(self, wm):
        if self._builder is not None:
            self._finalize_builder(fallback_wm=wm)
        if self._col_chunks:
            names = list(self._col_chunks[0][0])
            cat = {n: _concat([c[0][n] for c in self._col_chunks])
                   for n in names}
            tcat = _concat([c[1] for c in self._col_chunks])
            # everything buffered is fully staged by this batch, so the
            # newest row frontier applies
            w = int(max(int(c[2].max()) for c in self._col_chunks))
            self._col_chunks = []
            self._col_rows = 0
            self._advance_frontier(w)
            self._stage_columns(cat, tcat, w if w != WM_NONE else wm)
        self._advance_frontier(wm)
        if not self._ob.items:
            return
        if self._shard_probe is not None:
            self._shard_probe.items(self._ob.items)
        if self._wire_on:
            # record-path wire route: stack the open batch to SoA and
            # ship through the packed/wire pipeline.  Stamping is kept
            # EXACTLY the record path's (the open batch's min-folded
            # watermark, nothing newer), so wire on/off runs stay
            # record-for-record identical.
            from windflow_tpu.batch import _stack_records
            leaves = treedef = None
            try:
                soa = _stack_records(self._ob.items)
                leaves, treedef = jax.tree.flatten(soa)
                ok = all(getattr(l, "ndim", 0) == 1
                         and staging.packable_dtype(l.dtype)
                         for l in leaves)
            except Exception:  # lint: broad-except-ok (arbitrary user
                # records may not stack to SoA columns — ANY failure
                # means "take the uncompressed record path below")
                ok = False
            if ok:
                ob, self._ob = self._ob, _OpenBatch()
                tss = np.ascontiguousarray(ob.tss, np.int64)
                # stamp THIS batch with the open batch's min-folded wm
                # (exact record-path parity), then restore the running
                # row-frontier max: on a mixed record+columnar emitter
                # a later columnar batch must never stamp LOWER than
                # the wire-off run would (the frontier only rises)
                prev_wm = self._b_wm
                self._b_wm = ob.wm
                self._emit_columns_packed(leaves, treedef, tss,
                                          WM_NONE, None)
                self._b_wm = ob.wm
                self._finalize_builder()
                self._b_wm = max(prev_wm, ob.wm)
                return
        hb = HostBatch(self._ob.items, self._ob.tss, self._ob.wm)
        db = host_to_device(hb, capacity=self.output_batch_size,
                            device=self._stage_target,
                            frontier=self._frontier,
                            trace=self._new_trace(flightrec.STAGED))
        if self.stats is not None:
            self.stats.h2d_bytes += self._local_share(_db_nbytes(db))
            self.stats.h2d_logical_bytes += \
                self._local_share(_db_nbytes(db))
        d = self._next
        self._next = (self._next + 1) % len(self.dests)
        self._send(d, db)
        self._ob = _OpenBatch()


class KeyedDeviceStageEmitter(Emitter):
    """Host→TPU boundary with KEYBY routing (reference CPU→GPU
    ``KeyBy_Emitter_GPU``, ``keyby_emitter_gpu.hpp:400-476``): tuples are
    partitioned by ``splitmix64(key) % num_dests`` into per-destination
    staged batches, so every key's tuples flow through exactly one replica
    in arrival order — the invariant that makes shared per-key device state
    (ops/tpu_stateful.py) correct at parallelism > 1, exactly as the
    reference's keyby routing does for its stateful GPU operators
    (``std::hash % num_dests``, ``keyby_emitter.hpp:216``).  Hashing (the
    native ``wf_keyby_partition``) rather than a plain modulo keeps
    structured key sets (all-even ids, strided ids) from landing on one
    replica."""

    def __init__(self, dests, output_batch_size, key_extractor, mesh=None):
        super().__init__(dests, output_batch_size)
        self.key_extractor = key_extractor
        # one single-destination staging emitter per partition
        self._inner = [DeviceStageEmitter([d], output_batch_size, mesh=mesh)
                       for d in dests]
        #: shard-plane sketch (monitoring/shard_ledger.py), attached by
        #: the ledger at graph build; None leaves one check per tuple /
        #: per columnar chunk.  The per-tuple path buffers truncated
        #: keys (plain list appends) and bulk-updates every 256 tuples.
        self._sketch = None
        self._sk_buf = []
        #: key compactor (parallel/compaction.py), attached by the graph
        #: build when the consumer compacts: every key column admits at
        #: this boundary (host-fed consumers see a miss-free remap), and
        #: evictable compactors with placement_override route slotted
        #: keys by ``slot % n`` instead of the splitmix hash — hot keys
        #: balanced deterministically over the replicas.  None leaves
        #: one check per emit path.
        self._compactor = None
        #: reshard-executor key→shard override (windflow_tpu/serving):
        #: moved k32 keys route to their assigned shard BEFORE both the
        #: compaction placement and the splitmix hash
        self._override = None
        #: split_hot_key pre-aggregation (the executor's partial-combine
        #: tier): tuples of the named hot keys fold through the
        #: consumer's associative combiner AT THIS BOUNDARY and ship as
        #: one partial record per flush — the hot key's downstream load
        #: drops by the fold factor while the final per-key aggregate
        #: is unchanged (associativity; per-batch partials coarsen,
        #: the documented split semantic).  None leaves one check per
        #: emit path.
        self._preagg = None         # {"keys": set, "comb": fn}
        self._preagg_acc = {}       # k32 -> [record, max_ts, n]
        self.preagg_folds = 0       # tuples absorbed into partials

    def set_override(self, override) -> None:
        """Install/replace the key→destination override map, keyed by
        the int32-truncated key the device state collapses to."""
        if not override:
            self._override = None
            return
        self._override = {self._key32(k): d for k, d in override.items()}

    def set_preagg(self, keys, comb) -> None:
        """Enable the pre-aggregating partial combine for ``keys``
        (split_hot_key executor action); ``comb`` is the consumer's
        associative record combiner.  ``None``/empty disables."""
        self._flush_preagg(WM_NONE)
        if not keys or comb is None:
            self._preagg = None
            return
        self._preagg = {"keys": {self._key32(k) for k in keys},
                        "comb": comb}

    def _fold_into(self, k32, item, ts):
        acc = self._preagg_acc.get(k32)
        if acc is None:
            self._preagg_acc[k32] = [item, ts, 1]
            return
        acc[0] = self._preagg["comb"](acc[0], item)
        acc[1] = max(acc[1], ts)
        acc[2] += 1
        self.preagg_folds += 1

    def _flush_preagg(self, wm) -> None:
        if not self._preagg_acc:
            return
        acc, self._preagg_acc = self._preagg_acc, {}
        for k32, (item, ts, _n) in acc.items():
            self._route_one(k32, item, ts, wm)

    def bind_observability(self, stats, ring, flight):
        super().bind_observability(stats, ring, flight)
        for e in self._inner:
            e.bind_observability(stats, ring, flight)

    @staticmethod
    def _key32(k) -> int:
        """Truncate a numeric key to the int32 key space the device operator
        interns (its extractor output is cast to int32 on device) — routing
        must collapse exactly the keys the state table collapses, or one
        logical key would straddle replicas.  Canonical rule:
        ``basic.int32_key`` (shared with compaction admission, the
        reshard executor's state moves, and rescale re-bucketing)."""
        return int32_key(k)

    def emit(self, item, ts, wm, shared=False, tid=None):
        # scalar splitmix64 (bit-identical to the native/columnar path) —
        # pure int ops, no per-tuple FFI or array allocation
        k32 = self._key32(self.key_extractor(item))
        pa = self._preagg
        if pa is not None and k32 in pa["keys"]:
            self._fold_into(k32, item, ts)
            return
        self._route_one(k32, item, ts, wm)

    def _route_one(self, k32, item, ts, wm):
        comp = self._compactor
        d = None
        if comp is not None:
            try:
                comp.observe_one(k32)
                if comp.placement_override:
                    d = comp.place_one(k32, len(self.dests))
            except Exception:  # lint: broad-except-ok (admission is
                # telemetry-adjacent host work: a compactor failure
                # deactivates the plane, it must never take routing
                # down — the HostKeyProbe stance)
                comp.deactivate()
                self._compactor = None
        if self._override is not None:
            # executor move wins over every derived placement: the key
            # was moved deliberately, and state moved with it
            o = self._override.get(k32)
            if o is not None:
                d = o
        if d is None:
            d = splitmix64_int(k32) % len(self.dests)
        self._inner[d].emit(item, ts, wm)
        if self._sketch is not None:
            self._sk_buf.append(k32)
            if len(self._sk_buf) >= 256:
                self._drain_sketch_buf()

    def _drain_sketch_buf(self):
        buf, self._sk_buf = self._sk_buf, []
        try:
            # placement counts derive inside update_host from the same
            # splitmix hash this emit path routed with
            self._sketch.update_host(np.asarray(buf, np.int64))
        except Exception:  # lint: broad-except-ok (telemetry on the
            # staging path: a sketch failure disables the sketch, it
            # must never take routing down — the HostKeyProbe stance)
            self._sketch = None

    def emit_columns(self, cols, tss, wm, row_wms=None):
        from windflow_tpu import native
        n = len(self.dests)
        keys = None
        try:
            # Vectorized: per-record key fns are elementwise field math, so
            # they usually apply directly to the SoA columns.
            k = np.asarray(self.key_extractor(cols))
            if k.shape == (len(tss),):
                # int64→int32: the device's int32 truncation first, so
                # routing collapses exactly the keys the state collapses
                keys = k.astype(np.int64).astype(np.int32).astype(np.int64)
        except Exception:   # lint: broad-except-ok (speculative
            # vectorization probe of an arbitrary user extractor — ANY
            # failure means "not elementwise", handled by the per-row
            # fallback below)
            pass
        if keys is None:
            # Non-elementwise or scalar-returning extractor: per-row path.
            keys = np.array(
                [self._key32(self.key_extractor(
                    {k: v[i].item() for k, v in cols.items()}))
                 for i in range(len(tss))], np.int64)
        pa = self._preagg
        if pa is not None:
            hot = np.isin(keys, np.fromiter(pa["keys"], np.int64,
                                            len(pa["keys"])))
            if hot.any():
                self._fold_columns(pa, cols, tss, keys, hot)
                keep = ~hot
                if not keep.any():
                    return
                cols = {k: np.asarray(v)[keep] for k, v in cols.items()}
                tss = tss[keep]
                keys = keys[keep]
                if row_wms is not None:
                    row_wms = row_wms[keep]
        comp = self._compactor
        if comp is not None:
            try:
                # admission BEFORE the batch ships: host-fed compacted
                # consumers never see a remap miss
                comp.observe(keys)
            except Exception:  # lint: broad-except-ok (admission must
                # never take routing down — the HostKeyProbe stance)
                comp.deactivate()
                comp = self._compactor = None
        if comp is not None and comp.placement_override:
            # remap placement: slotted (hot) keys go to slot % n — the
            # same destinations the scalar emit path picks
            dest = comp.place_np(keys, n)
            counts = np.bincount(dest, minlength=n)
        else:
            # native C hash+count partition (wf_host.cpp
            # wf_keyby_partition)
            dest, counts = native.keyby_partition(keys, n)
        if self._override is not None:
            # executor moves re-place their keys over the derived
            # placement (a handful of entries: the advisor's move list)
            dest = np.asarray(dest).copy()
            for k, d_ov in self._override.items():
                dest[keys == k] = d_ov
            counts = np.bincount(dest, minlength=n)
        if self._sketch is not None:
            try:
                # the key column + per-destination counts already exist
                # here: the shard-plane update is bincount passes over
                # them
                self._sketch.update_host(keys, counts=counts)
            except Exception:  # lint: broad-except-ok (telemetry on the
                # staging path: a sketch failure disables the sketch,
                # never routing — the HostKeyProbe stance)
                self._sketch = None
        for d in range(n):
            if counts[d]:
                idx = np.nonzero(dest == d)[0]
                # the row frontier is global (covers rows of every
                # partition up to that point), so slicing it per partition
                # keeps each channel's stamps valid
                self._inner[d].emit_columns(
                    {k: v[idx] for k, v in cols.items()}, tss[idx], wm,
                    row_wms[idx] if row_wms is not None else None)

    def _fold_columns(self, pa, cols, tss, keys, hot) -> None:
        """Columnar half of the pre-aggregating partial combine: the hot
        rows of each hot key log-fold through the consumer's combiner
        (vectorized numpy halving — log2(n) combiner calls, associative
        regrouping only, the dense-path contract) into the running
        partial."""
        comb = pa["comb"]
        arrs = {n: np.asarray(v) for n, v in cols.items()}
        for k in np.unique(keys[hot]):
            idx = np.nonzero(keys == k)[0]
            rec = {n: v[idx] for n, v in arrs.items()}
            folded = _log_fold(comb, rec, len(idx))
            self.preagg_folds += len(idx) - 1
            self._fold_into(int(k), folded, int(tss[idx].max()))

    def emit_device_batch(self, batch):
        raise WindFlowError(
            "keyed staging emitter received a device batch; TPU→TPU keyed "
            "edges use DeviceKeyByEmitter")

    def flush(self, wm):
        self._flush_preagg(wm)
        if self._sketch is not None and self._sk_buf:
            self._drain_sketch_buf()
        for e in self._inner:
            e.flush(wm)

    def propagate_punctuation(self, wm):
        self._flush_preagg(wm)
        for e in self._inner:
            e.propagate_punctuation(wm)


class AlignedMeshStageEmitter(Emitter):
    """Host→mesh staging with KEY-ALIGNED placement (ROADMAP item 4b):
    each record is staged directly into the block of the ``(data,
    key)``-sharded batch owned by the key shard that owns its key, so
    the consumer's sharded program skips the data-axis ``all_gather``
    the ICI model names dominant (~232 modeled B/tuple vs ~17 B
    payload, docs/PERF.md r11) — the consuming FFAT step compiles its
    ``ingest="aligned"`` variant (parallel/mesh.py) whose gather is the
    identity on a 1-wide data axis and a kk-times-smaller within-column
    gather otherwise.

    Placement is the STRUCTURAL dense-range owner ``key // K_local`` —
    exactly the ownership ``mesh._ffat_shard_layout``'s ``key_base_fn``
    rebases by, so a tuple can never land on a shard that would drop
    it.  Reshard-executor key moves deliberately do NOT apply here
    (``set_override`` refuses loudly): the consumer's ownership is
    compiled into the sharded program, so an emitter-side move would
    stage a key onto a column whose shard masks it out-of-range and
    silently drops it — a mesh graph's reshard mechanism is the
    rescale-on-restore path (docs/DURABILITY.md), matching the PR-12
    executor limits.  Batches
    assemble per-column with per-block prefix validity computed on host
    (alignment breaks the single-fill-count derivation), and a shipped
    batch's watermark is capped at the minimum data timestamp of any
    row still buffered — a skew-retained row must never become late
    against its own channel's stamp.  Skewed streams reduce batch
    occupancy (a hot column fills while cold columns idle); that cost
    is visible in ``stats()`` occupancy and is the reshard advisor's
    problem, not a correctness risk."""

    def __init__(self, dests, output_batch_size, key_extractor, mesh,
                 max_keys: int):
        super().__init__(dests, output_batch_size)
        from jax.sharding import NamedSharding, PartitionSpec as _P

        from windflow_tpu.parallel.mesh import DATA_AXIS, KEY_AXIS
        kk = mesh.shape[KEY_AXIS]
        dd = mesh.shape[DATA_AXIS]
        if output_batch_size % (kk * dd):
            raise WindFlowError(
                f"output batch size {output_batch_size} not divisible by "
                f"the mesh's {kk * dd} devices (key-aligned ingest)")
        if max_keys % kk:
            raise WindFlowError(
                f"max_keys {max_keys} not divisible by the key axis {kk}")
        if jax.process_count() > 1:
            raise WindFlowError(
                "key-aligned ingest is single-process (multi-host meshes "
                "stage fully-sharded local lanes)")
        self.key_extractor = key_extractor
        self._kk, self._dd = kk, dd
        self._K_local = max_keys // kk
        self._col_cap = output_batch_size // kk
        self._blk = output_batch_size // (kk * dd)
        self._sharding = NamedSharding(mesh, _P((DATA_AXIS, KEY_AXIS)))
        # per-key-shard-column buffers: columnar chunks + record items
        self._chunks = [[] for _ in range(kk)]   # [(cols dict, tss)]
        self._items = [_OpenBatch() for _ in range(kk)]
        self._rows = [0] * kk
        self._wm = WM_NONE              # running max of received stamps
        #: shard-plane key probe (monitoring/shard_ledger.HostKeyProbe):
        #: keys are host-visible at this boundary, so the ledger probes
        #: them here; None leaves one check per chunk / materialize
        self._shard_probe = None
        self.batches_shipped = 0
        self.rows_shipped = 0

    def set_override(self, override) -> None:
        """Refused: the aligned consumer's key ownership is COMPILED
        into its sharded program (``key // K_local``), so an
        emitter-side move would stage the key onto a column whose shard
        masks it out-of-range — a silent drop, never a move.  Mesh
        reshard routes through rescale-on-restore (docs/DURABILITY.md);
        raising here keeps that boundary loud if a future executor ever
        discovers this emitter."""
        if override:
            raise WindFlowError(
                "key-aligned mesh ingest cannot apply executor key "
                "moves: ownership is compiled into the sharded step "
                "(reshard a mesh graph via rescale-on-restore, "
                "docs/DURABILITY.md)")

    # -- placement -----------------------------------------------------------
    def _owner_np(self, k32: np.ndarray) -> np.ndarray:
        return np.clip(k32 // self._K_local, 0,
                       self._kk - 1).astype(np.int64)

    def _note_wm(self, wm) -> None:
        if wm != WM_NONE and wm > self._wm:
            self._wm = wm

    # -- ingest --------------------------------------------------------------
    def emit(self, item, ts, wm, shared=False, tid=None):
        self._note_wm(wm)
        k32 = int32_key(self.key_extractor(item))
        c = min(max(k32 // self._K_local, 0), self._kk - 1)
        self._items[c].add(item, ts, wm)
        self._rows[c] += 1
        if self._rows[c] >= self._col_cap:
            self._ship_one()

    def emit_columns(self, cols, tss, wm, row_wms=None):
        self._note_wm(int(np.max(row_wms)) if row_wms is not None
                      and len(row_wms) else wm)
        if self._shard_probe is not None:
            self._shard_probe.columns(cols, len(tss))
        keys = None
        try:
            k = np.asarray(self.key_extractor(cols))
            if k.shape == (len(tss),):
                keys = k.astype(np.int64).astype(np.int32) \
                    .astype(np.int64)
        except Exception:   # lint: broad-except-ok (speculative
            # vectorization probe of an arbitrary user extractor — ANY
            # failure means "not elementwise", per-row fallback below)
            pass
        if keys is None:
            keys = np.array(
                [int32_key(self.key_extractor(
                    {n: v[i].item() for n, v in cols.items()}))
                 for i in range(len(tss))], np.int64)
        own = self._owner_np(keys)
        tss = np.ascontiguousarray(tss, np.int64)
        arrs = {n: np.asarray(v) for n, v in cols.items()}
        for c in range(self._kk):
            idx = np.nonzero(own == c)[0]
            if not len(idx):
                continue
            self._chunks[c].append(
                ({n: v[idx] for n, v in arrs.items()}, tss[idx]))
            self._rows[c] += len(idx)
        while any(r >= self._col_cap for r in self._rows):
            self._ship_one()

    def emit_device_batch(self, batch):
        raise WindFlowError(
            "key-aligned staging emitter received a device batch; "
            "TPU-fed mesh consumers keep the data-sharded ingest")

    # -- assembly ------------------------------------------------------------
    def _col_take(self, c: int):
        """Materialize and take up to ``col_cap`` rows of column ``c``
        (record items stack to SoA first); the remainder stays
        buffered."""
        from windflow_tpu.batch import _stack_records
        ob = self._items[c]
        if ob.items:
            if self._shard_probe is not None:
                self._shard_probe.items(ob.items)
            soa = _stack_records(ob.items)
            if not isinstance(soa, dict):
                raise WindFlowError(
                    "key-aligned ingest stages dict-shaped records "
                    f"(got {type(ob.items[0]).__name__}); disable "
                    "Config.key_aligned_ingest for this graph")
            self._chunks[c].append(
                ({n: np.asarray(v) for n, v in soa.items()},
                 np.asarray(ob.tss, np.int64)))
            self._items[c] = _OpenBatch()
        if not self._chunks[c]:
            return None
        names = list(self._chunks[c][0][0])
        cat = {n: _concat([ch[0][n] for ch in self._chunks[c]])
               for n in names}
        tcat = _concat([ch[1] for ch in self._chunks[c]])
        m = len(tcat)
        take = min(m, self._col_cap)
        if take < m:
            self._chunks[c] = [({n: a[take:] for n, a in cat.items()},
                                tcat[take:])]
            self._rows[c] = m - take
        else:
            self._chunks[c] = []
            self._rows[c] = 0
        return {n: a[:take] for n, a in cat.items()}, tcat[:take]

    def _pending_min_ts(self):
        lo = None
        for c in range(self._kk):
            for ch in self._chunks[c]:
                if len(ch[1]):
                    m = int(ch[1].min())
                    lo = m if lo is None else min(lo, m)
            if self._items[c].tss:
                m = min(self._items[c].tss)
                lo = m if lo is None else min(lo, m)
        return lo

    def _ship_one(self) -> None:
        takes = [self._col_take(c) for c in range(self._kk)]
        if not any(t is not None for t in takes):
            return
        cap, kk, dd, blk = (self.output_batch_size, self._kk, self._dd,
                            self._blk)
        first = next(t for t in takes if t is not None)
        lanes = {n: np.zeros((cap,) + a.shape[1:], a.dtype)
                 for n, a in first[0].items()}
        ts = np.zeros(cap, np.int64)
        valid = np.zeros(cap, bool)
        total = 0
        for c, t in enumerate(takes):
            if t is None:
                continue
            colv, colt = t
            m = len(colt)
            total += m
            # column rows split row-major over the dd data blocks: row r
            # lands at block r//blk of column c — exactly the order the
            # aligned step's data-axis gather reconstructs
            for d in range(dd):
                lo = d * blk
                hi = min(m, lo + blk)
                if hi <= lo:
                    break
                g0 = (d * kk + c) * blk
                seg = slice(g0, g0 + (hi - lo))
                for n, a in colv.items():
                    lanes[n][seg] = a[lo:hi]
                ts[seg] = colt[lo:hi]
                valid[seg] = True
        if total == 0:
            return
        # watermark capped at the minimum buffered data timestamp: a
        # skew-retained row must never become late against this
        # channel's own stamp (frontier capped identically — the
        # place-then-fire shortcut must not outrun retained rows)
        wm = self._wm
        pend = self._pending_min_ts()
        if wm != WM_NONE and pend is not None:
            wm = min(wm, pend)
        on = ts[valid]
        ts_lo, ts_hi = int(on.min()), int(on.max())
        payload = {n: jax.device_put(a, self._sharding)
                   for n, a in lanes.items()}
        db = DeviceBatch(payload, jax.device_put(ts, self._sharding),
                         jax.device_put(valid, self._sharding),
                         watermark=wm, size=total, frontier=wm,
                         ts_max=ts_hi, ts_min=ts_lo,
                         trace=self._new_trace(flightrec.STAGED))
        if self.stats is not None:
            nb = _db_nbytes(db)
            self.stats.h2d_bytes += nb
            self.stats.h2d_logical_bytes += nb
        staging.device_bytes.note(_db_nbytes(db))
        self.batches_shipped += 1
        self.rows_shipped += total
        self._send(0, db)

    def flush(self, wm):
        self._note_wm(wm)
        while any(self._rows) or any(ob.items for ob in self._items):
            before = (self.batches_shipped, self.rows_shipped)
            self._ship_one()
            if (self.batches_shipped, self.rows_shipped) == before:
                break   # defensive: never spin on an empty remainder


class DeviceKeyByEmitter(Emitter):
    """TPU→TPU KEYBY edge (reference GPU→GPU ``KeyBy_Emitter_GPU``,
    ``keyby_emitter_gpu.hpp:519-583``): one compiled program splits the batch
    into ``num_dests`` masked views by ``splitmix64(key) % num_dests`` (the
    same placement as the host-side keyed staging emitter).  The reference
    builds per-key index chains with sort kernels and copies per
    destination; here every destination shares the SAME immutable device
    buffers and differs only in its validity mask — consumers are
    mask-aware, so no sort, gather, or copy happens at the edge at all.
    Empty partitions still ship (an all-invalid mask) — skipping them
    would force a host sync on the partition counts."""

    can_emit_host_items = False

    def __init__(self, dests, key_extractor):
        super().__init__(dests, output_batch_size=0)
        self.key_extractor = key_extractor
        self._splits = {}
        #: shard-plane sketch (monitoring/shard_ledger.py): when
        #: attached at graph build, the split PROGRAM below also updates
        #: an on-device count-min/candidate state threaded through as
        #: one donated operand — zero extra dispatches; None leaves one
        #: check per batch
        self._sketch = None
        self._sk_state = None
        #: key compactor (parallel/compaction.py) with placement
        #: override, attached at graph build: the split program remaps
        #: slotted keys to ``slot % n`` destinations (hot keys balanced
        #: deterministically) with the cold tail on the splitmix hash —
        #: the same placement the host keyed staging emitter applies
        self._compactor = None

    def attach_shard_sketch(self, sketch) -> None:
        """Fold the shard-plane sketch update into the split program
        (called by the ledger at graph build, before any compile)."""
        self._sketch = sketch
        self._splits = {}   # force the sketch variant at first compile
        sketch.register_device_state(lambda: self._sk_state)

    def attach_compactor(self, comp) -> None:
        """Fold the remap placement override into the split program
        (called by the graph build, before any compile): the remap
        tables ride as two read-only operands, re-passed unchanged in
        steady state — zero extra dispatches."""
        self._compactor = comp
        self._splits = {}   # force the remap variant at first compile

    def _get_split(self, capacity: int):
        import jax
        import jax.numpy as jnp
        split = self._splits.get(capacity)
        if split is None:
            n = len(self.dests)
            key_fn = self.key_extractor
            sketched = self._sketch is not None
            if sketched:
                from windflow_tpu.monitoring.shard_ledger import \
                    device_sketch_update
            if self._compactor is not None:
                from windflow_tpu.parallel.compaction import lookup_slots

            def split(payload, ts, valid, keys, sk=None, tk=None,
                      tsl=None):
                if keys is None:
                    keys = jax.vmap(key_fn)(payload).astype(jnp.int32)
                # splitmix64 placement, bit-identical to the host staging
                # emitter's — a keyed operator fed by both a host edge and
                # a device edge must see each key on ONE replica
                h = (_splitmix64_dev(keys) % jnp.uint64(n)).astype(jnp.int32)
                if tk is not None:
                    # compaction placement override: slotted keys place
                    # by slot % n (the host keyed emitter's place_np),
                    # the cold tail keeps the hash
                    slot, hit = lookup_slots(tk, tsl, keys, valid)
                    h = jnp.where(hit, (slot % jnp.int32(n))
                                  .astype(jnp.int32), h)
                dest = jnp.where(valid, h, jnp.int32(n))
                # no per-destination sort or gather: consumers are
                # mask-aware, so every destination shares the SAME
                # immutable payload/ts/keys buffers and differs only in
                # its validity mask — O(capacity) total work instead of
                # O(capacity * num_dests) sorts+copies
                masks = [dest == d for d in range(n)]
                if sk is None:
                    return keys, masks
                # shard plane: the key-skew sketch updates INSIDE this
                # same program (a few fused scatter-adds on the donated
                # state) — the dispatch count is unchanged
                return keys, masks, device_sketch_update(
                    sk, keys, valid, n, dest=dest)

            from windflow_tpu.monitoring.jit_registry import wf_jit
            split = wf_jit(split, op_name="emitter.device_keyby_split",
                           donate_argnums=(4,) if sketched else ())
            self._splits[capacity] = split
        return split

    def emit_device_batch(self, batch):
        comp_args = ()
        if self._compactor is not None:
            comp_args = self._compactor.tables()
        if self._sketch is None:
            if comp_args:
                keys, masks = self._get_split(batch.capacity)(
                    batch.payload, batch.ts, batch.valid, batch.keys,
                    None, *comp_args)
            else:
                keys, masks = self._get_split(batch.capacity)(
                    batch.payload, batch.ts, batch.valid, batch.keys)
        else:
            if self._sk_state is None:
                from windflow_tpu.monitoring.shard_ledger import \
                    device_sketch_init
                self._sk_state = device_sketch_init(len(self.dests))
            keys, masks, self._sk_state = self._get_split(batch.capacity)(
                batch.payload, batch.ts, batch.valid, batch.keys,
                self._sk_state, *comp_args)
        for d, mask in enumerate(masks):
            self._send(d, DeviceBatch(batch.payload, batch.ts, mask,
                                      keys=keys,
                                      watermark=batch.watermark, size=None,
                                      frontier=batch.frontier,
                                      ts_max=batch.ts_max,
                                      ts_min=batch.ts_min,
                                      trace=batch.trace))


class DevicePassEmitter(Emitter):
    """TPU→TPU edge: device batches move by handle (no copies, no transfers).

    Forward/rebalancing round-robins destinations; broadcast shares the handle
    (immutability makes the reference's ``delete_counter`` multicast protocol
    unnecessary); keyby passes through — key grouping is resolved inside the
    consuming operator against the batch's key lane, and across chips by
    resharding collectives (parallel/mesh.py), not by emitter-side splits."""

    can_emit_host_items = False

    def __init__(self, dests, routing: RoutingMode):
        super().__init__(dests, output_batch_size=0)
        self.routing = routing
        self._next = 0

    def emit_device_batch(self, batch: DeviceBatch):
        if self.routing == RoutingMode.BROADCAST:
            for d in range(len(self.dests)):
                self._send(d, batch)
        else:
            d = self._next
            self._next = (self._next + 1) % len(self.dests)
            self._send(d, batch)


class DeviceToHostEmitter(Emitter):
    """TPU→host boundary (reference GPU→CPU paths,
    ``keyby_emitter_gpu.hpp:594-638``): transfers the batch back columnar
    (``device_to_host`` — one bulk copy per lane) and routes the whole
    HostBatch through the inner host emitter; only keyby falls back to
    per-tuple routing, as in the reference's per-dest re-split."""

    def __init__(self, inner: Emitter):
        super().__init__(inner.dests, inner.output_batch_size)
        self.inner = inner

    def bind_observability(self, stats, ring, flight):
        super().bind_observability(stats, ring, flight)
        self.inner.bind_observability(stats, ring, flight)

    def emit(self, item, ts, wm, shared=False, tid=None):
        self.inner.emit(item, ts, wm, shared, tid=tid)

    def emit_device_batch(self, batch: DeviceBatch):
        from windflow_tpu.batch import device_to_host
        if self.stats is not None:
            self.stats.d2h_bytes += _db_nbytes(batch)
        hb = device_to_host(batch)
        if hb.items:  # all-invalid batches (post-filter, empty split
            self.inner.emit_host_batch(hb)  # partitions) carry no data

    def emit_host_batch(self, hb):
        self.inner.emit_host_batch(hb)

    def propagate_punctuation(self, wm):
        self.inner.propagate_punctuation(wm)

    def flush(self, wm):
        self.inner.flush(wm)


def create_emitter(routing: RoutingMode,
                   dests,
                   output_batch_size: int,
                   src_is_tpu: bool,
                   dst_is_tpu: bool,
                   key_extractor: Optional[Callable] = None,
                   mesh=None) -> Emitter:
    """Pick the emitter for an edge from (routing, src-on-TPU, dst-on-TPU),
    mirroring the reference's dispatch (``multipipe.hpp:236-350``)."""
    if dst_is_tpu:
        dst_op = dests[0][0].op if dests else None
        if mesh is not None and not src_is_tpu \
                and routing == RoutingMode.KEYBY \
                and key_extractor is not None \
                and getattr(dst_op, "_ingest_mode", None) == "aligned":
            # key-aligned mesh ingest (ROADMAP item 4b): the graph build
            # marked this key-sharded consumer aligned (host-fed only),
            # so each record stages straight to its owning key shard and
            # the sharded step skips its cross-chip collectives.  The
            # placement bound is the consumer's dense key/slot space
            # (mesh._aligned_slot_bound — FFAT/reduce max_keys, stateful
            # num_key_slots).
            from windflow_tpu.parallel.mesh import _aligned_slot_bound
            return AlignedMeshStageEmitter(dests, output_batch_size,
                                           key_extractor, mesh,
                                           _aligned_slot_bound(dst_op))
        if routing == RoutingMode.KEYBY and len(dests) > 1 \
                and key_extractor is not None:
            # Key-partitioned delivery: each key's tuples always reach the
            # same replica, preserving per-key arrival order for shared
            # device state (reference: keyby routing is what makes stateful
            # Map_GPU/Filter_GPU correct across replicas).
            if src_is_tpu:
                return DeviceKeyByEmitter(dests, key_extractor)
            return KeyedDeviceStageEmitter(dests, output_batch_size,
                                           key_extractor, mesh=mesh)
        if src_is_tpu:
            return DevicePassEmitter(dests, routing)
        return DeviceStageEmitter(dests, output_batch_size, mesh=mesh)
    # host destination
    if src_is_tpu and routing != RoutingMode.KEYBY and dests \
            and all(getattr(r.op, "columnar", False) for r, _ in dests):
        # Columnar sinks consume DeviceBatches whole (bulk D2H inside the
        # sink replica, zero per-tuple Python); keyed columnar sinks still
        # need per-key routing and take the record path below.
        return DevicePassEmitter(dests, routing)
    if routing == RoutingMode.KEYBY:
        inner = KeyByEmitter(dests, output_batch_size, key_extractor)
    elif routing == RoutingMode.BROADCAST:
        inner = BroadcastEmitter(dests, output_batch_size)
    else:
        inner = ForwardEmitter(dests, output_batch_size)
    if src_is_tpu:
        return DeviceToHostEmitter(inner)
    return inner


class SplittingEmitter(Emitter):
    """Splitting logic at a MultiPipe split point (reference
    ``splitting_emitter.hpp:49-``): the user function maps a tuple to one
    branch index or an iterable of indexes; one inner emitter per branch
    (reference "tree mode", ``splitting_emitter.hpp:65-70``)."""

    def __init__(self, split_fn: Callable, branch_emitters: Sequence[Emitter]):
        super().__init__([], output_batch_size=0)
        self.split_fn = split_fn
        self.branches = list(branch_emitters)
        self._device_splits = {}  # capacity -> compiled split or None

    def bind_observability(self, stats, ring, flight):
        super().bind_observability(stats, ring, flight)
        for b in self.branches:
            b.bind_observability(stats, ring, flight)

    def emit(self, item, ts, wm, shared=False, tid=None):
        self._route(item, ts, wm, self.split_fn(item), shared, tid)

    def _route(self, item, ts, wm, dest, shared, tid):
        """Single place for the split routing semantics (int vs iterable,
        multicast CoW flag, origin-id branch suffixing) — shared by the
        host-tuple path and the device-batch host fallback."""
        if isinstance(dest, int):
            self.branches[dest].emit(item, ts, wm, shared, tid=tid)
            return
        dest = list(dest)
        # Multicast: every branch sees the same object; mark it shared so
        # in-place consumers copy lazily before mutating — no eager
        # per-branch deepcopy (reference pairs multicast with the
        # consumer-side copyOnWrite, map.hpp:57-215).
        multi = shared or len(dest) > 1
        for d in dest:
            # branch-suffix the origin id: multicast delivers the SAME
            # tuple to several branches, and a diamond re-merge into a
            # DETERMINISTIC stage needs the copies' ids distinct
            btid = tid + (-1, d) if tid is not None else None
            self.branches[d].emit(item, ts, wm, multi, tid=btid)

    def _get_device_split(self, capacity: int, payload):
        """Compile one mask-only split program per capacity
        (reference ``Splitting_Emitter_GPU`` / ``split_gpu``,
        ``splitting_emitter_gpu.hpp:53``, ``multipipe.hpp:1244-1281``).
        Requires a JAX-traceable single-destination split function; falls
        back to the host per-tuple path (returns None) for Python-level or
        multicast split functions."""
        if capacity in self._device_splits:
            return self._device_splits[capacity]
        import jax
        import jax.numpy as jnp
        n = len(self.branches)
        split_fn = self.split_fn
        compiled = None
        try:
            shape = jax.eval_shape(lambda p: jax.vmap(split_fn)(p), payload)
            ok = (getattr(shape, "shape", None) == (capacity,)
                  and jnp.issubdtype(shape.dtype, jnp.integer))
        except Exception:   # lint: broad-except-ok (eval_shape probe of an
            # arbitrary user split function — ANY failure means "host
            # per-tuple path", the documented fallback)
            ok = False
        if ok:
            def compiled(payload, ts, valid):
                idx = jax.vmap(split_fn)(payload).astype(jnp.int32)
                dest = jnp.where(valid, idx, jnp.int32(n))
                # mask-only split: every branch shares the same immutable
                # buffers with its own validity mask (see DeviceKeyByEmitter)
                return [dest == b for b in range(n)]

            from windflow_tpu.monitoring.jit_registry import wf_jit
            compiled = wf_jit(compiled, op_name="emitter.device_split")

        self._device_splits[capacity] = compiled
        return compiled

    def emit_device_batch(self, batch: DeviceBatch):
        split = self._get_device_split(batch.capacity, batch.payload)
        if split is not None:
            # Device-native split: branches share the same immutable
            # buffers with per-branch validity masks; empty partitions
            # still ship (all-invalid) — skipping them would force a host
            # sync on the partition counts.
            masks = split(batch.payload, batch.ts, batch.valid)
            for b, mask in enumerate(masks):
                self.branches[b].emit_device_batch(
                    DeviceBatch(batch.payload, batch.ts, mask,
                                watermark=batch.watermark,
                                size=None, frontier=batch.frontier,
                                ts_max=batch.ts_max,
                                ts_min=batch.ts_min,
                                trace=batch.trace))
            return
        # Fallback: host-side per-tuple split (Python or multicast split fn).
        # A device-only branch emitter cannot accept host items, but that is
        # an error only for a tuple actually ROUTED there — a non-traceable
        # split that happens to route exclusively to host branches keeps
        # working (same contract as the reference, whose GPU split requires
        # a __host__ __device__ functor, splitting_emitter_gpu.hpp).
        host_ok = [type(em).can_emit_host_items for em in self.branches]
        from windflow_tpu.batch import device_to_host
        hb = device_to_host(batch)
        for item, ts in zip(hb.items, hb.tss):
            dest = self.split_fn(item)
            if not isinstance(dest, int):
                dest = list(dest)
            for b in ((dest,) if isinstance(dest, int) else dest):
                if not host_ok[b]:
                    raise WindFlowError(
                        "split after a TPU stage routed a tuple to a TPU "
                        f"branch (branch {b}) through the host fallback, "
                        "so the split function must be JAX-traceable and "
                        "single-destination (got a Python-level or "
                        "multicast split function); make the split "
                        "function traceable or insert a host stage before "
                        "the TPU branch")
            self._route(item, ts, hb.watermark, dest, False, None)

    def propagate_punctuation(self, wm):
        for b in self.branches:
            b.propagate_punctuation(wm)

    def flush(self, wm):
        for b in self.branches:
            b.flush(wm)
