"""Device-side key compaction: the dense fast path for arbitrary keys.

BENCH_r05 put the declared-monoid dense reduce at 139.7M tup/s against
3.3M for the sorted arbitrary-key path — a 3–42× gap only ``withMaxKeys``
users could reach, because the dense scatter-combine tables need a
bounded key space.  This module closes the gap for UNDECLARED int32 key
spaces with a **device-resident key→dense-slot remap table** (the
Julia-GPU-primitives stance: keep fully generic operators on the
specialized fast path via a runtime remap):

* **Remap table.**  ``KeyCompactor`` owns a host dict ``key → stable
  slot`` mirrored on device as two arrays: ``table_keys`` (the admitted
  keys, sorted, sentinel-padded) and ``table_slots`` (the stable slot of
  each sorted position).  Lookup inside a compiled program is one
  ``searchsorted`` + gather; the sorted/slot indirection keeps slots
  STABLE across admissions (a new key shifts sorted positions, never
  slots), which is what lets stateful/FFAT state tables index by slot
  across batches.

* **Hot path, cold tail.**  A compacted ReduceTPU step scatter-combines
  remapped lanes into a dense ``[slots]`` monoid table and routes the
  remaining (miss) lanes through the EXISTING sorted segmented reduce —
  over a ``capacity//32`` overflow buffer when they fit (the common
  case), over the full batch under ``lax.cond`` when they do not
  (adversarial all-cold streams stay correct at sorted-path speed).
  Both halves run inside the consumer's one program: zero extra
  dispatches, and the merged output is bit-identical to the sorted
  path's (ascending distinct keys compacted to the front — see
  :func:`make_compacted_reduce`).

* **Seeding.**  Admission is host-driven where keys are host-visible
  anyway (the keyed staging emitter's key column, the staging probes) —
  steady state admits nothing and pays nothing.  Where keys are
  device-born (TPU→TPU edges, fused chains), the step's donated stats
  operand carries a miss-candidate ring (the PR 9 sketch pattern) and
  the reseed cadence folds it — together with the shard plane's
  count-min/hot-key candidates — into the table, evicting the coldest
  slots on a full table (the ``churn`` counter; pinned compactors for
  stateful/FFAT state never evict).

``Config.key_compaction`` / ``WF_TPU_KEY_COMPACTION=0`` is the kill
switch: no compactor attaches and every step keeps one ``is not None``
check (micro-asserted by tests/test_key_compaction.py).  Reserved key:
``INT32_MAX`` is the table's sentinel — a record keyed exactly 2^31-1
rides the overflow/sorted lane on reduce/stateful (never wrong, never
fast).  Compacted FFAT windows have NO overflow lane: a sentinel-keyed
record there follows the never-admitted-key contract (lanes masked and
counted — ``sentinel_rejects`` in the summary names the cause); declare
``withMaxKeys`` instead if INT32_MAX is a live key in your stream.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from windflow_tpu.basic import WindFlowError, int32_key

#: table sentinel: pads the sorted key array; a REAL key equal to it is
#: never admitted (its lanes take the overflow/sorted path)
KEY_SENTINEL = np.int32(2**31 - 1)
_SENT = int(KEY_SENTINEL)     # plain-int twin for the scalar hot path
#: miss-candidate ring geometry (the shard ledger's candidate pattern)
MISS_RING = 64
MISS_PER_BATCH = 8
#: overflow lane budget as a fraction of batch capacity: misses beyond
#: it take the full-width sorted fallback under lax.cond (rare).  The
#: lane's sort/merge cost scales with its width — on the CPU bench box
#: halving it from capacity//16 to //32 cut the with-miss step 22.6 →
#: 20.0 ms — so the budget is sized for a COLD TAIL (a batch missing
#: more than ~3% isn't hot-set shaped and belongs on the sorted
#: fallback until the reseed cadence catches up)
OVERFLOW_DENOM = 32


def overflow_cap(capacity: int) -> int:
    return min(capacity, max(32, capacity // OVERFLOW_DENOM))


# ---------------------------------------------------------------------------
# traced pieces (imported lazily into consumer programs — never at import)
# ---------------------------------------------------------------------------

def lookup_slots(table_keys, table_slots, keys, valid):
    """In-program remap lookup: ``(slot, hit)`` for an int32 key lane.
    ``slot`` carries the table size for misses (the stateful bodies'
    ignore sentinel); pad positions carry slot == size, so a user key
    colliding with the sentinel value reads as a miss, never a hit."""
    import jax.numpy as jnp
    size = int(table_keys.shape[0])
    k32 = keys.astype(jnp.int32)
    # scan_unrolled: ~3x cheaper than the default scan lowering for a
    # wide query lane over a small table (measured on the CPU bench box)
    pos = jnp.clip(jnp.searchsorted(table_keys, k32,
                                    method="scan_unrolled"), 0, size - 1)
    cand = table_slots[pos]
    hit = valid & (table_keys[pos] == k32) & (cand < size)
    return jnp.where(hit, cand, jnp.int32(size)), hit


def slots_to_user_keys(key_lane, table_keys, table_slots):
    """Traced inverse remap: fired records carry the SLOT in their
    "key" lane — map it back through the inverse table so downstream
    sees the user's keys, not the remap's internals (the extra T+1 row
    absorbs the sentinel-pad scatter writes)."""
    import jax.numpy as jnp
    T = int(table_keys.shape[0])
    inv = jnp.zeros(T + 1, table_keys.dtype).at[table_slots].set(
        table_keys, mode="drop")
    return inv[jnp.clip(key_lane, 0, T)].astype(key_lane.dtype)


def cstats_init():
    """Fresh on-device compaction stats state for one program site: the
    hit/miss counters plus the miss-candidate ring the reseed cadence
    reads.  One donated operand — the PR 9 sketch pattern."""
    import jax.numpy as jnp
    return {
        "hits": jnp.zeros((), jnp.int64),
        "misses": jnp.zeros((), jnp.int64),
        "batches": jnp.zeros((), jnp.int32),
        "big": jnp.zeros((), jnp.int64),
        "cand": jnp.full(MISS_RING, np.iinfo(np.int32).min, jnp.int32),
    }


def cstats_update(st, keys, hit, miss, big=None):
    """Traced stats update: counters plus a strided sample of MISS keys
    into the ring — a key carrying x% of the un-remapped stream appears
    among the candidates with probability ~x per batch, so the reseed
    cadence catches a shifted hot set with near-certainty.  The sample
    offset rotates with the batch counter: a fixed stride over a
    periodic key layout would alias onto one phase of the stream and
    never see the others."""
    import jax
    import jax.numpy as jnp
    k32 = keys.astype(jnp.int32)
    cap = int(k32.shape[0])
    c = min(MISS_PER_BATCH, cap)
    stride = max(1, cap // c)
    idx = (st["batches"] * jnp.int32(7)
           + jnp.int32(stride) * jnp.arange(c, dtype=jnp.int32)) \
        % jnp.int32(cap)
    cand_new = jnp.where(miss[idx], k32[idx],
                         jnp.int32(np.iinfo(np.int32).min))
    slots = max(1, MISS_RING // c)
    start = (st["batches"] % jnp.int32(slots)) * jnp.int32(c)
    cand = jax.lax.dynamic_update_slice(st["cand"], cand_new, (start,))
    return {
        "hits": st["hits"] + jnp.sum(hit, dtype=jnp.int64),
        "misses": st["misses"] + jnp.sum(miss, dtype=jnp.int64),
        "batches": st["batches"] + 1,
        "big": st["big"] + (jnp.zeros((), jnp.int64) if big is None
                            else big.astype(jnp.int64)),
        "cand": cand,
    }


def _pack_ok(dtype) -> bool:
    """True when a leaf dtype maps order-isomorphically into an int64
    carrier (the packed one-scatter dense combine under max/min)."""
    import jax.numpy as jnp
    dt = jnp.dtype(dtype)
    if dt == jnp.bool_ or dt in (jnp.dtype(jnp.float32),
                                 jnp.dtype(jnp.float64)):
        return True
    if jnp.issubdtype(dt, jnp.signedinteger):
        return True
    # unsigned fits the signed carrier only below 64 bits
    return jnp.issubdtype(dt, jnp.unsignedinteger) and dt.itemsize < 8


def _enc64(x):
    """Order-preserving map of one supported leaf into int64.  Floats
    use the sign-folded bitcast (exact, bijective — the scatter then
    compares INTEGERS, no float arithmetic at all); -0.0 folds onto
    +0.0 (equal under max/min) and NaNs have no total-order home, so
    packing is only used on NaN-free streams (the monoid-combiner
    contract already excludes them)."""
    import jax
    import jax.numpy as jnp
    dt = jnp.dtype(x.dtype)
    if dt == jnp.dtype(jnp.float32):
        bi = jax.lax.bitcast_convert_type(x, jnp.int32).astype(jnp.int64)
        return jnp.where(bi >= 0, bi, jnp.int64(-2**31) - bi)
    if dt == jnp.dtype(jnp.float64):
        bi = jax.lax.bitcast_convert_type(x, jnp.int64)
        # I64MIN - bi wraps (two's complement) — still bijective
        return jnp.where(bi >= 0, bi,
                         jnp.int64(np.iinfo(np.int64).min) - bi)
    return x.astype(jnp.int64)


def _dec64(c, dtype):
    """Inverse of :func:`_enc64` for one carrier column."""
    import jax
    import jax.numpy as jnp
    dt = jnp.dtype(dtype)
    if dt == jnp.dtype(jnp.float32):
        bi = jnp.where(c >= 0, c, jnp.int64(-2**31) - c).astype(jnp.int32)
        return jax.lax.bitcast_convert_type(bi, jnp.float32)
    if dt == jnp.dtype(jnp.float64):
        bi = jnp.where(c >= 0, c,
                       jnp.int64(np.iinfo(np.int64).min) - c)
        return jax.lax.bitcast_convert_type(bi, jnp.float64)
    return c.astype(dt)


def make_compacted_reduce(capacity: int, table_size: int, monoid: str,
                          comb, key_fn, prelude, bounded: bool,
                          pallas=None):
    """Build the compacted keyed-reduce program body.

    ``(keys, payload, ts, valid[, table_keys, table_slots], cstats) ->
    (out_payload, out_ts, out_valid, cstats')`` — remapped lanes
    scatter-combine into a dense ``[table_size]`` monoid table, miss
    lanes run the sorted segmented reduce (over the ``capacity//32``
    overflow buffer, or the full batch under ``lax.cond`` when they
    exceed it), and the two result sets merge by key RANK (two
    ``searchsorted`` passes over already-sorted key lists — no extra
    sort) into exactly the sorted path's output contract: distinct keys
    ascending, compacted to the front of a ``[capacity]`` batch, zero
    padding.  Bit-identical to ``_segmented_reduce`` whenever the
    declared monoid matches the combiner exactly (the existing
    ``withMonoidCombiner`` contract).

    ``bounded`` is the declared-``withMaxKeys`` variant: the remap is
    the identity over ``[0, max_keys)`` (no table operands) and
    out-of-range keys ride the overflow lane instead of being dropped —
    the retirement of the PR 1 silent-drop/RuntimeWarning path.

    ``pallas`` (a resolved :class:`windflow_tpu.kernels.PallasMode`):
    the dense half's one-scatter combine re-tiles through the Pallas
    segmented-reduce kernel where its gates hold — the packed int64
    carrier rides as one multi-column leaf, per-leaf scatters route
    per leaf — traced into this same program, bit-identical output
    (all-integer folds on the packed path)."""
    import jax
    import jax.numpy as jnp

    from windflow_tpu.ops.tpu import _bshape, _segmented_reduce
    from windflow_tpu.windows.ffat_kernels import (_monoid_identity,
                                                   _monoid_scatter)
    T = int(table_size)
    ovf = overflow_cap(capacity)
    I64MAX = jnp.int64(np.iinfo(np.int64).max)
    I64MIN = jnp.int64(np.iinfo(np.int64).min)

    def body(keys, payload, ts, valid, *rest):
        if bounded:
            (cst,) = rest
            table_keys = table_slots = None
        else:
            table_keys, table_slots, cst = rest
        if prelude is not None:
            # whole-chain fusion: the stateless members run inside this
            # same program and keys re-extract from its output — the
            # remap operands thread through the fused program unchanged
            payload, valid = prelude(payload, valid)
            keys = None
        if keys is None:
            keys = jax.vmap(key_fn)(payload).astype(jnp.int32)
        keys = keys.astype(jnp.int32)
        if bounded:
            hit = valid & (keys >= 0) & (keys < T)
            slot = keys
        else:
            slot, hit = lookup_slots(table_keys, table_slots, keys, valid)
        miss = valid & ~hit
        n_miss = jnp.sum(miss)

        # -- dense half: scatter-combine pass(es) into the [T] table ----
        # miss/invalid lanes route to dump row T (sliced off), so the
        # scatters take the RAW leaves — no per-leaf identity select.
        # The ts max-scatter doubles as the liveness bit: rows still at
        # the init identity received no lane this batch.  Lane ts of
        # exactly INT64_MIN is clamped up by one so a live row can never
        # read as dead — the one reserved ts value, documented beside
        # KEY_SENTINEL.
        row = jnp.where(hit, slot, jnp.int32(T))
        sts = jnp.maximum(ts, I64MIN + 1)
        leaves, treedef = jax.tree_util.tree_flatten(payload)
        packed = monoid in ("max", "min") and all(
            _pack_ok(l.dtype) for l in leaves)
        if packed:
            # ONE variadic-width scatter: every leaf encodes
            # order-isomorphically into int64 carrier columns
            # (scatter cost is dominated by per-index bookkeeping, not
            # update width — measured ~2.4x over per-leaf scatters on
            # the CPU bench box), and the ts max + liveness ride the
            # same pass as one extra column (negated under "min" so the
            # ts fold stays a MAX).  The "min" side needs one MORE
            # reserved ts value than the shared +1 clamp above:
            # -(I64MIN+1) == I64MAX IS the min identity, so a lane ts
            # of exactly I64MIN+1 would read its row back as dead —
            # clamp to I64MIN+2 before negating.
            tcol = sts if monoid == "max" \
                else -jnp.maximum(sts, I64MIN + 2)
            cols = [_enc64(l).reshape((capacity, -1)) for l in leaves]
            widths = [int(c.shape[1]) for c in cols]
            upd = jnp.concatenate(cols + [tcol[:, None]], axis=1)
            ident = I64MIN if monoid == "max" else I64MAX
            tbl = None
            if pallas is not None:
                from windflow_tpu import kernels as pk
                if pk.table_supported(capacity, T) \
                        and pk.table_leaf_ok(upd.shape, upd.dtype,
                                             pallas.interpret):
                    # Pallas segmented reduce over the packed carrier:
                    # all-integer masked folds — bit-identical to the
                    # variadic scatter
                    tbl = pk.dense_monoid_table(
                        row, [upd], [monoid], [ident], T,
                        pallas.interpret)[0]
            if tbl is None:
                buf = jnp.full((T + 1, int(upd.shape[1])), ident,
                               jnp.int64)
                tbl = _monoid_scatter(buf.at[row], monoid)(upd)[:T]
            has = tbl[:, -1] != ident
            ts_t = jnp.where(has, tbl[:, -1] if monoid == "max"
                             else -tbl[:, -1], I64MIN)
            outs, off = [], 0
            for leaf, w in zip(leaves, widths):
                col = tbl[:, off:off + w].reshape((T,) + leaf.shape[1:])
                outs.append(_dec64(col, leaf.dtype))
                off += w
            table = jax.tree_util.tree_unflatten(treedef, outs)
        else:
            # "sum" (or an unpackable leaf dtype): per-leaf scatters,
            # re-tiled through the Pallas kernel leaf by leaf where its
            # shape/dtype gates hold
            def scat(leaf):
                ident = _monoid_identity(monoid, leaf.dtype)
                buf = jnp.full((T + 1,) + leaf.shape[1:], ident,
                               leaf.dtype)
                return _monoid_scatter(buf.at[row], monoid)(leaf)[:T]

            def lax_ts():
                return jnp.full(T + 1, I64MIN, jnp.int64).at[row].max(
                    sts)[:T]

            routed = None
            if pallas is not None:
                from windflow_tpu import kernels as pk
                routed = pk.routed_monoid_tables(
                    row, payload, monoid, T, pallas.interpret,
                    lax_leaf=scat, ts=sts, ts_init=int(I64MIN),
                    lax_ts=lax_ts)
            if routed is not None:
                table, ts_t, _ = routed
            else:
                table = jax.tree.map(scat, payload)
                ts_t = lax_ts()
            has = ts_t != I64MIN

        # key-ascending view of the dense table: bounded slots ARE keys;
        # unbounded gathers slot rows by sorted-key position
        if bounded:
            dvals, dts, dhas = table, ts_t, has
            dkeys = jnp.arange(T, dtype=jnp.int64)
        else:
            perm = jnp.minimum(table_slots, jnp.int32(T - 1))
            live = table_slots < T
            dvals = jax.tree.map(lambda a: a[perm], table)
            dts = ts_t[perm]
            dhas = has[perm] & live
            dkeys = table_keys.astype(jnp.int64)

        n_d = jnp.sum(dhas)
        # gather-based compaction: ONE nonzero yields the live-row index
        # list, every leaf follows with a cheap gather — scatters are
        # serialized on CPU/TPU scalar cores, gathers vectorize, and the
        # index list amortizes across all leaves
        didx = jnp.nonzero(dhas, size=T, fill_value=0)[0]
        dlive = jnp.arange(T) < n_d

        def dcompact(a):
            return jnp.where(_bshape(dlive, a[didx]), a[didx],
                             jnp.zeros_like(a[didx]))

        cvals = jax.tree.map(dcompact, dvals)
        cts = dcompact(dts)
        ckeys = jnp.where(dlive, dkeys[didx], I64MAX)

        big = n_miss > ovf

        def no_miss(_):
            # all-hit batch (the steady state of a warm table over a
            # bounded hot set): the dense half IS the answer — skip the
            # overflow reduce and the rank merge entirely; lax.cond
            # executes only the taken branch at runtime, so the batch
            # pays lookup + dense scatter and nothing else
            def padd(a):
                if capacity <= T:
                    return a[:capacity]
                return jnp.concatenate(
                    [a, jnp.zeros((capacity - T,) + a.shape[1:],
                                  a.dtype)])

            return (jax.tree.map(padd, cvals), padd(cts),
                    jnp.arange(capacity) < n_d)

        def merge(okeys, ovals, ots, ovalid):
            # rank merge: two sorted, disjoint key lists interleave by
            # searchsorted rank — the output IS the sorted path's
            # layout.  The merge scatters the INDEX lanes once (int32,
            # T + W updates where W is the overflow lane's width — NOT
            # capacity-many), then every leaf gathers through the
            # merged index: 2 scatters total instead of 2 per leaf.
            W = int(okeys.shape[0])
            okeys_s = jnp.where(ovalid, okeys, I64MAX)
            n_o = jnp.sum(ovalid)
            drank = jnp.arange(T) + jnp.searchsorted(
                okeys_s, ckeys, method="scan_unrolled")
            orank = jnp.arange(W) + jnp.searchsorted(
                ckeys, okeys_s, method="scan_unrolled")
            dpos = jnp.where(dlive, drank, capacity)
            opos = jnp.where(ovalid, orank, capacity)
            gidx = jnp.zeros(capacity + 1, jnp.int32)
            gidx = gidx.at[dpos].set(
                jnp.arange(T, dtype=jnp.int32), mode="drop")
            gidx = gidx.at[opos].set(
                jnp.arange(W, dtype=jnp.int32) + T,
                mode="drop")[:capacity]
            out_valid = jnp.arange(capacity) < (n_d + n_o)

            def pick(src_d, src_o):
                src = jnp.concatenate([src_d, src_o], axis=0)
                g = src[gidx]
                return jnp.where(_bshape(out_valid, g), g,
                                 jnp.zeros_like(g))

            return (jax.tree.map(pick, cvals, ovals), pick(cts, ots),
                    out_valid)

        # -- overflow half: the cold tail on the existing sorted lane.
        # The common case gathers the misses into a [capacity//32]
        # buffer and sorts/merges at THAT width; the adversarial
        # all-cold batch falls back to the full-width sorted reduce
        # under the nested cond (sorted-path speed, never wrong).
        def ovf_small(_):
            # gather-only miss compaction: the j-th miss lives at the
            # first index whose running miss count reaches j+1 — a
            # binary search over the cumsum instead of jnp.nonzero's
            # full-width scatter lowering (~10x cheaper at this shape)
            cs = jnp.cumsum(miss.astype(jnp.int32))
            midx = jnp.minimum(
                jnp.searchsorted(cs, jnp.arange(1, ovf + 1,
                                                dtype=jnp.int32),
                                 method="scan_unrolled"),
                capacity - 1)
            mvalid = jnp.arange(ovf) < n_miss
            ok, op_, ots, ov = _segmented_reduce(
                keys[midx], jax.tree.map(lambda a: a[midx], payload),
                ts[midx], mvalid, comb, ovf)
            return merge(ok, op_, ots, ov)

        def ovf_big(_):
            return merge(*_segmented_reduce(keys, payload, ts, miss,
                                            comb, capacity))

        def with_miss(_):
            return jax.lax.cond(big, ovf_big, ovf_small, None)

        out_payload, out_ts, out_valid = jax.lax.cond(
            n_miss == 0, no_miss, with_miss, None)
        cst = cstats_update(cst, keys, hit, miss, big=big)
        return out_payload, out_ts, out_valid, cst

    return body


# ---------------------------------------------------------------------------
# the host-side compactor
# ---------------------------------------------------------------------------

class _PinnedFull(Exception):
    """Internal admission signal: a full pinned table whose consumer
    has a lossless host-interning escape (never escapes observe*)."""


class KeyCompactor:
    """Key→dense-slot remap for ONE compacted consumer operator.

    Host state is the authoritative ``key → stable slot`` dict plus the
    sorted/slot mirror arrays; ``dev_keys``/``dev_slots`` are their
    device copies, passed into the consumer's program as plain operands
    (rebuilt only on admission — steady state re-passes the same
    arrays).  ``pinned`` compactors (stateful/FFAT: slots index live
    per-key STATE) never evict; on a FULL pinned table an
    ``intern_fallback`` compactor deactivates so the consumer adopts
    the mapping into its host interner, which raises its own
    ``num_key_slots`` error on the overflowing key (the lossless
    contract), while a plain pinned table (FFAT) counts
    ``full_rejects`` and the consumer masks + counts the key's lanes —
    the operator's documented out-of-range contract.  Evictable
    compactors (per-batch reduces) recycle the coldest slots at reseed
    cadence — the ``churn`` counter — which is safe because a reduce's
    dense table is rebuilt every batch.  Thread-safety: sibling host
    emitter replicas of a parallel upstream drain CONCURRENTLY on the
    worker pool (the ShardSketch scenario), so admission, reseed,
    restore and the table/placement reads all hold ``_lock``;
    ``summary()`` may run from the monitor thread and only reads."""

    def __init__(self, slots: int, *, pinned: bool = False,
                 bounded: bool = False, reseed_every: int = 64,
                 placement_override: bool = False,
                 intern_fallback: bool = False,
                 name: str = "") -> None:
        self.slots = int(slots)
        self.pinned = pinned
        #: declared-withMaxKeys mode: the remap is the identity over
        #: [0, max_keys) — no table, the compactor only carries the
        #: stats surface and the overflow-reroute contract
        self.bounded = bounded
        self.reseed_every = max(1, int(reseed_every))
        #: keyby routing override: slotted keys place by ``slot % n``
        #: (balances hot keys deterministically); safe ONLY for
        #: per-batch consumers — moving a key between replicas
        #: mid-stream would break per-key order for stateful state
        self.placement_override = placement_override
        #: the consumer has a lossless host-interning fallback (stateful
        #: slot tables): a SENTINEL-valued user key (exactly 2^31-1,
        #: inadmissible by construction) deactivates the compactor so
        #: the consumer keeps the legacy path instead of dropping the
        #: record — a compacted REDUCE needs no such escape, its
        #: overflow lane already keeps sentinel-keyed records correct
        self.intern_fallback = intern_fallback
        self.name = name
        #: False after a host observation path failed (speculative
        #: extractor probe): consumers fall back to their legacy path
        self.active = True
        self._lock = threading.Lock()
        self._key_slot: dict = {}
        self._free = list(range(self.slots - 1, -1, -1))
        self._tk = np.full(self.slots, KEY_SENTINEL, np.int32)
        self._tsl = np.full(self.slots, self.slots, np.int32)
        self._dev = None          # (dev_keys, dev_slots) jnp mirrors
        self.admits = 0
        self.churn = 0
        self.reseeds = 0
        self.full_rejects = 0     # evictable table full at observe time
        self.sentinel_rejects = 0  # real keys == KEY_SENTINEL seen
        self._batches = 0
        self._sketch = None       # shard-plane ShardSketch (seeding)
        self._stats_getters = []  # device cstats sites (merge at read)

    # -- wiring --------------------------------------------------------------
    def bind_sketch(self, sketch) -> None:
        self._sketch = sketch

    def register_device_stats(self, getter) -> None:
        """Register one program site's live (cumulative, donated) cstats
        state getter; merged fresh at every summary/reseed read."""
        self._stats_getters.append(getter)

    # -- device mirrors ------------------------------------------------------
    def _rebuild(self) -> None:
        n = len(self._key_slot)
        tk = np.full(self.slots, KEY_SENTINEL, np.int32)
        tsl = np.full(self.slots, self.slots, np.int32)
        if n:
            ks = np.fromiter(self._key_slot.keys(), np.int32, count=n)
            sl = np.fromiter(self._key_slot.values(), np.int32, count=n)
            order = np.argsort(ks, kind="stable")
            tk[:n] = ks[order]
            tsl[:n] = sl[order]
        self._tk, self._tsl = tk, tsl
        self._dev = None          # re-uploaded lazily at next table read

    def tables(self):
        """The (table_keys, table_slots) device operands for this batch;
        uploaded only when admission changed the table.  The upload
        holds the lock so a sibling replica's mid-``_rebuild`` state
        can never pair a new key table with stale slots."""
        dev = self._dev
        if dev is None:
            import jax.numpy as jnp
            with self._lock:
                dev = self._dev
                if dev is None:
                    dev = self._dev = (jnp.asarray(self._tk),
                                       jnp.asarray(self._tsl))
        # returned from the LOCAL: a concurrent admission's _rebuild()
        # nulls self._dev, and a bare `return self._dev` could hand the
        # consumer step None between the check and the return
        return dev

    # -- admission (host-visible key paths) ----------------------------------
    def _admit(self, k32: int) -> bool:
        if k32 == int(KEY_SENTINEL):
            # reserved: rides the overflow lane (reduce/stateful);
            # compacted FFAT has NO overflow lane — its lanes are
            # masked + counted, so make the reserved-key encounter
            # visible instead of a bare False
            self.sentinel_rejects += 1
            return False
        if k32 in self._key_slot:
            return False
        if not self._free:
            if self.pinned and self.intern_fallback:
                # full pinned table with a lossless host-interning
                # escape: signal the caller to deactivate, so the
                # consumer adopts the mapping and the INTERNER raises
                # its num_key_slots error on this very key — the
                # record is never silently masked
                raise _PinnedFull
            self.full_rejects += 1
            return False          # evictable: reseed may recycle a
            # colder slot later; plain pinned (FFAT): the consumer
            # masks + counts the key's lanes (its out-of-range contract)
        self._key_slot[k32] = self._free.pop()
        self.admits += 1
        return True

    def observe(self, keys: np.ndarray) -> None:
        """Bulk host admission from a materialized key column (the keyed
        staging emitter / staging probes): new keys get slots BEFORE the
        batch ships, so host-fed consumers see a miss-free remap."""
        if not self.active:
            return
        u = np.unique(np.asarray(keys).astype(np.int64).astype(np.int32))
        if self.intern_fallback and u.size and u[-1] == KEY_SENTINEL:
            self.deactivate()   # sorted unique: the sentinel is last
            return
        full = False
        with self._lock:
            changed = False
            for k in u:
                try:
                    changed |= self._admit(int(k))
                except _PinnedFull:
                    full = True
                    break
            if changed:
                # keys admitted BEFORE the table filled still reach the
                # device mirror — their records stay on the fast path
                self._rebuild()
        if full:
            self.deactivate()   # consumer adopts the mapping; its
            # interner raises the num_key_slots error on this batch

    def observe_one(self, k32: int) -> None:
        """Scalar admission for the per-tuple emit path: pure int ops
        and a LOCK-FREE dict read in the admitted steady state (the
        emitter's no-FFI-no-allocation-per-tuple contract) — only a
        genuinely new key takes the lock."""
        if not self.active:
            return
        k = int32_key(k32)          # canonical int32 wrap, numpy-free
        if k == _SENT:
            if self.intern_fallback:
                self.deactivate()
            else:
                self.sentinel_rejects += 1
            return
        if k in self._key_slot:
            return              # steady state: GIL-atomic dict read
        if not self._free and not (self.pinned and self.intern_fallback):
            # full table: admission cannot seat the key (only the
            # reseed cadence can recycle a slot), so the per-tuple
            # path stays LOCK-FREE — a cold tail over a full table
            # must not serialize sibling emitters on the compactor
            # lock.  _free only ever shrinks outside restore(), so
            # the unlocked read is stable; the counter is telemetry
            # (racy increments acceptable).
            self.full_rejects += 1
            return
        try:
            with self._lock:
                if self._admit(k):
                    self._rebuild()
        except _PinnedFull:
            self.deactivate()

    def deactivate(self) -> None:
        """Host observation failed (speculative probe): consumers fall
        back to their legacy path at the next step check."""
        self.active = False

    def export_mapping(self) -> dict:
        """key → slot, for a consumer falling back to host interning
        after deactivation (the state table rows keyed by these slots
        must keep meaning the same keys)."""
        with self._lock:
            return dict(self._key_slot)

    # -- placement -----------------------------------------------------------
    def slot_of(self, k32: int) -> Optional[int]:
        return self._key_slot.get(int(np.int32(k32)))

    def place_np(self, keys: np.ndarray, n_dests: int):
        """Vectorized keyby placement with the remap override: slotted
        keys go to ``slot % n`` (hot keys balanced deterministically),
        the cold tail keeps the splitmix placement.  Returns the
        per-lane destination array."""
        from windflow_tpu.monitoring.shard_ledger import _splitmix64_np
        k = np.asarray(keys, np.int64)
        k32 = k.astype(np.int32)
        with self._lock:
            # consistent (tk, tsl, n) snapshot: _rebuild replaces the
            # arrays wholesale under the same lock, never in place
            tk, tsl, n = self._tk, self._tsl, len(self._key_slot)
        pos = np.searchsorted(tk[:max(1, n)], k32)
        pos = np.clip(pos, 0, max(0, n - 1))
        found = (n > 0) & (tk[pos] == k32) & (tsl[pos] < self.slots)
        slot = tsl[pos].astype(np.int64)
        h = (_splitmix64_np(k) % np.uint64(n_dests)).astype(np.int64)
        return np.where(found, slot % n_dests, h).astype(np.intp)

    def place_one(self, k32: int, n_dests: int) -> Optional[int]:
        s = self.slot_of(k32)
        return None if s is None else s % n_dests

    # -- reseed cadence ------------------------------------------------------
    def on_batch(self) -> None:
        """Per-consumer-step hook: counts batches and reseeds the table
        from the sketch + miss-ring candidates on the configured
        cadence (the only device sync the plane pays)."""
        self._batches += 1
        if self._batches % self.reseed_every == 0 and not self.bounded:
            self.reseed()

    def _miss_candidates(self) -> list:
        out = []
        sentinel = np.iinfo(np.int32).min
        for getter in self._stats_getters:
            try:
                st = getter()
                if st is None:
                    continue
                ring = np.asarray(st["cand"], np.int64)
            except Exception:  # lint: broad-except-ok (the cstats state
                # is a DONATED program operand: a read racing the
                # in-flight dispatch sees a deleted array — skip this
                # site for THIS read, the next cadence sees fresh state)
                continue
            out.extend(int(k) for k in ring if k != sentinel)
        return out

    def reseed(self) -> None:
        """Fold the shard sketch's hot candidates and the in-program
        miss rings into the table.  Pinned tables only admit; evictable
        tables recycle their coldest slots for hotter candidates (the
        churn counter counts each recycled slot)."""
        self.reseeds += 1
        cands = self._miss_candidates()
        est = {}
        if self._sketch is not None:
            try:
                for k, e in self._sketch.hot_candidates(self.slots):
                    est[int(np.int32(int(k)))] = int(e)
            except Exception:  # lint: broad-except-ok (sketch reads
                # merge donated device states — telemetry seeding
                # degrades to the miss ring, never takes the step down)
                pass
        for k in cands:
            # miss-ring candidates carry no CMS estimate — plain 0:
            # admitted only while slots are free, never able to clear
            # the 2x eviction hysteresis on a full table
            est.setdefault(k, 0)
        with self._lock:
            fresh = [k for k in est
                     if k not in self._key_slot
                     and k != int(KEY_SENTINEL)]
            if not fresh:
                return
            fresh.sort(key=lambda k: est.get(k, 0), reverse=True)
            changed = False
            residents = None
            ri = 0
            for k in fresh:
                if self._free:
                    changed |= self._admit(k)
                    continue
                if self.pinned:
                    break         # pinned tables never evict live state
                if residents is None:
                    # ONE estimation pass over the residents, coldest
                    # first — candidates walk it hottest-first, so the
                    # merge is two pointers, not O(slots^2) estimates
                    # inline on the consumer step path
                    residents = self._resident_coldness()
                if residents is None or ri >= len(residents):
                    break         # no estimates / nothing left to evict
                cold_est, coldest = residents[ri]
                if est.get(k, 0) < 2 * max(1, cold_est):
                    # 2x hysteresis against sketch noise; candidates
                    # are sorted hottest-first, so nothing later clears
                    break
                ri += 1
                changed = True
                self._key_slot[k] = self._key_slot.pop(coldest)
                self.admits += 1
                self.churn += 1
            if changed:
                self._rebuild()

    def _resident_coldness(self) -> Optional[list]:
        """``(estimate, key)`` for every resident key, coldest first —
        the eviction order one reseed consumes.  None blocks eviction
        (no sketch, or estimation failed this round)."""
        if self._sketch is None or not self._key_slot:
            return None
        out = []
        for k in self._key_slot:
            try:
                out.append((self._sketch._estimate(k), k))
            except Exception:  # lint: broad-except-ok (exact-histogram
                # sketches carry no CMS; estimation failure just blocks
                # eviction this round)
                return None
        out.sort()
        return out

    # -- read path -----------------------------------------------------------
    def summary(self) -> dict:
        """Merged host + device counters for ``stats()["Shard"]`` /
        ``dump_stats``: hit rate, overflow share, churn, occupancy."""
        hits = misses = big = 0
        batches = 0
        for getter in self._stats_getters:
            try:
                st = getter()
                if st is None:
                    continue
                hits += int(st["hits"])
                misses += int(st["misses"])
                big += int(st["big"])
                batches += int(st["batches"])
            except Exception:  # lint: broad-except-ok (donated operand
                # read racing the in-flight dispatch — skip the site
                # for this read, same stance as the sketch merge)
                continue
        total = hits + misses
        out = {
            "slots": self.slots,
            "occupied": len(self._key_slot),
            "pinned": self.pinned,
            "bounded": self.bounded,
            "batches": batches,
            "tuples": total,
            "hit_rate": round(hits / total, 4) if total else None,
            "overflow_share": round(misses / total, 4) if total else None,
            "overflow_tuples": misses,
            "big_fallbacks": big,
            "admits": self.admits,
            "churn": self.churn,
            "churn_per_sweep": round(self.churn / batches, 4)
            if batches else 0.0,
            "reseeds": self.reseeds,
            "placement_override": self.placement_override,
        }
        if self.full_rejects:
            out["full_rejects"] = self.full_rejects
        if self.sentinel_rejects:
            out["sentinel_rejects"] = self.sentinel_rejects
        if not self.active:
            out["deactivated"] = True
        return out

    # -- durable state (windflow_tpu/durability) -----------------------------
    def snapshot(self) -> dict:
        """The remap IS operator state: a restored stateful/FFAT table
        indexes rows by these slots, so replays stay record-for-record."""
        with self._lock:
            return {
                "key_slot": dict(self._key_slot),
                "free": list(self._free),
                "admits": self.admits,
                "churn": self.churn,
                "reseeds": self.reseeds,
                "batches": self._batches,
                "active": self.active,
            }

    def restore(self, blob: dict) -> None:
        with self._lock:
            self._key_slot = {int(k): int(v)
                              for k, v in blob["key_slot"].items()}
            self._free = [int(s) for s in blob["free"]]
            self.admits = blob["admits"]
            self.churn = blob["churn"]
            self.reseeds = blob["reseeds"]
            self._batches = blob["batches"]
            self.active = blob["active"]
            self._rebuild()


# ---------------------------------------------------------------------------
# graph attachment (PipeGraph._build, after the shard plane)
# ---------------------------------------------------------------------------

def attach_compaction(graph) -> None:
    """Attach KeyCompactors to every qualifying keyed consumer and wire
    the feeding emitters for host admission / placement override.  Runs
    AFTER fusion and the shard plane (preludes installed, sketches
    attached, nothing compiled yet); with ``Config.key_compaction`` off
    this never runs and every step keeps one ``is not None`` check."""
    from windflow_tpu.fusion.executor import _upstream_edges
    from windflow_tpu.monitoring.shard_ledger import HostKeyProbe
    from windflow_tpu.ops.tpu import ReduceTPU
    from windflow_tpu.ops.tpu_stateful import _StatefulTPUBase
    from windflow_tpu.parallel.emitters import (DeviceKeyByEmitter,
                                                DeviceStageEmitter,
                                                DeviceToHostEmitter,
                                                KeyedDeviceStageEmitter,
                                                SplittingEmitter)
    from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU

    cfg = graph.config
    slots = max(2, int(getattr(cfg, "key_compaction_slots", 1024)))
    reseed = max(1, int(getattr(cfg, "key_compaction_reseed", 64)))
    upstreams = _upstream_edges(graph)
    sketches = graph._shard._sketches if graph._shard is not None else {}

    def host_fed(op) -> bool:
        ups = upstreams.get(id(op))
        return bool(ups) and all(not u.is_tpu for u, _ in ups)

    for op in graph._operators:
        comp = None
        if isinstance(op, ReduceTPU):
            if op.key_extractor is None:
                continue
            if op.mesh is not None:
                if op.max_keys is None:
                    # arbitrary-key mesh reduce: the remap overrides the
                    # owner hash (hot keys balanced over chips); the
                    # per-chip sort path itself is unchanged
                    comp = KeyCompactor(slots, reseed_every=reseed,
                                        placement_override=True,
                                        name=op.name)
            elif op.monoid is not None:
                bounded = op.max_keys is not None
                comp = KeyCompactor(
                    op.max_keys if bounded else slots,
                    bounded=bounded, reseed_every=reseed,
                    # slot%n placement balancing is per-batch-safe only,
                    # and meaningless for the identity (bounded) remap
                    placement_override=not bounded and op.parallelism > 1,
                    name=op.name)
        elif isinstance(op, _StatefulTPUBase):
            # device-resident interner: slots resolve in-program, so the
            # per-batch D2H intern sync disappears.  Requires every
            # feeding edge host-staged (admission sees every key before
            # its batch ships) and no fused prelude (post-prelude keys
            # are never host-visible).
            if op.dense_keys or op.mesh is not None \
                    or op._fused_prelude is not None \
                    or not host_fed(op) or len(op._interner):
                continue
            comp = KeyCompactor(op.num_key_slots, pinned=True,
                                reseed_every=reseed,
                                intern_fallback=True, name=op.name)
        elif isinstance(op, FfatWindowsTPU):
            if op.max_keys is not None or op.key_extractor is None:
                continue
            if op.mesh is not None:
                raise WindFlowError(
                    f"operator '{op.name}': compacted key spaces are "
                    "single-chip; declare withMaxKeys (divisible by the "
                    "key axis) for mesh execution")
            comp = KeyCompactor(slots, pinned=True, reseed_every=reseed,
                                name=op.name)
        if comp is None:
            continue
        comp.bind_sketch(sketches.get(id(op)))
        op.enable_compaction(comp)

    # emitter wiring: host admission + placement override, mirroring the
    # shard ledger's attach walk
    def visit(em):
        if em is None:
            return
        if isinstance(em, SplittingEmitter):
            for b in em.branches:
                visit(b)
            return
        if isinstance(em, DeviceToHostEmitter):
            visit(em.inner)
            return
        if not em.dests:
            return
        consumer = em.dests[0][0].op
        comp = consumer._compactor
        if comp is None or comp.bounded:
            return
        if isinstance(em, KeyedDeviceStageEmitter):
            # fused tails re-extract keys POST-prelude in-program
            # (make_compacted_reduce sets keys=None after the prelude);
            # host admission here would feed PRE-prelude keys into the
            # table — phantom entries the lookup never hits.  Reseeds
            # from the in-program post-prelude sketch still admit.
            if getattr(consumer, "_fused_prelude", None) is None:
                em._compactor = comp
        elif isinstance(em, DeviceKeyByEmitter):
            if comp.placement_override:
                em.attach_compactor(comp)
        elif isinstance(em, DeviceStageEmitter):
            kx = consumer.key_extractor
            if kx is not None and consumer._fused_prelude is None:
                if em._shard_probe is not None:
                    em._shard_probe.compactor = comp
                else:
                    em._shard_probe = HostKeyProbe(None, kx,
                                                   compactor=comp)

    for op in graph._operators:
        for rep in op.replicas:
            visit(rep.emitter)
