"""Collectors: per-replica input alignment for the three execution modes.

Re-design of the reference collectors, which are FastFlow multi-input nodes
prepended to each replica (``multipipe.hpp:199-232``):

* DEFAULT        → :class:`WatermarkCollector` (``watermark_collector.hpp:50-140``)
* DETERMINISTIC  → :class:`OrderingCollector`  (``ordering_collector.hpp:51-``)
* PROBABILISTIC  → :class:`KSlackCollector`    (``kslack_collector.hpp:52-``)

Here a collector is a plain object the replica consults when draining its
inbox: it receives ``(channel, message)`` and returns the messages that are
ready to process, with their watermark rewritten to the alignment frontier.
Control stays on the host — exactly as in the reference, where collectors run
on the replica's thread before the operator logic.
"""

from __future__ import annotations

import heapq
from typing import List

from windflow_tpu.basic import ExecutionMode
from windflow_tpu.batch import HostBatch, Punctuation, WM_NONE


class Collector:
    def __init__(self, num_channels: int) -> None:
        self.num_channels = num_channels
        self.num_dropped = 0

    def on_message(self, channel: int, msg) -> List:
        """Feed one inbound message; return messages ready for the operator."""
        raise NotImplementedError

    def on_channel_eos(self, channel: int) -> List:
        """A channel reached end-of-stream; release anything it was holding."""
        return []


class WatermarkCollector(Collector):
    """DEFAULT mode: track the max watermark per input channel and rewrite each
    message's watermark to the min over channels that have been heard from
    (reference ``watermark_collector.hpp:63-76,109-130``).  Data flows through
    unchanged and unordered — out-of-order tolerance is downstream's job
    (lateness gates on windows)."""

    def __init__(self, num_channels: int) -> None:
        super().__init__(num_channels)
        self._wms = [WM_NONE] * num_channels
        self._closed = [False] * num_channels

    def _frontier(self) -> int:
        seen = [w for w, c in zip(self._wms, self._closed)
                if not c and w != WM_NONE]
        if seen:
            return min(seen)
        return WM_NONE

    def on_message(self, channel, msg):
        wm = msg.watermark
        if wm != WM_NONE and wm > self._wms[channel]:
            self._wms[channel] = wm
        msg.watermark = self._frontier()
        return [msg]

    def on_channel_eos(self, channel):
        self._closed[channel] = True
        return []


class OrderingCollector(Collector):
    """DETERMINISTIC mode: merge the (per-channel ordered) input streams into
    one globally timestamp-ordered stream, releasing a tuple only when every
    open channel has something buffered — so no earlier tuple can still arrive
    (reference ``ordering_collector.hpp``; also used for id-ordering in WLQ /
    REDUCE window stages).  Batches are unpacked: determinism is defined at
    tuple granularity.  Ties break on (ts, channel, arrival seq)."""

    def __init__(self, num_channels: int) -> None:
        super().__init__(num_channels)
        self._queues: List[List] = [[] for _ in range(num_channels)]
        self._closed = [False] * num_channels
        self._seq = 0

    def _drain_ready(self):
        out = []
        while True:
            heads = []
            for ch in range(self.num_channels):
                if self._queues[ch]:
                    heads.append((self._queues[ch][0], ch))
                elif not self._closed[ch]:
                    # An open, empty channel could still deliver the minimum.
                    return out
            if not heads:
                return out
            (key, item, ts, wm), ch = min(heads, key=lambda h: h[0][0])
            self._queues[ch].pop(0)
            out.append(HostBatch([item], [ts], wm))
        return out

    def on_message(self, channel, msg):
        if isinstance(msg, Punctuation):
            # Watermarks are deterministic byproducts here; punctuations only
            # matter for EOS, which arrives via on_channel_eos.
            return []
        assert isinstance(msg, HostBatch), \
            "DETERMINISTIC mode supports host operators only (parity: GPU ops are DEFAULT-only)"
        for item, ts in zip(msg.items, msg.tss):
            self._seq += 1
            self._queues[channel].append(
                ((ts, channel, self._seq), item, ts, msg.watermark))
        return self._drain_ready()

    def on_channel_eos(self, channel):
        self._closed[channel] = True
        return self._drain_ready()


class KSlackCollector(Collector):
    """PROBABILISTIC mode: adaptive K-slack reordering buffer (reference
    ``kslack_collector.hpp:58,120``).  K tracks the maximum observed delay
    ``max_ts_seen - ts``; a buffered tuple is released once
    ``ts <= max_ts_seen - K``.  Tuples arriving behind the release frontier
    are dropped and counted (reference ``atomic_num_dropped``)."""

    def __init__(self, num_channels: int) -> None:
        super().__init__(num_channels)
        self._heap: List = []  # (ts, seq, item, wm)
        self._seq = 0
        self._k = 0
        self._max_ts = WM_NONE
        self._frontier = WM_NONE  # last released ts
        self._open = num_channels

    def _release(self, limit: int) -> List[HostBatch]:
        out = []
        while self._heap and self._heap[0][0] <= limit:
            ts, _, item, _ = heapq.heappop(self._heap)
            self._frontier = max(self._frontier, ts)
            out.append(HostBatch([item], [ts], self._frontier))
        return out

    def on_message(self, channel, msg):
        if isinstance(msg, Punctuation):
            return []
        assert isinstance(msg, HostBatch), \
            "PROBABILISTIC mode supports host operators only"
        for item, ts in zip(msg.items, msg.tss):
            if ts < self._frontier:
                self.num_dropped += 1  # too late even for the slack buffer
                continue
            self._max_ts = max(self._max_ts, ts)
            self._k = max(self._k, self._max_ts - ts)
            self._seq += 1
            heapq.heappush(self._heap, (ts, self._seq, item, msg.watermark))
        return self._release(self._max_ts - self._k)

    def on_channel_eos(self, channel):
        self._open -= 1
        if self._open == 0 and self._heap:
            return self._release(max(h[0] for h in self._heap))
        return []


def create_collector(mode: ExecutionMode, num_channels: int) -> Collector:
    """Reference ``multipipe.hpp:199-232``: DETERMINISTIC→Ordering,
    PROBABILISTIC→KSlack, DEFAULT→Watermark."""
    if mode == ExecutionMode.DETERMINISTIC:
        return OrderingCollector(num_channels)
    if mode == ExecutionMode.PROBABILISTIC:
        return KSlackCollector(num_channels)
    return WatermarkCollector(num_channels)
