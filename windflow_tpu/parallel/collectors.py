"""Collectors: per-replica input alignment for the three execution modes.

Re-design of the reference collectors, which are FastFlow multi-input nodes
prepended to each replica (``multipipe.hpp:199-232``):

* DEFAULT        → :class:`WatermarkCollector` (``watermark_collector.hpp:50-140``)
* DETERMINISTIC  → :class:`OrderingCollector`  (``ordering_collector.hpp:51-``)
* PROBABILISTIC  → :class:`KSlackCollector`    (``kslack_collector.hpp:52-``)

Here a collector is a plain object the replica consults when draining its
inbox: it receives ``(channel, message)`` and returns the messages that are
ready to process, with their watermark rewritten to the alignment frontier.
Control stays on the host — exactly as in the reference, where collectors run
on the replica's thread before the operator logic.
"""

from __future__ import annotations

import dataclasses
import heapq
from collections import deque
from typing import List

from windflow_tpu.analysis.hotpath import hot_path
from windflow_tpu.basic import ExecutionMode
from windflow_tpu.batch import DeviceBatch, HostBatch, Punctuation, WM_NONE


class Collector:
    def __init__(self, num_channels: int) -> None:
        self.num_channels = num_channels
        self.num_dropped = 0

    def on_message(self, channel: int, msg) -> List:
        """Feed one inbound message; return messages ready for the operator."""
        raise NotImplementedError

    def on_channel_eos(self, channel: int) -> List:
        """A channel reached end-of-stream; release anything it was holding."""
        return []


class WatermarkCollector(Collector):
    """DEFAULT mode: track the max watermark per input channel and rewrite each
    message's watermark to the min over channels that have been heard from
    (reference ``watermark_collector.hpp:63-76,109-130``).  Data flows through
    unchanged and unordered — out-of-order tolerance is downstream's job
    (lateness gates on windows)."""

    def __init__(self, num_channels: int) -> None:
        super().__init__(num_channels)
        import numpy as np
        self._wms = np.full(num_channels, WM_NONE, np.int64)
        # Per-channel newest frontier (DeviceBatch.frontier stamps): always
        # >= the propagated watermark, aligned the same way so a multi-input
        # device operator never fires ahead of a lagging sibling channel.
        self._fronts = np.full(num_channels, WM_NONE, np.int64)
        self._closed = np.zeros(num_channels, bool)

    def _fold(self, slots) -> int:
        """Min over OPEN channels; a channel not yet heard from holds the
        frontier down (reference initializes per-channel maxs to zero and
        mins over all of them, ``watermark_collector.hpp:63-76``) —
        otherwise a fast channel's watermark fires time windows before a
        slow sibling's older tuples arrive, silently dropping them as late.
        Punctuation cadence keeps genuinely idle channels advancing.
        Small fan-ins (the common case) fold in a plain Python loop; wide
        fan-ins use the native fold (``wf_host.cpp wf_min_watermark``)
        where the loop cost actually shows."""
        if self.num_channels <= 8:
            lo = WM_NONE
            for w, c in zip(slots, self._closed):
                if c:
                    continue
                if w == WM_NONE:
                    return WM_NONE
                lo = w if lo == WM_NONE else min(lo, int(w))
            return lo
        from windflow_tpu import native
        return native.min_watermark(slots[~self._closed], WM_NONE)

    def _frontier(self) -> int:
        return self._fold(self._wms)

    @hot_path
    def on_message(self, channel, msg):
        wm = msg.watermark
        if wm != WM_NONE and wm > self._wms[channel]:
            self._wms[channel] = wm
        # Punctuations/host batches advance the channel frontier by their
        # watermark; device batches by their (tighter) staging frontier.
        fr = msg.frontier if isinstance(msg, DeviceBatch) else wm
        if fr != WM_NONE and fr > self._fronts[channel]:
            self._fronts[channel] = fr
        f = self._frontier()
        if isinstance(msg, DeviceBatch):
            ff = self._fold(self._fronts)
            if f != msg.watermark or ff != msg.frontier:
                # Rewrite on a fresh wrapper, never in place: batches are
                # multicast by handle (BROADCAST / device pass-through), so
                # an in-place rewrite by one consumer would corrupt the
                # frontier a sibling replica reads.
                msg = DeviceBatch(msg.payload, msg.ts, msg.valid,
                                  keys=msg.keys, watermark=f,
                                  size=msg.known_size, frontier=ff,
                                  ts_max=msg.ts_max, ts_min=msg.ts_min,
                                  trace=msg.trace)
        elif f != msg.watermark:
            if isinstance(msg, HostBatch):
                msg = dataclasses.replace(msg, watermark=f)
            else:
                assert isinstance(msg, Punctuation)
                msg = Punctuation(f)
        return [msg]

    def on_channel_eos(self, channel):
        self._closed[channel] = True
        return []


#: sort-key sentinel ordering id-less tuples after id-carrying ones at the
#: same timestamp (tuple compare: any real origin ordinal < _NO_TID)
_NO_TID = 1 << 60


class OrderingCollector(Collector):
    """DETERMINISTIC mode: merge the (per-channel ordered) input streams into
    one globally timestamp-ordered stream, releasing a tuple only when every
    open channel has something buffered — so no earlier tuple can still arrive
    (reference ``ordering_collector.hpp:51-`` uses priority queues; also used
    for id-ordering in WLQ / REDUCE window stages).  The k-way merge keeps a
    heap of channel heads over per-channel deques — O(log C) per released
    tuple — and batches each release run into one HostBatch, so long
    DETERMINISTIC streams stay linear instead of the naive per-tuple
    quadratic.  Ties break on (ts, origin id): origin ids are stamped at
    sources and relayed by one-to-one/one-to-many host stages
    (HostBatch.ids — the reference's Single_t id), so equal-timestamp
    tuples order the same under ANY parallelism/batching configuration;
    id-less tuples (aggregate outputs) fall back to (channel, arrival
    seq)."""

    def __init__(self, num_channels: int) -> None:
        super().__init__(num_channels)
        self._queues: List[deque] = [deque() for _ in range(num_channels)]
        self._closed = [False] * num_channels
        self._seq = 0
        #: channels currently gating release: open with an empty queue
        self._empty_open = num_channels
        #: heap of (sort_key, channel) for the head of each non-empty queue
        self._heads: List = []

    def _push_head(self, ch: int) -> None:
        heapq.heappush(self._heads, (self._queues[ch][0][0], ch))

    def _drain_ready(self):
        # release is gated while any open channel is empty — the minimum
        # could still arrive there
        if self._empty_open:
            return []
        items, tss, wms, ids = [], [], [], []
        any_tid = False
        shared = False
        while self._heads and not self._empty_open:
            _, ch = heapq.heappop(self._heads)
            q = self._queues[ch]
            _, item, ts, wm, sh, tid = q.popleft()
            items.append(item)
            tss.append(ts)
            wms.append(wm)
            ids.append(tid)
            any_tid |= tid is not None
            shared |= sh
            if q:
                self._push_head(ch)
            elif not self._closed[ch]:
                self._empty_open += 1
        if not items:
            return []
        # one ordered batch per release run; the conservative min watermark
        # (items from slower channels may carry older frontiers); ids relay
        # so a second ordered stage can break ties the same way
        wm = min((w for w in wms if w != WM_NONE), default=WM_NONE)
        return [HostBatch(items, tss, wm, shared=shared,
                          ids=ids if any_tid else None)]

    def on_message(self, channel, msg):
        if isinstance(msg, Punctuation):
            # Watermarks are deterministic byproducts here; punctuations only
            # matter for EOS, which arrives via on_channel_eos.
            return []
        assert isinstance(msg, HostBatch), \
            "DETERMINISTIC mode supports host operators only (parity: GPU ops are DEFAULT-only)"
        if not len(msg):
            return []
        q = self._queues[channel]
        was_empty = not q
        for item, ts, tid in zip(msg.items, msg.tss, msg.ids_or_nones()):
            self._seq += 1
            key = (ts, tid) if tid is not None                 else (ts, (_NO_TID, channel, self._seq))
            q.append((key, item, ts, msg.watermark,
                      msg.shared, tid))
        if was_empty:
            self._push_head(channel)
            if not self._closed[channel]:
                self._empty_open -= 1
        return self._drain_ready()

    def on_channel_eos(self, channel):
        self._closed[channel] = True
        if not self._queues[channel]:
            self._empty_open -= 1
        return self._drain_ready()


class KSlackCollector(Collector):
    """PROBABILISTIC mode: adaptive K-slack reordering buffer (reference
    ``kslack_collector.hpp:58,120``).  K tracks the maximum observed delay
    ``max_ts_seen - ts``; a buffered tuple is released once
    ``ts <= max_ts_seen - K``.  Tuples arriving behind the release frontier
    are dropped and counted (reference ``atomic_num_dropped``)."""

    def __init__(self, num_channels: int) -> None:
        super().__init__(num_channels)
        self._heap: List = []  # (ts, seq, item, wm, shared)
        self._seq = 0
        self._k = 0
        self._max_ts = WM_NONE
        self._frontier = WM_NONE  # last released ts
        self._open = num_channels

    def _release(self, limit: int) -> List[HostBatch]:
        # one HostBatch per release run (the OrderingCollector batches its
        # release runs the same way): a K-slack burst must not turn into
        # per-tuple singleton batches that tax every downstream stage.
        # HostBatch carries ONE shared flag, so the run splits on
        # shared-flag boundaries — OR-folding the flags would make one
        # multicast tuple force copy-on-write deep copies of the whole
        # run in every in-place downstream replica (ops/base.py _dispatch).
        out = []
        items, tss = [], []
        cur_shared = False
        while self._heap and self._heap[0][0] <= limit:
            ts, _, item, _, sh = heapq.heappop(self._heap)
            self._frontier = max(self._frontier, ts)
            if items and sh != cur_shared:
                out.append(HostBatch(items, tss, tss[-1],
                                     shared=cur_shared))
                items, tss = [], []
            cur_shared = sh
            items.append(item)
            tss.append(ts)
        if items:
            out.append(HostBatch(items, tss, self._frontier,
                                 shared=cur_shared))
        return out

    def on_message(self, channel, msg):
        if isinstance(msg, Punctuation):
            return []
        assert isinstance(msg, HostBatch), \
            "PROBABILISTIC mode supports host operators only"
        for item, ts in zip(msg.items, msg.tss):
            if ts < self._frontier:
                self.num_dropped += 1  # too late even for the slack buffer
                continue
            self._max_ts = max(self._max_ts, ts)
            self._k = max(self._k, self._max_ts - ts)
            self._seq += 1
            heapq.heappush(self._heap,
                           (ts, self._seq, item, msg.watermark, msg.shared))
        return self._release(self._max_ts - self._k)

    def on_channel_eos(self, channel):
        self._open -= 1
        if self._open == 0 and self._heap:
            return self._release(max(h[0] for h in self._heap))
        return []


def create_collector(mode: ExecutionMode, num_channels: int) -> Collector:
    """Reference ``multipipe.hpp:199-232``: DETERMINISTIC→Ordering,
    PROBABILISTIC→KSlack, DEFAULT→Watermark."""
    if mode == ExecutionMode.DETERMINISTIC:
        return OrderingCollector(num_channels)
    if mode == ExecutionMode.PROBABILISTIC:
        return KSlackCollector(num_channels)
    return WatermarkCollector(num_channels)
