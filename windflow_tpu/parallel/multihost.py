"""Multi-host execution: DCN-aware meshes and process-local staging.

The reference is explicitly single-node ("At the moment WindFlow is for
single-node execution", ``README.md:15``); its scale-out story ends at
OS threads + lock-free queues.  The TPU design extends the same two mesh
axes across hosts:

* **key axis across DCN, data axis within ICI.**  Keyed state (dense
  per-key tables: windows, reduces) is sharded over the key axis, which is
  laid out so host boundaries fall along it.  The per-step ``all_gather``
  of staged tuples happens over the *data* axis — entirely within each
  host's ICI domain — while only the small dense partial tables (keyed
  reduce ``psum``) ever cross DCN.  That is the bandwidth hierarchy the
  scaling recipe prescribes: bulk traffic on ICI, reductions on DCN.
* Every process runs the same host driver; each stages only its local
  shard of the batch (``stage_local``), and XLA's collectives do the rest.

``initialize()`` wraps ``jax.distributed.initialize`` (coordinator address /
process count / process id from arguments or the standard environment
variables).  On one process everything degenerates to the single-host mesh
layer (``parallel/mesh.py``) — which is also how the test suite exercises
this module, by emulating host groups on a virtual CPU mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from windflow_tpu.basic import WindFlowError
from windflow_tpu.batch import DeviceBatch, HostBatch, host_to_device
from windflow_tpu.parallel.mesh import DATA_AXIS, KEY_AXIS

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host job (no-op when single-process or already
    joined).  Arguments default to the standard JAX coordinator environment
    (``JAX_COORDINATOR_ADDRESS`` etc.), exactly as ``jax.distributed``."""
    global _initialized
    if _initialized:
        return
    if coordinator_address is None and num_processes in (None, 1):
        _initialized = True  # single-process: nothing to join
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True


def make_multihost_mesh(local_data: int = 1,
                        devices: Optional[Sequence] = None,
                        emulate_hosts: Optional[int] = None) -> Mesh:
    """Build the ``(data, key)`` mesh with host boundaries along the key
    axis.

    ``local_data`` is the data-parallel extent *within* each host (its
    devices split ``local_data × local_key``); the key axis concatenates
    every host's key block, so keyed state shards across hosts and the
    data-axis ``all_gather`` of staged tuples never leaves a host's ICI
    domain.

    ``emulate_hosts`` partitions a single process's devices into that many
    virtual host groups — the testing configuration (virtual CPU mesh); on
    a real multi-host job leave it None and the actual process topology is
    used."""
    if devices is not None:
        devs = list(devices)
        groups = _split_groups(devs, emulate_hosts or 1)
    elif emulate_hosts:
        devs = list(jax.devices())
        groups = _split_groups(devs, emulate_hosts)
    else:
        devs = list(jax.devices())
        n_proc = jax.process_count()
        if n_proc == 1:
            groups = [devs]
        else:
            groups = [[] for _ in range(n_proc)]
            for d in devs:
                groups[d.process_index].append(d)
    local = len(groups[0])
    if any(len(g) != local for g in groups):
        raise WindFlowError("hosts expose unequal device counts")
    if local % local_data:
        raise WindFlowError(
            f"{local} devices per host not divisible by "
            f"local_data={local_data}")
    local_key = local // local_data
    arr = np.empty((local_data, len(groups) * local_key), dtype=object)
    for p, g in enumerate(groups):
        block = np.array(g, dtype=object).reshape(local_data, local_key)
        arr[:, p * local_key:(p + 1) * local_key] = block
    return Mesh(arr, (DATA_AXIS, KEY_AXIS))


def _split_groups(devs, n_groups: int):
    if len(devs) % n_groups:
        raise WindFlowError(
            f"{len(devs)} devices not divisible into {n_groups} host groups")
    per = len(devs) // n_groups
    return [devs[i * per:(i + 1) * per] for i in range(n_groups)]


def stage_local(hb: HostBatch, capacity: int, mesh: Mesh,
                spec: Optional[P] = None) -> DeviceBatch:
    """Stage a host batch on a (possibly multi-process) mesh.

    Single-process: plain sharded ``device_put``.  Multi-process: this
    process contributes only its slice of the global batch —
    ``capacity`` is the *global* lane count, ``hb`` holds the lanes this
    process ingested (``capacity / process_count`` of them), and the global
    array is assembled with ``jax.make_array_from_process_local_data``.

    The default spec shards lanes over every mesh axis (the keyed-reduce
    ingest layout, where any host may ingest any tuple).  Key-sharded
    window state instead wants each tuple ingested by the host owning its
    key — that is upstream KEYBY routing's job (e.g. Kafka partition
    assignment per host), after which each host group runs the data-axis
    ``all_gather`` purely inside its own ICI domain."""
    if spec is None:
        spec = P((DATA_AXIS, KEY_AXIS))
    sh = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        db = host_to_device(hb, capacity=capacity)
        return DeviceBatch(
            jax.tree.map(lambda a: jax.device_put(a, sh), db.payload),
            jax.device_put(db.ts, sh), jax.device_put(db.valid, sh),
            watermark=db.watermark, size=db.known_size)
    local_cap = capacity // jax.process_count()
    db = host_to_device(hb, capacity=local_cap)

    def assemble(local_arr):
        return jax.make_array_from_process_local_data(
            sh, np.asarray(local_arr), (capacity,) + local_arr.shape[1:])

    return DeviceBatch(
        jax.tree.map(assemble, db.payload),
        assemble(db.ts), assemble(db.valid),
        watermark=db.watermark, size=None)
