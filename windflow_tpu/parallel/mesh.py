"""Multi-chip execution: device meshes, key-sharded window state, and
collective keyed reduction over ICI.

This is the slot the reference fills with thread replication + emitter routing
(SURVEY.md §2.6 item 10: "GPU offload batching … This is the slot where the
TPU backend goes").  Where WindFlow scales an operator by cloning replicas
onto OS threads and hashing keys across lock-free queues
(``keyby_emitter.hpp:216``), the TPU design scales by **sharding over a
device mesh**:

* mesh axes ``("data", "key")`` — ``data`` shards the *tuples* of each staged
  batch (the analogue of replicating stateless operators), ``key`` shards the
  *keyed state space* (the analogue of KEYBY partitioning of stateful
  operators).
* stateless Map/Filter steps run on data-sharded batches with zero
  communication.
* keyed windows (:func:`make_sharded_ffat_step`) keep their dense per-key
  state sharded along ``key``; each key-shard sees the full batch via an
  ``all_gather`` over ``data`` (tuples ride ICI once) and updates only the
  keys it owns.
* keyed reduction (:func:`make_sharded_keyed_reduce`) computes per-chip
  dense partial tables and combines them across the mesh with ``psum``
  (sum-like combiners) or a gather+fold (arbitrary associative combiners) —
  the ICI expression of the reference's ``thrust::reduce_by_key`` +
  inter-replica merge.

All collectives are XLA collectives over the mesh (``psum``/``all_gather``);
on real hardware they ride ICI, multi-host meshes extend over DCN with the
same program (the driver validates this path on a virtual CPU mesh).
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax.shard_map is the >= 0.6 spelling (replication check kwarg
# `check_vma`); the 0.4.x floor ships it under jax.experimental with the
# check named `check_rep` — resolve once so every collective below works
# on both
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)

from windflow_tpu.basic import WindFlowError
from windflow_tpu.batch import DeviceBatch, HostBatch, host_to_device
from windflow_tpu.monitoring.jit_registry import wf_jit
from windflow_tpu.windows.ffat_kernels import (_b, _masked_reduce_last,
                                           _monoid_identity, _seg_scan,
                                           make_ffat_flush,
                                           make_ffat_state, make_ffat_step,
                                           make_ffat_tb_state,
                                           make_ffat_tb_step,
                                           monoid_collective,
                                           resolve_monoid)
from windflow_tpu.windows.grouping import auto_order

DATA_AXIS = "data"
KEY_AXIS = "key"


def make_mesh(n_devices: Optional[int] = None, data: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Create a ``(data, key)`` mesh over the first ``n_devices`` devices.

    ``data`` fixes the data-parallel extent; the key axis takes the rest.
    With ``data=1`` the mesh degenerates to pure key sharding (the keyed
    Reduce/FFAT scaling configuration from BASELINE.json)."""
    devs = list(devices) if devices is not None else list(jax.devices())
    if n_devices is not None:
        if len(devs) < n_devices:
            raise WindFlowError(
                f"requested {n_devices} devices, only {len(devs)} visible")
        devs = devs[:n_devices]
    n = len(devs)
    if n % data != 0:
        raise WindFlowError(f"{n} devices not divisible by data={data}")
    arr = np.array(devs).reshape(data, n // data)
    return Mesh(arr, (DATA_AXIS, KEY_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for staged batch lanes: tuples split along ``data``,
    replicated along ``key``."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def state_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for dense per-key state tables: split along ``key``."""
    return NamedSharding(mesh, P(KEY_AXIS))


def stage_batch(hb: HostBatch, capacity: int, mesh: Mesh) -> DeviceBatch:
    """Host→mesh staging: pad to ``capacity`` and lay tuples out data-sharded
    (the multi-chip form of the reference's pinned-staging H2D path)."""
    db = host_to_device(hb, capacity=capacity)
    sh = batch_sharding(mesh)
    return DeviceBatch(
        jax.tree.map(lambda a: jax.device_put(a, sh), db.payload),
        jax.device_put(db.ts, sh), jax.device_put(db.valid, sh),
        watermark=db.watermark, size=db.known_size)


def _aligned_slot_bound(op) -> Optional[int]:
    """The dense slot space an aligned emitter would place by, or None
    when this operator kind/configuration cannot take aligned ingest:

    * key-sharded ``FfatWindowsTPU`` with a declared dense key space
      (the PR 13 original);
    * declared-``withMaxKeys`` ``ReduceTPU`` — the sharded dense
      reduce (ROADMAP item-4 leftover: pre-placed lanes let each key
      shard build ONLY its own partial rows, so the cross-chip table
      collective — psum for monoids, all_gather+fold for generic
      combiners — disappears entirely);
    * ``withDenseKeys`` stateful Map/Filter — pre-placed lanes are
      exactly the lanes whose slots the shard owns, so the data-axis
      all_gather AND the psum lane merge both vanish.

    Compacted key spaces stay unaligned (admission runs at the keyed
    staging boundary of a replica-sharded consumer)."""
    from windflow_tpu.ops.tpu import ReduceTPU
    from windflow_tpu.ops.tpu_stateful import _StatefulTPUBase
    from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU
    if op.key_extractor is None:
        return None
    if isinstance(op, FfatWindowsTPU):
        if op.max_keys is None or getattr(op, "_compact_keys", False):
            return None
        return op.max_keys
    if isinstance(op, ReduceTPU):
        return op.max_keys      # None (arbitrary/compacted) = unaligned
    if isinstance(op, _StatefulTPUBase):
        return op.num_key_slots if op.dense_keys else None
    return None


def mark_aligned_ingest(graph) -> None:
    """Mark the mesh consumers eligible for KEY-ALIGNED ingest (ROADMAP
    item 4b; ``Config.key_aligned_ingest`` / ``WF_TPU_KEY_ALIGNED=0``
    kill switch): a key-sharded consumer with a declared dense key/slot
    space (:func:`_aligned_slot_bound` — FFAT windows, dense
    ``ReduceTPU``, dense-key stateful Map/Filter), fed EXCLUSIVELY by
    host staging edges under KEYBY routing, is stamped
    ``_ingest_mode="aligned"`` — the graph wiring then installs
    :class:`~windflow_tpu.parallel.emitters.AlignedMeshStageEmitter` on
    those edges and the consumer's sharded step compiles its
    no-all_gather variant (``_ffat_shard_layout`` ``"aligned"`` /
    ``make_sharded_reduce_step`` / ``make_sharded_stateful_step``
    ``ingest="aligned"``).  Device-fed consumers keep the data-sharded
    ingest (a TPU→TPU edge has no host boundary to align at), as do
    compacted key spaces (their admission runs at the keyed staging
    boundary of a REPLICA-sharded consumer) and multi-process graphs
    (each process stages only its local lanes).

    Called by ``PipeGraph._build`` after replica construction, before
    edge wiring — the emitter dispatch reads the stamp."""
    cfg = graph.config
    mesh = cfg.mesh
    if mesh is None or jax.process_count() > 1:
        return
    from windflow_tpu.basic import RoutingMode
    kk = mesh.shape[KEY_AXIS]
    dd = mesh.shape[DATA_AXIS]
    ups = {}
    for edge in graph._edges():
        if edge[0] == "op":
            _, a, b = edge
            ups.setdefault(id(b), []).append(a)
        else:
            _, mp = edge
            src = mp.operators[-1]
            for child in mp.split_children:
                if child.operators:
                    ups.setdefault(id(child.operators[0]),
                                   []).append(src)
    for op in graph._topo_operators():
        if not getattr(op, "is_tpu", False):
            continue
        bound = _aligned_slot_bound(op)
        if bound is None or op.routing != RoutingMode.KEYBY \
                or op.parallelism != 1:
            continue
        if bound % kk:
            continue        # WF402 territory: the mesh pass reports it
        feeds = ups.get(id(op), [])
        if not feeds or any(u.is_tpu for u in feeds):
            continue        # device-fed: no host boundary to align at
        if any((u.output_batch_size or 0) % (kk * dd)
               for u in feeds):
            continue        # indivisible staging capacity: keep default
        op._ingest_mode = "aligned"


# ---------------------------------------------------------------------------
# Keyed reduce over the mesh (reference Reduce_GPU + cross-replica merge;
# BASELINE.json: "keyby-sharded Reduce … linear scaling to 8 chips").
# ---------------------------------------------------------------------------

def _dense_keyed_partial(keys, vals, valid, comb, K):
    """Per-chip dense partial table: sort by key, segmented scan, scatter the
    segment tails into rows of a ``[K, ...]`` table.  The XLA/ICI-friendly
    replacement for ``thrust::sort_by_key`` + ``reduce_by_key``
    (``reduce_gpu.hpp:227-258``) producing a *dense* table so cross-chip
    combination is a collective, not a re-shuffle."""
    sk = jnp.where(valid & (keys >= 0) & (keys < K), keys, K)
    order = auto_order(sk, K + 1)   # O(n) dense grouping (grouping.py)
    sk_s = sk[order]
    sv = jax.tree.map(lambda a: a[order], vals)
    starts = jnp.concatenate([jnp.array([True]), sk_s[1:] != sk_s[:-1]])
    scanned = _seg_scan(comb, starts, sv)
    ends = jnp.concatenate([sk_s[:-1] != sk_s[1:], jnp.array([True])])
    row = jnp.where(ends & (sk_s < K), sk_s, K)

    def scat(leaf):
        buf = jnp.zeros((K + 1,) + leaf.shape[1:], leaf.dtype)
        return buf.at[row].set(leaf, mode="drop")[:K]

    table = jax.tree.map(scat, scanned)
    has = jnp.zeros(K + 1, bool).at[row].set(True)[:K]
    return table, has


def make_sharded_reduce_step(mesh: Mesh, capacity: int, K: int,
                             comb: Callable, key_fn: Optional[Callable],
                             use_psum: bool = False,
                             monoid: Optional[str] = None,
                             ingest: str = "data",
                             op_name: str = "mesh.reduce_step"):
    """Sharded ReduceTPU step with the operator's batch contract: returns
    ``fn(payload, ts, valid) -> (table, ts_out, has, n_dropped)`` where
    ``table`` is the dense ``[K]`` combined-record table, ``ts_out`` the
    per-key max input timestamp, ``has`` the occupancy mask — i.e. a
    DeviceBatch of capacity ``K`` whose valid lanes are the distinct keys —
    and ``n_dropped`` the count of valid tuples whose key fell outside
    ``[0, K)`` (the dense tables cannot hold them; the count surfaces in
    stats rather than vanishing silently).  This is what ``ReduceTPU``
    compiles when the graph runs on a mesh (Config.mesh): per-chip dense
    partials over the flattened ``(data, key)`` axes combined with a
    single reduce collective — ``psum``/``pmax``/``pmin`` for declared
    monoid combiners (``monoid``; legacy ``use_psum=True`` means
    ``"sum"``) — or all_gather + log-fold for arbitrary combiners
    (reference: Reduce_GPU per replica + cross-replica merge,
    ``reduce_gpu.hpp:227-283``).

    Non-keyed reduces pass ``key_fn=None`` with ``K == 1`` (the
    ``thrust::reduce`` global path).

    ``ingest="aligned"`` (key-aligned mesh ingest, ROADMAP item-4
    leftover): the host pre-placed every tuple on its key-owner's
    ``(data, key)`` column (AlignedMeshStageEmitter, dense-range owner
    ``key // K_local``), so each key shard builds ONLY its own
    ``K_local`` partial rows from its own ``capacity/kk`` lanes and
    the cross-chip table combine — ``psum``/``pmax``/``pmin`` of
    ``[K, ...]`` tables for declared monoids, ``all_gather`` + log-fold
    for generic combiners — disappears ENTIRELY; only the within-column
    data-axis gather remains (identity at ``data=1``), and the output
    tables return key-sharded instead of replicated (same global
    ``[K]`` contract)."""
    monoid = resolve_monoid(use_psum, monoid)
    n_total = math.prod(mesh.devices.shape)
    if capacity % n_total:
        raise WindFlowError(
            f"capacity {capacity} not divisible by {n_total} devices")
    axes = (DATA_AXIS, KEY_AXIS)
    if ingest not in ("data", "aligned"):
        raise WindFlowError(f"unknown reduce ingest layout '{ingest}'")
    if ingest == "aligned":
        kk = mesh.shape[KEY_AXIS]
        dd = mesh.shape[DATA_AXIS]
        if K % kk:
            raise WindFlowError(
                f"max_keys {K} not divisible by key axis {kk}")
        K_local = K // kk

        def local_aligned(payload, ts, valid):
            keys = jax.vmap(key_fn)(payload).astype(jnp.int32)
            base = (jax.lax.axis_index(KEY_AXIS)
                    * K_local).astype(jnp.int32)
            lk = keys - base
            in_range = (keys >= 0) & (keys < K) \
                & (lk >= 0) & (lk < K_local)
            # out-of-range keys clip onto an edge column host-side and
            # mask out here — counted exactly like the unaligned drop
            n_drop = jax.lax.psum(
                jnp.sum(valid & ~in_range, dtype=jnp.int64), axes)
            ok = valid & in_range
            if dd > 1:
                # within-column hop only (1/kk of the all_gather bytes):
                # every data row of a key column folds the same lanes
                ag = lambda a: jax.lax.all_gather(a, DATA_AXIS, axis=0,
                                                  tiled=True)
                payload = jax.tree.map(ag, payload)
                lk, ts, ok = ag(lk), ag(ts), ag(ok)
            vals = (payload, ts)
            comb2 = lambda a, b: (comb(a[0], b[0]),
                                  jnp.maximum(a[1], b[1]))
            (table, ts_t), has = _dense_keyed_partial(
                lk, vals, ok, comb2, K_local)
            # each shard's rows are FINAL — no cross-chip combine; rows
            # a shard never saw stay invalid exactly as the collective
            # path leaves them identity-filled/unfolded
            ts_out = jnp.where(has, ts_t, jnp.int64(-1))
            return table, ts_out, has, n_drop

        bspec = P((DATA_AXIS, KEY_AXIS))
        fn = shard_map(local_aligned, mesh=mesh,
                       in_specs=(bspec, bspec, bspec),
                       out_specs=(P(KEY_AXIS), P(KEY_AXIS),
                                  P(KEY_AXIS), P()),
                       check_vma=False)
        return wf_jit(fn, op_name=op_name)

    def local(payload, ts, valid):
        if key_fn is not None:
            keys = jax.vmap(key_fn)(payload).astype(jnp.int32)
        else:
            keys = jnp.zeros(ts.shape[0], jnp.int32)
        n_drop = jnp.sum(valid & ((keys < 0) | (keys >= K)),
                         dtype=jnp.int64)
        n_drop = jax.lax.psum(n_drop, axes)
        # fold ts with the payload so the segment tails carry max-ts too
        vals = (payload, ts)
        comb2 = lambda a, b: (comb(a[0], b[0]), jnp.maximum(a[1], b[1]))
        (table, ts_t), has = _dense_keyed_partial(keys, vals, valid, comb2, K)
        if monoid is not None:
            coll = monoid_collective(monoid)
            z = jax.tree.map(
                lambda a: jnp.where(_b(has, a), a,
                                    _monoid_identity(monoid, a.dtype)),
                table)
            out = jax.tree.map(lambda a: coll(a, axes), z)
            ts_out = jax.lax.pmax(jnp.where(has, ts_t, jnp.int64(-1)), axes)
            any_has = jax.lax.psum(has.astype(jnp.int32), axes) > 0
            return out, ts_out, any_has, n_drop
        g_t = jax.tree.map(lambda a: jax.lax.all_gather(a, axes),
                           (table, ts_t))
        g_h = jax.lax.all_gather(has, axes)
        anyf, (folded, ts_f) = _masked_reduce_last(comb2, g_h, g_t, axis=0)
        return folded, ts_f, anyf, n_drop

    fn = shard_map(local, mesh=mesh,
                       in_specs=(P(axes), P(axes), P(axes)),
                       out_specs=(P(), P(), P(), P()), check_vma=False)
    return wf_jit(fn, op_name=op_name)


def make_sharded_reduce_arbitrary(mesh: Mesh, capacity: int, comb: Callable,
                                  key_fn: Callable,
                                  op_name: str = "mesh.reduce_arbitrary",
                                  remap: bool = False):
    """Keyed reduce over the mesh for an ARBITRARY int32 key space — no
    ``withMaxKeys`` bound and no dropped keys (VERDICT r2 item 5).

    Keys are hash-sharded: each chip buckets its local lanes by owner chip
    (``key mod n`` on the uint32 reinterpretation), one ``all_to_all`` over
    ICI routes every lane to its owner, and each chip then runs the plain
    sort + segmented reduce over the keys it owns (the distributed form of
    the reference's arbitrary-key ``thrust::sort_by_key`` +
    ``reduce_by_key``, ``reduce_gpu.hpp:227-258``, with the shuffle the
    reference does between replicas done as one collective).

    Returns ``fn(payload, ts, valid) -> (payload, ts, valid, n_dropped)``;
    each chip's distinct-key rows are left-compacted into its ``[capacity]``
    block of the concatenated output (worst case one chip owns every key,
    so the per-chip block cannot shrink below ``capacity``); ``n_dropped``
    is always 0 — nothing is out of range by construction.

    ``remap=True`` is the key-compaction variant (parallel/compaction.py):
    the signature grows two REPLICATED read-only operands
    ``(table_keys, table_slots)`` and slotted (hot) keys route to owner
    ``slot % n`` instead of the uint32 hash — the remap balances hot
    keys over chips deterministically while the cold tail keeps the
    hash.  The per-chip sort/segment path itself is unchanged, so the
    output contract is identical."""
    axes = (DATA_AXIS, KEY_AXIS)
    n = math.prod(mesh.devices.shape)
    if capacity % n:
        raise WindFlowError(
            f"capacity {capacity} not divisible by {n} devices")
    local_cap = capacity // n

    def local(payload, ts, valid, *tables):
        from windflow_tpu.ops.tpu import _segmented_reduce
        keys = jax.vmap(key_fn)(payload).astype(jnp.int32)
        own = (keys.astype(jnp.uint32) % n).astype(jnp.int32)
        if tables:
            from windflow_tpu.parallel.compaction import lookup_slots
            tk, tsl = tables
            slot, hit = lookup_slots(tk, tsl, keys, valid)
            own = jnp.where(hit, slot % jnp.int32(n), own)
        owner = jnp.where(valid, own, jnp.int32(n))
        # group local lanes by owner: rank within the owner run indexes the
        # outgoing bucket row (a run can never exceed local_cap lanes)
        order = auto_order(owner, n + 1)
        so = owner[order]
        sp = jax.tree.map(lambda a: a[order], payload)
        st, sv = ts[order], valid[order]
        pos = jnp.arange(local_cap)
        starts = jnp.concatenate([jnp.array([True]), so[1:] != so[:-1]])
        seg_start = jax.lax.associative_scan(
            jnp.maximum, jnp.where(starts, pos, 0))
        rank = (pos - seg_start).astype(jnp.int32)
        row = jnp.where(sv & (so < n), so, n)

        def scat(leaf):
            buf = jnp.zeros((n + 1, local_cap) + leaf.shape[1:], leaf.dtype)
            return buf.at[row, rank].set(leaf)[:n]
        bp = jax.tree.map(scat, sp)
        bt = scat(st)
        bmask = jnp.zeros((n + 1, local_cap), bool) \
            .at[row, rank].set(sv & (so < n))[:n]
        # one collective: bucket row i of every chip lands on chip i
        a2a = lambda x: jax.lax.all_to_all(x, axes, split_axis=0,
                                           concat_axis=0, tiled=True)
        rp = jax.tree.map(a2a, bp)
        rt, rm = a2a(bt), a2a(bmask)
        flat = lambda a: a.reshape((capacity,) + a.shape[2:])
        rp = jax.tree.map(flat, rp)
        rt, rm = flat(rt), flat(rm)
        rkeys = jax.vmap(key_fn)(rp).astype(jnp.int32)
        _, out_payload, out_ts, out_valid = _segmented_reduce(
            rkeys, rp, rt, rm, comb, capacity)
        return out_payload, out_ts, out_valid, jnp.zeros((), jnp.int64)

    in_specs = (P(axes), P(axes), P(axes))
    if remap:
        # remap tables are replicated: every chip owns the same table
        in_specs = in_specs + (P(), P())
    fn = shard_map(local, mesh=mesh, in_specs=in_specs,
                       out_specs=(P(axes), P(axes), P(axes), P()),
                       check_vma=False)
    return wf_jit(fn, op_name=op_name)


def make_sharded_keyed_reduce(mesh: Mesh, capacity: int, K: int,
                              comb: Callable, key_fn: Callable,
                              use_psum: bool = False,
                              monoid: Optional[str] = None,
                              op_name: str = "mesh.keyed_reduce"):
    """Compile a keyed reduce over the whole mesh; thin wrapper over
    :func:`make_sharded_reduce_step` (one implementation of the collective
    combine) that drops the timestamp/drop-count outputs.  Returns
    ``fn(payload, valid) -> (table, has)`` with both outputs replicated on
    every chip."""
    step = make_sharded_reduce_step(mesh, capacity, K, comb, key_fn,
                                    use_psum=use_psum, monoid=monoid)

    def fn(payload, valid):
        ts = jnp.zeros(valid.shape[0], jnp.int64)
        table, _, has, _ = step(payload, ts, valid)
        return table, has

    return wf_jit(fn, op_name=op_name)


# ---------------------------------------------------------------------------
# Key-sharded FFAT windows (reference Ffat_Windows_GPU replicas each owning a
# key subset; here shards of one dense state table own key ranges).
# ---------------------------------------------------------------------------

def _ffat_shard_layout(mesh: Mesh, capacity: int, K: int,
                       ingest: str = "data"):
    """Shared guards + layout for key-sharded FFAT variants: returns
    ``(K_local, key_base_fn, gather, batch_spec, step_cap)`` where
    ``step_cap`` is the lane count each key shard's local step actually
    sees after ``gather``.

    ``ingest`` picks the staged-batch layout the step consumes:

    * ``"data"`` (single-host default): lanes split along ``data``,
      replicated along ``key`` — ``gather`` is one all_gather over the
      data axis, entirely within a host's ICI domain (identity on a
      1-wide data axis).
    * ``"flat"`` (multi-host graphs): lanes fully sharded over
      ``(data, key)`` — the only layout a process can assemble from the
      lanes IT ingested (batch.py ``_stage_soa``) — and ``gather``
      reconstructs the logical lane order with an all_gather over
      ``key`` then ``data`` (data-major block order = the logical
      P((data, key)) order).  The key-axis hop crosses DCN.
    * ``"aligned"`` (key-aligned ingest, ROADMAP item 4b): lanes fully
      sharded over ``(data, key)`` with the HOST having already placed
      every tuple in its key-owner's column
      (parallel/emitters.AlignedMeshStageEmitter — the same
      ``key // K_local`` ownership ``key_base_fn`` rebases by).  The
      gather collapses to the within-column data-axis hop — identity on
      a 1-wide data axis — killing the all_gather that dominates the
      modeled ICI bytes/tuple (docs/PERF.md r11): each key shard
      processes only its own ``capacity/kk`` lanes."""
    kk = mesh.shape[KEY_AXIS]
    dd = mesh.shape[DATA_AXIS]
    if K % kk:
        raise WindFlowError(f"max_keys {K} not divisible by key axis {kk}")
    if capacity % dd:
        raise WindFlowError(
            f"capacity {capacity} not divisible by data axis {dd}")
    if ingest not in ("data", "flat", "aligned"):
        raise WindFlowError(f"unknown ffat ingest layout '{ingest}'")
    K_local = K // kk
    key_base_fn = lambda: jax.lax.axis_index(KEY_AXIS) * K_local

    if ingest in ("flat", "aligned"):
        if capacity % (dd * kk):
            raise WindFlowError(
                f"capacity {capacity} not divisible by the mesh's "
                f"{dd * kk} devices")

    if ingest == "flat":
        def gather(payload, ts, valid):
            def ag(a):
                a = jax.lax.all_gather(a, KEY_AXIS, axis=0, tiled=True)
                if dd > 1:
                    a = jax.lax.all_gather(a, DATA_AXIS, axis=0,
                                           tiled=True)
                return a
            return jax.tree.map(ag, payload), ag(ts), ag(valid)

        return (K_local, key_base_fn, gather, P((DATA_AXIS, KEY_AXIS)),
                capacity)

    if ingest == "aligned":
        def gather(payload, ts, valid):
            if dd == 1:
                return payload, ts, valid
            # within-column hop only: each key shard re-assembles its
            # OWN column's rows (d-major block order = the aligned
            # emitter's row order); no key-axis traffic at all
            ag = lambda a: jax.lax.all_gather(a, DATA_AXIS, axis=0,
                                              tiled=True)
            return jax.tree.map(ag, payload), ag(ts), ag(valid)

        return (K_local, key_base_fn, gather, P((DATA_AXIS, KEY_AXIS)),
                capacity // kk)

    def gather(payload, ts, valid):
        if dd == 1:
            return payload, ts, valid
        ag = lambda a: jax.lax.all_gather(a, DATA_AXIS, axis=0, tiled=True)
        return jax.tree.map(ag, payload), ag(ts), ag(valid)

    return K_local, key_base_fn, gather, P(DATA_AXIS), capacity


def make_sharded_ffat_step(mesh: Mesh, capacity: int, K: int, Pn: int, R: int,
                           D: int, lift: Callable, comb: Callable,
                           key_fn: Optional[Callable],
                           sum_like: bool = False,
                           grouping: str = "rank_scatter",
                           ingest: str = "data",
                           monoid: Optional[str] = None,
                           op_name: str = "mesh.ffat_step"):
    """Compile one FFAT window step sharded over the mesh.

    State tables are split along ``key`` (chip *i* owns keys
    ``[i*K/kk, (i+1)*K/kk)``); the staged batch arrives data-sharded and is
    ``all_gather``-ed across ``data`` inside the program so every key shard
    sees every tuple exactly once over ICI.  Fired-window outputs come back
    key-sharded, one row block per chip."""
    K_local, key_base_fn, gather, bspec, step_cap = _ffat_shard_layout(
        mesh, capacity, K, ingest)
    step_local = make_ffat_step(step_cap, K_local, Pn, R, D, lift, comb,
                                key_fn, key_base_fn=key_base_fn,
                                sum_like=sum_like, grouping=grouping,
                                monoid=monoid)

    def local(state, payload, ts, valid):
        payload, ts, valid = gather(payload, ts, valid)
        return step_local(state, payload, ts, valid)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(KEY_AXIS), bspec, bspec, bspec),
        out_specs=(P(KEY_AXIS), P(KEY_AXIS), P(KEY_AXIS), P(KEY_AXIS)),
        check_vma=False)
    return wf_jit(fn, op_name=op_name, donate_argnums=(0,))


def make_sharded_ffat_flush(mesh: Mesh, K: int, Pn: int, R: int, D: int,
                            comb: Callable,
                            op_name: str = "mesh.ffat_flush"):
    """EOS flush of the key-sharded CB state as an explicit shard_map:
    each key shard flushes its own rows (keys rebased by the shard's
    base) and the outputs stay key-sharded — so each host's sink reads
    exactly its own keys' partial windows (a plain jit lets XLA pick the
    output layout, which scrambled per-process reads)."""
    kk = mesh.shape[KEY_AXIS]
    if K % kk:
        raise WindFlowError(f"max_keys {K} not divisible by key axis {kk}")
    K_local = K // kk
    key_base_fn = lambda: jax.lax.axis_index(KEY_AXIS) * K_local
    flush_local = make_ffat_flush(K_local, Pn, R, D, comb,
                                  key_base_fn=key_base_fn)
    fn = shard_map(
        flush_local, mesh=mesh,
        in_specs=(P(KEY_AXIS),),
        out_specs=(P(KEY_AXIS), P(KEY_AXIS), P(KEY_AXIS)),
        check_vma=False)
    return wf_jit(fn, op_name=op_name)


def make_sharded_ffat_state(agg_spec, K: int, R: int, mesh: Mesh):
    """Allocate the dense FFAT state pre-sharded along ``key``."""
    state = make_ffat_state(agg_spec, K, R)
    sh = state_sharding(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, sh), state)


def make_sharded_stateful_step(mesh: Mesh, capacity: int, S: int,
                               body_factory: Callable,
                               key_fn: Callable, dense: bool,
                               is_filter: bool, ingest: str = "data",
                               op_name: str = "mesh.stateful_step"):
    """Key-sharded stateful Map/Filter step (reference stateful ``Map_GPU``
    whose keyed state is one shared table, ``map_gpu.hpp:114-115``; here the
    dense ``[num_key_slots, ...]`` table is split along ``key`` so each chip
    owns a slot range).

    Layout mirrors the FFAT sharding: the data-sharded batch is
    ``all_gather``-ed across ``data`` so every key shard sees every lane;
    each shard runs the per-key in-order body over the lanes whose slot it
    owns (non-owned lanes contribute the body's neutral output), and lane
    results merge across key shards with one ``psum`` — each lane has
    exactly one owner, so the sum selects its real result.  Outputs return
    data-sharded, matching the batch layout downstream stages expect.

    ``ingest="aligned"`` (key-aligned mesh ingest; dense slot spaces
    only — AlignedMeshStageEmitter places by the same ``slot //
    S_local`` dense-range owner): each key shard's lanes are exactly
    the lanes whose slots it owns, so BOTH collectives of the default
    layout vanish — no data-axis all_gather to see foreign lanes, no
    psum lane merge to reconcile owners (every lane has its owner's
    verdict in place).  Outputs stay in the aligned ``(data, key)``
    layout; the only residual hop is the within-column data gather at
    ``data > 1``.  Per-key arrival order is preserved (the emitter
    appends each column in arrival order), so state evolution is
    record-identical to the unaligned layout per key."""
    kk = mesh.shape[KEY_AXIS]
    dd = mesh.shape[DATA_AXIS]
    if S % kk:
        raise WindFlowError(
            f"num_key_slots {S} not divisible by key axis {kk}")
    if capacity % dd:
        raise WindFlowError(
            f"capacity {capacity} not divisible by data axis {dd}")
    S_local = S // kk
    blk = capacity // dd
    if ingest not in ("data", "aligned"):
        raise WindFlowError(
            f"unknown stateful ingest layout '{ingest}'")
    if ingest == "aligned":
        if not dense:
            raise WindFlowError(
                "key-aligned stateful ingest requires withDenseKeys")
        if capacity % (dd * kk):
            raise WindFlowError(
                f"capacity {capacity} not divisible by the mesh's "
                f"{dd * kk} devices (key-aligned ingest)")
        col_cap = capacity // kk        # lanes one key column holds
        blk_col = capacity // (dd * kk)  # one device's block of them
        body_a = body_factory(col_cap, S_local)

        def local_aligned(state, payload, valid, _uk, _us):
            if dd > 1:
                ag = lambda a: jax.lax.all_gather(a, DATA_AXIS, axis=0,
                                                  tiled=True)
                payload = jax.tree.map(ag, payload)
                valid = ag(valid)
            keys = jax.vmap(key_fn)(payload).astype(jnp.int32)
            base = (jax.lax.axis_index(KEY_AXIS)
                    * S_local).astype(jnp.int32)
            lslot = keys - base
            owned = valid & (keys >= 0) & (keys < S) \
                & (lslot >= 0) & (lslot < S_local)
            lslot = jnp.where(owned, lslot, jnp.int32(S_local))
            new_state, out_payload, out_valid = body_a(
                state, payload, owned, lslot)
            d = jax.lax.axis_index(DATA_AXIS) * blk_col
            sl = lambda a: jax.lax.dynamic_slice_in_dim(a, d, blk_col,
                                                        axis=0)
            owned_b = sl(owned)
            if is_filter:
                # the owner's verdict is in place — un-owned (foreign /
                # out-of-range) lanes drop, the single-chip contract
                return (new_state, jax.tree.map(sl, payload),
                        sl(out_valid) & owned_b)
            return (new_state, jax.tree.map(sl, out_payload), owned_b)

        bspec = P((DATA_AXIS, KEY_AXIS))
        fn = shard_map(
            local_aligned, mesh=mesh,
            in_specs=(P(KEY_AXIS), bspec, bspec, P(), P()),
            out_specs=(P(KEY_AXIS), bspec, bspec),
            check_vma=False)
        return wf_jit(fn, op_name=op_name, donate_argnums=(0,))
    body = body_factory(capacity, S_local)

    def merge_lanes(leaf, owned):
        # zero out non-owned lanes, sum across key shards (bool via int32)
        if leaf.dtype == jnp.bool_:
            z = jnp.where(_b(owned, leaf), leaf, False)
            return jax.lax.psum(z.astype(jnp.int32), KEY_AXIS) > 0
        z = jnp.where(_b(owned, leaf), leaf, jnp.zeros_like(leaf))
        return jax.lax.psum(z, KEY_AXIS)

    def local(state, payload, valid, uniq_keys, uniq_slots):
        if dd > 1:
            ag = lambda a: jax.lax.all_gather(a, DATA_AXIS, axis=0,
                                              tiled=True)
            payload = jax.tree.map(ag, payload)
            valid = ag(valid)
        keys = jax.vmap(key_fn)(payload).astype(jnp.int32)
        if dense:
            slots = keys
            ok = valid & (keys >= 0) & (keys < S)
        else:
            pos = jnp.clip(jnp.searchsorted(uniq_keys, keys),
                           0, capacity - 1)
            slots = uniq_slots[pos]
            ok = valid & (slots < S)
        base = (jax.lax.axis_index(KEY_AXIS) * S_local).astype(jnp.int32)
        lslot = slots - base
        owned = ok & (lslot >= 0) & (lslot < S_local)
        lslot = jnp.where(owned, lslot, jnp.int32(S_local))
        new_state, out_payload, out_valid = body(state, payload, owned,
                                                 lslot)
        # back to the data-sharded layout FIRST: psum over KEY_AXIS and the
        # per-data-row block slice commute, and slicing first divides the
        # collective volume by the data-axis extent
        d = jax.lax.axis_index(DATA_AXIS) * blk
        sl = lambda a: jax.lax.dynamic_slice_in_dim(a, d, blk, axis=0)
        owned_b, valid_b = sl(owned), sl(valid)
        # a lane is real only if SOME shard owns its slot — out-of-range
        # keys have no owner and must drop, exactly as on a single chip
        owned_any = jax.lax.psum(owned_b.astype(jnp.int32), KEY_AXIS) > 0
        if is_filter:
            # non-owner shards keep their lanes; the owner's verdict is the
            # only veto (out_valid from the body is owned & keep)
            keep = sl(out_valid) | ~owned_b
            vetoed = jax.lax.psum((~keep).astype(jnp.int32), KEY_AXIS) > 0
            return (new_state, jax.tree.map(sl, payload),
                    valid_b & owned_any & ~vetoed)
        merged_payload = jax.tree.map(
            lambda l: merge_lanes(sl(l), owned_b), out_payload)
        return new_state, merged_payload, valid_b & owned_any

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(KEY_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(), P()),
        out_specs=(P(KEY_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        check_vma=False)
    return wf_jit(fn, op_name=op_name, donate_argnums=(0,))


# Time-based FFAT on the mesh.  The single-chip TB state keeps scalar pane
# clocks shared by all keys (ffat_kernels.make_ffat_tb_state); sharded along
# ``key`` each shard's ring evolves independently — its capacity roll depends
# on the panes of the keys it owns — so the scalars become one lane per key
# shard, sharded the same way as the ``[K, NP]`` cells.
_TB_SCALARS = ("base", "win_next", "max_seen", "n_late", "n_evicted",
               "n_win_dropped")


def make_sharded_ffat_tb_state(agg_spec, K: int, NP: int, mesh: Mesh):
    """Allocate the TB pane-ring state pre-sharded along ``key``: cells split
    by key rows, one scalar-clock lane per key shard."""
    kk = mesh.shape[KEY_AXIS]
    state = make_ffat_tb_state(agg_spec, K, NP)
    for name in _TB_SCALARS:
        state[name] = jnp.broadcast_to(state[name], (kk,))
    sh = state_sharding(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, sh), state)


def make_sharded_ffat_tb_step(mesh: Mesh, capacity: int, K: int, P_usec: int,
                              R: int, D: int, NP: int, lift: Callable,
                              comb: Callable, key_fn: Optional[Callable],
                              drop_tainted: bool = False,
                              grouping: str = "rank_scatter",
                              ingest: str = "data",
                              sum_like: bool = False,
                              monoid: Optional[str] = None,
                              op_name: str = "mesh.ffat_tb_step"):
    """Compile one time-based FFAT step sharded over the mesh.

    Same layout as the CB variant (:func:`make_sharded_ffat_step`): state
    split along ``key`` — chip *i* owns keys ``[i*K/kk, (i+1)*K/kk)`` and its
    own pane-ring clock — the data-sharded batch ``all_gather``-ed across
    ``data`` so every key shard sees every tuple once over ICI, and the
    watermark pane frontier passed replicated (it is host metadata, identical
    on every chip).  Reference: ``Ffat_Windows_GPU`` TB replicas each owning
    a key subset with quantum panes, ``ffat_replica_gpu.hpp:92-216,438-514``."""
    K_local, key_base_fn, gather, bspec, step_cap = _ffat_shard_layout(
        mesh, capacity, K, ingest)
    step_local = make_ffat_tb_step(step_cap, K_local, P_usec, R, D, NP,
                                   lift, comb, key_fn,
                                   key_base_fn=key_base_fn,
                                   drop_tainted=drop_tainted,
                                   grouping=grouping, sum_like=sum_like,
                                   monoid=monoid)

    def local(state, payload, ts, valid, wm_pane):
        payload, ts, valid = gather(payload, ts, valid)
        sstate = {k: (v[0] if k in _TB_SCALARS else v)
                  for k, v in state.items()}
        new_state, out, fired, out_ts, n_adv = step_local(
            sstate, payload, ts, valid, wm_pane)
        new_state = {k: (v[None] if k in _TB_SCALARS else v)
                     for k, v in new_state.items()}
        # Total window advance across key shards (drivers loop flushes on
        # it).  Along ``data`` the value is already replicated — every data
        # row of a key shard saw the same gathered batch — so summing over
        # KEY_AXIS alone keeps it both exact and mesh-replicated.
        n_adv = jax.lax.psum(n_adv, KEY_AXIS)
        return new_state, out, fired, out_ts, n_adv

    sspec = {k: P(KEY_AXIS) for k in
             ("cells", "cell_valid", "horizon") + _TB_SCALARS}
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(sspec, bspec, bspec, bspec, P()),
        out_specs=(sspec, P(KEY_AXIS), P(KEY_AXIS), P(KEY_AXIS), P()),
        check_vma=False)
    return wf_jit(fn, op_name=op_name, donate_argnums=(0,))
