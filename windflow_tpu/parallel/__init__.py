"""Routing + distribution plane: emitters, collectors, and multi-chip sharding.

This package is the TPU-native replacement for the reference's communication
backend (SURVEY.md §5.8): lock-free thread queues + pointer multicast become a
host driver moving batch handles between stages, and cross-chip distribution
rides XLA collectives over ICI (``windflow_tpu.parallel.mesh``).
"""

from windflow_tpu.parallel.emitters import (
    Emitter, ForwardEmitter, KeyByEmitter, BroadcastEmitter,
    DeviceStageEmitter, create_emitter,
)
from windflow_tpu.parallel.collectors import (
    Collector, WatermarkCollector, OrderingCollector, KSlackCollector,
    create_collector,
)
