"""Basic definitions: enums, defaults, and small shared helpers.

TPU-native re-design of the reference's basic definitions
(``/root/reference/wf/basic.hpp:78-87`` execution/time/window/routing enums,
``:189-206`` default knobs).  Where the reference configures everything through
compile-time macros, this framework uses a runtime :class:`Config` layer
(SURVEY.md §5.6 calls this out as a required replacement).
"""

from __future__ import annotations

import dataclasses
import enum
import os
import time
import zlib


class ExecutionMode(enum.Enum):
    """How replicas treat out-of-order inputs (reference ``basic.hpp:78``).

    * DEFAULT        – out-of-order processing gated by watermarks.
    * DETERMINISTIC  – inputs re-ordered by id/timestamp before processing, so
                       every run produces the same sequence of outputs.
    * PROBABILISTIC  – approximate ordering with an adaptive K-slack buffer;
                       tuples later than the slack are dropped (and counted).
    """

    DEFAULT = "default"
    DETERMINISTIC = "deterministic"
    PROBABILISTIC = "probabilistic"


class TimePolicy(enum.Enum):
    """Timestamping policy (reference ``basic.hpp:84``).

    * INGRESS – timestamps/watermarks assigned by the source shipper at entry.
    * EVENT   – timestamps supplied by the user (``push_with_timestamp``);
                watermarks are still monotonized by the shipper.
    """

    INGRESS = "ingress"
    EVENT = "event"


class WinType(enum.Enum):
    """Window domain (reference ``basic.hpp:80``): count-based or time-based."""

    CB = "count"
    TB = "time"


class RoutingMode(enum.Enum):
    """How an emitter distributes outputs (reference ``basic.hpp:87``)."""

    NONE = "none"
    FORWARD = "forward"
    KEYBY = "keyby"
    BROADCAST = "broadcast"
    REBALANCING = "rebalancing"


class WindowRole(enum.Enum):
    """Role of a window stage inside compound window operators
    (reference ``basic.hpp:219``): plain sequential, pane-level query,
    window-level query, map stage, reduce stage."""

    SEQ = "seq"
    PLQ = "plq"
    WLQ = "wlq"
    MAP = "map"
    REDUCE = "reduce"


class WindowEvent(enum.Enum):
    """Classification of a tuple w.r.t. one window
    (reference ``window_structure.hpp:49-115`` triggerer outcomes)."""

    OLD = "old"
    IN = "in"
    FIRED = "fired"


@dataclasses.dataclass
class Config:
    """Runtime configuration.  Replaces the reference's compile-time macro set
    (``WF_DEFAULT_VECTOR_CAPACITY``, ``WF_DEFAULT_WM_INTERVAL_USEC``,
    ``WF_DEFAULT_WM_AMOUNT``, ``WF_GPU_*`` — SURVEY.md §5.6) with values that
    can be set per-process or per-graph.
    """

    # Default device batch capacity (tuples per compiled step).  The TPU
    # analogue of the reference's GPU batch size: large enough to keep the
    # VPU/MXU busy, small enough to bound latency.
    default_batch_size: int = 4096
    # Punctuation (watermark flush) cadence for idle emitters, microseconds
    # (reference default 100 ms, basic.hpp:195).
    punctuation_interval_usec: int = 100_000
    # Punctuation cadence in number of inputs (reference default 1000,
    # basic.hpp:195).  0 disables the count trigger: a punctuation flushes
    # open/staged batches (the watermark must never overtake buffered data),
    # and unlike the reference — whose batches are at most a few hundred
    # tuples — TPU staging batches run to 10^5+ lanes, where a count cadence
    # below the batch capacity would chronically ship padded batches.  The
    # interval cadence above is what keeps idle streams firing.
    punctuation_amount: int = 0
    # Cap on outstanding device batches per operator before the host driver
    # throttles source ticks (reference: in-transit counter +
    # WF_GPU_FREE_MEMORY_LIMIT, recycling_gpu.hpp:88-126).  Each queued
    # DeviceBatch pins ~capacity x payload-width bytes of HBM, so this bounds
    # device memory the way the reference's FullGPUMemoryException retry does.
    max_inflight_batches: int = 8
    # Cap on total queued messages per replica inbox (host batches included)
    # before source throttling — the runtime analogue of the reference's
    # FF_BOUNDED_BUFFER bounded queues (README.md:36-39).
    max_inbox_messages: int = 8192
    # Tuples pulled from each live source per scheduler sweep; 0 means
    # "one staged batch worth" (the source's output_batch_size, or 256).
    source_tick_chunk: int = 0
    # Messages one replica may process per scheduler sweep; bounding this
    # interleaves sibling replicas fairly (the cooperative-loop analogue of
    # the reference's thread-parallel arrival order, which matters for the
    # KSlack collector's adaptive slack).
    sweep_drain_limit: int = 16
    # Directory where per-operator stats JSON logs are dumped at wait_end
    # (reference WF_LOG_DIR, basic_operator.hpp:297-303).
    log_dir: str = os.environ.get("WF_TPU_LOG_DIR", "log")
    # Dashboard endpoint (reference WF_DASHBOARD_MACHINE/PORT,
    # monitoring.hpp:184-196).
    dashboard_host: str = os.environ.get("WF_TPU_DASHBOARD_HOST", "localhost")
    dashboard_port: int = int(os.environ.get("WF_TPU_DASHBOARD_PORT", "20207"))
    # Enable runtime tracing (reference compile-time -DWF_TRACING_ENABLED).
    tracing_enabled: bool = bool(int(os.environ.get("WF_TPU_TRACING", "0")))
    # Flight recorder (monitoring/recorder.py): per-batch span tracing into
    # preallocated per-replica ring buffers + staged→sunk latency
    # histograms.  Default ON at 1-in-`trace_sample_every` batch sampling
    # with a documented <2% overhead budget (docs/OBSERVABILITY.md;
    # tests/test_observability.py asserts it); switching it off removes
    # every hook but a single `is not None` check per batch.
    flight_recorder: bool = bool(int(os.environ.get(
        "WF_TPU_FLIGHT_RECORDER", "1")))
    # 1-in-N batch sampling rate for span traces (N=1 traces everything —
    # tests/debugging only; the overhead budget assumes the default).
    trace_sample_every: int = int(os.environ.get("WF_TPU_TRACE_SAMPLE",
                                                 "64"))
    # Total span events retained across all replica rings (split evenly;
    # old events are overwritten when a ring wraps — no allocation).
    trace_ring_events: int = int(os.environ.get("WF_TPU_TRACE_RING",
                                                "65536"))
    # Every M-th TRACED batch additionally records `device_done` by calling
    # block_until_ready on the operator's output — a real device sync, so
    # it runs 1 in (trace_sample_every * M) batches.  0 disables the sync
    # (spans then end at `dispatched`/`collected`).
    trace_device_sync_every: int = int(os.environ.get(
        "WF_TPU_TRACE_DEVICE_SYNC", "8"))
    # Host-side worker threads draining host-operator replicas in parallel
    # (reference: one OS thread per replica via FastFlow,
    # basic_operator.hpp:54-235, so a CPU-operator pipeline scales across
    # cores).  0 = the single cooperative dispatch loop (device-heavy
    # pipelines need nothing more — XLA dispatch is already async).  N > 0
    # = an N-thread pool drains host replicas each sweep; TPU replicas and
    # sources stay on the driver thread (stateful device ops share operator
    # state serialized by construction).  Host operators whose hot work is
    # numpy/native (GIL-releasing) scale near-linearly; pure-Python
    # per-tuple functions are GIL-bound, as in any CPython thread pool.
    host_worker_threads: int = int(os.environ.get("WF_TPU_HOST_WORKERS",
                                                  "0"))
    # Staging-plane lookahead (windflow_tpu/staging): extra source-tick
    # passes per scheduler sweep AFTER the drain phase, so batch N+1 is
    # packed into a (pooled) host staging buffer while batch N's
    # asynchronously dispatched XLA step still runs — the driver-loop form
    # of the reference's 2-deep pinned double buffering
    # (forward_emitter_gpu.hpp:254-300).  Each pass re-checks backpressure
    # first, so the in-transit caps above still bound lookahead depth.
    # 0 disables (sources tick once per sweep, pre-r6 behavior).
    stage_prefetch_depth: int = int(os.environ.get("WF_TPU_STAGE_PREFETCH",
                                                   "1"))
    # FFAT batch-grouping algorithm: "rank_scatter" (default) groups each
    # batch by key with the O(n) dense-key counting permutation
    # (windows/grouping.py — no comparison sort; the reference pays
    # thrust::sort_by_key for the same grouping); "argsort" keeps the
    # stable-comparison-sort baseline (bit-identical results, both order
    # by (key, arrival)).  Time-based steps whose (key, pane) id space
    # exceeds int32 (max_keys * pane_capacity >= 2^31) fall back to
    # argsort regardless — the counting ids are int32.
    ffat_grouping: str = os.environ.get("WF_TPU_FFAT_GROUPING",
                                        "rank_scatter")
    # Profiler bridge (monitoring/device_metrics, docs/OBSERVABILITY.md):
    # directory PipeGraph.profile(duration_ms) writes its jax.profiler
    # capture into ("" = "{log_dir}/{name}_xprof").  The capture lines up
    # with dump_trace()'s Chrome trace through the per-batch
    # "op:<name> trace:<id>" TraceAnnotations the dispatch path puts on
    # sampled (trace-lane) batches.
    profiler_dir: str = os.environ.get("WF_TPU_PROFILER_DIR", "")
    # Pre-flight static analysis (windflow_tpu/analysis): PipeGraph.start()
    # runs PipeGraph.check() — abstract evaluation of the whole graph, zero
    # device work — and "error" fails fast with the FULL list of
    # error-severity diagnostics (warnings are warned), "warn" downgrades
    # everything to warnings, "off" skips the pass entirely.
    preflight: str = os.environ.get("WF_TPU_PREFLIGHT", "error")
    # Health plane (monitoring/health.py, docs/OBSERVABILITY.md): a
    # watchdog evaluated at monitor cadence (never per batch) derives a
    # per-operator OK/BACKPRESSURED/STALLED/FAILED state from the sampled
    # gauges, attributes stalls to a root-cause operator, and feeds the
    # postmortem bundle.  Off removes the plane entirely — every call
    # site keeps one `is not None` check.
    health_watchdog: bool = bool(int(os.environ.get("WF_TPU_HEALTH", "1")))
    # An operator with pending input whose progress counters (inputs
    # received, watermark frontier) have not moved for this long is
    # STALLED (microseconds).
    health_stall_grace_usec: int = int(os.environ.get(
        "WF_TPU_HEALTH_STALL_GRACE", "5000000"))
    # Summed replica inbox depth at/above which an operator that is still
    # making progress is BACKPRESSURED.  0 = derive from the in-transit
    # cap (max_inbox_messages // 2).
    health_backpressure_depth: int = int(os.environ.get(
        "WF_TPU_HEALTH_BP_DEPTH", "0"))
    # Compile-watcher recompiles per op name at/above which the operator
    # is flagged as in a recompilation storm (BACKPRESSURED verdict).
    health_recompile_storm: int = int(os.environ.get(
        "WF_TPU_HEALTH_RECOMPILE_STORM", "4"))
    # Health state-change timeline entries retained for the postmortem.
    health_history: int = int(os.environ.get("WF_TPU_HEALTH_HISTORY",
                                             "256"))
    # Black-box postmortem bundle directory written by
    # PipeGraph.dump_postmortem — best-effort on the wait_end crash path
    # and on watchdog-confirmed stalls ("" = "{log_dir}/{name}_postmortem";
    # tools/wf_doctor.py renders/validates a bundle offline).
    health_postmortem_dir: str = os.environ.get(
        "WF_TPU_HEALTH_POSTMORTEM_DIR", "")
    # Write the postmortem bundle automatically when wait_end crashes or
    # the watchdog confirms a stall (the bundle is exactly the telemetry
    # a crash used to discard).  dump_postmortem() stays callable either
    # way.
    health_postmortem_on_crash: bool = bool(int(os.environ.get(
        "WF_TPU_HEALTH_POSTMORTEM", "1")))
    # Latency ledger (monitoring/latency_ledger.py, docs/OBSERVABILITY.md
    # "Latency plane & SLO"): per-batch critical-path decomposition of the
    # flight recorder's span lane — each sampled batch's staged→emitted,
    # emitted→dispatched (the megastep K-wait), dispatched→device_done,
    # device_done→collected and collected→sunk segments land in
    # per-operator per-segment log2 histograms, plus window-freshness
    # gauges and the megastep freshness floor.  Harvested from the
    # existing rings only at monitor/stats cadence — zero new hot-path
    # work; off removes the plane entirely and every call site keeps one
    # `is not None` check (micro-asserted by tests/test_latency_plane.py).
    # Requires the flight recorder (off recorder -> no ledger).
    latency_ledger: bool = bool(int(os.environ.get("WF_TPU_LATENCY", "1")))
    # Declarative end-to-end latency target in milliseconds (0 = no SLO).
    # When set, the ledger evaluates the recent staged→sunk p99 against
    # the budget at watchdog cadence and the health plane raises an
    # SLO_VIOLATED verdict attributed to the dominant segment of the
    # dominant operator; analysis/latency.py + tools/wf_slo.py turn the
    # measured decomposition into the per-operator megastep/tick-chunk
    # plan the adaptive sizer consumes.
    latency_slo_ms: float = float(os.environ.get("WF_TPU_LATENCY_SLO_MS",
                                                 "0"))
    # Tenant plane (monitoring/tenant_ledger.py, docs/OBSERVABILITY.md
    # "Tenant plane"): the tenant label this graph's telemetry is
    # attributed under when N PipeGraphs share one process/mesh (ROADMAP
    # item 2 — the multi-tenant serving shape).  "" (the default)
    # resolves to the graph's own app name at build, so single-app
    # deployments need no configuration; several graphs sharing one
    # label pool their attribution under one tenant row.
    tenant: str = os.environ.get("WF_TPU_TENANT", "")
    # Kill switch for the tenant plane.  On, every graph registers into
    # the process-level tenant registry at build and the shared ledger
    # attributes HBM bytes, dispatches/compile wall-ms, H2D/D2H wire
    # bytes, modeled ICI bytes and latency budget share per tenant — all
    # read from telemetry the other planes already maintain, only at
    # monitor/stats cadence (zero per-batch hot-path work).  Off removes
    # the plane entirely and every call site keeps one `is not None`
    # check (micro-asserted by tests/test_tenant_plane.py).
    tenant_ledger: bool = bool(int(os.environ.get("WF_TPU_TENANT_LEDGER",
                                                  "1")))
    # Per-tenant HBM budget in bytes (0 = no budget declared).  When
    # set, the tenant ledger evaluates the tenant's attributed device
    # bytes against the budget at watchdog cadence; sustained overage
    # enters a latched OVER_BUDGET health verdict attributed to the
    # tenant's heaviest op (the SLO_VIOLATED contract applied to
    # memory), and analysis/tenancy.py + tools/wf_tenant.py turn the
    # measured pressure into the drain/rescale/throttle plan the PR-20
    # tenant scheduler consumes.
    hbm_budget_bytes: int = int(os.environ.get(
        "WF_TPU_HBM_BUDGET_BYTES", "0"))
    # Sweep ledger (monitoring/sweep_ledger.py, docs/OBSERVABILITY.md):
    # per-operator-hop attribution of jitted dispatches and XLA
    # cost-analysis HBM bytes per staged batch, donation-miss tripwires,
    # and hop-boundary residency (fusion fuel for tools/wf_advisor.py).
    # Evaluated only at stats/postmortem cadence from counters the
    # compile watcher already maintains — the per-batch cost is the
    # watcher's one integer add per dispatch, and switching the ledger
    # off leaves one `is not None` check at each read site.
    sweep_ledger: bool = bool(int(os.environ.get("WF_TPU_SWEEP_LEDGER",
                                                 "1")))
    # Shard plane (monitoring/shard_ledger.py, docs/OBSERVABILITY.md
    # "Shard plane"): per-shard/per-replica attribution of the gauges the
    # earlier planes only report per OPERATOR — queue depth, watermark
    # frontier/lag, service latency, HBM bytes — plus key-skew sketches
    # on the keyed edges (count-min + hot-key tables computed in-program
    # on the existing keys lane: folded into the keyby split / fused
    # chain programs, zero extra dispatches, merged to host only at
    # monitor cadence) and a reshard advisor
    # (analysis/resharding.py, tools/wf_shard.py).  Off removes the
    # plane entirely: no sketches attach and every call site keeps one
    # `is not None` check (micro-asserted by tests/test_shard_plane.py).
    shard_ledger: bool = bool(int(os.environ.get("WF_TPU_SHARD_LEDGER",
                                                 "1")))
    # Hot keys retained per keyed edge in the shard ledger's top-K table
    # (stats()["Shard"] hot_keys, the reshard advisor's move candidates).
    shard_topk: int = int(os.environ.get("WF_TPU_SHARD_TOPK", "8"))
    # Device-side key compaction (parallel/compaction.py, docs/PERF.md
    # round 12): keyed consumers over UNDECLARED int32 key spaces get a
    # device-resident key→dense-slot remap table — hot keys run the
    # dense scatter-combine / dense-slot stateful path, the cold tail
    # falls back to the sorted lane inside the SAME program (zero extra
    # dispatches), and the table is seeded from the shard plane's
    # count-min/hot-key sketches plus an in-program miss-candidate
    # ring.  Off removes the plane entirely: no compactor attaches and
    # every step keeps one `is not None` check (micro-asserted by
    # tests/test_key_compaction.py, same stance as the other planes).
    key_compaction: bool = bool(int(os.environ.get(
        "WF_TPU_KEY_COMPACTION", "1")))
    # Dense slots per compacted consumer (the remap table capacity):
    # hot keys get stable slots here; the cold tail overflows to the
    # sorted lane.  Stateful/FFAT consumers use their own slot bound
    # (num_key_slots / the compacted key space) instead.
    key_compaction_slots: int = int(os.environ.get(
        "WF_TPU_KEY_COMPACTION_SLOTS", "1024"))
    # Remap reseed cadence in consumer batches: every N-th batch the
    # compactor folds the sketch's hot candidates and the in-program
    # miss ring into the table (evicting the coldest slots on a full
    # table — the only churn source).  The only device sync the plane
    # pays, at this cadence.
    key_compaction_reseed: int = int(os.environ.get(
        "WF_TPU_KEY_COMPACTION_RESEED", "64"))
    # Wire compression (windflow_tpu/wire.py, docs/PERF.md round 13 /
    # docs/OBSERVABILITY.md "Wire plane"): staged batches' packed
    # buffers are re-encoded lane by lane (delta/delta-of-delta for
    # monotone ts/id lanes, dictionary for low-cardinality int lanes,
    # constant collapse, bit-packing; raw passthrough fallback) before
    # the ONE fused host→device transfer, and the inverse decode is
    # traced INTO the existing unpack program — zero extra dispatches.
    # Engages only on edges with a declared/inferred record spec
    # (Source_Builder.withRecordSpec / DeviceSource inference); a
    # spec-less source downgrades to raw passthrough with a WF606
    # preflight warning.  Per-lane codec choice re-evaluates on the
    # key_compaction_reseed cadence and surfaces in
    # stats()["Staging"]["Wire"].  Default "auto": ON whenever the
    # default backend is a real accelerator (the wire is a slow link
    # worth shrinking — the tentpole case) and OFF on the CPU fallback,
    # where host and "device" share memory and encode/decode would be
    # pure overhead on the staged path.  WF_TPU_WIRE=1 forces on
    # anywhere (the bench wire leg and the A/B tests do), =0 is the
    # kill switch: no encoder attaches and each staged batch keeps one
    # flag check.  Typed loosely: True/False/"auto"/"1"/"0" all work
    # (wire.wire_enabled resolves it).
    wire_compression: object = os.environ.get("WF_TPU_WIRE", "auto")
    # Pallas TPU kernels for the FFAT hot loop (windflow_tpu/kernels,
    # docs/PERF.md round 14): hand-written kernels for segmented
    # grouping, the pane-level sliding fold, and the dense segmented
    # reduce drop into the hottest regions of the SAME wf_jit programs
    # the lax compositions occupied — zero dispatch-count change,
    # record-for-record identical output.  Default "auto": compiled
    # Mosaic kernels on TPU backends, interpret=True on the CPU
    # fallback so tier-1 executes the real kernel bodies (the
    # interpreter emulation is a correctness vehicle, not a perf path —
    # bench's legacy sections pin =0 on CPU to keep their history
    # comparable).  =1 forces (downgrades get a WF607 preflight
    # warning: non-TPU/CPU backends have no lowering, and windows with
    # GENERIC traced combiners keep the lax fold — only declared
    # sum/max/min monoids ride the MXU pane combine); =0 is the kill
    # switch restoring the lax path verbatim (no kernel builds, one
    # resolve per program build).
    pallas_kernels: object = os.environ.get("WF_TPU_PALLAS", "auto")
    # Device-resident sweep megastep (windflow_tpu/megastep.py,
    # docs/PERF.md round 15): fold K consecutive batch sweeps of a
    # host→TPU staged edge into ONE wf_jit program — a lax.scan over a
    # super-batch of K packed wire buffers whose body is the existing
    # fused per-sweep program (unpack decode + prelude + tail step), so
    # the host pacer pays one dispatch, one H2D stack, and one D2H
    # drain per K batches instead of per batch.  The fusion executor's
    # move lifted one level: per-sweep → per-K-sweeps.  Only edges whose
    # staging emitter feeds a single megastep-capable tail qualify
    # (FFAT windows, keyed/dense reduce, dense-key stateful — all
    # non-mesh, non-compacted); everything else keeps the per-batch
    # cadence.  Default "auto": K=8 on real accelerator backends, K=1
    # on the CPU fallback (tier-1 cadence unchanged).  An explicit
    # integer forces that K anywhere (bench/tests set it directly);
    # graphs that cannot honor a forced K>1 downgrade to per-batch with
    # a WF608 preflight warning.  =1 is the kill switch: no plane
    # attaches and the per-batch path runs verbatim.  Durability epochs
    # round UP to a multiple of K (quiesce lands only on megastep
    # boundaries, keeping the chaos A/B diff meaningful).
    megastep_sweeps: object = os.environ.get("WF_TPU_MEGASTEP", "auto")
    # Key-aligned mesh ingest (parallel/emitters.AlignedMeshStageEmitter
    # + mesh.py ingest="aligned", docs/OBSERVABILITY.md "Wire plane"):
    # host-fed key-sharded FFAT consumers take their batches PRE-PLACED
    # on the owning key shard (the dense-range owner the sharded step
    # compiles; executor key moves deliberately do not apply — mesh
    # reshard routes through rescale-on-restore), killing the data-axis
    # all_gather the ICI model names dominant.  Off
    # (WF_TPU_KEY_ALIGNED=0) keeps the data-sharded ingest +
    # in-program gather everywhere.
    key_aligned_ingest: bool = bool(int(os.environ.get(
        "WF_TPU_KEY_ALIGNED", "1")))
    # IR-level program audit (analysis/ir_audit.py, tools/wf_ir.py,
    # docs/ANALYSIS.md "wfir"): parse the StableHLO text of every wf_jit
    # program off the compile watcher's EXISTING first-compile lowering
    # (the cost-table capture — zero extra compiles, cold path only) and
    # flag the WF9xx family: collectives on promised-collective-free
    # aligned-ingest edges (WF901), host callbacks/infeed (WF902),
    # f64/i64 on TPU (WF903), dynamic shapes (WF904), donation misses at
    # IR level (WF905), mid-program host transfers (WF906), and Pallas
    # programs that lost their Mosaic lowering (WF907).  Findings land in
    # stats()["IR_audit"], the postmortem's ir_audit.json, and the
    # preflight table; =0 is the kill switch — no capture, no parsing,
    # one flag check on the (already cold) first-compile path.  Capture
    # rides the cost-analysis lowering, so WF_TPU_COST_ANALYSIS=off also
    # disables it.
    ir_audit: bool = bool(int(os.environ.get("WF_TPU_IR_AUDIT", "1")))
    # Whole-chain fusion (windflow_tpu/fusion, docs/PERF.md round 10):
    # at graph build, maximal fusible runs of adjacent TPU operators
    # (the fusion advisor's plan — analysis/fusion.py) lower into ONE
    # wf_jit program per batch sweep: the stateless members' record
    # transforms are inlined ahead of the tail's program (map/filter
    # prelude before a window lift/combine, keyed reduce, or dense-key
    # stateful step), so the interior hop boundaries never materialize
    # in HBM and the chain pays one dispatch where it paid N.  Member
    # operators stay in the graph (stats/health/preflight contracts
    # unchanged; their numbers are attributed from the fused hop).
    # Fusion is skipped on a mesh (sharded program factories compose
    # differently) and for stateful tails that intern keys on the host.
    # Kill switch: WF_TPU_FUSE=0 restores one-dispatch-per-hop sweeps.
    whole_chain_fusion: bool = bool(int(os.environ.get("WF_TPU_FUSE",
                                                       "1")))
    # Durable state (windflow_tpu/durability, docs/DURABILITY.md): the
    # directory holding the graph's epoch-versioned checkpoint store.
    # Non-empty enables watermark-aligned checkpointing — at every
    # `durability_epoch_sweeps`-th scheduler sweep the driver quiesces the
    # graph (flush + drain to an aligned barrier), commits exactly-once
    # sink epochs (fenced Kafka commit / atomic file rename), snapshots
    # all operator state (FFAT rings, stateful tables, reduce states,
    # Kafka offsets, watermark frontiers) into the persistent LogKV, and
    # writes the epoch manifest as the commit point.  A stopped/crashed
    # graph rebuilds at the last complete epoch via PipeGraph.restore().
    # "" (the default) is the kill switch: the plane is never built and
    # the sweep loop keeps exactly one `is None` check (micro-asserted by
    # tests/test_durability.py, same stance as the health/ledger planes).
    durability: str = os.environ.get("WF_TPU_DURABILITY", "")
    # Checkpoint cadence in scheduler sweeps.  Sweep-counted (not
    # wall-clock) so two runs of the same graph over the same data place
    # their barriers at the same stream positions — what makes the chaos
    # harness's record-for-record A/B diff meaningful.
    durability_epoch_sweeps: int = int(os.environ.get(
        "WF_TPU_DURABILITY_EPOCH_SWEEPS", "64"))
    # Complete epochs retained in the checkpoint store; older epochs are
    # tombstoned (LogKV auto-compaction reclaims the log space).
    durability_keep: int = int(os.environ.get(
        "WF_TPU_DURABILITY_KEEP", "2"))
    # Reshard/failover executor (windflow_tpu/serving, docs/OBSERVABILITY.md
    # "Reshard executor"): closes the shard-plane loop — health-plane
    # BACKPRESSURED verdicts / sustained imbalance drive the reshard
    # advisor's move_keys plans live (quiesce → re-place the key→shard
    # override → resume, keyed state moved with the keys), split_hot_key
    # becomes a pre-aggregating partial combine at the keyed staging
    # boundary, and when no plan can help, admission control throttles the
    # sources instead of letting inboxes grow without bound.  Default OFF:
    # unlike the observe-only planes, the executor MUTATES routing —
    # opt in per deployment (WF_TPU_RESHARD=1).  Off leaves one
    # `is not None` check per sweep (micro-asserted).
    reshard_executor: bool = bool(int(os.environ.get(
        "WF_TPU_RESHARD", "0")))
    # Executor tick cadence in scheduler sweeps (each tick reads the
    # health verdicts + shard section — cadence-rate work, never per
    # batch) and the state-machine thresholds: consecutive bad ticks
    # before a plan applies, consecutive good ticks before an applied
    # plan counts as recovered (and admission control backs off).
    reshard_check_sweeps: int = int(os.environ.get(
        "WF_TPU_RESHARD_CHECK_SWEEPS", "32"))
    reshard_trigger_ticks: int = int(os.environ.get(
        "WF_TPU_RESHARD_TRIGGER_TICKS", "2"))
    reshard_ok_ticks: int = int(os.environ.get(
        "WF_TPU_RESHARD_OK_TICKS", "4"))
    # Imbalance ratio (max shard load / mean) above which the executor
    # treats an operator as degraded even without a health verdict —
    # the advisor's own actionability threshold.
    reshard_imbalance_threshold: float = float(os.environ.get(
        "WF_TPU_RESHARD_IMBALANCE", "1.25"))
    # Sustained-OK ticks before the executor consolidates keys off the
    # least-loaded shard (scale-down via the same quiesce→re-place
    # path).  0 (default) records scale-down candidates without acting.
    reshard_scale_down_ticks: int = int(os.environ.get(
        "WF_TPU_RESHARD_SCALE_DOWN_TICKS", "0"))
    # Calibration store (monitoring/calibration.py, tools/wf_calibrate.py,
    # docs/OBSERVABILITY.md "Calibration plane"): path of a versioned
    # calibration.json (probe-measured values for the modeled constants:
    # ICI B/s, H2D tunnel B/s, HBM B/s, dispatch overhead, sampled-sync
    # cost, kernel step time) keyed by device kind + jax version.  When
    # set, the shard ledger's ICI model, the tenant ledger, the live
    # roofline, and bench's gap_diagnosis compute from the calibrated
    # constants and their provenance tags flip `modeled` →
    # `calibrated(<age>)`; stale past WF_TPU_CALIBRATION_TTL_S (default
    # 7 days) or a device-kind mismatch degrades back to `modeled` with
    # a one-time warning.  "" (default) runs uncalibrated;
    # WF_TPU_CALIBRATION=0 is the kill switch — no store loads anywhere
    # and every read site keeps one `is not None` check (micro-asserted
    # by tests/test_calibration.py).
    calibration: str = os.environ.get("WF_TPU_CALIBRATION", "")
    # Live roofline plane (monitoring/calibration.RooflineLedger): the
    # bench-only roofline decomposition as a monitor-cadence gauge —
    # per-hop achieved tup/s (deltas over counters the replicas already
    # keep; zero per-batch work) joined with the sweep ledger's
    # bytes/tuple and the calibrated bandwidth into stats()["Roofline"]
    # + wf_roofline_* OpenMetrics families, plus a latched advisory
    # ROOFLINE_DEGRADED health verdict when the dominant hop's
    # throughput collapses vs its own trailing baseline (the SLO
    # plane's enter/latch/clear hysteresis).  Requires the sweep ledger
    # for the bytes join (rates-only without it).  WF_TPU_ROOFLINE=0
    # removes the plane: no ledger attaches and each call site keeps
    # one `is not None` check (micro-asserted).
    roofline_plane: bool = bool(int(os.environ.get("WF_TPU_ROOFLINE",
                                                   "1")))
    # Multi-chip execution: a jax.sharding.Mesh with ("data", "key") axes
    # (see windflow_tpu.parallel.mesh.make_mesh).  When set, staging emitters
    # lay batches out data-sharded across the mesh and mesh-aware TPU
    # operators (FfatWindowsTPU, ReduceTPU) compile their sharded variants —
    # the mesh takes the role the reference fills with operator replication
    # over threads (SURVEY.md §2.6 item 10).  Requires output_batch_size
    # divisible by the data-axis extent and max_keys divisible by the
    # key-axis extent.  Typed Any so importing this module never imports jax.
    mesh: object = None


#: Process-wide default configuration; graphs copy it at construction so later
#: mutation does not affect running graphs.
default_config = Config()


def stable_hash(key) -> int:
    """Deterministic key hash (reference uses ``std::hash`` —
    ``keyby_emitter.hpp:216``).  Python's ``hash`` is salted for str/bytes,
    so use crc32 there to keep keyby placement (and Kafka partition
    placement, ``kafka/client.py``) reproducible across processes."""
    if isinstance(key, int):
        return key
    if isinstance(key, str):
        return zlib.crc32(key.encode())
    if isinstance(key, bytes):
        return zlib.crc32(key)
    return hash(key)


def int32_key(k) -> int:
    """Wrap a numeric key to the int32 value the device state collapses
    to (keyed device extractors cast to int32 on chip).  THE canonical
    copy: keyed routing (parallel/emitters.py), compaction admission,
    the reshard executor's state moves, and rescale re-bucketing
    (durability/rebucket.py) must all collapse exactly the same keys,
    or one logical key would straddle shards."""
    i = int(k) & 0xFFFFFFFF
    return i - (1 << 32) if i >= (1 << 31) else i


def current_time_usecs() -> int:
    """Monotonic-ish wall clock in microseconds (reference
    ``basic.hpp`` ``current_time_usecs``)."""
    return time.time_ns() // 1_000


#: Sentinel key used by non-keyed stateful operators
#: (reference ``empty_key_t``, basic.hpp:306-318).
EMPTY_KEY = 0


class WindFlowError(RuntimeError):
    """Raised for user/API misuse.  The reference aborts the process with a
    colored message (``basic_operator.hpp:269-272``); a library should raise."""
