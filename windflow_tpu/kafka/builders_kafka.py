"""Fluent builders for the Kafka operators (reference
``/root/reference/wf/kafka/builders_kafka.hpp:128,293``): brokers, topics,
per-topic starting offsets, consumer group id and idleness for the source;
brokers for the sink."""

from __future__ import annotations

from typing import Callable, Optional, Sequence

from windflow_tpu.basic import WindFlowError
from windflow_tpu.graph.builders import _BuilderBase
from windflow_tpu.kafka.kafka_sink import KafkaSink
from windflow_tpu.kafka.kafka_source import KafkaSource


class KafkaSource_Builder(_BuilderBase):
    _default_name = "kafka_source"

    def __init__(self, deser_fn: Callable) -> None:
        super().__init__()
        self._deser_fn = deser_fn
        self._brokers = None
        self._topics: list = []
        self._group_id = "windflow"
        self._offsets: Optional[list] = None
        self._idle_usec = 100_000
        self._assignment_policy = "cooperative-sticky"

    def withBrokers(self, brokers):
        """A broker address string ('host:port') or an InMemoryBroker."""
        self._brokers = brokers
        return self

    def withTopics(self, *topics: str):
        self._topics = list(topics)
        return self

    def withGroupID(self, group_id: str):
        self._group_id = group_id
        return self

    def withOffsets(self, offsets: Sequence[int]):
        """Starting offset per topic; -1 keeps the group's current position
        (reference rebalance-callback offset override)."""
        self._offsets = list(offsets)
        return self

    def withIdleness(self, idle_usec: int):
        self._idle_usec = int(idle_usec)
        return self

    def withAssignmentPolicy(self, policy: str):
        """Partition assignment strategy (reference withAssignmentPolicy,
        ``builders_kafka.hpp``): one of "cooperative-sticky" (default),
        "roundrobin", "range" — passed to librdkafka by the real-client
        adapter; the in-memory broker's single cooperative round-robin
        assignment serves all three."""
        self._assignment_policy = policy
        return self

    def withKeyBy(self, *_):
        raise WindFlowError("a Kafka_Source has no input to key by")

    def withKafkaClosingFunction(self, fn: Callable):
        """Reference-named alias of withClosingFunction
        (``builders_kafka.hpp`` withKafkaClosingFunction): Kafka replicas
        own a KafkaRuntimeContext, so ``fn(ctx)`` receives it directly."""
        return self.withClosingFunction(fn)

    def build(self) -> KafkaSource:
        if self._brokers is None:
            raise WindFlowError("Kafka_Source needs withBrokers(...)")
        return KafkaSource(self._deser_fn, self._brokers, self._topics,
                           group_id=self._group_id, offsets=self._offsets,
                           idle_time_usec=self._idle_usec,
                           assignment_policy=self._assignment_policy,
                           name=self._name,
                           parallelism=self._parallelism,
                           output_batch_size=self._output_batch_size)


class KafkaSink_Builder(_BuilderBase):
    _default_name = "kafka_sink"

    def __init__(self, ser_fn: Callable) -> None:
        super().__init__()
        self._ser_fn = ser_fn
        self._brokers = None

    def withBrokers(self, brokers):
        self._brokers = brokers
        return self

    def withOutputBatchSize(self, *_):
        raise WindFlowError("a Kafka_Sink has no output to batch")

    def withKafkaClosingFunction(self, fn: Callable):
        """Reference-named alias of withClosingFunction (see
        KafkaSource_Builder.withKafkaClosingFunction)."""
        return self.withClosingFunction(fn)

    def build(self) -> KafkaSink:
        if self._brokers is None:
            raise WindFlowError("Kafka_Sink needs withBrokers(...)")
        return KafkaSink(self._ser_fn, self._brokers, name=self._name,
                         parallelism=self._parallelism,
                         key_extractor=self._key_extractor)
