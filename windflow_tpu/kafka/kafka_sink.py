"""Kafka_Sink operator (reference ``/root/reference/wf/kafka/
kafka_sink.hpp:71,229``): terminal operator producing each tuple to Kafka
through a per-replica producer (``kafka_sink.hpp:86,123-132``).

The user serializer runs per tuple:
``fn(item[, kafka_ctx]) -> KafkaSinkMessage | None`` — ``None`` drops the
tuple (produces nothing); otherwise the returned message names the topic,
payload and optional partition/key (reference serializer returns
topic+payload, ``kafka_sink.hpp:179-182``).  The producer is flushed at EOS.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

from windflow_tpu.basic import RoutingMode, WindFlowError
from windflow_tpu.kafka.client import make_producer
from windflow_tpu.kafka.kafka_context import KafkaRuntimeContext
from windflow_tpu.meta import adapt
from windflow_tpu.ops.base import Operator, Replica


@dataclasses.dataclass
class KafkaSinkMessage:
    """What the serializer returns (reference ``wf_kafka_sink_msg``)."""
    topic: str
    payload: Any
    partition: Optional[int] = None
    key: Optional[bytes] = None


class KafkaSinkReplica(Replica):
    def __init__(self, op: "KafkaSink", index: int) -> None:
        super().__init__(op, index)
        self._fn = adapt(op.ser_fn, 1)
        self._producer = make_producer(op.brokers)
        self.context = KafkaRuntimeContext(
            op.parallelism, index, op.name, producer=self._producer)
        # exactly-once plumbing (windflow_tpu/durability): with the
        # durability plane active, serialized messages BUFFER per epoch
        # and publish atomically at the checkpoint barrier through the
        # broker fence, deduped on the replica-lifetime sequence number
        # (the checkpoint restores `_seq`, so replay regenerates the
        # same seqs and already-committed messages skip).  Without the
        # plane every produce ships immediately, as before.
        self._durable = False       # set by the plane at graph build
        self._fence_id = None
        self._pending = []          # [(seq, topic, value, key, part, ts)]
        self._seq = 0               # lifetime serialized-message count
        self._epoch = 0             # epoch currently buffering
        self._dedupe_hits = 0
        # EOS fence: once on_eos flushed, the producer's output is final
        # — a straggler produce would either silently vanish into the
        # closed producer (the pre-fence latent drop) or duplicate after
        # a restore that replays past EOS; fail loudly instead
        self._fenced = False

    def process_single(self, item, ts, wm):
        msg = self._fn(item, self.context)
        if msg is None:
            return
        if self._fenced:
            raise WindFlowError(
                f"Kafka sink '{self.op.name}' received a tuple after its "
                "EOS flush-and-fence — the produce would race the "
                "producer teardown and be silently dropped")
        self.stats.outputs_sent += 1
        if self._durable:
            self._seq += 1
            self._pending.append((self._seq, msg.topic, msg.payload,
                                  msg.key, msg.partition, ts))
            return
        self._producer.produce(msg.topic, msg.payload, key=msg.key,
                               partition=msg.partition,
                               timestamp_usec=ts)

    # -- durability-plane hooks ----------------------------------------------
    def commit_epoch(self, epoch: int) -> None:
        """Publish the epoch's buffered messages atomically.  Brokers
        with a fence (InMemoryBroker) dedupe on the lifetime seq —
        exactly-once across restore even when the kill lands between the
        sink commit and the checkpoint manifest; fence-less producers
        (real librdkafka) degrade to produce+flush per epoch
        (at-least-once, docs/DURABILITY.md limits)."""
        msgs, self._pending = self._pending, []
        fc = getattr(self._producer, "fenced_commit", None)
        if fc is not None:
            _, deduped = fc(self._fence_id, epoch, msgs)
            self._dedupe_hits += deduped
        else:
            for _, topic, value, key, partition, ts in msgs:
                self._producer.produce(topic, value, key=key,
                                       partition=partition,
                                       timestamp_usec=ts)
            self._producer.flush()
        self._epoch = epoch + 1

    def on_eos(self):
        # flush-AND-fence: the final epoch's buffered messages commit
        # through the same fence as barrier commits (restore after a
        # clean EOS replays nothing), the producer drains its in-flight
        # queue, and the fence flag turns any straggler produce into a
        # loud error instead of a silent drop.  The closing function
        # (reference kafka_closing_func) still runs after on_eos with
        # the producer usable for final side-channel messages;
        # _terminate below closes it afterwards.
        if self._durable:
            self.commit_epoch(self._epoch)
        self._producer.flush()
        self._fenced = True

    def _terminate(self):
        was_done = self.done
        super()._terminate()   # on_eos flush-and-fence → closing_func
        if not was_done:
            self._producer.flush()
            self._producer.close()


class KafkaSink(Operator):
    replica_class = KafkaSinkReplica
    is_terminal = True

    def __init__(self, ser_fn: Callable, brokers,
                 name: str = "kafka_sink", parallelism: int = 1,
                 routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor=None) -> None:
        super().__init__(name, parallelism, routing=routing,
                         key_extractor=key_extractor)
        self.ser_fn = ser_fn
        self.brokers = brokers
