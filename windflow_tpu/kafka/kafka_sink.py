"""Kafka_Sink operator (reference ``/root/reference/wf/kafka/
kafka_sink.hpp:71,229``): terminal operator producing each tuple to Kafka
through a per-replica producer (``kafka_sink.hpp:86,123-132``).

The user serializer runs per tuple:
``fn(item[, kafka_ctx]) -> KafkaSinkMessage | None`` — ``None`` drops the
tuple (produces nothing); otherwise the returned message names the topic,
payload and optional partition/key (reference serializer returns
topic+payload, ``kafka_sink.hpp:179-182``).  The producer is flushed at EOS.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

from windflow_tpu.basic import RoutingMode
from windflow_tpu.kafka.client import make_producer
from windflow_tpu.kafka.kafka_context import KafkaRuntimeContext
from windflow_tpu.meta import adapt
from windflow_tpu.ops.base import Operator, Replica


@dataclasses.dataclass
class KafkaSinkMessage:
    """What the serializer returns (reference ``wf_kafka_sink_msg``)."""
    topic: str
    payload: Any
    partition: Optional[int] = None
    key: Optional[bytes] = None


class KafkaSinkReplica(Replica):
    def __init__(self, op: "KafkaSink", index: int) -> None:
        super().__init__(op, index)
        self._fn = adapt(op.ser_fn, 1)
        self._producer = make_producer(op.brokers)
        self.context = KafkaRuntimeContext(
            op.parallelism, index, op.name, producer=self._producer)

    def process_single(self, item, ts, wm):
        msg = self._fn(item, self.context)
        if msg is None:
            return
        self.stats.outputs_sent += 1
        self._producer.produce(msg.topic, msg.payload, key=msg.key,
                               partition=msg.partition,
                               timestamp_usec=ts)

    def on_eos(self):
        # flush only: the closing function (reference kafka_closing_func)
        # runs after on_eos with the producer still usable for final
        # side-channel messages (kafka_sink.hpp runs it before teardown);
        # _terminate below closes the producer afterwards
        self._producer.flush()

    def _terminate(self):
        was_done = self.done
        super()._terminate()   # on_eos flush → emitter → closing_func
        if not was_done:
            self._producer.flush()
            self._producer.close()


class KafkaSink(Operator):
    replica_class = KafkaSinkReplica
    is_terminal = True

    def __init__(self, ser_fn: Callable, brokers,
                 name: str = "kafka_sink", parallelism: int = 1,
                 routing: RoutingMode = RoutingMode.FORWARD,
                 key_extractor=None) -> None:
        super().__init__(name, parallelism, routing=routing,
                         key_extractor=key_extractor)
        self.ser_fn = ser_fn
        self.brokers = brokers
