"""Kafka client layer: message type, abstract consumer/producer, an
in-process broker for tests/local runs, and a gated adapter for a real
client library.

The reference binds directly to librdkafka (``/root/reference/wf/kafka/
kafka_source.hpp:57-123`` consumer + rebalance callback, ``kafka_sink.hpp:86``
per-replica producer).  Here the operators talk to a small client interface
so the same operator code runs against:

* :class:`InMemoryBroker` — an in-process broker with topics, partitions and
  consumer groups (partition assignment + cooperative rebalance), used by
  the test suite exactly as the reference's Kafka tests use a live local
  broker;
* ``confluent_kafka`` — when the library is installed (it is not baked into
  this image, so the adapter import-gates; see ``make_consumer``).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from windflow_tpu.basic import (WindFlowError, current_time_usecs,
                                stable_hash)


@dataclasses.dataclass
class KafkaMessage:
    """One consumed record (reference ``RdKafka::Message`` surface the user
    deserializer touches: topic/partition/offset/key/payload/timestamp)."""
    topic: str
    partition: int
    offset: int
    key: Optional[bytes]
    value: Any
    timestamp_usec: int


#: partition assignment strategies the client layer understands; the
#: in-memory broker implements one cooperative round-robin assignment (the
#: names map onto it), the confluent adapter passes the choice to librdkafka
ASSIGNMENT_POLICIES = ("cooperative-sticky", "roundrobin", "range")


class ConsumerClient:
    #: selected partition assignment strategy (withAssignmentPolicy)
    assignment_policy = "cooperative-sticky"

    def idle_partitions(self):
        """Partitions confirmed drained/idle, or None when the client
        cannot know (the source then uses wall-clock idleness)."""
        return None

    def positions(self):
        """Next-poll offset per assigned (topic, partition) — what a
        durability checkpoint records so restore resumes exactly where
        the barrier drained to — or None when the client cannot tell."""
        return None

    def seek_positions(self, positions) -> None:
        """Rewind/advance the consumer to explicit per-partition
        offsets (restore path).  Default: unsupported, ignored — the
        source then falls back to the coarser per-topic start offsets."""

    def subscribe(self, topics: Sequence[str], group_id: str,
                  offsets: Optional[Sequence[int]] = None) -> None:
        raise NotImplementedError

    def poll(self, max_msgs: int) -> List[KafkaMessage]:
        raise NotImplementedError

    def assignment(self) -> List[Tuple[str, int]]:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class ProducerClient:
    def produce(self, topic: str, value: Any, key: Optional[bytes] = None,
                partition: Optional[int] = None,
                timestamp_usec: Optional[int] = None) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# In-process broker
# ---------------------------------------------------------------------------

class _Partition:
    __slots__ = ("log",)

    def __init__(self) -> None:
        self.log: List[KafkaMessage] = []


class InMemoryBroker:
    """Topics × partitions with consumer-group assignment.

    Rebalance model: joining or leaving a group recomputes the round-robin
    assignment of every subscribed (topic, partition) over the group's
    members in join order; read positions live with the *group* (per
    topic-partition), so a partition handed to another member resumes where
    the previous owner stopped — the in-process analogue of the reference's
    cooperative incremental rebalance (``kafka_source.hpp:77-123``)."""

    def __init__(self) -> None:
        self._topics: Dict[str, List[_Partition]] = {}
        self._groups: Dict[str, "_Group"] = {}
        self._lock = threading.Lock()
        self._rr = itertools.count()
        # exactly-once sink fences (windflow_tpu/durability): fence_id ->
        # (epoch, seq) of the LAST message committed through
        # fenced_commit.  The in-process stand-in for Kafka transactions:
        # commit + fence advance are atomic under the broker lock, so a
        # kill can never half-publish an epoch, and a replayed commit
        # dedupes on the producer-lifetime sequence number.
        self._fences: Dict[str, Tuple[int, int]] = {}

    # -- admin ---------------------------------------------------------------
    def create_topic(self, name: str, num_partitions: int = 1) -> None:
        with self._lock:
            if name in self._topics:
                if len(self._topics[name]) != num_partitions:
                    raise WindFlowError(
                        f"topic '{name}' already exists with "
                        f"{len(self._topics[name])} partitions")
                return
            self._topics[name] = [_Partition()
                                  for _ in range(num_partitions)]
            self._rebalance_subscribers(name)

    def _rebalance_subscribers(self, topic: str) -> None:
        """New topic (explicit or auto-created by produce): groups already
        subscribed to it must pick up its partitions, like a metadata
        refresh on a real broker.  Caller holds the lock."""
        for g in self._groups.values():
            if any(topic in m._topics for m in g.members):
                g.rebalance(self)

    def partitions(self, topic: str) -> int:
        with self._lock:
            if topic not in self._topics:
                raise WindFlowError(f"unknown topic '{topic}'")
            return len(self._topics[topic])

    def topic_size(self, topic: str) -> int:
        with self._lock:
            return sum(len(p.log) for p in self._topics.get(topic, ()))

    # -- produce -------------------------------------------------------------
    def _append(self, topic: str, value: Any, key: Optional[bytes],
                partition: Optional[int], ts: Optional[int]) -> None:
        with self._lock:
            self._append_locked(topic, value, key, partition, ts)

    def _append_locked(self, topic: str, value: Any, key: Optional[bytes],
                       partition: Optional[int], ts: Optional[int]) -> None:
        parts = self._topics.get(topic)
        if parts is None:
            parts = self._topics[topic] = [_Partition()]
            self._rebalance_subscribers(topic)
        if partition is None:
            if key is not None:
                # deterministic placement: Python's hash() is salted
                # per process, which would scatter one key across
                # partitions between producer processes (Kafka uses
                # murmur2 for the same reason); stable_hash is crc32
                # for bytes
                partition = stable_hash(key) % len(parts)
            else:
                partition = next(self._rr) % len(parts)
        if not 0 <= partition < len(parts):
            raise WindFlowError(
                f"partition {partition} out of range for '{topic}'")
        p = parts[partition]
        p.log.append(KafkaMessage(
            topic=topic, partition=partition, offset=len(p.log), key=key,
            value=value,
            timestamp_usec=ts if ts is not None else current_time_usecs()))

    # -- exactly-once sink fence (windflow_tpu/durability) -------------------
    def fenced_commit(self, fence_id: str, epoch: int, msgs) -> Tuple[int,
                                                                      int]:
        """Atomically publish an epoch's buffered sink messages, deduping
        on the producer-lifetime sequence number: ``msgs`` is a list of
        ``(seq, topic, value, key, partition, ts)`` with ``seq`` strictly
        increasing across the replica's whole lifetime (checkpoint state
        restores it, so a replayed epoch regenerates the SAME seqs).
        Messages at/below the fence were already committed by the run
        that crashed after its commit — they are skipped, which is the
        whole exactly-once story for the mid-sink-flush kill window.
        Returns ``(appended, deduped)``."""
        with self._lock:
            _, fseq = self._fences.get(fence_id, (-1, -1))
            appended = deduped = 0
            for seq, topic, value, key, partition, ts in msgs:
                if seq <= fseq:
                    deduped += 1
                    continue
                self._append_locked(topic, value, key, partition, ts)
                self._fences[fence_id] = (epoch, seq)
                fseq = seq
                appended += 1
            return appended, deduped

    def fence(self, fence_id: str):
        """Last committed (epoch, seq) for a sink fence, or None."""
        with self._lock:
            return self._fences.get(fence_id)

    # -- clients -------------------------------------------------------------
    def producer(self) -> "InMemoryProducer":
        return InMemoryProducer(self)

    def consumer(self) -> "InMemoryConsumer":
        return InMemoryConsumer(self)


class _Group:
    def __init__(self) -> None:
        self.members: List["InMemoryConsumer"] = []
        # group-held read positions: (topic, partition) -> next offset
        self.positions: Dict[Tuple[str, int], int] = {}

    def rebalance(self, broker: InMemoryBroker) -> None:
        tps: List[Tuple[str, int]] = []
        topics = sorted({t for m in self.members for t in m._topics})
        for t in topics:
            for p in range(len(broker._topics.get(t, ()))):
                tps.append((t, p))
        for m in self.members:
            m._assignment = []
        for i, tp in enumerate(tps):
            owners = [m for m in self.members if tp[0] in m._topics]
            if owners:
                owners[i % len(owners)]._assignment.append(tp)


class InMemoryProducer(ProducerClient):
    def __init__(self, broker: InMemoryBroker) -> None:
        self._broker = broker
        self.produced = 0

    def produce(self, topic, value, key=None, partition=None,
                timestamp_usec=None):
        self._broker._append(topic, value, key, partition, timestamp_usec)
        self.produced += 1

    def fenced_commit(self, fence_id: str, epoch: int, msgs):
        """Exactly-once epoch commit (windflow_tpu/durability): the
        broker appends + fence-advances atomically.  Real-client
        producers have no fence — the sink detects the missing attribute
        and degrades to flush-per-epoch (at-least-once, documented in
        docs/DURABILITY.md limits)."""
        appended, deduped = self._broker.fenced_commit(fence_id, epoch,
                                                       msgs)
        self.produced += appended
        return appended, deduped

    def flush(self) -> None:
        pass  # appends are synchronous

    def close(self) -> None:
        pass


class InMemoryConsumer(ConsumerClient):
    def __init__(self, broker: InMemoryBroker) -> None:
        self._broker = broker
        self._group: Optional[_Group] = None
        self._group_id: Optional[str] = None
        self._topics: List[str] = []
        self._assignment: List[Tuple[str, int]] = []
        self._next_part = 0
        self._closed = False

    def subscribe(self, topics, group_id, offsets=None):
        with self._broker._lock:
            self._topics = list(topics)
            self._group_id = group_id
            g = self._broker._groups.setdefault(group_id, _Group())
            self._group = g
            if self not in g.members:
                g.members.append(self)
            # explicit starting offsets: one per topic, -1 = keep current
            # (reference rebalance-callback offset override,
            # kafka_source.hpp:81-91)
            if offsets:
                for t, off in zip(topics, offsets):
                    if off is not None and off > -1:
                        for p in range(len(self._broker._topics.get(t, ()))):
                            g.positions[(t, p)] = off
            g.rebalance(self._broker)

    def poll(self, max_msgs: int) -> List[KafkaMessage]:
        if self._group is None:
            raise WindFlowError("poll before subscribe")
        out: List[KafkaMessage] = []
        with self._broker._lock:
            n_parts = len(self._assignment)
            for _ in range(n_parts):
                if len(out) >= max_msgs:
                    break
                tp = self._assignment[self._next_part % n_parts]
                self._next_part += 1
                t, p = tp
                log = self._broker._topics[t][p].log
                pos = self._group.positions.get(tp, 0)
                take = min(max_msgs - len(out), len(log) - pos)
                if take > 0:
                    out.extend(log[pos:pos + take])
                    self._group.positions[tp] = pos + take
        return out

    def positions(self):
        """Next-poll offset per assigned partition (group-held read
        positions) — the durability checkpoint's replay cursor."""
        with self._broker._lock:
            return {tp: self._group.positions.get(tp, 0)
                    for tp in self._assignment}

    def seek_positions(self, positions) -> None:
        """Restore path: rewind the GROUP's read positions to the
        checkpointed offsets.  Group-level on purpose — whichever
        replica a partition lands on after the restart resumes at the
        barrier's cursor, exactly as committed offsets behave on a real
        broker."""
        with self._broker._lock:
            self._group.positions.update(dict(positions))

    def idle_partitions(self):
        """Assigned partitions with nothing pending RIGHT NOW (consumer
        position at the log end) — the exact form of 'idle' the source's
        per-partition watermark fold wants (such a partition must not gate
        or pin event time).  Computed live under the broker lock, so a
        partition refilled since its last visit immediately resumes
        gating.  Real-client adapters return None (unknown) and the source
        falls back to wall-clock idleness."""
        out = set()
        with self._broker._lock:
            for tp in self._assignment:
                t, p = tp
                log = self._broker._topics[t][p].log
                if self._group.positions.get(tp, 0) >= len(log):
                    out.add(tp)
        return out

    def assignment(self) -> List[Tuple[str, int]]:
        return list(self._assignment)

    def close(self) -> None:
        if self._closed or self._group is None:
            return
        self._closed = True
        with self._broker._lock:
            self._group.members.remove(self)
            self._group.rebalance(self._broker)


# ---------------------------------------------------------------------------
# Real-client adapters (gated: confluent_kafka is not in this image)
#
# VALIDATION STATUS (precise, per VERDICT r3 item 8): these adapters have
# been exercised against a *faked* confluent_kafka module
# (tests/test_kafka.py) and the operator surface against the in-process
# broker — never against a live broker (neither confluent_kafka nor any
# broker binary exists in the build environment; zero egress).  What the
# fake CANNOT prove, and therefore remains UNVERIFIED against real Kafka:
#
# 1. Rebalance callback ordering under the COOPERATIVE protocol: librdkafka
#    invokes on_assign with only the *incremental* partitions; the fake
#    replays full assignments.  The `_consumed_tps` guard in subscribe()
#    assumes EAGER re-delivery semantics; under cooperative-sticky the
#    guard is redundant but harmless — untested against a real group.
# 2. Offset commit on revoke: the reference commits synchronously in its
#    revoke callback (kafka_source.hpp:96-112); this adapter relies on
#    librdkafka auto-commit — whether a revoked partition's last offsets
#    land before reassignment on a real broker is unverified.
# 3. idle_partitions() returns None here (real consumers cannot cheaply
#    confirm a drained partition), so KafkaSourceReplica's per-partition
#    watermark fold uses the wall-clock grace path — exercised in tests
#    only through the fake's timing, not real consumer-lag timing.
# 4. Broker-side errors (session timeouts, coordinator migration,
#    msg.error() codes other than _PARTITION_EOF) pass through the
#    poll loop untested.
#
# tests/test_kafka_live.py now exercises items 1, 2 and 4 against a REAL
# broker (roundtrip across partitions, two-replica group assignment,
# committed-offset resume); it skips unless confluent_kafka + a broker at
# KAFKA_BOOTSTRAP are available — dockerimages/Dockerfile_cpu provides
# both (single-node KRaft via ci/run_tests_with_kafka.sh).  Item 3
# (real consumer-lag timing of the watermark grace path) remains
# environment-untested.  In THIS build environment (zero egress, no
# broker) the adapters stay validated only against the fake.
# ---------------------------------------------------------------------------

def _require_confluent():
    try:
        import confluent_kafka  # noqa: F401
        return confluent_kafka
    except ImportError as e:
        raise WindFlowError(
            "connecting to a real Kafka broker requires the "
            "'confluent_kafka' package, which is not installed; pass an "
            "InMemoryBroker for in-process streaming") from e


class ConfluentConsumer(ConsumerClient):
    """Thin adapter over confluent_kafka.Consumer (librdkafka underneath —
    the same library the reference binds)."""

    def __init__(self, brokers: str,
                 assignment_policy: str = "cooperative-sticky") -> None:
        self._ck = _require_confluent()
        self._brokers = brokers
        self.assignment_policy = assignment_policy
        self._consumer = None
        self._consumed_tps = set()   # partitions that delivered data
        #: restore cursors awaiting assignment (seek_positions):
        #: librdkafka assignment materializes asynchronously through
        #: on_assign during later poll()s, so an immediate seek() right
        #: after subscribe() would hit unassigned partitions and raise —
        #: the cursors are applied in on_assign instead, exactly like
        #: the user start-offset path below
        self._pending_seek = {}

    def subscribe(self, topics, group_id, offsets=None):
        self._consumed_tps = set()   # scoped to this consumer session
        cooperative = self.assignment_policy == "cooperative-sticky"
        conf = {"bootstrap.servers": self._brokers,
                "group.id": group_id,
                "auto.offset.reset": "earliest",
                "partition.assignment.strategy": self.assignment_policy}
        self._consumer = self._ck.Consumer(conf)

        def on_assign(consumer, partitions):
            for part in partitions:
                tp = (part.topic, part.partition)
                # apply a start cursor only until the partition has
                # actually DELIVERED data (tracked in poll): an EAGER
                # rebalance re-delivers the full assignment, and
                # re-seeking a mid-stream partition would rewind it
                # into duplicates — but a partition revoked before
                # consuming anything must still get its cursor, not
                # auto.offset.reset.  Durability restore cursors
                # (seek_positions — exact per-partition offsets) take
                # precedence over the user's per-topic start offsets.
                if tp in self._consumed_tps:
                    continue
                seek = self._pending_seek.get(tp)
                if seek is not None:
                    part.offset = seek
                    continue
                if not offsets:
                    continue
                try:
                    off = offsets[topics.index(part.topic)]
                except (ValueError, IndexError):
                    continue
                if off is not None and off > -1:
                    part.offset = off
            # librdkafka requires incremental_assign under the
            # COOPERATIVE protocol and plain assign under EAGER
            # strategies (roundrobin/range)
            if cooperative:
                consumer.incremental_assign(partitions)
            else:
                consumer.assign(partitions)

        # the callback is always installed: restore cursors arrive via
        # seek_positions AFTER subscribe() but BEFORE the first poll —
        # the only point librdkafka lets them apply is on_assign
        self._consumer.subscribe(list(topics), on_assign=on_assign)

    def poll(self, max_msgs: int) -> List[KafkaMessage]:
        out = []
        for _ in range(max_msgs):
            msg = self._consumer.poll(0)
            if msg is None:
                break
            if msg.error():
                continue
            ts_type, ts_ms = msg.timestamp()
            self._consumed_tps.add((msg.topic(), msg.partition()))
            out.append(KafkaMessage(
                topic=msg.topic(), partition=msg.partition(),
                offset=msg.offset(), key=msg.key(), value=msg.value(),
                timestamp_usec=ts_ms * 1000 if ts_type else
                current_time_usecs()))
        return out

    def assignment(self):
        return [(p.topic, p.partition)
                for p in self._consumer.assignment()]

    def positions(self):
        """Durability checkpoint cursor via librdkafka position() — the
        next offset to be fetched per assigned partition.  Every
        assigned partition gets a cursor: a never-fetched partition
        reports OFFSET_INVALID and falls back to the group's committed
        offset (then to 0 = earliest, matching auto.offset.reset) —
        omitting it would let the group's auto-commit advance it past
        the barrier and the restore skip unreplayed records.
        UNVERIFIED against a live broker in this build environment
        (zero egress — same validation status as the adapter notes
        below)."""
        try:
            parts = self._consumer.assignment()
            out = {}
            missing = []
            for p in self._consumer.position(parts):
                if p.offset is not None and p.offset >= 0:
                    out[(p.topic, p.partition)] = p.offset
                else:
                    missing.append(p)
            if missing:
                for p in self._consumer.committed(missing, timeout=5):
                    off = p.offset if p.offset is not None \
                        and p.offset >= 0 else 0
                    out[(p.topic, p.partition)] = off
            return out
        except Exception:  # lint: broad-except-ok (a position probe must
            # degrade to "unknown" — the checkpoint then records no
            # cursor and restore falls back to the per-topic offsets)
            return None

    def seek_positions(self, positions) -> None:
        """Restore path: stage the checkpointed per-partition cursors
        for ``subscribe``'s on_assign callback — assignment does not
        exist yet when the source calls this (right after subscribe),
        so an immediate ``seek()`` would raise on every partition;
        partitions already assigned (a later re-seek) ARE sought
        directly.  UNVERIFIED against a live broker (see the adapter
        validation notes below)."""
        self._pending_seek.update(dict(positions))
        TopicPartition = self._ck.TopicPartition
        try:
            assigned = {(p.topic, p.partition)
                        for p in self._consumer.assignment()}
        except Exception:  # lint: broad-except-ok (no assignment yet —
            # the normal restore case; on_assign applies the cursors)
            return
        for (topic, part), off in dict(positions).items():
            if (topic, part) in assigned:
                self._consumer.seek(TopicPartition(topic, part, off))

    def close(self):
        if self._consumer is not None:
            self._consumer.close()


class ConfluentProducer(ProducerClient):
    def __init__(self, brokers: str) -> None:
        self._ck = _require_confluent()
        self._producer = self._ck.Producer({"bootstrap.servers": brokers})

    def produce(self, topic, value, key=None, partition=None,
                timestamp_usec=None):
        kwargs = {}
        if partition is not None:
            kwargs["partition"] = partition
        if timestamp_usec is not None:
            kwargs["timestamp"] = timestamp_usec // 1000
        while True:
            try:
                self._producer.produce(topic, value=value, key=key, **kwargs)
                break
            except BufferError:
                # librdkafka's delivery queue is full: service callbacks
                # until there is room (sustained backpressure can take
                # several poll rounds)
                self._producer.poll(1.0)
        self._producer.poll(0)  # service delivery callbacks as we go

    def flush(self):
        self._producer.flush()

    def close(self):
        self.flush()


def make_consumer(brokers,
                  assignment_policy: str = "cooperative-sticky") \
        -> ConsumerClient:
    if isinstance(brokers, InMemoryBroker):
        c = brokers.consumer()
        # the in-memory broker's single cooperative round-robin assignment
        # serves every strategy; record the choice for introspection
        c.assignment_policy = assignment_policy
        return c
    return ConfluentConsumer(str(brokers), assignment_policy)


def make_producer(brokers) -> ProducerClient:
    if isinstance(brokers, InMemoryBroker):
        return brokers.producer()
    return ConfluentProducer(str(brokers))
