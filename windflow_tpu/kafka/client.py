"""Kafka client layer: message type, abstract consumer/producer, an
in-process broker for tests/local runs, and a gated adapter for a real
client library.

The reference binds directly to librdkafka (``/root/reference/wf/kafka/
kafka_source.hpp:57-123`` consumer + rebalance callback, ``kafka_sink.hpp:86``
per-replica producer).  Here the operators talk to a small client interface
so the same operator code runs against:

* :class:`InMemoryBroker` — an in-process broker with topics, partitions and
  consumer groups (partition assignment + cooperative rebalance), used by
  the test suite exactly as the reference's Kafka tests use a live local
  broker;
* ``confluent_kafka`` — when the library is installed (it is not baked into
  this image, so the adapter import-gates; see ``make_consumer``).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from windflow_tpu.basic import (WindFlowError, current_time_usecs,
                                stable_hash)


@dataclasses.dataclass
class KafkaMessage:
    """One consumed record (reference ``RdKafka::Message`` surface the user
    deserializer touches: topic/partition/offset/key/payload/timestamp)."""
    topic: str
    partition: int
    offset: int
    key: Optional[bytes]
    value: Any
    timestamp_usec: int


#: partition assignment strategies the client layer understands; the
#: in-memory broker implements one cooperative round-robin assignment (the
#: names map onto it), the confluent adapter passes the choice to librdkafka
ASSIGNMENT_POLICIES = ("cooperative-sticky", "roundrobin", "range")


class ConsumerClient:
    #: selected partition assignment strategy (withAssignmentPolicy)
    assignment_policy = "cooperative-sticky"

    def idle_partitions(self):
        """Partitions confirmed drained/idle, or None when the client
        cannot know (the source then uses wall-clock idleness)."""
        return None

    def subscribe(self, topics: Sequence[str], group_id: str,
                  offsets: Optional[Sequence[int]] = None) -> None:
        raise NotImplementedError

    def poll(self, max_msgs: int) -> List[KafkaMessage]:
        raise NotImplementedError

    def assignment(self) -> List[Tuple[str, int]]:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class ProducerClient:
    def produce(self, topic: str, value: Any, key: Optional[bytes] = None,
                partition: Optional[int] = None,
                timestamp_usec: Optional[int] = None) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# In-process broker
# ---------------------------------------------------------------------------

class _Partition:
    __slots__ = ("log",)

    def __init__(self) -> None:
        self.log: List[KafkaMessage] = []


class InMemoryBroker:
    """Topics × partitions with consumer-group assignment.

    Rebalance model: joining or leaving a group recomputes the round-robin
    assignment of every subscribed (topic, partition) over the group's
    members in join order; read positions live with the *group* (per
    topic-partition), so a partition handed to another member resumes where
    the previous owner stopped — the in-process analogue of the reference's
    cooperative incremental rebalance (``kafka_source.hpp:77-123``)."""

    def __init__(self) -> None:
        self._topics: Dict[str, List[_Partition]] = {}
        self._groups: Dict[str, "_Group"] = {}
        self._lock = threading.Lock()
        self._rr = itertools.count()

    # -- admin ---------------------------------------------------------------
    def create_topic(self, name: str, num_partitions: int = 1) -> None:
        with self._lock:
            if name in self._topics:
                if len(self._topics[name]) != num_partitions:
                    raise WindFlowError(
                        f"topic '{name}' already exists with "
                        f"{len(self._topics[name])} partitions")
                return
            self._topics[name] = [_Partition()
                                  for _ in range(num_partitions)]
            self._rebalance_subscribers(name)

    def _rebalance_subscribers(self, topic: str) -> None:
        """New topic (explicit or auto-created by produce): groups already
        subscribed to it must pick up its partitions, like a metadata
        refresh on a real broker.  Caller holds the lock."""
        for g in self._groups.values():
            if any(topic in m._topics for m in g.members):
                g.rebalance(self)

    def partitions(self, topic: str) -> int:
        with self._lock:
            if topic not in self._topics:
                raise WindFlowError(f"unknown topic '{topic}'")
            return len(self._topics[topic])

    def topic_size(self, topic: str) -> int:
        with self._lock:
            return sum(len(p.log) for p in self._topics.get(topic, ()))

    # -- produce -------------------------------------------------------------
    def _append(self, topic: str, value: Any, key: Optional[bytes],
                partition: Optional[int], ts: Optional[int]) -> None:
        with self._lock:
            parts = self._topics.get(topic)
            if parts is None:
                parts = self._topics[topic] = [_Partition()]
                self._rebalance_subscribers(topic)
            if partition is None:
                if key is not None:
                    # deterministic placement: Python's hash() is salted
                    # per process, which would scatter one key across
                    # partitions between producer processes (Kafka uses
                    # murmur2 for the same reason); stable_hash is crc32
                    # for bytes
                    partition = stable_hash(key) % len(parts)
                else:
                    partition = next(self._rr) % len(parts)
            if not 0 <= partition < len(parts):
                raise WindFlowError(
                    f"partition {partition} out of range for '{topic}'")
            p = parts[partition]
            p.log.append(KafkaMessage(
                topic=topic, partition=partition, offset=len(p.log), key=key,
                value=value,
                timestamp_usec=ts if ts is not None else current_time_usecs()))

    # -- clients -------------------------------------------------------------
    def producer(self) -> "InMemoryProducer":
        return InMemoryProducer(self)

    def consumer(self) -> "InMemoryConsumer":
        return InMemoryConsumer(self)


class _Group:
    def __init__(self) -> None:
        self.members: List["InMemoryConsumer"] = []
        # group-held read positions: (topic, partition) -> next offset
        self.positions: Dict[Tuple[str, int], int] = {}

    def rebalance(self, broker: InMemoryBroker) -> None:
        tps: List[Tuple[str, int]] = []
        topics = sorted({t for m in self.members for t in m._topics})
        for t in topics:
            for p in range(len(broker._topics.get(t, ()))):
                tps.append((t, p))
        for m in self.members:
            m._assignment = []
        for i, tp in enumerate(tps):
            owners = [m for m in self.members if tp[0] in m._topics]
            if owners:
                owners[i % len(owners)]._assignment.append(tp)


class InMemoryProducer(ProducerClient):
    def __init__(self, broker: InMemoryBroker) -> None:
        self._broker = broker
        self.produced = 0

    def produce(self, topic, value, key=None, partition=None,
                timestamp_usec=None):
        self._broker._append(topic, value, key, partition, timestamp_usec)
        self.produced += 1

    def flush(self) -> None:
        pass  # appends are synchronous

    def close(self) -> None:
        pass


class InMemoryConsumer(ConsumerClient):
    def __init__(self, broker: InMemoryBroker) -> None:
        self._broker = broker
        self._group: Optional[_Group] = None
        self._group_id: Optional[str] = None
        self._topics: List[str] = []
        self._assignment: List[Tuple[str, int]] = []
        self._next_part = 0
        self._closed = False

    def subscribe(self, topics, group_id, offsets=None):
        with self._broker._lock:
            self._topics = list(topics)
            self._group_id = group_id
            g = self._broker._groups.setdefault(group_id, _Group())
            self._group = g
            if self not in g.members:
                g.members.append(self)
            # explicit starting offsets: one per topic, -1 = keep current
            # (reference rebalance-callback offset override,
            # kafka_source.hpp:81-91)
            if offsets:
                for t, off in zip(topics, offsets):
                    if off is not None and off > -1:
                        for p in range(len(self._broker._topics.get(t, ()))):
                            g.positions[(t, p)] = off
            g.rebalance(self._broker)

    def poll(self, max_msgs: int) -> List[KafkaMessage]:
        if self._group is None:
            raise WindFlowError("poll before subscribe")
        out: List[KafkaMessage] = []
        with self._broker._lock:
            n_parts = len(self._assignment)
            for _ in range(n_parts):
                if len(out) >= max_msgs:
                    break
                tp = self._assignment[self._next_part % n_parts]
                self._next_part += 1
                t, p = tp
                log = self._broker._topics[t][p].log
                pos = self._group.positions.get(tp, 0)
                take = min(max_msgs - len(out), len(log) - pos)
                if take > 0:
                    out.extend(log[pos:pos + take])
                    self._group.positions[tp] = pos + take
        return out

    def idle_partitions(self):
        """Assigned partitions with nothing pending RIGHT NOW (consumer
        position at the log end) — the exact form of 'idle' the source's
        per-partition watermark fold wants (such a partition must not gate
        or pin event time).  Computed live under the broker lock, so a
        partition refilled since its last visit immediately resumes
        gating.  Real-client adapters return None (unknown) and the source
        falls back to wall-clock idleness."""
        out = set()
        with self._broker._lock:
            for tp in self._assignment:
                t, p = tp
                log = self._broker._topics[t][p].log
                if self._group.positions.get(tp, 0) >= len(log):
                    out.add(tp)
        return out

    def assignment(self) -> List[Tuple[str, int]]:
        return list(self._assignment)

    def close(self) -> None:
        if self._closed or self._group is None:
            return
        self._closed = True
        with self._broker._lock:
            self._group.members.remove(self)
            self._group.rebalance(self._broker)


# ---------------------------------------------------------------------------
# Real-client adapters (gated: confluent_kafka is not in this image)
#
# VALIDATION STATUS (precise, per VERDICT r3 item 8): these adapters have
# been exercised against a *faked* confluent_kafka module
# (tests/test_kafka.py) and the operator surface against the in-process
# broker — never against a live broker (neither confluent_kafka nor any
# broker binary exists in the build environment; zero egress).  What the
# fake CANNOT prove, and therefore remains UNVERIFIED against real Kafka:
#
# 1. Rebalance callback ordering under the COOPERATIVE protocol: librdkafka
#    invokes on_assign with only the *incremental* partitions; the fake
#    replays full assignments.  The `_consumed_tps` guard in subscribe()
#    assumes EAGER re-delivery semantics; under cooperative-sticky the
#    guard is redundant but harmless — untested against a real group.
# 2. Offset commit on revoke: the reference commits synchronously in its
#    revoke callback (kafka_source.hpp:96-112); this adapter relies on
#    librdkafka auto-commit — whether a revoked partition's last offsets
#    land before reassignment on a real broker is unverified.
# 3. idle_partitions() returns None here (real consumers cannot cheaply
#    confirm a drained partition), so KafkaSourceReplica's per-partition
#    watermark fold uses the wall-clock grace path — exercised in tests
#    only through the fake's timing, not real consumer-lag timing.
# 4. Broker-side errors (session timeouts, coordinator migration,
#    msg.error() codes other than _PARTITION_EOF) pass through the
#    poll loop untested.
#
# tests/test_kafka_live.py now exercises items 1, 2 and 4 against a REAL
# broker (roundtrip across partitions, two-replica group assignment,
# committed-offset resume); it skips unless confluent_kafka + a broker at
# KAFKA_BOOTSTRAP are available — dockerimages/Dockerfile_cpu provides
# both (single-node KRaft via ci/run_tests_with_kafka.sh).  Item 3
# (real consumer-lag timing of the watermark grace path) remains
# environment-untested.  In THIS build environment (zero egress, no
# broker) the adapters stay validated only against the fake.
# ---------------------------------------------------------------------------

def _require_confluent():
    try:
        import confluent_kafka  # noqa: F401
        return confluent_kafka
    except ImportError as e:
        raise WindFlowError(
            "connecting to a real Kafka broker requires the "
            "'confluent_kafka' package, which is not installed; pass an "
            "InMemoryBroker for in-process streaming") from e


class ConfluentConsumer(ConsumerClient):
    """Thin adapter over confluent_kafka.Consumer (librdkafka underneath —
    the same library the reference binds)."""

    def __init__(self, brokers: str,
                 assignment_policy: str = "cooperative-sticky") -> None:
        self._ck = _require_confluent()
        self._brokers = brokers
        self.assignment_policy = assignment_policy
        self._consumer = None
        self._consumed_tps = set()   # partitions that delivered data

    def subscribe(self, topics, group_id, offsets=None):
        self._consumed_tps = set()   # scoped to this consumer session
        cooperative = self.assignment_policy == "cooperative-sticky"
        conf = {"bootstrap.servers": self._brokers,
                "group.id": group_id,
                "auto.offset.reset": "earliest",
                "partition.assignment.strategy": self.assignment_policy}
        self._consumer = self._ck.Consumer(conf)
        if offsets:
            def on_assign(consumer, partitions):
                for part in partitions:
                    tp = (part.topic, part.partition)
                    # apply the user's START offset only until the
                    # partition has actually DELIVERED data (tracked in
                    # poll): an EAGER rebalance re-delivers the full
                    # assignment, and re-seeking a mid-stream partition
                    # would rewind it into duplicates — but a partition
                    # revoked before consuming anything must still get
                    # its start offset, not auto.offset.reset
                    if tp in self._consumed_tps:
                        continue
                    try:
                        off = offsets[topics.index(part.topic)]
                    except (ValueError, IndexError):
                        continue
                    if off is not None and off > -1:
                        part.offset = off
                # librdkafka requires incremental_assign under the
                # COOPERATIVE protocol and plain assign under EAGER
                # strategies (roundrobin/range)
                if cooperative:
                    consumer.incremental_assign(partitions)
                else:
                    consumer.assign(partitions)

            self._consumer.subscribe(list(topics), on_assign=on_assign)
        else:
            self._consumer.subscribe(list(topics))

    def poll(self, max_msgs: int) -> List[KafkaMessage]:
        out = []
        for _ in range(max_msgs):
            msg = self._consumer.poll(0)
            if msg is None:
                break
            if msg.error():
                continue
            ts_type, ts_ms = msg.timestamp()
            self._consumed_tps.add((msg.topic(), msg.partition()))
            out.append(KafkaMessage(
                topic=msg.topic(), partition=msg.partition(),
                offset=msg.offset(), key=msg.key(), value=msg.value(),
                timestamp_usec=ts_ms * 1000 if ts_type else
                current_time_usecs()))
        return out

    def assignment(self):
        return [(p.topic, p.partition)
                for p in self._consumer.assignment()]

    def close(self):
        if self._consumer is not None:
            self._consumer.close()


class ConfluentProducer(ProducerClient):
    def __init__(self, brokers: str) -> None:
        self._ck = _require_confluent()
        self._producer = self._ck.Producer({"bootstrap.servers": brokers})

    def produce(self, topic, value, key=None, partition=None,
                timestamp_usec=None):
        kwargs = {}
        if partition is not None:
            kwargs["partition"] = partition
        if timestamp_usec is not None:
            kwargs["timestamp"] = timestamp_usec // 1000
        while True:
            try:
                self._producer.produce(topic, value=value, key=key, **kwargs)
                break
            except BufferError:
                # librdkafka's delivery queue is full: service callbacks
                # until there is room (sustained backpressure can take
                # several poll rounds)
                self._producer.poll(1.0)
        self._producer.poll(0)  # service delivery callbacks as we go

    def flush(self):
        self._producer.flush()

    def close(self):
        self.flush()


def make_consumer(brokers,
                  assignment_policy: str = "cooperative-sticky") \
        -> ConsumerClient:
    if isinstance(brokers, InMemoryBroker):
        c = brokers.consumer()
        # the in-memory broker's single cooperative round-robin assignment
        # serves every strategy; record the choice for introspection
        c.assignment_policy = assignment_policy
        return c
    return ConfluentConsumer(str(brokers), assignment_policy)


def make_producer(brokers) -> ProducerClient:
    if isinstance(brokers, InMemoryBroker):
        return brokers.producer()
    return ConfluentProducer(str(brokers))
