"""Kafka_Source operator (reference ``/root/reference/wf/kafka/
kafka_source.hpp:127,355``).

Each replica owns one consumer joined to the operator's consumer group, so
topic partitions spread across replicas and rebalance when replicas come
and go — exactly the reference's per-replica ``KafkaConsumer`` with the
cooperative rebalance callback (``kafka_source.hpp:57-123``).

The user deserializer runs per consumed message:
``fn(msg: KafkaMessage | None, shipper[, kafka_ctx]) -> bool | None`` —
``None`` msg means the consumer has been idle for ``idle_time_usec``
(reference ``consume(idleTime)`` timeout path); returning ``False`` stops
this replica (its EOS then flows through the graph).  Any other return
continues.  The shipper mirrors ``Source_Shipper``: ``push`` (ingress
timestamping) and ``pushWithTimestamp`` (event time).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from windflow_tpu.basic import WindFlowError, current_time_usecs
from windflow_tpu.kafka.client import (ASSIGNMENT_POLICIES,
                                       make_consumer)
from windflow_tpu.kafka.kafka_context import KafkaRuntimeContext
from windflow_tpu.meta import adapt
from windflow_tpu.ops.source import Source, SourceReplica


class KafkaShipper:
    """Push interface handed to the deserializer (reference
    ``Source_Shipper``, ``source_shipper.hpp:59-``)."""

    __slots__ = ("_replica",)

    def __init__(self, replica: "KafkaSourceReplica") -> None:
        self._replica = replica

    def push(self, item: Any) -> None:
        r = self._replica
        ts = current_time_usecs()
        if ts <= r._last_ts:
            ts = r._last_ts + 1
        self.pushWithTimestamp(item, ts)

    def pushWithTimestamp(self, item: Any, ts: int) -> None:
        r = self._replica
        r._last_ts = max(r._last_ts, int(ts))
        # Per-partition watermarking: a replica assigned several partitions
        # must not let one partition's progress mark a lagging sibling's
        # tuples late — its watermark is the MIN over its assigned
        # partitions' event-time progress (what Kafka ecosystems call
        # per-partition watermarks).  An assigned partition that has not
        # delivered yet HOLDS THE WATERMARK DOWN (poll rotation may simply
        # not have reached it), until it stays silent for idle_time_usec —
        # then it stops gating (an empty partition must not stall event
        # time forever).  Pushes with no current partition (idle callback,
        # closing function) fold through the same gated per-partition
        # minimum — the replica-wide max could jump the watermark past a
        # lagging partition's pending data.
        if r._cur_tp is not None:
            pm = r._part_max
            prev = pm.get(r._cur_tp)
            advanced = prev is None or ts > prev
            if advanced:
                pm[r._cur_tp] = int(ts)
            # recompute only when this partition's frontier moved or the
            # fold was gated on an unheard partition — otherwise the min
            # is unchanged and the scan (and its clock read) is skipped
            if advanced or r._wm_gated:
                wm = r._partition_wm()
                r._wm_gated = wm is None
                if wm is not None:
                    r._advance_wm(wm)
        else:
            wm = r._partition_wm()
            if wm is None:
                # Distinguish "gated by a lagging partition" (hold the
                # watermark) from "no partitions assigned at all" (e.g.
                # parallelism > partition count): a partition-less
                # replica's heartbeat pushes exist precisely to keep
                # event time flowing — nothing can lag, so the replica-
                # wide max is safe there.
                asn = r._poll_asn
                if asn is None and r._consumer is not None:
                    asn = r._consumer.assignment()
                if not asn:
                    wm = r._last_ts
            if wm is not None:
                r._advance_wm(wm)
        r.stats.outputs_sent += 1
        r._tid_seq += 1
        r.emitter.emit(item, int(ts), r.current_wm,
                       tid=(r.op.ordinal, r.index, r._tid_seq))
        r._count_toward_punctuation(1)


class KafkaSourceReplica(SourceReplica):
    def __init__(self, op: "KafkaSource", index: int) -> None:
        super().__init__(op, index)
        self._fn = adapt(op.deser_fn, 2)
        self._shipper = KafkaShipper(self)
        self._consumer = None
        self._last_activity = 0
        #: (topic, partition) of the message currently being deserialized
        self._cur_tp = None
        #: per-partition max pushed event ts (see KafkaShipper watermarking)
        self._part_max = {}
        #: first wall time each assigned partition was observed (grace
        #: anchor — per partition, so one gained in a later REBALANCE gets
        #: its own hold-down window, not the replica's long-expired one)
        self._part_seen_at = {}
        #: wall time of each partition's last delivered message — a heard
        #: partition silent past idle_time_usec stops gating the fold (it
        #: would otherwise pin the watermark forever on a live stream)
        self._part_last_at = {}
        self._wm_gated = True
        #: per-poll snapshots of assignment / idle partitions (tick
        #: refreshes; None until the first poll → computed on demand)
        self._poll_asn = None
        self._poll_idle = None

    def _partition_wm(self):
        """Min event-time progress over assigned LIVE partitions; None
        while an assigned partition still gates — unheard with data
        possibly pending (the watermark must not advance past data poll
        rotation hasn't reached).  An IDLE partition — confirmed drained
        by the consumer (exact, in-memory broker), or silent past
        idle_time_usec (wall-clock fallback, real-client adapters) — stops
        gating until it delivers again: it must not stall or pin event
        time on a live stream."""
        # per-poll snapshots (tick refreshes them): the per-push fast path
        # must not hit the consumer per tuple
        asn = self._poll_asn
        caught = self._poll_idle
        if asn is None:
            asn = self._consumer.assignment()
            caught = self._consumer.idle_partitions()
        idle_usec = self.op.idle_time_usec
        now = None
        lo = None
        for tp in asn:
            idle = caught is not None and tp in caught
            pts = self._part_max.get(tp)
            if pts is None:
                if idle:
                    continue         # confirmed empty: not gating
                if caught is None:
                    if now is None:
                        now = current_time_usecs()
                    seen = self._part_seen_at.setdefault(tp, now)
                    if now - seen >= idle_usec:
                        continue     # silent past the grace window
                return None          # unheard, possibly pending: gate
            if idle:
                continue             # heard, confirmed drained: no gate
            if caught is None and len(asn) > 1:
                if now is None:
                    now = current_time_usecs()
                if now - self._part_last_at.get(tp, now) >= idle_usec:
                    continue         # heard-then-silent: stops gating
            if lo is None or pts < lo:
                lo = pts
        return lo

    def start(self) -> None:
        self._consumer = make_consumer(self.op.brokers,
                                       self.op.assignment_policy)
        self._consumer.subscribe(self.op.topics, self.op.group_id,
                                 self.op.offsets)
        # durability restore (windflow_tpu/durability): seek back to the
        # checkpointed per-partition cursors — the group may still hold
        # post-barrier positions from the run that crashed (messages it
        # polled but lost), and replaying them is exactly the point
        if self.op._restore_positions:
            self._consumer.seek_positions(self.op._restore_positions)
        if self.op._restore_part_max:
            # group-level per-partition event-time frontiers: every
            # replica seeds the full merged map (assignment may differ
            # from the checkpointing run); the first poll prunes entries
            # for partitions this replica does not own
            self._part_max.update(self.op._restore_part_max)
        # riched deserializers see a KafkaRuntimeContext (reference passes
        # KafkaRuntimeContext instead of RuntimeContext, kafka_source.hpp:134)
        self.context = KafkaRuntimeContext(
            self.op.parallelism, self.index, self.op.name,
            consumer=self._consumer)
        self._last_activity = current_time_usecs()

    def tick(self, max_items: int) -> bool:
        if self._exhausted:
            return False
        msgs = self._consumer.poll(max_items)
        run = True
        # snapshot once per poll for the per-push watermark fold: idleness
        # as of this poll (a refilled partition resumes gating at the next
        # poll; within-poll pushes can't contain its data anyway).  A
        # partition that DELIVERED in this poll is live by definition even
        # if the poll drained it — in the normal steady state (consumer
        # keeping pace) every partition is always caught up, and treating
        # that as idle would freeze the watermark forever.
        self._poll_asn = asn = self._consumer.assignment()
        # a partition revoked in a rebalance must not leave stale tracking
        # behind: re-gained later, it starts a fresh grace window and a
        # fresh event-time frontier (its backlog would otherwise be gated
        # by a long-expired _part_seen_at anchor and marked late)
        if asn is not None:
            live = set(asn)
            for d in (self._part_max, self._part_seen_at,
                      self._part_last_at):
                for tp in [t for t in d if t not in live]:
                    del d[tp]
        caught = self._consumer.idle_partitions()
        if caught is not None and msgs:
            caught = caught - {(m.topic, m.partition) for m in msgs}
        self._poll_idle = caught
        if msgs:
            self._last_activity = current_time_usecs()
            for msg in msgs:
                self._cur_tp = tp = (msg.topic, msg.partition)
                # delivery = liveness, even if the deserializer pushes
                # nothing for this message (one clock read per poll)
                self._part_last_at[tp] = self._last_activity
                ret = self._fn(msg, self._shipper, self.context)
                self._cur_tp = None
                self.stats.inputs_received += 1
                if ret is False:
                    run = False
                    break
        else:
            now = current_time_usecs()
            if now - self._last_activity >= self.op.idle_time_usec:
                self._last_activity = now
                ret = self._fn(None, self._shipper, self.context)
                if ret is False:
                    run = False
        if not run:
            self._exhausted = True
            # terminate first: the closing function (reference
            # kafka_closing_func, kafka_source.hpp:296) must see a live
            # consumer (commit offsets, read assignment); close after
            self._terminate()
            self._consumer.close()
            return True  # termination (EOS cascade) is progress
        return True


class KafkaSource(Source):
    replica_class = KafkaSourceReplica

    #: per-(topic, partition) cursors a durability restore stashes before
    #: start(); replicas seek to them right after subscribing (None on
    #: fresh runs — one attribute check at start, nothing per poll)
    _restore_positions = None
    #: merged per-partition event-time frontiers (same restore path):
    #: group-level, seeded into every replica at start
    _restore_part_max = None

    def __init__(self, deser_fn: Callable, brokers, topics: Sequence[str],
                 group_id: str = "windflow",
                 offsets: Optional[Sequence[int]] = None,
                 idle_time_usec: int = 100_000,
                 assignment_policy: str = "cooperative-sticky",
                 name: str = "kafka_source", parallelism: int = 1,
                 output_batch_size: int = 0) -> None:
        if not topics:
            raise WindFlowError("Kafka_Source needs at least one topic")
        if assignment_policy not in ASSIGNMENT_POLICIES:
            raise WindFlowError(
                f"unknown assignment policy '{assignment_policy}' "
                f"(one of {ASSIGNMENT_POLICIES})")
        # bypass Source.__init__'s generator plumbing; Operator init only
        super().__init__(gen_fn=lambda: iter(()), name=name,
                         parallelism=parallelism,
                         output_batch_size=output_batch_size)
        self.deser_fn = deser_fn
        self.brokers = brokers
        self.topics = list(topics)
        self.group_id = group_id
        self.offsets = list(offsets) if offsets is not None else None
        self.idle_time_usec = idle_time_usec
        self.assignment_policy = assignment_policy
