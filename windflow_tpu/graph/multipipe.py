"""MultiPipe: a linear (then split/merged) composition of operators.

Re-design of the reference ``MultiPipe`` (``/root/reference/wf/multipipe.hpp``).
The reference implements composition with nested FastFlow all-to-all building
blocks ("matrioskas", ``multipipe.hpp:502-514``); here a MultiPipe simply
records the operator sequence and routing, and the PipeGraph wires replica
inboxes/emitters at ``run()`` — the dataflow structure is metadata for a host
driver, not a thread topology.

Operator chaining (reference ``chain_operator``, ``multipipe.hpp:553-569``,
thread fusion) maps to program fusion: chained TPU operators compose their
traced functions into one XLA program (see ``windflow_tpu.ops.chained``), which
is strictly cheaper than the reference's same-thread fusion — XLA fuses the
loops themselves.
"""

from __future__ import annotations

from typing import List, Optional

from windflow_tpu.basic import RoutingMode, WindFlowError
from windflow_tpu.ops.base import Operator
from windflow_tpu.ops.sink import Sink
from windflow_tpu.ops.source import Source


class MultiPipe:
    def __init__(self, graph: "PipeGraph", source: Source) -> None:
        self.graph = graph
        self.operators: List[Operator] = [source]
        self.has_sink = False
        self.has_source = True
        self.merged_into: Optional["MultiPipe"] = None
        self.split_children: List["MultiPipe"] = []
        self.split_fn = None
        self.split_parent: Optional["MultiPipe"] = None
        self.merge_parents: List["MultiPipe"] = []
        # Edges are (upstream_op, downstream_op, routing) triples resolved at
        # wiring time; intra-pipe edges are implicit in `operators` order.

    @classmethod
    def _empty(cls, graph: "PipeGraph") -> "MultiPipe":
        """A source-less pipe: a split branch or a merge result."""
        mp = cls.__new__(cls)
        mp.graph = graph
        mp.operators = []
        mp.has_sink = False
        mp.has_source = False
        mp.merged_into = None
        mp.split_children = []
        mp.split_fn = None
        mp.split_parent = None
        mp.merge_parents = []
        return mp

    # -- composition ---------------------------------------------------------
    def _check_open(self):
        if self.has_sink:
            raise WindFlowError("cannot extend a MultiPipe after its sink")
        if self.split_children:
            raise WindFlowError("cannot extend a split MultiPipe directly; "
                                "extend its branches")
        if self.merged_into is not None:
            raise WindFlowError("cannot extend a merged MultiPipe")

    def add(self, op: Operator) -> "MultiPipe":
        """Append an operator with a shuffle/forward connection (reference
        ``MultiPipe::add``, ``multipipe.hpp:936-1027``)."""
        if hasattr(op, "stages"):
            # composite window operators expand into their pipeline stages
            # (reference adds PLQ+WLQ / MAP+REDUCE as two operators,
            # multipipe.hpp:965-999)
            cf = getattr(op, "closing_func", None)
            for stage in op.stages():
                if cf is not None and stage.closing_func is None:
                    stage.closing_func = cf
                self.add(stage)
            return self
        self._check_open()
        if isinstance(op, Source):
            raise WindFlowError("a Source can only start a MultiPipe")
        for prev in self._upstream_ops():
            if op.is_tpu and prev.output_batch_size <= 0 and not prev.is_tpu:
                raise WindFlowError(
                    f"TPU operator '{op.name}' must be preceded by an "
                    "operator with output batch size > 0 (reference "
                    "multipipe.hpp:441-444)")
        self.operators.append(op)
        return self

    def _upstream_ops(self) -> List[Operator]:
        """Operators feeding the next appended operator: the pipe's own tail,
        or — for a fresh split branch / merged pipe — the tails of the parent
        pipes (the reference resolves these via the Application Tree,
        ``pipegraph.hpp:268-464``)."""
        if self.operators:
            return [self.operators[-1]]
        if self.split_parent is not None:
            return self.split_parent._upstream_ops()
        if self.merge_parents:
            return [p.operators[-1] for p in self.merge_parents
                    if p.operators]
        return []

    def chain(self, op: Operator) -> "MultiPipe":
        """Fuse ``op`` with the previous stage when possible: same parallelism
        and FORWARD routing (reference conditions, ``multipipe.hpp:553``);
        otherwise falls back to ``add`` exactly like the reference."""
        from windflow_tpu.ops.reduce_op import Reduce
        if hasattr(op, "stages") or isinstance(op, Reduce) \
                or not self.operators:
            # composites and Reduce cannot be chained (multipipe.hpp:1042-1045);
            # a fresh split branch / merged pipe has nothing to fuse with
            return self.add(op)
        prev = self.operators[-1]
        can_fuse = (op.routing == RoutingMode.FORWARD
                    and op.parallelism == prev.parallelism
                    and not isinstance(prev, Source)
                    and prev.is_tpu == op.is_tpu
                    and type(prev).__name__ in _FUSABLE
                    and type(op).__name__ in _FUSABLE)
        if can_fuse:
            from windflow_tpu.ops.chained import fuse
            self.operators[-1] = fuse(prev, op)
            return self
        return self.add(op)

    def add_sink(self, sink: Sink) -> "MultiPipe":
        self.add(sink)
        self.has_sink = True
        return self

    def chain_sink(self, sink: Sink) -> "MultiPipe":
        self.chain(sink)
        self.has_sink = True
        return self

    # -- DAG composition (reference multipipe.hpp:1158-1303) -----------------
    def split(self, split_fn, n_branches: int) -> "MultiPipe":
        """Split this MultiPipe into ``n_branches`` children; ``split_fn(item)``
        returns a destination index or an iterable of indexes."""
        self._check_open()
        if not self.operators:
            raise WindFlowError(
                "cannot split an empty MultiPipe — add an operator to this "
                "branch first")
        self.split_fn = split_fn
        for _ in range(n_branches):
            child = MultiPipe._empty(self.graph)
            child.split_parent = self
            self.split_children.append(child)
        self.graph._register_split(self)
        return self

    def select(self, index: int) -> "MultiPipe":
        if not self.split_children:
            raise WindFlowError("select() on a MultiPipe that was not split")
        return self.split_children[index]

    def merge(self, *others: "MultiPipe") -> "MultiPipe":
        """Merge this MultiPipe with others into a new one (reference
        ``MultiPipe::merge`` + PipeGraph LCA logic)."""
        pipes = [self, *others]
        for p in pipes:
            p._check_open()
        merged = MultiPipe._empty(self.graph)
        merged.merge_parents = pipes
        for p in pipes:
            p.merged_into = merged
        self.graph._register_merge(merged)
        return merged


#: Operator type names that participate in chain fusion.
_FUSABLE = {"Map", "Filter", "FlatMap", "ChainedHost",
            "MapTPU", "FilterTPU", "ChainedTPU"}
