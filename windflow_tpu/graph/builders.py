"""Fluent operator builders (reference ``/root/reference/wf/builders.hpp:57-127``
and the GPU variants in ``builders_gpu.hpp:54-673``).

Method names keep the reference's camelCase (``withParallelism``,
``withKeyBy``, ``withOutputBatchSize``) so a WindFlow user can transliterate
their program; TPU builders mirror the ``*GPU_Builder`` family.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from windflow_tpu.basic import RoutingMode, WindFlowError
from windflow_tpu.ops.filter_op import Filter
from windflow_tpu.ops.flatmap_op import FlatMap
from windflow_tpu.ops.map_op import Map
from windflow_tpu.ops.reduce_op import Reduce
from windflow_tpu.ops.sink import Sink
from windflow_tpu.ops.source import Source
from windflow_tpu.ops.tpu import FilterTPU, MapTPU, ReduceTPU
from windflow_tpu.ops.tpu_stateful import StatefulFilterTPU, StatefulMapTPU


class _BuilderBase:
    _default_name = "op"
    _closing_func: Optional[Callable] = None

    def __init__(self) -> None:
        self._name = self._default_name
        self._parallelism = 1
        self._output_batch_size = 0
        self._key_extractor: Optional[Callable] = None

    def __init_subclass__(cls, **kwargs):
        # every builder's build() applies the clauses _BuilderBase owns but
        # the per-builder constructors don't know about (closing function);
        # wrapping here keeps the ~20 build() methods oblivious
        super().__init_subclass__(**kwargs)
        orig = cls.__dict__.get("build")
        if orig is not None:
            def build(self, _orig=orig):
                op = _orig(self)
                if self._closing_func is not None:
                    op.closing_func = self._closing_func
                return op
            build.__doc__ = orig.__doc__
            cls.build = build

    def withName(self, name: str):
        self._name = name
        return self

    def withClosingFunction(self, fn: Callable):
        """Per-replica shutdown callback, run once when the replica
        terminates at EOS — ``fn(ctx)`` with the replica's RuntimeContext,
        or ``fn()`` (reference ``closing_func`` accepted by every operator
        builder, e.g. ``map.hpp:335-343``)."""
        self._closing_func = fn
        return self

    def withParallelism(self, parallelism: int):
        self._parallelism = parallelism
        return self

    def withOutputBatchSize(self, size: int):
        self._output_batch_size = size
        return self

    def withKeyBy(self, key_extractor: Callable[[Any], Any]):
        self._key_extractor = key_extractor
        return self

    def withRebalancing(self):
        """Round-robin input distribution even after an upstream KEYBY
        (reference REBALANCING routing, ``basic.hpp:87`` / builders
        ``withRebalancing``).  Mutually exclusive with withKeyBy."""
        self._rebalancing = True
        return self

    def _routing(self) -> RoutingMode:
        if getattr(self, "_broadcast", False):
            if self._key_extractor is not None \
                    or getattr(self, "_rebalancing", False):
                raise WindFlowError(
                    "withBroadcast is mutually exclusive with withKeyBy "
                    "and withRebalancing")
            return RoutingMode.BROADCAST
        if getattr(self, "_rebalancing", False):
            if self._key_extractor is not None:
                raise WindFlowError(
                    "withRebalancing and withKeyBy are mutually exclusive")
            return RoutingMode.REBALANCING
        return (RoutingMode.KEYBY if self._key_extractor is not None
                else RoutingMode.FORWARD)


class _BroadcastMixin:
    """withBroadcast for the operators the reference offers it on
    (Map/Filter/FlatMap/Sink, ``builders.hpp:252-1471``): every replica of
    the built operator receives every input tuple."""

    def withBroadcast(self):
        self._broadcast = True
        return self


class Source_Builder(_BuilderBase):
    _default_name = "source"

    def __init__(self, gen_fn: Callable) -> None:
        super().__init__()
        self._gen_fn = gen_fn
        self._ts_extractor = None
        self._record_spec = None

    def withTimestampExtractor(self, fn: Callable[[Any], int]):
        """EVENT-time sources: extract the event timestamp (µs) from each
        generated item (reference: ``Source_Shipper::pushWithTimestamp``)."""
        self._ts_extractor = fn
        return self

    def withRecordSpec(self, example: Any):
        """Declare the records this source emits — an example record
        (pytree of scalars/arrays) or a pytree of ``jax.ShapeDtypeStruct``
        — so ``PipeGraph.check()`` can abstractly evaluate every
        downstream kernel before dispatch (docs/ANALYSIS.md).  Static
        metadata only: never fed to the generator."""
        self._record_spec = example
        return self

    def withKeyBy(self, *_):
        raise WindFlowError("a Source has no input to key by")

    def withRebalancing(self):
        raise WindFlowError("a Source has no input to rebalance")

    def build(self) -> Source:
        return Source(self._gen_fn, name=self._name,
                      parallelism=self._parallelism,
                      output_batch_size=self._output_batch_size,
                      ts_extractor=self._ts_extractor,
                      record_spec=self._record_spec)


class DeviceSource_Builder(_BuilderBase):
    """Source whose batches are generated ON DEVICE by a jitted program —
    no host staging on the hot path (io/device_source.py; the reference
    has no analogue: its GPU sources stage host tuples,
    ``batch_gpu_t.hpp:51-229``).  ``batch_fn(i)`` is JAX-traceable,
    int32 batch index -> payload pytree of [capacity] leaves."""

    _default_name = "device_source"

    def __init__(self, batch_fn: Callable) -> None:
        super().__init__()
        self._batch_fn = batch_fn
        self._capacity = 0
        self._n_batches = 0
        self._ts_fn = None
        self._wm_fn = None
        self._ts_bounds_fn = None

    def withCapacity(self, n: int):
        """Lanes per generated batch (the compiled batch shape)."""
        self._capacity = n
        return self

    def withNumBatches(self, n: int):
        """Total batches across all replicas (replicas stride the index)."""
        self._n_batches = n
        return self

    def withTimestampFn(self, ts_fn: Callable, wm_fn: Callable[[int], int]):
        """EVENT time: ``ts_fn(i) -> int64[capacity]`` device lane (traced
        into the generator program) + ``wm_fn(i) -> int`` host frontier —
        the host never reads device lanes back to learn time."""
        self._ts_fn = ts_fn
        self._wm_fn = wm_fn
        return self

    def withTimestampBounds(self, ts_bounds_fn: Callable):
        """HOST fn ``i -> (ts_min, ts_max)`` bounding batch ``i``'s event
        timestamps: attaches the data-ts extrema that let downstream TB
        window rings size themselves preemptively without a device sync
        (DeviceBatch.ts_min/ts_max; EVENT time only)."""
        self._ts_bounds_fn = ts_bounds_fn
        return self

    def withKeyBy(self, *_):
        raise WindFlowError("a Source has no input to key by")

    def withRebalancing(self):
        raise WindFlowError("a Source has no input to rebalance")

    def withOutputBatchSize(self, n: int):
        raise WindFlowError(
            "DeviceSource batch size IS its capacity (withCapacity)")

    def build(self):
        from windflow_tpu.io.device_source import DeviceSource
        return DeviceSource(self._batch_fn, self._capacity, self._n_batches,
                            name=self._name, parallelism=self._parallelism,
                            ts_fn=self._ts_fn, wm_fn=self._wm_fn,
                            ts_bounds_fn=self._ts_bounds_fn)


class Map_Builder(_BroadcastMixin, _BuilderBase):
    _default_name = "map"

    def __init__(self, fn: Callable) -> None:
        super().__init__()
        self._fn = fn

    def build(self) -> Map:
        return Map(self._fn, name=self._name, parallelism=self._parallelism,
                   routing=self._routing(),
                   output_batch_size=self._output_batch_size,
                   key_extractor=self._key_extractor)


class Filter_Builder(_BroadcastMixin, _BuilderBase):
    _default_name = "filter"

    def __init__(self, fn: Callable) -> None:
        super().__init__()
        self._fn = fn

    def build(self) -> Filter:
        return Filter(self._fn, name=self._name,
                      parallelism=self._parallelism,
                      routing=self._routing(),
                      output_batch_size=self._output_batch_size,
                      key_extractor=self._key_extractor)


class FlatMap_Builder(_BroadcastMixin, _BuilderBase):
    _default_name = "flatmap"

    def __init__(self, fn: Callable) -> None:
        super().__init__()
        self._fn = fn

    def build(self) -> FlatMap:
        return FlatMap(self._fn, name=self._name,
                       parallelism=self._parallelism,
                       routing=self._routing(),
                       output_batch_size=self._output_batch_size,
                       key_extractor=self._key_extractor)


class Reduce_Builder(_BuilderBase):
    _default_name = "reduce"

    def __init__(self, fn: Callable, initial_state: Any) -> None:
        super().__init__()
        self._fn = fn
        self._initial_state = initial_state

    def withRebalancing(self):
        raise WindFlowError(
            "Reduce routes by key (or runs non-replicated); REBALANCING "
            "does not apply")

    def build(self) -> Reduce:
        return Reduce(self._fn, self._initial_state, name=self._name,
                      parallelism=self._parallelism,
                      key_extractor=self._key_extractor,
                      output_batch_size=self._output_batch_size)


class Sink_Builder(_BroadcastMixin, _BuilderBase):
    _default_name = "sink"

    def __init__(self, fn: Callable) -> None:
        super().__init__()
        self._fn = fn
        self._columnar = False
        self._columnar_defer = 2

    def withColumnarSink(self, defer: int = 2):
        """Deliver TPU→Sink batches as SoA numpy columns (``SinkColumns``)
        instead of per-record dicts — one bulk device→host copy, zero
        per-tuple Python (egress twin of the columnar ingest path).
        ``defer`` batches are held before conversion so the device→host
        transfer overlaps later batches' compute (0 = convert eagerly)."""
        self._columnar = True
        self._columnar_defer = defer
        return self

    def build(self) -> Sink:
        return Sink(self._fn, name=self._name, parallelism=self._parallelism,
                    routing=self._routing(),
                    key_extractor=self._key_extractor,
                    columnar=self._columnar,
                    columnar_defer=self._columnar_defer)


# ---------------------------------------------------------------------------
# TPU builders (reference MapGPU_Builder / FilterGPU_Builder /
# ReduceGPU_Builder, builders_gpu.hpp:54-673)
# ---------------------------------------------------------------------------

class _StatefulTPUMixin:
    """Stateful knobs shared by MapTPU/FilterTPU builders (reference:
    stateful ``MapGPU_Builder``/``FilterGPU_Builder`` variants are selected
    by the functor's (tuple, state) signature, ``builders_gpu.hpp:54-673``;
    here the per-key initial state is explicit)."""

    _initial_state = None
    _num_key_slots = 4096
    _dense_keys = False
    _assoc = None

    def withInitialState(self, state):
        """Per-key initial state prototype — switches the operator to the
        stateful keyed path (requires ``withKeyBy``).

        Skew warning: the default stateful kernel applies each key's tuples
        in order via a rank wavefront — a batch whose hottest key holds r
        tuples costs r sequential device steps, so ONE key receiving the
        whole batch degrades to batch-length serialization.  For
        ASSOCIATIVE updates, ``withAssociativeUpdate`` switches to a
        log-depth segmented scan that is immune to skew (see
        ops/tpu_stateful.py)."""
        self._initial_state = state
        return self

    def withNumKeySlots(self, n: int):
        """Capacity of the dense device state table (max distinct keys)."""
        self._num_key_slots = n
        return self

    def withDenseKeys(self):
        """Declare that the key extractor already returns dense slot ids in
        [0, num_key_slots): host-side key interning is skipped, so every
        batch is one fully-asynchronous device program (no per-batch D2H
        sync).  Out-of-range keys are masked invalid, as in FfatWindowsTPU."""
        self._dense_keys = True
        return self

    def withAssociativeUpdate(self, lift, comb, project):
        """Declare the state update associative:
        ``state' = comb(state, lift(record))`` and the output is
        ``project(record, state_including_this_record)`` (for filters,
        project returns the keep bool).  The operator then runs a log-depth
        segmented scan instead of the rank wavefront, so a single hot key
        costs the same as uniform keys.  The plain fn passed to the builder
        is ignored."""
        self._assoc = (lift, comb, project)
        return self


class MapTPU_Builder(_StatefulTPUMixin, _BuilderBase):
    _default_name = "map_tpu"

    def __init__(self, fn: Callable, batch_fn: bool = False) -> None:
        super().__init__()
        self._fn = fn
        self._batch_fn = batch_fn

    def build(self):
        if self._initial_state is not None:
            if self._batch_fn:
                raise WindFlowError(
                    "batch_fn is not supported for stateful MapTPU: the "
                    "stateful function operates per record as "
                    "fn(record, state) -> (record, state)")
            if getattr(self, "_rebalancing", False):
                raise WindFlowError(
                    "stateful TPU operators route by key; REBALANCING "
                    "does not apply")
            return StatefulMapTPU(self._fn, self._initial_state,
                                  name=self._name,
                                  parallelism=self._parallelism,
                                  key_extractor=self._key_extractor,
                                  num_key_slots=self._num_key_slots,
                                  dense_keys=self._dense_keys,
                                  assoc=self._assoc)
        return MapTPU(self._fn, name=self._name,
                      parallelism=self._parallelism,
                      batch_fn=self._batch_fn, routing=self._routing(),
                      key_extractor=self._key_extractor)


class FilterTPU_Builder(_StatefulTPUMixin, _BuilderBase):
    _default_name = "filter_tpu"

    def __init__(self, fn: Callable) -> None:
        super().__init__()
        self._fn = fn

    def build(self):
        if self._initial_state is not None:
            if getattr(self, "_rebalancing", False):
                raise WindFlowError(
                    "stateful TPU operators route by key; REBALANCING "
                    "does not apply")
            return StatefulFilterTPU(self._fn, self._initial_state,
                                     name=self._name,
                                     parallelism=self._parallelism,
                                     key_extractor=self._key_extractor,
                                     num_key_slots=self._num_key_slots,
                                     dense_keys=self._dense_keys,
                                     assoc=self._assoc)
        return FilterTPU(self._fn, name=self._name,
                         parallelism=self._parallelism,
                         routing=self._routing(),
                         key_extractor=self._key_extractor)


class ReduceTPU_Builder(_BuilderBase):
    _default_name = "reduce_tpu"

    def __init__(self, comb: Callable) -> None:
        super().__init__()
        self._comb = comb
        self._max_keys = None
        self._monoid = None

    def withRebalancing(self):
        raise WindFlowError(
            "ReduceTPU routes by key (or reduces globally); REBALANCING "
            "does not apply")

    def withMaxKeys(self, n: int):
        """Bound of the dense key space [0, n).  Required for mesh
        execution (cross-chip partial tables, Config.mesh).  On a single
        chip it is ignored by undeclared reduces (they sort arbitrary
        int32 keys) but, combined with ``withMonoidCombiner``, routes the
        reduce onto the sort-free dense scatter-combine path — keys
        outside [0, n) are then dropped and counted
        (Out_of_range_keys_dropped), the same key-space contract the mesh
        path enforces."""
        self._max_keys = int(n)
        return self

    def withSumCombiner(self):
        """Shorthand for ``withMonoidCombiner("sum")`` (strictly additive:
        ``comb(a, b) == a + b`` on every leaf)."""
        self._monoid = "sum"
        return self

    def withMonoidCombiner(self, kind: str):
        """Declare the combiner a leafwise commutative monoid — ``"sum"``
        (``a + b``), ``"max"`` (``maximum``) or ``"min"`` (``minimum``)
        on every leaf.  On a mesh, the cross-chip combine then rides ONE
        reduce collective (``lax.psum``/``pmax``/``pmin``) instead of
        all_gather + fold; on a single chip, together with
        ``withMaxKeys``, the whole sort + segmented scan is replaced by
        one dense scatter-combine pass.  The declared operation is
        applied without calling ``comb``, so the declaration must match
        the combiner exactly on every leaf (a wrong kind silently
        computes the declared operation).  This includes a record's key
        FIELD: under ``"sum"`` the output's key field is the leafwise
        sum ``key * count`` — route by the key EXTRACTOR and read the
        dense output's position (ascending key order), or prefer
        ``"max"``/``"min"``, which are idempotent and leave a key field
        intact."""
        self._monoid = kind
        return self

    def build(self) -> ReduceTPU:
        return ReduceTPU(self._comb, name=self._name,
                         parallelism=self._parallelism,
                         key_extractor=self._key_extractor,
                         max_keys=self._max_keys, monoid=self._monoid)


# ---------------------------------------------------------------------------
# Window builders (reference Keyed_Windows_Builder / Parallel_Windows_Builder /
# Paned_Windows_Builder / MapReduce_Windows_Builder / Ffat_Windows_Builder /
# Ffat_WindowsGPU_Builder, builders.hpp + builders_gpu.hpp:576)
# ---------------------------------------------------------------------------

from windflow_tpu.basic import WinType  # noqa: E402
from windflow_tpu.meta import _positional_arity  # noqa: E402
from windflow_tpu.windows.engine import WindowSpec  # noqa: E402
from windflow_tpu.windows.ops import (KeyedWindows, MapReduceWindows,  # noqa: E402
                                      PanedWindows, ParallelWindows)
from windflow_tpu.windows.ffat_op import FfatWindows  # noqa: E402
from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU  # noqa: E402


class _WindowBuilderBase(_BuilderBase):
    def withRebalancing(self):
        raise WindFlowError(
            "window operators route by key / broadcast; REBALANCING does "
            "not apply")

    def __init__(self):
        super().__init__()
        self._win_type = None
        self._win_len = 0
        self._slide = 0
        self._lateness = 0

    def withCBWindows(self, win_len: int, slide: int):
        self._win_type = WinType.CB
        self._win_len, self._slide = int(win_len), int(slide)
        return self

    def withTBWindows(self, win_usec: int, slide_usec: int):
        self._win_type = WinType.TB
        self._win_len, self._slide = int(win_usec), int(slide_usec)
        return self

    def withLateness(self, lateness_usec: int):
        self._lateness = int(lateness_usec)
        return self

    def _spec(self) -> WindowSpec:
        if self._win_type is None:
            raise WindFlowError(
                "window operator needs withCBWindows or withTBWindows")
        if self._win_len <= 0 or self._slide <= 0:
            raise WindFlowError("window length and slide must be > 0")
        return WindowSpec(self._win_type, self._win_len, self._slide,
                          self._lateness)


def _detect_incremental(fn) -> bool:
    """Non-incremental window logic takes the item list (arity 1);
    incremental logic takes (tuple, accumulator) (arity 2) — the Python
    analogue of the reference's type-based dispatch (meta.hpp)."""
    return _positional_arity(fn) == 2


class Keyed_Windows_Builder(_WindowBuilderBase):
    _default_name = "keyed_windows"

    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def build(self) -> KeyedWindows:
        return KeyedWindows(
            self._fn, self._spec(), name=self._name,
            parallelism=self._parallelism, key_extractor=self._key_extractor,
            incremental=_detect_incremental(self._fn),
            output_batch_size=self._output_batch_size)


class Parallel_Windows_Builder(_WindowBuilderBase):
    _default_name = "parallel_windows"

    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def build(self) -> ParallelWindows:
        return ParallelWindows(
            self._fn, self._spec(), name=self._name,
            parallelism=self._parallelism, key_extractor=self._key_extractor,
            incremental=_detect_incremental(self._fn),
            output_batch_size=self._output_batch_size)


class Paned_Windows_Builder(_WindowBuilderBase):
    _default_name = "paned_windows"

    def __init__(self, plq_fn, wlq_fn):
        super().__init__()
        self._plq_fn = plq_fn
        self._wlq_fn = wlq_fn
        self._wlq_parallelism = 1

    def withParallelisms(self, plq: int, wlq: int):
        self._parallelism = plq
        self._wlq_parallelism = wlq
        return self

    def build(self) -> PanedWindows:
        return PanedWindows(
            self._plq_fn, self._wlq_fn, self._spec(),
            name=self._name,
            plq_parallelism=self._parallelism,
            wlq_parallelism=self._wlq_parallelism,
            key_extractor=self._key_extractor,
            plq_incremental=_detect_incremental(self._plq_fn),
            wlq_incremental=_detect_incremental(self._wlq_fn),
            output_batch_size=self._output_batch_size)


class MapReduce_Windows_Builder(_WindowBuilderBase):
    _default_name = "mapreduce_windows"

    def __init__(self, map_fn, reduce_fn):
        super().__init__()
        self._map_fn = map_fn
        self._reduce_fn = reduce_fn
        self._reduce_parallelism = 1

    def withParallelisms(self, map_p: int, reduce_p: int):
        self._parallelism = map_p
        self._reduce_parallelism = reduce_p
        return self

    def build(self) -> MapReduceWindows:
        return MapReduceWindows(
            self._map_fn, self._reduce_fn, self._spec(),
            name=self._name,
            map_parallelism=self._parallelism,
            reduce_parallelism=self._reduce_parallelism,
            key_extractor=self._key_extractor,
            map_incremental=_detect_incremental(self._map_fn),
            reduce_incremental=_detect_incremental(self._reduce_fn),
            output_batch_size=self._output_batch_size)


class Ffat_Windows_Builder(_WindowBuilderBase):
    _default_name = "ffat_windows"

    def __init__(self, lift_fn, comb_fn):
        super().__init__()
        self._lift = lift_fn
        self._comb = comb_fn

    def build(self) -> FfatWindows:
        return FfatWindows(
            self._lift, self._comb, self._spec(),
            name=self._name,
            parallelism=self._parallelism, key_extractor=self._key_extractor,
            lateness=self._lateness,
            output_batch_size=self._output_batch_size)


class Ffat_WindowsTPU_Builder(_WindowBuilderBase):
    """Reference ``Ffat_WindowsGPU_Builder`` (builders_gpu.hpp:576); the
    ``withNumWinPerBatch`` knob is unnecessary here — every window a batch
    completes is computed in the one fused program.  Supports both CB
    windows (rank panes) and TB windows (time-quantum panes + watermark
    firing; lateness applies)."""

    _default_name = "ffat_windows_tpu"

    def __init__(self, lift_fn, comb_fn):
        super().__init__()
        self._lift = lift_fn
        self._comb = comb_fn
        self._max_keys = 1
        self._pane_capacity = None
        self._overflow_policy = "drop"
        self._monoid = None

    def withMaxKeys(self, n: int):
        """Size of the dense device key space [0, n)."""
        self._max_keys = int(n)
        return self

    def withCompactedKeys(self):
        """ARBITRARY int32 keys via device-side key compaction
        (parallel/compaction.py, docs/PERF.md round 12): the graph build
        attaches a key→dense-slot remap table sized by
        ``Config.key_compaction_slots``, so the dense pane rings work
        without a declared key bound — new keys are admitted at the
        host staging boundary (and from the in-program miss ring at
        reseed cadence); keys beyond the slot budget are masked invalid
        and counted, the operator's existing out-of-range contract.
        Requires ``withKeyBy`` and ``Config.key_compaction`` on; a
        declared ``withMaxKeys`` always beats compaction when the key
        space is actually bounded (preflight WF404 says so)."""
        self._max_keys = None
        return self

    def withSumCombiner(self):
        """Declare the combiner leafwise ADDITION (``comb(a, b) == a + b``
        on every leaf — the same strictly-additive contract as
        ReduceTPU_Builder.withSumCombiner, whose mesh path rides
        ``lax.psum``).  Shorthand for ``withMonoidCombiner("sum")`` —
        see there for what the declaration buys and its exactness
        contract (a merely zero-absorbing combiner like max must declare
        its OWN kind, never "sum")."""
        self._monoid = "sum"
        return self

    def withMonoidCombiner(self, kind: str):
        """Declare the combiner a leafwise commutative monoid —
        ``"sum"`` (``a + b``), ``"max"`` (``maximum(a, b)``) or ``"min"``
        (``minimum(a, b)``) on every leaf.  Count-based windows then run
        a flagless sliding fold with half the operand traffic AND, under
        the default ``rank_scatter`` grouping with ``withMaxKeys <=
        4096`` (the bound on the rank table), skip the batch permutation
        entirely — lifts scatter-combine straight into pane cells (for
        "sum", float rounding order may differ from the sequential fold,
        exactly as under psum; max/min are idempotent, so results are
        identical).  Time-based windows gain even more: a TB tuple's
        pane cell is pure timestamp arithmetic, so placement needs no
        grouping at all and the whole sort/segmented-scan machinery
        disappears.  The declaration must match the combiner EXACTLY on
        every leaf — declaring the wrong kind silently computes the
        declared operation instead of the combiner's.  Reference anchor:
        the CUDA FFAT pays its sort/tree for every combiner alike
        (``ffat_replica_gpu.hpp:751,917``); declared monoids are the
        TPU-side win for the common aggregates (sum/count/avg via sum,
        max, min)."""
        self._monoid = kind
        return self

    def withPaneCapacity(self, n: int):
        """TB only: length of the on-device pane ring (window span panes
        plus slack for the time spread of in-flight batches; default
        ``max(2*R, R+64)``)."""
        self._pane_capacity = int(n)
        return self

    def withOverflowPolicy(self, policy: str):
        """TB ring-overflow behavior: ``"drop"`` (default — suppress windows
        that lost data panes, count them in Windows_dropped_on_overflow),
        ``"count"`` (fire them over surviving panes only; wrong aggregates,
        surfaced via Pane_cells_evicted), or ``"error"`` (raise at the next
        host checkpoint)."""
        self._overflow_policy = policy
        return self

    def build(self) -> FfatWindowsTPU:
        return FfatWindowsTPU(
            self._lift, self._comb, self._spec(), max_keys=self._max_keys,
            name=self._name,
            parallelism=self._parallelism,
            key_extractor=self._key_extractor,
            pane_capacity=self._pane_capacity,
            overflow_policy=self._overflow_policy,
            monoid=self._monoid)
