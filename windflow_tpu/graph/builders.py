"""Fluent operator builders (reference ``/root/reference/wf/builders.hpp:57-127``
and the GPU variants in ``builders_gpu.hpp:54-673``).

Method names keep the reference's camelCase (``withParallelism``,
``withKeyBy``, ``withOutputBatchSize``) so a WindFlow user can transliterate
their program; TPU builders mirror the ``*GPU_Builder`` family.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from windflow_tpu.basic import RoutingMode, WindFlowError
from windflow_tpu.ops.filter_op import Filter
from windflow_tpu.ops.flatmap_op import FlatMap
from windflow_tpu.ops.map_op import Map
from windflow_tpu.ops.reduce_op import Reduce
from windflow_tpu.ops.sink import Sink
from windflow_tpu.ops.source import Source
from windflow_tpu.ops.tpu import FilterTPU, MapTPU, ReduceTPU


class _BuilderBase:
    _default_name = "op"

    def __init__(self) -> None:
        self._name = self._default_name
        self._parallelism = 1
        self._output_batch_size = 0
        self._key_extractor: Optional[Callable] = None

    def withName(self, name: str):
        self._name = name
        return self

    def withParallelism(self, parallelism: int):
        self._parallelism = parallelism
        return self

    def withOutputBatchSize(self, size: int):
        self._output_batch_size = size
        return self

    def withKeyBy(self, key_extractor: Callable[[Any], Any]):
        self._key_extractor = key_extractor
        return self

    def _routing(self) -> RoutingMode:
        return (RoutingMode.KEYBY if self._key_extractor is not None
                else RoutingMode.FORWARD)


class Source_Builder(_BuilderBase):
    _default_name = "source"

    def __init__(self, gen_fn: Callable) -> None:
        super().__init__()
        self._gen_fn = gen_fn
        self._ts_extractor = None

    def withTimestampExtractor(self, fn: Callable[[Any], int]):
        """EVENT-time sources: extract the event timestamp (µs) from each
        generated item (reference: ``Source_Shipper::pushWithTimestamp``)."""
        self._ts_extractor = fn
        return self

    def withKeyBy(self, *_):
        raise WindFlowError("a Source has no input to key by")

    def build(self) -> Source:
        return Source(self._gen_fn, name=self._name,
                      parallelism=self._parallelism,
                      output_batch_size=self._output_batch_size,
                      ts_extractor=self._ts_extractor)


class Map_Builder(_BuilderBase):
    _default_name = "map"

    def __init__(self, fn: Callable) -> None:
        super().__init__()
        self._fn = fn

    def build(self) -> Map:
        return Map(self._fn, name=self._name, parallelism=self._parallelism,
                   routing=self._routing(),
                   output_batch_size=self._output_batch_size,
                   key_extractor=self._key_extractor)


class Filter_Builder(_BuilderBase):
    _default_name = "filter"

    def __init__(self, fn: Callable) -> None:
        super().__init__()
        self._fn = fn

    def build(self) -> Filter:
        return Filter(self._fn, name=self._name,
                      parallelism=self._parallelism,
                      routing=self._routing(),
                      output_batch_size=self._output_batch_size,
                      key_extractor=self._key_extractor)


class FlatMap_Builder(_BuilderBase):
    _default_name = "flatmap"

    def __init__(self, fn: Callable) -> None:
        super().__init__()
        self._fn = fn

    def build(self) -> FlatMap:
        return FlatMap(self._fn, name=self._name,
                       parallelism=self._parallelism,
                       routing=self._routing(),
                       output_batch_size=self._output_batch_size,
                       key_extractor=self._key_extractor)


class Reduce_Builder(_BuilderBase):
    _default_name = "reduce"

    def __init__(self, fn: Callable, initial_state: Any) -> None:
        super().__init__()
        self._fn = fn
        self._initial_state = initial_state

    def build(self) -> Reduce:
        return Reduce(self._fn, self._initial_state, name=self._name,
                      parallelism=self._parallelism,
                      key_extractor=self._key_extractor,
                      output_batch_size=self._output_batch_size)


class Sink_Builder(_BuilderBase):
    _default_name = "sink"

    def __init__(self, fn: Callable) -> None:
        super().__init__()
        self._fn = fn

    def build(self) -> Sink:
        return Sink(self._fn, name=self._name, parallelism=self._parallelism,
                    routing=self._routing(),
                    key_extractor=self._key_extractor)


# ---------------------------------------------------------------------------
# TPU builders (reference MapGPU_Builder / FilterGPU_Builder /
# ReduceGPU_Builder, builders_gpu.hpp:54-673)
# ---------------------------------------------------------------------------

class MapTPU_Builder(_BuilderBase):
    _default_name = "map_tpu"

    def __init__(self, fn: Callable, batch_fn: bool = False) -> None:
        super().__init__()
        self._fn = fn
        self._batch_fn = batch_fn

    def build(self) -> MapTPU:
        return MapTPU(self._fn, name=self._name,
                      parallelism=self._parallelism,
                      batch_fn=self._batch_fn, routing=self._routing(),
                      key_extractor=self._key_extractor)


class FilterTPU_Builder(_BuilderBase):
    _default_name = "filter_tpu"

    def __init__(self, fn: Callable) -> None:
        super().__init__()
        self._fn = fn

    def build(self) -> FilterTPU:
        return FilterTPU(self._fn, name=self._name,
                         parallelism=self._parallelism,
                         routing=self._routing(),
                         key_extractor=self._key_extractor)


class ReduceTPU_Builder(_BuilderBase):
    _default_name = "reduce_tpu"

    def __init__(self, comb: Callable) -> None:
        super().__init__()
        self._comb = comb

    def build(self) -> ReduceTPU:
        return ReduceTPU(self._comb, name=self._name,
                         parallelism=self._parallelism,
                         key_extractor=self._key_extractor)
