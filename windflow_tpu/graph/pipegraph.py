"""PipeGraph: application container, wiring, and the host driver loop.

Re-design of the reference ``PipeGraph`` (``/root/reference/wf/pipegraph.hpp``).
``run()`` in the reference spawns one OS thread per replica/collector through
FastFlow (``pipegraph.hpp:614-697``); here it wires replica inboxes, emitters
and collectors, then drives everything from a **single cooperative dispatch
loop**.  On TPU the host's only job is to keep compiled programs and transfers
enqueued — JAX dispatch is asynchronous, so while the device crunches batch N
the loop is already staging N+1; thread-per-replica would add contention, not
parallelism (SURVEY.md §7 design stance; and see parallel/mesh.py for how
replication maps to chips instead).

Host-heavy pipelines are the exception: window engines, FlatMaps, sink
serializers all share the driver thread, capping a CPU-operator pipeline at
one core where the reference scales thread-per-replica
(``basic_operator.hpp:54``).  ``Config.host_worker_threads > 0`` restores
that capability with a worker pool: each sweep, host replicas with pending
input drain concurrently (one task per replica, so per-replica processing
stays serial and keyed routing still pins a key to one replica); sources and
TPU replicas stay on the driver thread.  GIL-releasing host work (numpy,
native calls) then scales across cores; see ``bench_host.py``.

End of run mirrors ``PipeGraph::wait_end`` (``pipegraph.hpp:703-768``): EOS
punctuations cascade, window state flushes, and per-operator stats JSON is
dumped when tracing is enabled.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import List, Optional

from windflow_tpu.basic import (Config, ExecutionMode, RoutingMode,
                                TimePolicy, WindFlowError,
                                current_time_usecs, default_config)
from windflow_tpu.graph.multipipe import MultiPipe
from windflow_tpu.ops.base import Operator
from windflow_tpu.ops.source import Source, SourceReplica
from windflow_tpu.parallel.collectors import create_collector
from windflow_tpu.parallel.emitters import SplittingEmitter, create_emitter


def _staging_pool_stats() -> dict:
    """Hit/miss counters of the process-wide staging-buffer recycling pool
    (windflow_tpu/staging), surfaced through the monitoring stats dump."""
    from windflow_tpu import staging
    return staging.default_pool().stats()


def _calibration_summary() -> dict:
    """Provenance frame of every modeled constant (monitoring/
    calibration.py), for dump_trace metadata and the postmortem's
    calibration.json — guarded like every other telemetry read."""
    try:
        from windflow_tpu.monitoring import calibration
        return calibration.provenance_summary()
    except Exception as e:  # lint: broad-except-ok (a provenance read
        # must never take a trace dump or postmortem down)
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _rss_kb() -> float:
    """Resident set size in KiB (reference ``get_MemUsage``,
    ``monitoring.hpp:52-70``)."""
    try:
        with open("/proc/self/statm") as f:
            resident_pages = int(f.read().split()[1])
        return resident_pages * (os.sysconf("SC_PAGE_SIZE") / 1024.0)
    except (OSError, ValueError, IndexError):
        return 0.0


class PipeGraph:
    def __init__(self, name: str = "app",
                 mode: ExecutionMode = ExecutionMode.DEFAULT,
                 time_policy: TimePolicy = TimePolicy.INGRESS,
                 config: Optional[Config] = None) -> None:
        self.name = name
        self.mode = mode
        self.time_policy = time_policy
        self.config = config or dataclasses.replace(default_config)
        self.pipes: List[MultiPipe] = []
        self._splits: List[MultiPipe] = []
        self._merges: List[MultiPipe] = []
        self._started = False
        self._collectors = []
        self._all_replicas = []
        self._source_replicas: List[SourceReplica] = []
        self._operators: List[Operator] = []
        self._monitor = None
        # backpressure telemetry (high-water marks + throttle count)
        self._throttle_events = 0
        self._max_inbox_seen = 0
        self._max_inflight_device_seen = 0
        # staging-plane lookahead telemetry (Config.stage_prefetch_depth)
        self._prefetch_ticks = 0
        # flight recorder (monitoring/recorder.py): built in _build when
        # Config.flight_recorder is on; None means every hook is inert
        self._recorder = None
        # health plane (monitoring/health.py): watchdog built in _build
        # when Config.health_watchdog is on; None means every call site
        # is one flag check (the documented off-path)
        self._health = None
        # sweep ledger (monitoring/sweep_ledger.py): per-hop dispatch/HBM
        # attribution built in _build when Config.sweep_ledger is on;
        # None leaves one `is not None` check at each read site (stats,
        # trace metadata, postmortem) — nothing on the per-batch path
        self._ledger = None
        # shard plane (monitoring/shard_ledger.py): per-shard load/ICI
        # attribution + key-skew sketches on the keyed edges, built in
        # _build when Config.shard_ledger is on; None leaves one
        # `is not None` check at each read site and attaches no sketch
        # anywhere (the per-batch paths then carry one check each)
        self._shard = None
        # whole-chain fusion (windflow_tpu/fusion): the executable fused
        # segments installed by _build when Config.whole_chain_fusion is
        # on — each routes a whole operator chain as ONE jitted dispatch
        # per batch.  Read by the wiring redirect below, the sweep
        # ledger's fusion section, and stats attribution; empty means
        # every hop dispatches its own program (the pre-fusion sweep).
        self._fused_segments = []
        # durability plane (windflow_tpu/durability): epoch checkpoints +
        # restore, built in _build when Config.durability names a
        # directory; None leaves one `is None` check per sweep (the
        # documented off-path, micro-asserted like health/ledger)
        self._durability = None
        # reshard executor (windflow_tpu/serving): applies the shard
        # plane's move_keys/split_hot_key plans live, built in _build
        # when Config.reshard_executor is on (default OFF: unlike the
        # observe-only planes, this one mutates routing); None leaves
        # one `is not None` check per sweep + one per source tick chunk
        self._reshard = None
        # megastep plane (windflow_tpu/megastep.py): K batch sweeps per
        # compiled program on the eligible staged edges, built in _build
        # when Config.megastep_sweeps resolves to K>1; None/inactive
        # leaves the per-batch cadence verbatim (one check per finalize
        # on the staging emitters, nothing anywhere else)
        self._megastep_plane = None
        # latency ledger (monitoring/latency_ledger.py): per-batch
        # critical-path decomposition + SLO verdicts, built in _build
        # when Config.latency_ledger AND the flight recorder are on;
        # None leaves one `is not None` check at each cadence/read site
        # and binds nothing to any replica (micro-asserted)
        self._latency = None
        # tenant plane (monitoring/tenant_ledger.py): this graph's handle
        # into the PROCESS-level tenant ledger — per-tenant HBM/dispatch/
        # byte attribution + budget verdicts across every co-resident
        # PipeGraph, built in _build when Config.tenant_ledger is on;
        # None leaves one `is not None` check at each cadence/read site
        # and registers nothing anywhere (micro-asserted)
        self._tenant = None
        # roofline plane (monitoring/calibration.RooflineLedger): the
        # live achieved-vs-roofline gauge + the advisory
        # ROOFLINE_DEGRADED verdict, built in _build when
        # Config.roofline_plane is on; None leaves one `is not None`
        # check at each cadence/read site and reads no counter anywhere
        # (micro-asserted by tests/test_calibration.py)
        self._roofline = None
        # checkpoint blobs stashed by restore() for the plane to apply
        # after _build (operator state) and before the first source tick
        self._pending_restore = None
        # last postmortem bundle written (crash path or dump_postmortem);
        # the lock serializes writers — the monitor thread's watchdog
        # auto-bundle and the driver's stall/crash path may race into
        # the same directory
        self._postmortem_dir = None
        self._postmortem_lock = threading.Lock()
        # rolling-throughput gauge samples: (wall_s, tuples_sunk_total),
        # appended by sample_gauges() (the monitoring thread calls it once
        # per second; stats() also samples so headless runs get gauges)
        self._thr_samples = deque(maxlen=64)
        # host worker pool (Config.host_worker_threads): replicas drained
        # off the driver thread, and the driver-thread remainder
        self._pool = None
        self._pool_replicas = []
        self._main_replicas = []
        # pre-flight analysis (windflow_tpu/analysis): last check()'s
        # diagnostics + wall cost, surfaced through stats() and bench.py
        self._preflight_diags = None
        self._preflight_ms = None
        # wfverify (analysis/tracecheck.py): the object-level verifier's
        # last report (diagnostics folded into _preflight_diags; the
        # report keeps the suppressed findings and per-callable counts)
        self._tracecheck_report = None
        # wfir (analysis/ir_audit.py): the IR auditor's last report —
        # WF9xx findings over the lowered StableHLO of this graph's
        # programs (check() stores it; stats()/postmortem re-audit live)
        self._ir_audit_report = None
        # profiler bridge: directory the last profile() capture actually
        # landed in, so dump_trace()'s cross-reference points at a real
        # capture even when profile(log_dir=...) overrode the config
        self._last_profile_dir = None

    # -- construction --------------------------------------------------------
    def add_source(self, source: Source) -> MultiPipe:
        if self._started:
            raise WindFlowError("cannot add sources to a running PipeGraph")
        mp = MultiPipe(self, source)
        self.pipes.append(mp)
        return mp

    def _register_split(self, mp: MultiPipe) -> None:
        self._splits.append(mp)

    def _register_merge(self, mp: MultiPipe) -> None:
        self._merges.append(mp)
        self.pipes.append(mp)

    # -- wiring --------------------------------------------------------------
    def _all_pipes(self):
        """Every MultiPipe in the graph, including transitive split branches
        (the single traversal used by both replica construction and edge
        wiring, so the two can never diverge)."""
        out = []

        def collect(mp: MultiPipe):
            out.append(mp)
            for child in mp.split_children:
                collect(child)

        for mp in self.pipes:
            collect(mp)
        return out

    def _check_fixed_capacity_ops(self):
        """Fixed-capacity device operators (``Operator.fixed_capacity_label``
        is set: FfatWindowsTPU pane state, StatefulMap/FilterTPU slot
        tables, dense-key ReduceTPU cross-chip tables — each compiles a
        state layout tied to ONE batch capacity) fed by several upstream
        paths — a merge relayed through capacity-preserving TPU stages —
        must see ONE capacity; surface the mismatch at build time with the
        offending sizes instead of a mid-run step error.  (Backstop for
        ``Config.preflight="off"`` runs: the walk itself lives in
        analysis/preflight.py, where :meth:`check` reports it as WF403.)"""
        from windflow_tpu.analysis.preflight import capacity_conflicts
        for op, label, caps in capacity_conflicts(self):
            raise WindFlowError(
                f"'{op.name}' ({label}) compiles for one "
                f"fixed batch capacity but its upstream paths "
                f"deliver {sorted(caps)}; give the merged branches "
                "equal withOutputBatchSize")

    def _edges(self):
        """Yield (src_op, dst_op_or_split, routing) for every graph edge, in
        topological order of the MultiPipe DAG."""
        edges = []
        for mp in self._all_pipes():
            ops = mp.operators
            for a, b in zip(ops, ops[1:]):
                edges.append(("op", a, b))
            if mp.split_children:
                edges.append(("split", mp))
        for merged in self._merges:
            for parent in merged.merge_parents:
                src = parent.operators[-1] if parent.operators else None
                if src is None:
                    raise WindFlowError("cannot merge an empty MultiPipe")
                edges.append(("op", src, merged.operators[0]))
        return edges

    def _topo_operators(self):
        """Every distinct operator in _build's enumeration order — the
        ordinal space checkpoint manifests pin, factored out so restore
        can validate a composed-but-unbuilt graph against a manifest
        (durability/checkpoint.topology_signature) without the two
        traversals ever diverging."""
        seen, out = set(), []
        for mp in self._all_pipes():
            for op in mp.operators:
                if id(op) not in seen:
                    seen.add(id(op))
                    out.append(op)
        return out

    def _build(self) -> None:
        # 1. instantiate replicas
        for op in self._topo_operators():
            op.ordinal = len(self._operators)  # stable topo index
            self._operators.append(op)
            op.mesh = self.config.mesh
            op.config = self.config
            op.build_replicas(self.mode, self.time_policy)
        for op in self._operators:
            self._all_replicas.extend(op.replicas)
            if isinstance(op, Source):
                self._source_replicas.extend(op.replicas)
        for rep in self._all_replicas:
            rep.config = self.config
        if getattr(self.config, "preflight", "error") == "off":
            # preflight reported capacity conflicts already (WF403: raised
            # under "error", warned under "warn" — the promised bypass);
            # only an "off" run needs the original hard build-time check
            self._check_fixed_capacity_ops()

        # 1a. key-aligned mesh ingest (ROADMAP item 4b): stamp eligible
        # host-fed key-sharded FFAT consumers BEFORE wiring — the
        # emitter dispatch (create_emitter) and the op's sharded step
        # factory both read the stamp (parallel/mesh.mark_aligned_ingest)
        if self.config.mesh is not None \
                and getattr(self.config, "key_aligned_ingest", True):
            from windflow_tpu.parallel.mesh import mark_aligned_ingest
            mark_aligned_ingest(self)

        # 1b. whole-chain fusion (windflow_tpu/fusion): executable fused
        # segments lower into ONE program per batch — installed BEFORE
        # wiring so the redirect below can route each segment as one hop.
        # Preflight already ran (start() order), so the chains were
        # type-checked as their constituent specs.  Skipped on a mesh:
        # the sharded program factories compose differently.
        from windflow_tpu.fusion import executor as _fusion
        if getattr(self.config, "whole_chain_fusion", True) \
                and self.config.mesh is None:
            self._fused_segments = _fusion.apply_fusion(self)
        fused_host = {}         # id(segment head/member) -> host op
        fused_edge_skip = set()  # interior (src, dst) id pairs
        for seg in self._fused_segments:
            members = seg["members"]
            for m in members[:-1]:
                fused_host[id(m)] = members[-1]
            for fa, fb in zip(members, members[1:]):
                fused_edge_skip.add((id(fa), id(fb)))

        # 2. wire edges: emitters on sources of the edge, collectors +
        #    channels on destinations.  ``route_op`` carries the edge's
        #    routing contract; ``dst_op`` owns the consuming replicas —
        #    they differ exactly when a fused segment's head hands its
        #    edge to the segment host.
        def wire_edge(src_op: Operator, route_op: Operator,
                      dst_op: Operator):
            emitters = []
            for src_rep in src_op.replicas:
                dests = [(dst_rep, dst_rep.add_channel())
                         for dst_rep in dst_op.replicas]
                em = create_emitter(
                    route_op.routing, dests, src_op.output_batch_size,
                    src_is_tpu=src_op.is_tpu, dst_is_tpu=dst_op.is_tpu,
                    key_extractor=route_op.key_extractor,
                    mesh=self.config.mesh)
                emitters.append(em)
            return emitters

        # downstream-keyby key forwarding (fusion satellite): a chain op
        # feeding exactly one KEYBY device consumer extracts that
        # consumer's keys INSIDE its own program and ships them on the
        # batch's keys lane, so the consumer (or its keyby emitter)
        # never re-extracts — collected while wiring, applied after
        fanout = {}
        key_forward = {}
        for edge in self._edges():
            if edge[0] == "op":
                fanout[id(edge[1])] = fanout.get(id(edge[1]), 0) + 1
            else:
                src = edge[1].operators[-1]
                fanout[id(src)] = fanout.get(id(src), 0) \
                    + len(edge[1].split_children)

        def note_key_forward(a, route_op):
            # skipped when the CONSUMER is a fused-segment head too: the
            # segment host re-extracts in-program (its prelude forces
            # keys=None), so a forwarded lane would be computed per
            # batch and provably discarded
            if route_op.routing == RoutingMode.KEYBY \
                    and route_op.is_tpu \
                    and route_op.key_extractor is not None \
                    and fanout.get(id(a)) == 1 \
                    and id(a) not in fused_host \
                    and id(route_op) not in fused_host:
                key_forward[id(a)] = (a, route_op.key_extractor)

        for edge in self._edges():
            if edge[0] == "op":
                _, a, b = edge
                if (id(a), id(b)) in fused_edge_skip:
                    continue    # interior to a fused segment: no hop
                tgt = fused_host.get(id(b), b)
                note_key_forward(a, b)
                for rep, em in zip(a.replicas, wire_edge(a, b, tgt)):
                    rep.emitter = em
            else:  # split point
                _, mp = edge
                src_op = mp.operators[-1]
                branch_heads = [child.operators[0]
                                for child in mp.split_children]
                per_src_branch_emitters = [
                    wire_edge(src_op, head,
                              fused_host.get(id(head), head))
                    for head in branch_heads]
                # transpose: one SplittingEmitter per source replica
                for i, rep in enumerate(src_op.replicas):
                    branches = [per_src_branch_emitters[b_idx][i]
                                for b_idx in range(len(branch_heads))]
                    rep.emitter = SplittingEmitter(mp.split_fn, branches)

        # 2b. apply the collected key forwards + safe input donation on
        # chain programs (see ops/chained.py; fusion hosts donate through
        # their own program build).  Donation is independent of the
        # fusion flag: the chained-pair step's donation misses exist on
        # un-fused sweeps too (sweep-ledger tripwire).
        from windflow_tpu.ops.chained import ChainedTPU
        upstreams = _fusion._upstream_edges(self)
        for a, kx in key_forward.values():
            if a._fusion_exec is not None:
                a._fusion_exec.set_downstream_key_extractor(kx)
            elif isinstance(a, ChainedTPU):
                a.set_downstream_key_extractor(kx)
        for op in self._operators:
            if isinstance(op, ChainedTPU) and id(op) not in fused_host \
                    and op._fusion_exec is None \
                    and _fusion.input_donation_safe(op, upstreams):
                op.enable_input_donation()

        # 2c. fused-segment members are inert: their replicas receive no
        # channels (interior edges skipped above) and never terminate
        # through the EOS cascade — mark them done so is_done() and the
        # watchdog read them as cleanly terminated; their stats are
        # attributed from the fused hop at read time (stats()).
        for seg in self._fused_segments:
            for m in seg["members"][:-1]:
                for rep in m.replicas:
                    rep.done = True
                    rep.stats.is_terminated = True

        # 3. collectors: one per replica with input channels
        for rep in self._all_replicas:
            if rep.num_channels > 0:
                rep.collector = create_collector(self.mode, rep.num_channels)
                self._collectors.append(rep.collector)

        # 3b. observability: the flight recorder's per-replica rings and
        # the emitters' stats/ring/flight binding (monitoring/recorder.py).
        # Transfer byte counters are bound even with the recorder off —
        # they are plain integer adds, and the H2D/D2H totals must be real
        # on every run (stats_record.hpp:152-160 parity).
        cfg = self.config
        if cfg.flight_recorder and cfg.trace_sample_every > 0:
            from windflow_tpu.monitoring.recorder import FlightRecorder
            self._recorder = FlightRecorder(
                sample_every=cfg.trace_sample_every,
                ring_events=cfg.trace_ring_events,
                device_sync_every=cfg.trace_device_sync_every,
                expected_rings=len(self._all_replicas))
            for rep in self._all_replicas:
                rep.ring = self._recorder.ring_for(rep.op.name, rep.index)
        for rep in self._all_replicas:
            if rep.emitter is not None:
                rep.emitter.bind_observability(rep.stats, rep.ring,
                                               self._recorder)

        # 3c. health plane (monitoring/health.py): per-operator watchdog
        # evaluated at monitor cadence — built here so the operator list
        # is final; off leaves _health None (one flag check per call site)
        if cfg.health_watchdog:
            from windflow_tpu.monitoring.health import HealthPlane
            self._health = HealthPlane(self)

        # 3d'. durability plane (windflow_tpu/durability): built after
        # replicas exist so it can switch Kafka sink replicas to fenced
        # exactly-once buffering; checkpoints run at sweep cadence from
        # step(), restore state is applied by start() before the first
        # source tick
        if cfg.durability:
            from windflow_tpu.durability.checkpoint import DurabilityPlane
            self._durability = DurabilityPlane(self)

        # 3d. sweep ledger (monitoring/sweep_ledger.py): built AFTER the
        # operator list is final and BEFORE any batch runs, so its
        # registry baseline excludes every earlier graph's dispatches in
        # this process while capturing all of this one's
        if cfg.sweep_ledger:
            from windflow_tpu.monitoring.sweep_ledger import SweepLedger
            self._ledger = SweepLedger(self)

        # 3e. shard plane (monitoring/shard_ledger.py): built AFTER
        # wiring and fusion (it attaches key-skew sketches to the keyed
        # emitters and folds the in-program updates into the keyby
        # split / fused-chain programs, all of which must exist and
        # none of which may have compiled yet)
        if getattr(cfg, "shard_ledger", True):
            from windflow_tpu.monitoring.shard_ledger import ShardLedger
            self._shard = ShardLedger(self)

        # 3f. key compaction (parallel/compaction.py): attach remap
        # tables to qualifying keyed consumers and wire the feeding
        # emitters for host admission / placement override — AFTER
        # fusion (preludes installed, fused hosts known) and the shard
        # plane (sketches exist to seed from), before anything compiles.
        # Off attaches nothing: every step keeps one `is not None` check.
        if getattr(cfg, "key_compaction", True):
            from windflow_tpu.parallel.compaction import attach_compaction
            attach_compaction(self)

        # 3f'. wire plane (windflow_tpu/wire.py): enable columnar wire
        # compression on the staging emitters whose feeding edge has a
        # declared/inferred record spec — AFTER wiring (the emitters
        # exist) and before anything stages.  Spec-less edges stay raw
        # passthrough (preflight named them as WF606); off/auto-on-CPU
        # attaches no encoder anywhere.
        from windflow_tpu.wire import attach_wire, wire_enabled
        if wire_enabled(cfg):
            attach_wire(self)

        # 3f''. megastep plane (windflow_tpu/megastep.py): hook the
        # eligible staged edges so K consecutive batch sweeps fold into
        # ONE lax.scan dispatch — built AFTER fusion (the tail may be a
        # fused segment host) and the wire plane (the scan body inlines
        # the same wire decode the per-batch unpack runs), before
        # anything stages.  The durability epoch cadence converts here
        # from logical sweeps to K-granular driver sweeps (whole
        # megasteps), so every commit's quiesce lands between megasteps
        # and each epoch covers the stream extent it covered per-batch.
        from windflow_tpu.megastep import (attach_plane,
                                           round_epoch_to_megastep)
        self._megastep_plane = attach_plane(cfg, self._source_replicas)
        round_epoch_to_megastep(cfg, self._megastep_plane)

        # 3f'''. latency ledger (monitoring/latency_ledger.py): per-batch
        # critical-path decomposition of the recorder's span lane + the
        # SLO verdict state machine — built AFTER the recorder (it
        # harvests the rings at cadence) and the megastep plane (the
        # per-edge K and freshness floor feed the verdict/advisor).
        # Window replicas get the ledger bound for the fire-freshness
        # gauge at their existing sampled-sync site; everything else
        # keeps `latency = None` (one check, micro-asserted).
        if getattr(cfg, "latency_ledger", True) \
                and self._recorder is not None:
            from windflow_tpu.monitoring.latency_ledger import LatencyLedger
            from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU
            self._latency = LatencyLedger(
                self._recorder,
                slo_ms=getattr(cfg, "latency_slo_ms", 0.0) or 0.0)
            self._latency.megastep_plane = self._megastep_plane
            for op in self._operators:
                if isinstance(op, FfatWindowsTPU):
                    for rep in op.replicas:
                        rep.latency = self._latency
            if self._health is not None:
                self._health.latency = self._latency

        # 3f''''. tenant plane (monitoring/tenant_ledger.py): register
        # this graph with the PROCESS-level tenant ledger — built AFTER
        # every other plane (attribution baselines must see the final
        # operator/wrapper set, and the ledger reads the shard/latency
        # planes at collect cadence).  Config.tenant defaults to the app
        # name; Config.hbm_budget_bytes > 0 arms the budget state
        # machine whose latched OVER_BUDGET verdict the health plane
        # paints on the tenant's heaviest op.
        if getattr(cfg, "tenant_ledger", True):
            from windflow_tpu.monitoring.tenant_ledger import default_ledger
            tenant = getattr(cfg, "tenant", "") or self.name
            self._tenant = default_ledger().register(
                self, tenant, getattr(cfg, "hbm_budget_bytes", 0))
            if self._health is not None:
                self._health.tenant = self._tenant

        # 3f'''''. calibration store + roofline plane (monitoring/
        # calibration.py): Config.calibration installs the probe-measured
        # constants process-wide (the shard ICI model, the tenant
        # ledger, gap_diagnosis, and the roofline ceiling all read
        # through calibration.constant — their provenance tags flip
        # `modeled` → `calibrated(<age>)`), and the RooflineLedger turns
        # the replicas' existing throughput counters into the live
        # achieved-vs-roofline gauge at monitor cadence.  Built after
        # the sweep/tenant planes (the bytes join reads the sweep
        # section) and before the reshard executor.
        from windflow_tpu.monitoring import calibration as _calib
        if getattr(cfg, "calibration", "") and not _calib.killed():
            try:
                _calib.set_default_store(_calib.load(cfg.calibration))
            except Exception as e:  # lint: broad-except-ok (a corrupt
                # store must degrade the process to its modeled
                # defaults with a warning, never fail graph build)
                import warnings as _w
                _w.warn(f"Config.calibration={cfg.calibration!r} failed "
                        f"to load ({e}) — running uncalibrated",
                        RuntimeWarning)
        if getattr(cfg, "roofline_plane", True):
            self._roofline = _calib.RooflineLedger(self)
            if self._health is not None:
                self._health.roofline = self._roofline

        # 3g. reshard executor (windflow_tpu/serving): built LAST — it
        # discovers the keyed emitters the wiring installed, reads the
        # health plane and shard ledger at tick cadence, and mutates
        # routing only through the quiesce barrier.  Mesh graphs are
        # not executor targets (their reshard mechanism is the rescale
        # restore, docs/DURABILITY.md); replica-sharded keyed operators
        # are.
        if getattr(cfg, "reshard_executor", False) \
                and self.config.mesh is None:
            from windflow_tpu.serving import ReshardExecutor
            self._reshard = ReshardExecutor(self)

        # sanity: every non-sink replica must have an emitter (fused
        # members are inert by design — the segment host emits for them)
        for op in self._operators:
            if op._fused_into is not None:
                continue
            for rep in op.replicas:
                if rep.emitter is None and not op.is_terminal:
                    raise WindFlowError(
                        f"operator '{op.name}' has no downstream consumer — "
                        "every MultiPipe must end in a Sink")

        # 4. host worker pool partition: host (non-source, pool-safe)
        #    replicas drain concurrently; sources tick on the driver thread
        #    and TPU replicas stay there too (stateful device operators
        #    share state across replicas, serialized by construction —
        #    the role of the reference's spinlock, map_gpu.hpp:114-115)
        if self.config.host_worker_threads > 0:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=self.config.host_worker_threads,
                thread_name_prefix=f"wf-{self.name}")
            for op in self._operators:
                pooled = (not op.is_tpu and op.host_pool_safe
                          and not isinstance(op, Source))
                (self._pool_replicas if pooled
                 else self._main_replicas).extend(op.replicas)
        else:
            self._main_replicas = self._all_replicas

    # -- execution -----------------------------------------------------------
    def run(self) -> "PipeGraph":
        """Build, then drive the whole graph to completion — the
        reference's ``run()`` (``start()`` + ``wait_end()``,
        ``pipegraph.hpp:614-697``); both halves are also public so the
        reference idiom ``g.start(); ...; g.wait_end()`` transliterates."""
        self.start()
        return self.wait_end()

    def wait_end(self) -> "PipeGraph":
        """Drive a started graph to completion (reference
        ``PipeGraph::wait_end``, ``pipegraph.hpp:703-768``); a streaming
        deployment would call :meth:`step` from its own loop instead."""
        if not self._started:
            raise WindFlowError("wait_end before start")
        try:
            while not self.is_done():
                if not self.step():
                    raise self._stall_error()
        except BaseException as exc:
            # Crash path: salvage the telemetry FIRST (health attribution
            # + postmortem bundle — the rings/histograms/jit tables are
            # most valuable exactly now), then release threads.  Do NOT
            # dump stats: a stats dump touching a dead backend would raise
            # inside the handler and mask the root-cause operator error;
            # the postmortem writer guards every section individually.
            try:
                if self._health is not None:
                    # the synthetic stall error has no replica frame in
                    # its traceback, so attribution is a no-op for it; a
                    # genuine replica-raised WindFlowError attributes
                    # like any crash
                    self._health.note_failure(exc)
                self._write_crash_postmortem(exc)
            except BaseException:  # lint: broad-except-ok (salvage must
                # never mask the root-cause error re-raised below — a
                # second Ctrl-C here aborts the salvage, not the teardown)
                pass
            finally:
                self._finalize(dump=False, aborted=True)
            raise
        self._finalize()
        return self

    def _stall_error(self) -> WindFlowError:
        """Build the stall error with the health plane's root-cause
        diagnosis (per-op queue depth, frontier, last-advance age) —
        "routing bug?" told the user nothing.  Also writes the postmortem
        bundle (watchdog-confirmed stall) so the message can point at it."""
        head = ("PipeGraph stalled: no replica made progress but the "
                "graph has not terminated. ")
        if self._health is None:
            return WindFlowError(
                head + "Health watchdog is off (Config.health_watchdog / "
                "WF_TPU_HEALTH=0) — no diagnosis available; re-run with "
                "it on for root-cause attribution")
        try:
            diag = self._health.diagnose_stall()
            msg = head + self._health.format_diagnosis(diag)
        except Exception as e:  # lint: broad-except-ok (same stance as
            # every other health read: a watchdog bug must not replace
            # the stall error — an undiagnosed stall beats a KeyError)
            msg = head + (f"(health diagnosis failed: "
                          f"{type(e).__name__}: {e}"[:200] + ")")
        err = WindFlowError(msg)
        if self.config.health_postmortem_on_crash:
            # always dump a fresh frame here — a watchdog bundle written
            # minutes ago (possibly for a recovered transient stall) is
            # staler than the diagnosis just taken; the write is
            # serialized by the postmortem lock
            bundle = self._safe_postmortem("stall")
            if bundle:
                # mark THE exception as already bundled: the crash-path
                # handler keys off this, not graph state, so neither a
                # manual snapshot nor an old watchdog bundle can suppress
                # a genuine crash bundle later
                err._wf_postmortem_bundle = bundle
                err.args = (msg + f". Postmortem bundle: {bundle}",)
        return err

    def _write_crash_postmortem(self, exc: BaseException) -> None:
        """Best-effort bundle on abnormal termination.  Skipped only when
        THIS exception is the stall error whose bundle _stall_error just
        wrote — any other failure captures crash-time telemetry no matter
        what was bundled before."""
        if self.config.health_postmortem_on_crash \
                and getattr(exc, "_wf_postmortem_bundle", None) is None:
            self._safe_postmortem(f"crash: {type(exc).__name__}: "
                                  f"{exc}"[:300])

    def _safe_postmortem(self, reason: str) -> Optional[str]:
        try:
            return self.dump_postmortem(reason=reason)
        except Exception:  # lint: broad-except-ok (the postmortem writer
            # runs inside crash handlers; any failure here must never mask
            # the root-cause operator error being propagated)
            return None

    # -- static analysis (windflow_tpu/analysis) -----------------------------
    def check(self) -> list:
        """Pre-flight static analysis of the composed graph: abstract
        evaluation of every operator chain (``jax.eval_shape`` on the user
        kernels — zero device work), window-spec consistency, keyby/mesh
        shard-divisibility, and watermark-mode compatibility across
        merge/split points.  Returns the FULL list of
        :class:`~windflow_tpu.analysis.Diagnostic` findings (never just
        the first); ``start()`` runs it automatically under
        ``Config.preflight`` and ``tools/wf_check.py`` wraps it as a CLI."""
        from windflow_tpu.analysis.preflight import check_graph
        t0 = time.perf_counter()
        diags = check_graph(self)
        self._preflight_ms = round((time.perf_counter() - t0) * 1e3, 3)
        self._preflight_diags = diags
        return diags

    def _run_preflight(self) -> None:
        mode = getattr(self.config, "preflight", "error")
        if mode not in ("error", "warn", "off"):
            raise WindFlowError(
                f"Config.preflight must be 'error', 'warn' or 'off', "
                f"got {mode!r}")
        if mode == "off":
            return
        import warnings
        from windflow_tpu.analysis.diagnostics import (PreflightError,
                                                       PreflightWarning)
        diags = self.check()
        errors = [d for d in diags if d.severity == "error"]
        for d in diags:
            if d.severity != "error" or mode == "warn":
                warnings.warn(str(d), PreflightWarning, stacklevel=3)
        if errors and mode == "error":
            raise PreflightError(errors)

    def start(self) -> None:
        if self._started:
            raise WindFlowError("PipeGraph already started")
        self._run_preflight()
        self._started = True
        self._build()
        if self._durability is not None and self._pending_restore is not None:
            # restore(): apply the checkpointed operator/replica state
            # now — replicas and fusion preludes exist, no source has
            # ticked, the monitor has not sampled
            pending, self._pending_restore = self._pending_restore, None
            self._durability.apply_restore(pending)
        try:
            if self.config.tracing_enabled:
                # reference: tracing spawns a MonitoringThread at run()
                # (pipegraph.hpp:676-678)
                from windflow_tpu.monitoring.monitor import MonitoringThread
                self._monitor = MonitoringThread(self)
                self._monitor.start()
            for sr in self._source_replicas:
                sr.start()
        except BaseException:
            # _build() created the (non-daemon) worker pool; a failing
            # monitor/source start must not leak its threads.  Streaming
            # deployments that drive step() directly instead of wait_end()
            # carry the same duty: call _finalize(dump=False) when
            # abandoning a started graph on error.
            self._finalize(dump=False)
            raise

    def step(self) -> bool:
        """One scheduler sweep: pull a chunk from each live source (unless
        backpressured), then drain every replica in topological order.
        Returns True on any progress."""
        progress = False
        throttled = self._backpressured()
        if throttled:
            # Source ticks are deferred this sweep: downstream inboxes are at
            # the in-transit cap (reference: allocateBatch_GPU_t blocks on
            # FullGPUMemoryException, recycling_gpu.hpp:88-126).  Draining
            # below continues, so the graph keeps moving.
            self._throttle_events += 1
        for sr in self._source_replicas:
            if not sr.exhausted and not throttled:
                if sr.tick(self._tick_chunk(sr)):
                    progress = True
                # Cadence punctuation keeps watermarks advancing on idle
                # streams.  Skipped while throttled: a punctuation flushes
                # the emitter's open batch first (the watermark must never
                # overtake buffered data), which would ship a data batch
                # into inboxes already at the cap.  Under backpressure data
                # is in flight anyway, so watermarks advance with it.
                sr.maybe_punctuate()
        limit = self.config.sweep_drain_limit
        if self._pool is not None:
            # one task per replica-with-work: per-replica processing stays
            # serial (single consumer per inbox), cross-replica it runs on
            # the pool; the sweep barrier below keeps the topological
            # drain of the driver-thread replicas race-free
            futures = [self._pool.submit(rep.drain, limit)
                       for rep in self._pool_replicas if rep.inbox]
        for rep in self._main_replicas:
            if rep.drain(limit):
                progress = True
        if self._pool is not None:
            for f in futures:
                if f.result():
                    progress = True
        # Staging-plane prefetch (Config.stage_prefetch_depth): the drain
        # above only DISPATCHED device work (JAX dispatch is async), so the
        # host is idle while the chip crunches — use it to pack batch N+1
        # into the recycled staging buffers now (windflow_tpu/staging),
        # the driver-loop form of the reference's 2-deep pinned double
        # buffering.  Each pass re-checks the in-transit caps, so
        # lookahead never overruns backpressure; punctuation cadence stays
        # with the main tick pass.
        for _ in range(max(0, self.config.stage_prefetch_depth)):
            if self._backpressured():
                break
            ticked = False
            for sr in self._source_replicas:
                if not sr.exhausted and sr.tick(self._tick_chunk(sr)):
                    ticked = True
            if not ticked:
                break
            progress = True
            self._prefetch_ticks += 1
        if not progress:
            # Sources were deferred but nothing drained (e.g. limit=0 edge
            # cases): force one tick so the graph cannot deadlock on its own
            # throttle.
            for sr in self._source_replicas:
                if not sr.exhausted and sr.tick(self._tick_chunk(sr)):
                    progress = True
        if self._durability is not None:
            # epoch cadence (windflow_tpu/durability): counts sweeps and,
            # every Config.durability_epoch_sweeps-th, quiesces to the
            # aligned barrier and commits a checkpoint epoch.  Off-path
            # cost is exactly this one check (micro-asserted).  Under an
            # active megastep plane one driver sweep covers K logical
            # batch sweeps and this call site sits BETWEEN driver
            # sweeps, so every quiesce already lands between megasteps;
            # round_epoch_to_megastep converted the configured cadence
            # to driver sweeps at build.
            self._durability.on_sweep()
        if self._reshard is not None:
            # executor cadence (windflow_tpu/serving): one counter
            # compare per sweep; every Config.reshard_check_sweeps-th
            # it reads health + the shard plan and applies what fires.
            self._reshard.on_sweep()
        return progress

    def _tick_chunk(self, sr) -> int:
        chunk = self.config.source_tick_chunk \
            or sr.op.output_batch_size or 256
        plane = self._megastep_plane
        if plane is not None and plane.active \
                and getattr(sr.emitter, "_megastep", None) is not None:
            # K-granular pacing: pull K batches' worth per tick so the
            # staging emitter fills a whole megastep group each sweep
            # instead of parking K-1 sweeps' batches in the queue
            chunk *= plane.k
        if self._reshard is not None:
            # admission control (docs/OBSERVABILITY.md "Reshard
            # executor"): when no plan can help a degraded operator,
            # the source intake throttles instead of growing inboxes
            chunk = self._reshard.admit_chunk(chunk)
        return chunk

    def _backpressured(self) -> bool:
        """True when any replica inbox is at the in-transit cap.  Also folds
        the high-water marks reported by :meth:`stats`.

        The ``inflight_device``/``inbox`` reads are deliberately lock-free:
        pool threads mutate them under the replica's inflight lock, but
        CPython guarantees tear-free reads, so throttling sees an at most
        one-sweep-stale value — the cap is a soft bound, not an invariant,
        and taking K locks per sweep would serialize the pool on its
        hottest path."""
        cfg = self.config
        hit = False
        for rep in self._all_replicas:
            depth = len(rep.inbox)
            if depth > self._max_inbox_seen:
                self._max_inbox_seen = depth
            if rep.inflight_device > self._max_inflight_device_seen:
                self._max_inflight_device_seen = rep.inflight_device
            if rep.inflight_device >= cfg.max_inflight_batches \
                    or depth >= cfg.max_inbox_messages:
                hit = True
        return hit

    def is_done(self) -> bool:
        return all(r.done for r in self._all_replicas)

    def restore(self, checkpoint_dir: Optional[str] = None) -> "PipeGraph":
        """Rebuild this composed-but-unstarted graph at the last complete
        checkpoint epoch (windflow_tpu/durability, docs/DURABILITY.md):
        validates the manifest's topology signature against the graph
        (WF602 named diff on mismatch), restores every operator's state
        — FFAT pane rings, stateful slot tables, reduce states — plus
        per-replica watermark frontiers, seeks Kafka sources back to the
        checkpointed offsets, and re-fences exactly-once sinks so the
        replay neither loses nor duplicates a record.  Returns the graph
        STARTED; drive it with :meth:`wait_end` (or :meth:`step`)."""
        from windflow_tpu.durability.checkpoint import restore_graph
        return restore_graph(self, checkpoint_dir)

    def _finalize(self, dump: bool = True, aborted: bool = False) -> None:
        if self._tenant is not None:
            # freeze this graph's attribution in the process tenant
            # ledger before teardown, so the tenant roll-up keeps its
            # history after the replicas are gone (guarded: shutdown
            # telemetry must never block shutdown)
            try:
                self._tenant.freeze()
            except Exception:  # lint: broad-except-ok (see above)
                pass
        if self._durability is not None:
            # flush + close the checkpoint store (counters stay readable:
            # stats() reads the cached section fields, not the KV)
            self._durability.close()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        if self._monitor is not None:
            # abnormal termination still ships a final report + END_APP
            # best-effort (the dashboard used to show crashed apps live
            # forever); the monitor marks the report Aborted
            self._monitor.stop(aborted=aborted)
            self._monitor = None
        if dump and self.config.tracing_enabled:
            self.dump_stats()

    # -- introspection (reference pipegraph.hpp:721-789) ---------------------
    def get_num_dropped_tuples(self) -> int:
        return sum(c.num_dropped for c in self._collectors) \
            + sum(op.num_dropped_tuples() for op in self._operators)

    def to_dot(self) -> str:
        """Graphviz DOT diagram of the graph (reference
        ``pipegraph.hpp:560-576``)."""
        from windflow_tpu.monitoring.diagram import to_dot
        return to_dot(self)

    def getNumDroppedTuples(self) -> int:
        """Reference-spelled alias of :meth:`get_num_dropped_tuples`
        (``pipegraph.hpp:786-789``)."""
        return self.get_num_dropped_tuples()

    # -- observability: gauges, latency, span traces -------------------------
    def sample_gauges(self) -> None:
        """Append one rolling-throughput sample.  The monitoring thread
        calls this once per second; ``stats()`` also samples so headless
        runs (no dashboard) still get the rolling gauges."""
        total = sum(r.stats.inputs_received for op in self._operators
                    if op.is_terminal for r in op.replicas)
        self._thr_samples.append((time.monotonic(), total))

    def health_tick(self) -> None:
        """One watchdog evaluation (monitoring/health.py).  The monitoring
        thread calls this on its cadence — and, like ``sample_gauges``,
        headless runs get the same tick from every ``stats()`` read.  With
        ``Config.health_watchdog`` off this is the whole cost: one check."""
        if self._latency is not None:
            # harvest + SLO evaluation BEFORE the watchdog samples, so
            # the health verdicts read this tick's decomposition (with
            # the ledger off this is the whole cost: one check)
            try:
                self._latency.tick()
            except Exception:  # lint: broad-except-ok (a telemetry
                # harvest must never take the watchdog down; the
                # Latency_plane section surfaces the error on read)
                pass
        if self._tenant is not None:
            # budget state machine tick BEFORE the watchdog samples, so
            # the health verdicts read this tick's OVER_BUDGET latch
            # (with the ledger off this is the whole cost: one check)
            try:
                self._tenant.tick()
            except Exception:  # lint: broad-except-ok (a telemetry
                # collect must never take the watchdog down; the Tenant
                # section surfaces the error on read)
                pass
        if self._roofline is not None:
            # roofline rate tick BEFORE the watchdog samples, so the
            # health verdicts read this tick's collapse latch (with the
            # plane off this is the whole cost: one check)
            try:
                self._roofline.tick()
            except Exception:  # lint: broad-except-ok (a telemetry
                # rate read must never take the watchdog down; the
                # Roofline section surfaces the error on read)
                pass
        if self._health is not None:
            self._health.sample()

    def _health_section(self) -> dict:
        if self._health is None:
            return {"enabled": False}
        try:
            return self._health.section()
        except Exception as e:  # lint: broad-except-ok (same stance as
            # the device section: a watchdog read must never take the
            # pipeline or a stats dump down)
            return {"enabled": True, "error": f"{type(e).__name__}: "
                                              f"{e}"[:200]}

    def _latency_plane_section(self) -> dict:
        """Guarded like the health/device sections; with
        ``Config.latency_ledger`` off this is the whole cost: one
        check.  Harvests before reading so a headless ``stats()`` call
        sees completed traces without a monitor thread."""
        if self._latency is None:
            return {"enabled": False}
        try:
            self._latency.harvest()
            return self._latency.section()
        except Exception as e:  # lint: broad-except-ok (a decomposition
            # read must never take the pipeline or a stats dump down —
            # same stance as every other plane section)
            return {"enabled": True, "error": f"{type(e).__name__}: "
                                              f"{e}"[:200]}

    def _tenant_section(self) -> dict:
        """Guarded like the health/latency sections; with
        ``Config.tenant_ledger`` off this is the whole cost: one
        check.  Reports the WHOLE process tenant table (every
        co-resident graph), focused on this graph's row/tenant — one
        tenant's stats dump is enough for the advisor to plan across
        tenants."""
        if self._tenant is None:
            return {"enabled": False}
        try:
            return self._tenant.section()
        except Exception as e:  # lint: broad-except-ok (an attribution
            # read must never take the pipeline or a stats dump down —
            # same stance as every other plane section)
            return {"enabled": True, "error": f"{type(e).__name__}: "
                                              f"{e}"[:200]}

    def _roofline_section(self) -> dict:
        """Guarded like the health/latency/tenant sections; with
        ``Config.roofline_plane`` off this is the whole cost: one
        check.  Ticks once before reading so a headless ``stats()``
        call sees current rates without a monitor thread."""
        if self._roofline is None:
            return {"enabled": False}
        try:
            self._roofline.tick()
            return self._roofline.section()
        except Exception as e:  # lint: broad-except-ok (a rate/ratio
            # read must never take the pipeline or a stats dump down —
            # same stance as every other plane section)
            return {"enabled": True, "error": f"{type(e).__name__}: "
                                              f"{e}"[:200]}

    def _durability_section(self) -> dict:
        """Guarded like the health/device/sweep sections; with
        ``Config.durability`` unset this is the whole cost: one check."""
        if self._durability is None:
            return {"enabled": False}
        try:
            return self._durability.section()
        except Exception as e:  # lint: broad-except-ok (a checkpoint
            # telemetry read must never take the pipeline or a stats
            # dump down — same stance as every other plane section)
            return {"enabled": True, "error": f"{type(e).__name__}: "
                                              f"{e}"[:200]}

    def _reshard_section(self) -> dict:
        """Guarded like the health/durability sections; with
        ``Config.reshard_executor`` off this is the whole cost: one
        check."""
        if self._reshard is None:
            return {"enabled": False}
        try:
            return self._reshard.section()
        except Exception as e:  # lint: broad-except-ok (an executor
            # telemetry read must never take the pipeline or a stats
            # dump down — same stance as every other plane section)
            return {"enabled": True, "error": f"{type(e).__name__}: "
                                              f"{e}"[:200]}

    def _sweep_section(self) -> dict:
        """Guarded like the health/device sections: a ledger read must
        never take the pipeline or a stats dump down.  With
        ``Config.sweep_ledger`` off this is the whole cost: one check."""
        if self._ledger is None:
            return {"enabled": False}
        try:
            return self._ledger.section()
        except Exception as e:  # lint: broad-except-ok (the ledger walks
            # registry snapshots and abstract specs at stats cadence —
            # telemetry degrades, the report still ships)
            return {"enabled": True, "error": f"{type(e).__name__}: "
                                              f"{e}"[:200]}

    def _ir_audit_section(self) -> dict:
        """wfir (analysis/ir_audit.py): WF9xx findings over the lowered
        StableHLO of this graph's compiled programs.  Re-audits the
        compile watcher's program store at read cadence (cold path, no
        compiles); guarded like every other plane section.  With
        ``Config.ir_audit`` off (or ``WF_TPU_IR_AUDIT=0``) this is the
        whole cost: one check."""
        try:
            from windflow_tpu.analysis import ir_audit
            if not ir_audit.enabled(self.config):
                return {"enabled": False}
            report = ir_audit.audit_graph(self, dry_lower=False)
            self._ir_audit_report = report
            out = {"enabled": True}
            out.update(report.to_json())
            return out
        except Exception as e:  # lint: broad-except-ok (the auditor
            # parses backend-emitted IR text at stats cadence —
            # telemetry degrades, the report still ships)
            return {"enabled": True, "error": f"{type(e).__name__}: "
                                              f"{e}"[:200]}

    def _shard_section(self) -> dict:
        """Guarded like the health/device/sweep sections: a shard-plane
        read must never take the pipeline or a stats dump down.  With
        ``Config.shard_ledger`` off this is the whole cost: one check."""
        if self._shard is None:
            return {"enabled": False}
        try:
            return self._shard.section()
        except Exception as e:  # lint: broad-except-ok (the ledger
            # merges device sketch states and walks abstract specs at
            # stats cadence — telemetry degrades, the report still ships)
            return {"enabled": True, "error": f"{type(e).__name__}: "
                                              f"{e}"[:200]}

    def _rolling_rate(self, window_s: float) -> float:
        """Sunk-tuples/sec over (at least) the trailing ``window_s``: the
        delta between the newest sample and the youngest sample that is at
        least ``window_s`` old (the whole retained window when none is)."""
        if len(self._thr_samples) < 2:
            return 0.0
        now_t, now_v = self._thr_samples[-1]
        base = None
        for t, v in self._thr_samples:
            if now_t - t >= window_s:
                base = (t, v)      # samples are time-ordered: keep the
            else:                  # youngest one old enough
                break
        if base is None:
            base = self._thr_samples[0]
        dt = now_t - base[0]
        return (now_v - base[1]) / dt if dt > 0 else 0.0

    def op_frontier_and_depth(self, op) -> tuple:
        """``(summed inbox depth, watermark frontier)`` for one operator.
        Frontier = MIN over replicas (watermark semantics): the lag gauge
        and the health watchdog must surface a stalled replica, not hide
        it behind its most-advanced sibling.  Shared by :meth:`gauges`
        and the health plane's stall detection so the two can never
        drift."""
        from windflow_tpu.batch import WM_MAX, WM_NONE
        depth = 0
        fronts = []
        for rep in op.replicas:
            depth += len(rep.inbox)
            wm = rep.current_wm
            if wm != WM_NONE and wm < WM_MAX:
                fronts.append(wm)
        return depth, (min(fronts) if fronts else None)

    def gauges(self) -> dict:
        """Point-in-time gauges (sampled by the monitoring thread into the
        NEW_REPORT payload): per-operator watermark lag (wall clock minus
        frontier — meaningful under INGRESS/wall-based EVENT time) and
        inbox queue depth, staging-pool occupancy, rolling throughput."""
        from windflow_tpu import staging
        now = current_time_usecs()
        per_op = {}
        for op in self._operators:
            depth, front = self.op_frontier_and_depth(op)
            per_op[op.name] = {
                "queue_depth": depth,
                "watermark_frontier_usec": front,
                "watermark_lag_usec":
                    max(0, now - front) if front is not None else None,
            }
        pool = staging.default_pool()
        return {
            "sampled_at_usec": now,
            "operators": per_op,
            "staging_pool_held_bytes": pool.stats()["held_bytes"],
            "throughput_1s_tps": round(self._rolling_rate(1.0), 1),
            "throughput_10s_tps": round(self._rolling_rate(10.0), 1),
        }

    def _latency_section(self) -> dict:
        """Per-operator service-span and end-to-end staged→sunk latency
        distributions (p50/p95/p99), merged across replicas from the
        log-bucketed histograms (monitoring/recorder.py)."""
        from windflow_tpu.monitoring.recorder import LatencyHistogram
        per_op = {}
        e2e = LatencyHistogram()
        for op in self._operators:
            h = LatencyHistogram()
            for rep in op.replicas:
                h.merge(rep.stats.service_hist)
                e2e.merge(rep.stats.e2e_hist)   # nonzero only at sinks
            per_op[op.name] = h.quantiles()
        return {"service_usec_per_operator": per_op,
                "end_to_end_usec": e2e.quantiles()}

    def profile(self, duration_ms: float = 1000.0,
                log_dir: Optional[str] = None) -> str:
        """Profiler bridge: capture a ``jax.profiler`` device trace while
        driving the started graph for ``duration_ms`` (or until it
        finishes).  The capture lands in ``log_dir`` /
        ``Config.profiler_dir`` (default ``{log_dir}/{name}_xprof``) as a
        TensorBoard/Perfetto ``plugins/profile`` directory; because the
        dispatch path wraps every sampled trace-lane batch in a
        ``TraceAnnotation("op:<name> trace:<id>")`` (ops/tpu.py), the XLA
        device spans in that capture line up with :meth:`dump_trace`'s
        flight-recorder spans by trace id.  Returns the capture
        directory."""
        if not self._started:
            raise WindFlowError("profile() needs a started graph — call "
                                "start() first (run() profiles nothing: "
                                "it returns only when the graph is done)")
        import jax.profiler
        d = log_dir or self.config.profiler_dir \
            or os.path.join(self.config.log_dir, f"{self.name}_xprof")
        os.makedirs(d, exist_ok=True)
        self._last_profile_dir = d
        jax.profiler.start_trace(d)
        try:
            deadline = time.monotonic() + duration_ms / 1e3
            while time.monotonic() < deadline and not self.is_done():
                if not self.step():
                    break
        finally:
            jax.profiler.stop_trace()
        return d

    def dump_trace(self, path: Optional[str] = None) -> str:
        """Write the flight recorder's span events as Chrome-trace JSON
        (``{name}_trace.json`` under ``Config.log_dir``), loadable in
        ``chrome://tracing`` / Perfetto next to a ``jax.profiler`` capture
        (``otherData`` carries the annotation format + capture directory
        that cross-reference the two); the raw events ride along as
        ``{name}_events.json`` for offline re-export through
        ``tools/trace_export.py``.  Returns the trace path."""
        if self._recorder is None:
            raise WindFlowError(
                "flight recorder is off (Config.flight_recorder) or the "
                "graph has not been built — nothing to dump")
        from windflow_tpu.monitoring.recorder import write_chrome_trace
        d = self.config.log_dir
        os.makedirs(d, exist_ok=True)
        path = path or os.path.join(d, f"{self.name}_trace.json")
        events = self._recorder.events()
        write_chrome_trace(events, path, metadata={
            # profiler-bridge cross-reference: the jax.profiler capture's
            # device spans carry these annotations for the same trace ids
            "profiler_annotation_format": "op:<operator> trace:<trace_id>",
            "profiler_dir": self._last_profile_dir
            or self.config.profiler_dir
            or os.path.join(self.config.log_dir, f"{self.name}_xprof"),
            # sweep-ledger cross-reference: per-hop dispatch counts and
            # attributed HBM bytes for the spans in this trace
            "sweep": self._sweep_section(),
            # shard-plane cross-reference: per-shard load + hot keys for
            # the operators whose spans this trace carries
            "shard": self._shard_section(),
            # tenant-plane cross-reference: which tenant this graph's
            # spans bill to, and the process tenant roll-up at dump time
            "tenant": self._tenant_section(),
            # calibration cross-reference: where every modeled constant
            # behind the trace's derived numbers currently comes from
            # (measured/modeled/calibrated provenance + store age)
            "calibration": _calibration_summary(),
        })
        root, ext = os.path.splitext(path)
        base = root[:-len("_trace")] if root.endswith("_trace") else root
        with open(f"{base}_events{ext or '.json'}", "w") as f:
            json.dump(events, f)
        return path

    def stats(self) -> dict:
        """Stats report; schema follows the reference's dashboard JSON
        (``pipegraph.hpp:468-526``).  The fixed reference fields describe the
        FastFlow runtime; here they describe the host driver equivalents."""
        self.sample_gauges()
        if self._fused_segments:
            # per-op stats for fused members are attributed from the
            # fused hop at read cadence (never on the batch path)
            from windflow_tpu.fusion import attribute_member_stats
            attribute_member_stats(self)
        return {
            "PipeGraph_name": self.name,
            "Mode": self.mode.value,
            # in-transit batch throttling (see _backpressured): source ticks
            # are deferred while any inbox is at the cap
            "Backpressure": f"ON (max_inflight_batches="
                            f"{self.config.max_inflight_batches}, "
                            f"max_inbox_messages="
                            f"{self.config.max_inbox_messages})",
            "Backpressure_throttle_events": self._throttle_events,
            "Max_inbox_depth_seen": self._max_inbox_seen,
            "Max_inflight_device_batches_seen":
                self._max_inflight_device_seen,
            "Non_blocking": "ON",     # async XLA dispatch
            "Thread_pinning": "OFF",  # driver loop + pool, no pinning
            "Host_worker_threads": self.config.host_worker_threads,
            # staging plane (windflow_tpu/staging): host-buffer recycling
            # pool counters + lookahead tick count
            "Staging_pool": _staging_pool_stats(),
            # wire plane (windflow_tpu/wire.py): per-lane codec table +
            # wire-vs-logical byte counters of this graph's staging
            # emitters (docs/OBSERVABILITY.md "Wire plane")
            "Staging": {"Wire": self._wire_section()},
            "Stage_prefetch_depth": self.config.stage_prefetch_depth,
            "Stage_prefetch_ticks": self._prefetch_ticks,
            "Dropped_tuples": self.get_num_dropped_tuples(),
            "Operator_number": len(self._operators),
            "Thread_number": 1 + self.config.host_worker_threads
                               + (1 if self._monitor is not None else 0),
            "rss_size_kb": _rss_kb(),
            # graph-level transfer totals (reference per-replica H2D/D2H
            # counters, stats_record.hpp:152-160, summed here).
            # Bytes_H2D_total is the WIRE total (bytes actually moved);
            # the logical total is what the decoded lanes occupy — the
            # two diverge exactly by the wire plane's compression, and
            # equating them would let compression silently inflate every
            # bytes-derived ratio (wire-round honesty fix)
            "Bytes_H2D_total": sum(r.stats.h2d_bytes
                                   for r in self._all_replicas),
            "Bytes_H2D_logical_total": sum(r.stats.h2d_logical_bytes
                                           for r in self._all_replicas),
            "Bytes_D2H_total": sum(r.stats.d2h_bytes
                                   for r in self._all_replicas),
            # flight-recorder layer (monitoring/recorder.py): latency
            # distributions + point-in-time gauges, shipped to the
            # dashboard in every NEW_REPORT
            "Flight_recorder": (self._recorder.summary()
                                if self._recorder is not None
                                else {"enabled": False}),
            # pre-flight analysis (windflow_tpu/analysis): check() cost +
            # finding counts, so preflight stays visible in every dump
            "Preflight": {
                "mode": getattr(self.config, "preflight", "error"),
                "check_ms": self._preflight_ms,
                "diagnostics": (None if self._preflight_diags is None
                                else [str(d) for d in
                                      self._preflight_diags]),
            },
            "Latency": self._latency_section(),
            # latency ledger (monitoring/latency_ledger.py): per-batch
            # critical-path segment decomposition, window freshness,
            # and the SLO verdict — the measurement layer the adaptive
            # sizer (analysis/latency.py, tools/wf_slo.py) plans against
            "Latency_plane": self._latency_plane_section(),
            # tenant plane (monitoring/tenant_ledger.py): per-tenant
            # HBM/ICI/dispatch attribution + budget verdicts across
            # every PipeGraph in the process — the measurement layer
            # the tenant advisor (analysis/tenancy.py, tools/
            # wf_tenant.py) and PR 20's tenant scheduler plan against
            "Tenant": self._tenant_section(),
            # roofline plane (monitoring/calibration.RooflineLedger):
            # per-hop achieved tup/s vs the calibrated bandwidth
            # ceiling, with measured/modeled/calibrated provenance on
            # every column and the latched ROOFLINE_DEGRADED verdict —
            # docs/OBSERVABILITY.md "Calibration plane"
            "Roofline": self._roofline_section(),
            "Gauges": self.gauges(),
            # health plane (monitoring/health.py): per-operator watchdog
            # verdicts, stall counters + attribution, verdict timeline
            "Health": self._health_section(),
            # device plane (monitoring/device_metrics.py): compile-watcher
            # per-op table, HBM/live-buffer gauges, staging-attributed
            # device bytes — the ``"Device"`` half of the telemetry story
            "Device": self._device_section(),
            # sweep ledger (monitoring/sweep_ledger.py): per-hop jitted
            # dispatches + XLA-cost HBM bytes per staged batch, donation
            # misses, hop-boundary residency — the attribution layer the
            # fusion advisor (tools/wf_advisor.py) plans against
            "Sweep": self._sweep_section(),
            # shard plane (monitoring/shard_ledger.py): per-shard queue/
            # lag/latency/HBM attribution, key-skew sketches on keyed
            # edges, mesh ICI model — the measurement layer the reshard
            # advisor (tools/wf_shard.py) plans against
            "Shard": self._shard_section(),
            # wfir (analysis/ir_audit.py): WF9xx audit of the lowered
            # StableHLO of this graph's compiled programs — collectives,
            # callbacks, donation aliasing, Pallas lowering proven on
            # the IR the chip actually runs (docs/ANALYSIS.md "wfir")
            "IR_audit": self._ir_audit_section(),
            # megastep plane (windflow_tpu/megastep.py): resolved K and
            # per-edge megastep/fallback counters — docs/OBSERVABILITY.md
            # "Megastep in the ledger"
            "Megastep": (self._megastep_plane.summary()
                         if self._megastep_plane is not None
                         else {"k": 1, "edges": []}),
            # durability plane (windflow_tpu/durability): epochs
            # committed, checkpoint/restore wall cost + bytes, sink
            # fence dedupe hits — docs/DURABILITY.md
            "Durability": self._durability_section(),
            # reshard executor (windflow_tpu/serving): plans applied,
            # keys moved, quiesce/recovery wall cost, admission factor,
            # action timeline — docs/OBSERVABILITY.md
            "Reshard": self._reshard_section(),
            "Operators": [op.dump_stats() for op in self._operators],
        }

    def _wire_section(self) -> dict:
        """Guarded like every other plane section; with
        ``Config.wire_compression`` off the emitters carry no encoders
        and the section reports enabled=False with zero counters."""
        try:
            from windflow_tpu.wire import wire_section
            return wire_section(self)
        except Exception as e:  # lint: broad-except-ok (a telemetry
            # read must never take the pipeline or a stats dump down —
            # same stance as every other plane section)
            return {"enabled": None, "error": f"{type(e).__name__}: "
                                              f"{e}"[:200]}

    def _device_section(self) -> dict:
        """Guarded: a metrics read must never take the pipeline down
        (same stance as the monitoring thread's quiet switch-off)."""
        from windflow_tpu.monitoring import device_metrics
        try:
            return device_metrics.device_section(self)
        except Exception as e:  # lint: broad-except-ok (backend probes —
            # memory_stats/live_arrays — may fail arbitrarily on exotic
            # runtimes; telemetry degrades, the report still ships)
            return {"error": f"{type(e).__name__}: {e}"[:200]}

    def dump_stats(self, log_dir: Optional[str] = None) -> str:
        d = log_dir or self.config.log_dir
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"{self.name}_stats.json")
        with open(path, "w") as f:
            json.dump(self.stats(), f, indent=2)
        return path

    def dump_postmortem(self, dir: Optional[str] = None,
                        reason: str = "manual") -> str:
        """Black-box postmortem bundle: flight-recorder rings, the last
        ``stats()``, health verdict timeline + stall attribution, jit and
        device tables, the sweep ledger's per-hop dispatch/HBM
        attribution, preflight findings — written as one directory of
        JSON files that ``tools/wf_doctor.py`` renders and validates with
        no jax installed.  Every section is individually guarded (section
        failures land in the manifest's ``errors`` map, they never abort
        the bundle): the crash path calls this exactly when parts of the
        telemetry may be broken.  Returns the bundle directory."""
        with self._postmortem_lock:
            return self._dump_postmortem_locked(dir, reason)

    def _dump_postmortem_locked(self, dir: Optional[str],
                                reason: str) -> str:
        # suppress the watchdog auto-bundle on THIS thread for the
        # duration of the write: the stats section below re-enters
        # HealthPlane.sample(), and an auto-bundle fired from there
        # would re-enter this non-reentrant lock and deadlock inside a
        # crash handler.  Thread-scoped suppression only — a manual
        # snapshot must not consume the once-per-graph auto-bundle, and
        # another thread's concurrent auto-bundle just serializes behind
        # the lock.
        if self._health is not None:
            self._health._bundle_thread = threading.get_ident()
        try:
            return self._dump_postmortem_impl(dir, reason)
        finally:
            if self._health is not None:
                self._health._bundle_thread = None

    def _dump_postmortem_impl(self, dir: Optional[str],
                              reason: str) -> str:
        d = dir or self.config.health_postmortem_dir \
            or os.path.join(self.config.log_dir, f"{self.name}_postmortem")
        os.makedirs(d, exist_ok=True)
        files: List[str] = []
        errors: dict = {}

        def write(name: str, build) -> None:
            try:
                obj = build()
                with open(os.path.join(d, name), "w") as f:
                    json.dump(obj, f, indent=1, default=str)
                files.append(name)
            except Exception as e:  # lint: broad-except-ok (postmortem
                # sections must degrade independently — a dead backend
                # breaking stats() must not lose the rings or verdicts)
                errors[name] = f"{type(e).__name__}: {e}"[:300]

        write("stats.json", self.stats)
        write("events.json",
              lambda: self._recorder.events()
              if self._recorder is not None else [])
        write("health.json",
              lambda: self._health.section(sample_first=False)
              if self._health is not None else {"enabled": False})
        write("device.json", self._device_section)

        def jit_tables():
            from windflow_tpu.monitoring.jit_registry import \
                default_registry
            reg = default_registry()
            return {"jit": reg.snapshot(), "totals": reg.totals()}
        write("jit.json", jit_tables)
        write("sweep.json", self._sweep_section)
        write("shard.json", self._shard_section)
        write("ir_audit.json", self._ir_audit_section)
        write("latency.json", self._latency_plane_section)
        write("tenant.json", self._tenant_section)
        write("roofline.json", self._roofline_section)
        write("calibration.json", _calibration_summary)
        write("durability.json", self._durability_section)
        write("reshard.json", self._reshard_section)
        write("preflight.json", lambda: {
            "mode": getattr(self.config, "preflight", "error"),
            "check_ms": self._preflight_ms,
            "diagnostics": (None if self._preflight_diags is None
                            else [str(dg) for dg in self._preflight_diags]),
        })
        from windflow_tpu.monitoring.health import POSTMORTEM_SCHEMA
        manifest = {
            "schema": POSTMORTEM_SCHEMA,
            "app": self.name,
            "reason": reason,
            "written_at_usec": current_time_usecs(),
            "files": files,
            "errors": errors,
        }
        with open(os.path.join(d, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1)
        self._postmortem_dir = d
        return d
