"""Prometheus text exposition of ``PipeGraph.stats()``.

One stats report (the dashboard ``NEW_REPORT`` payload / ``dump_stats``
JSON) renders into the Prometheus text format (version 0.0.4 — what every
Prometheus/OpenMetrics scraper ingests): counters for the lifetime
totals, gauges for the point-in-time sections, and real
``_bucket``/``_sum``/``_count`` histograms re-exposed from the flight
recorder's log2-bucketed latency histograms (bucket upper bounds are the
``2^b`` bucket edges, cumulative counts, ``+Inf`` closing the series).

Escaping follows the exposition-format spec: label values escape ``\\``,
``"`` and newline; HELP text escapes ``\\`` and newline.  The module is
pure stdlib (no jax, no numpy) so ``tools/wf_metrics.py`` and the
dashboard render without touching a backend.

:func:`parse_exposition` is the matching strict parser — the round-trip
check behind ``wf_metrics.py --check`` and the golden-format tests: it
rejects samples with no preceding ``# TYPE``, malformed metric/label
names, broken escaping, non-monotonic histogram buckets, and
``+Inf``/``_count`` disagreement.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def escape_label_value(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_le(le: float) -> str:
    return "+Inf" if math.isinf(le) else _fmt_value(le)


class MetricFamily:
    """One family: name, type, help, and its samples (suffix + labels +
    value; histogram bucket/sum/count samples carry their suffix)."""

    def __init__(self, name: str, mtype: str, help_text: str) -> None:
        self.name = name
        self.mtype = mtype
        self.help = help_text
        self.samples: List[Tuple[str, dict, object]] = []

    def add(self, value, labels: Optional[dict] = None,
            suffix: str = "") -> None:
        self.samples.append((suffix, dict(labels or {}), value))

    def add_histogram(self, buckets: List[Tuple[float, int]], hsum: float,
                      count: int, labels: Optional[dict] = None) -> None:
        """``buckets`` are (upper_bound, per-bucket count) pairs — this
        accumulates and closes the series with ``+Inf``."""
        labels = dict(labels or {})
        cum = 0
        for le, c in sorted(buckets, key=lambda p: p[0]):
            cum += c
            self.add(cum, dict(labels, le=_fmt_le(le)), suffix="_bucket")
        self.add(count, dict(labels, le="+Inf"), suffix="_bucket")
        self.add(hsum, labels, suffix="_sum")
        self.add(count, labels, suffix="_count")

    def render(self) -> str:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.mtype}"]
        for suffix, labels, value in self.samples:
            if labels:
                lab = ",".join(
                    f'{k}="{escape_label_value(v)}"'
                    for k, v in labels.items())
                lines.append(f"{self.name}{suffix}{{{lab}}} "
                             f"{_fmt_value(value)}")
            else:
                lines.append(f"{self.name}{suffix} {_fmt_value(value)}")
        return "\n".join(lines)


def _hist_from_stats(fam: MetricFamily, q: Optional[dict],
                     labels: dict) -> None:
    """Re-expose one LatencyHistogram.quantiles() dict (with its
    ``buckets``/``sum`` extension) as a real Prometheus histogram."""
    if not isinstance(q, dict) or "buckets" not in q:
        return
    fam.add_histogram([(float(le), int(c)) for le, c in q["buckets"]],
                      float(q.get("sum", 0.0)), int(q.get("count", 0)),
                      labels)


def render_openmetrics(stats: dict,
                       base_labels: Optional[dict] = None) -> str:
    """Render one ``PipeGraph.stats()`` dict as Prometheus text
    exposition.  ``base_labels`` (e.g. ``{"app": name}``) are attached to
    every sample."""
    return render_openmetrics_multi([(base_labels, stats)])


def render_openmetrics_multi(reports) -> str:
    """Render several ``(base_labels, stats)`` reports into ONE valid
    exposition: each metric family appears once (a single
    ``# HELP``/``# TYPE`` pair) with every report's samples merged under
    it — duplicate TYPE lines per family are a format violation the
    strict parser rejects, so the dashboard's multi-app ``/metrics`` must
    merge, not concatenate."""
    merged: Dict[str, MetricFamily] = {}
    order: List[str] = []
    for base_labels, stats in reports:
        for f in _families(stats, base_labels):
            m = merged.get(f.name)
            if m is None:
                merged[f.name] = f
                order.append(f.name)
            else:
                m.samples.extend(f.samples)
    return "\n".join(merged[n].render() for n in order
                     if merged[n].samples) + "\n"


def _families(stats: dict,
              base_labels: Optional[dict] = None) -> List["MetricFamily"]:
    base = dict(base_labels or {})
    if "app" not in base and stats.get("PipeGraph_name"):
        base["app"] = stats["PipeGraph_name"]
    # tenant label (monitoring/tenant_ledger.py): every sample of this
    # report is billed to the graph's tenant — the disambiguator that
    # keeps two same-topology apps' operator samples apart in the
    # dashboard's merged multi-app exposition
    tenant_section = stats.get("Tenant") or {}
    if "tenant" not in base and isinstance(tenant_section, dict) \
            and tenant_section.get("tenant"):
        base["tenant"] = tenant_section["tenant"]
    fams: List[MetricFamily] = []

    def fam(name, mtype, help_text) -> MetricFamily:
        f = MetricFamily(name, mtype, help_text)
        fams.append(f)
        return f

    # -- per-operator lifetime counters --------------------------------------
    # one sample per REPLICA with a `replica` label (stats are tracked
    # per replica; the old per-op collapse hid skew — sum over the label
    # in PromQL for the per-operator view).  A single-replica operator
    # still gets exactly one sample per family, so existing consumers
    # reading one value per op keep working.
    ops = stats.get("Operators") or []
    f_in = fam("wf_operator_inputs_total", "counter",
               "Tuples received per operator replica (shard)")
    f_out = fam("wf_operator_outputs_total", "counter",
                "Tuples emitted per operator replica")
    f_ign = fam("wf_operator_inputs_ignored_total", "counter",
                "Tuples ignored per operator replica (e.g. late at "
                "windows)")
    f_prog = fam("wf_operator_device_programs_total", "counter",
                 "Compiled-program dispatches per operator replica")
    for op in ops:
        name = op.get("Operator_name") or op.get("Name") or "?"
        for idx, r in enumerate(op.get("Replicas") or []):
            lab = dict(base, operator=name,
                       replica=str(r.get("Replica_id", idx)))
            f_in.add(r.get("Inputs_received", 0), lab)
            f_out.add(r.get("Outputs_sent", 0), lab)
            f_ign.add(r.get("Inputs_ignored", 0), lab)
            f_prog.add(r.get("Device_programs_launched", 0), lab)

    # -- graph-level counters / gauges ---------------------------------------
    for key, mname, mtype, help_text in (
            ("Bytes_H2D_total", "wf_bytes_h2d_total", "counter",
             "Host-to-device bytes shipped by the staging plane"),
            ("Bytes_D2H_total", "wf_bytes_d2h_total", "counter",
             "Device-to-host bytes fetched at egress"),
            ("Dropped_tuples", "wf_dropped_tuples_total", "counter",
             "Tuples dropped graph-wide"),
            ("Backpressure_throttle_events",
             "wf_backpressure_throttle_events_total", "counter",
             "Scheduler sweeps that deferred source ticks"),
            ("rss_size_kb", "wf_rss_kb", "gauge",
             "Resident set size of the driver process (KiB)")):
        if key in stats:
            fam(mname, mtype, help_text).add(stats[key] or 0, base)

    # -- gauges section ------------------------------------------------------
    gauges = stats.get("Gauges") or {}
    f_lag = fam("wf_watermark_lag_usec", "gauge",
                "Wall clock minus operator watermark frontier")
    f_depth = fam("wf_queue_depth", "gauge",
                  "Queued inbox messages per operator")
    for name, g in (gauges.get("operators") or {}).items():
        lab = dict(base, operator=name)
        if g.get("watermark_lag_usec") is not None:
            f_lag.add(g["watermark_lag_usec"], lab)
        f_depth.add(g.get("queue_depth", 0), lab)
    f_thr = fam("wf_throughput_tps", "gauge",
                "Rolling sunk-tuples/sec over the trailing window")
    for window, key in (("1s", "throughput_1s_tps"),
                        ("10s", "throughput_10s_tps")):
        if key in gauges:
            f_thr.add(gauges[key], dict(base, window=window))
    if "staging_pool_held_bytes" in gauges:
        fam("wf_staging_pool_held_bytes", "gauge",
            "Host bytes retained by the staging recycling pool") \
            .add(gauges["staging_pool_held_bytes"], base)

    # -- health plane --------------------------------------------------------
    health = stats.get("Health") or {}
    if health.get("enabled"):
        # enum gauge (the Prometheus enum pattern): one sample per
        # (operator, state) with 1 on the active state — alertable with
        # `wf_operator_health{state="stalled"} == 1` and graphable as a
        # state timeline without label joins
        f_health = fam("wf_operator_health", "gauge",
                       "Per-operator watchdog state (enum gauge: 1 on "
                       "the active state)")
        for name, v in (health.get("verdicts") or {}).items():
            active = str(v.get("state", "")).lower()
            for state in ("ok", "roofline_degraded", "slo_violated",
                          "over_budget", "backpressured", "stalled",
                          "failed"):
                f_health.add(1 if active == state else 0,
                             dict(base, operator=name, state=state))
        fam("wf_stall_events_total", "counter",
            "Watchdog-confirmed stall events (root-cause attributed)") \
            .add(health.get("stall_events", 0), base)
        f_age = fam("wf_health_last_advance_age_usec", "gauge",
                    "Age of the operator's last progress "
                    "(inputs/frontier) observation")
        for name, v in (health.get("verdicts") or {}).items():
            if v.get("last_advance_age_usec") is not None:
                f_age.add(v["last_advance_age_usec"],
                          dict(base, operator=name))

    # -- sweep ledger --------------------------------------------------------
    sweep = stats.get("Sweep") or {}
    if sweep.get("enabled"):
        f_sd = fam("wf_sweep_dispatches_per_batch", "gauge",
                   "Jitted dispatches per staged batch per operator hop "
                   "(sweep ledger)")
        f_sb = fam("wf_sweep_bytes_per_tuple", "gauge",
                   "XLA cost-analysis HBM bytes per tuple attributed to "
                   "the hop")
        f_sx = fam("wf_sweep_excess_vs_model", "gauge",
                   "Attributed bytes over the declared record-spec "
                   "payload model")
        f_dm = fam("wf_sweep_donation_miss_bytes_per_batch", "gauge",
                   "Bytes copied per batch because donatable inputs are "
                   "not donated")
        for name, h in (sweep.get("per_hop") or {}).items():
            lab = dict(base, operator=name)
            if isinstance(h.get("dispatches_per_batch"), (int, float)):
                f_sd.add(h["dispatches_per_batch"], lab)
            if isinstance(h.get("bytes_per_tuple"), (int, float)):
                # cost-table attribution, never a byte counter — the
                # provenance label says so on the wire (calibration.py)
                f_sb.add(h["bytes_per_tuple"],
                         dict(lab, provenance=h.get("bytes_provenance",
                                                    "modeled")))
            if isinstance(h.get("excess_vs_model"), (int, float)):
                f_sx.add(h["excess_vs_model"], lab)
            miss = (h.get("donation_miss") or {}).get("bytes_per_batch")
            if isinstance(miss, (int, float)):
                f_dm.add(miss, lab)
        totals = sweep.get("totals") or {}
        if isinstance(totals.get("bytes_per_tuple"), (int, float)):
            fam("wf_sweep_bytes_per_tuple_total", "gauge",
                "Summed attributed HBM bytes per tuple across all hops") \
                .add(totals["bytes_per_tuple"], base)
        fusion = sweep.get("fusion") or {}
        if fusion.get("enabled") and isinstance(
                fusion.get("dispatches_saved_per_batch"), (int, float)):
            fam("wf_fusion_dispatches_saved_per_batch", "gauge",
                "Jitted dispatches per batch elided by whole-chain "
                "fusion (windflow_tpu/fusion)") \
                .add(fusion["dispatches_saved_per_batch"], base)

    # -- wire plane ----------------------------------------------------------
    wire = (stats.get("Staging") or {}).get("Wire") or {}
    if wire.get("enabled") and isinstance(wire.get("wire_bytes"),
                                          (int, float)):
        fam("wf_wire_bytes_total", "counter",
            "Bytes actually transferred host->device by wire-compressed "
            "staging (windflow_tpu/wire.py)") \
            .add(wire["wire_bytes"], base)
        fam("wf_wire_logical_bytes_total", "counter",
            "Decoded (pre-compression) bytes behind the wire transfers") \
            .add(wire.get("logical_bytes", 0), base)
        fam("wf_wire_batches_total", "counter",
            "Staged batches shipped wire-compressed") \
            .add(wire.get("batches", 0), base)
        fam("wf_wire_raw_batches_total", "counter",
            "Staged batches where compression lost and the logical "
            "buffer shipped unchanged") \
            .add(wire.get("raw_batches", 0), base)
        fam("wf_wire_fallback_lanes_total", "counter",
            "Per-batch lane codec misfits degraded to raw") \
            .add(wire.get("fallback_lanes", 0), base)
        if isinstance(wire.get("compression_ratio"), (int, float)):
            fam("wf_wire_compression_ratio", "gauge",
                "Logical over wire bytes of the graph's compressed "
                "staging (docs/OBSERVABILITY.md wire plane)") \
                .add(wire["compression_ratio"], base)

    # -- shard plane ---------------------------------------------------------
    shard = stats.get("Shard") or {}
    if shard.get("enabled"):
        f_sht = fam("wf_shard_tuples_total", "counter",
                    "Tuples routed to each shard of a keyed operator "
                    "(key-skew sketch / exact histogram)")
        f_shq = fam("wf_shard_queue_depth", "gauge",
                    "Queued inbox messages per operator shard (replica)")
        f_shl = fam("wf_shard_watermark_lag_usec", "gauge",
                    "Wall clock minus the shard's own watermark frontier")
        f_shb = fam("wf_shard_hbm_bytes_total", "counter",
                    "Steady XLA-cost HBM bytes attributed to the "
                    "shard's own dispatches")
        f_shi = fam("wf_shard_imbalance_ratio", "gauge",
                    "Max over mean per-shard load of a keyed operator")
        f_shh = fam("wf_shard_hot_key_share", "gauge",
                    "Share of the operator's stream carried by its "
                    "hottest key")
        f_ici = fam("wf_shard_ici_bytes_per_tuple", "gauge",
                    "Modeled ICI collective bytes per tuple for the "
                    "operator's sharded program (mesh graphs)")
        for name, entry in (shard.get("per_op") or {}).items():
            lab = dict(base, operator=name)
            for rep in entry.get("replicas") or []:
                rlab = dict(lab, shard=str(rep.get("shard", "?")))
                f_shq.add(rep.get("queue_depth", 0), rlab)
                if rep.get("watermark_lag_usec") is not None:
                    f_shl.add(rep["watermark_lag_usec"], rlab)
                if isinstance(rep.get("hbm_bytes"), (int, float)):
                    f_shb.add(rep["hbm_bytes"], rlab)
            load = entry.get("load") or {}
            for i, n_t in enumerate(load.get("tuples") or []):
                f_sht.add(n_t, dict(lab, shard=str(i)))
            if isinstance(load.get("imbalance_ratio"), (int, float)):
                f_shi.add(load["imbalance_ratio"], lab)
            if isinstance(load.get("hot_key_share"), (int, float)):
                f_shh.add(load["hot_key_share"], lab)
            ici = entry.get("ici") or {}
            if isinstance(ici.get("ici_bytes_per_tuple"), (int, float)):
                # structural collective model — labeled so a dashboard
                # can never mistake it for a measured counter
                f_ici.add(ici["ici_bytes_per_tuple"],
                          dict(lab, provenance=ici.get("provenance",
                                                       "modeled")))

    # -- durability plane ----------------------------------------------------
    dur = stats.get("Durability") or {}
    if dur.get("enabled"):
        fam("wf_durability_epochs_committed_total", "counter",
            "Checkpoint epochs committed (manifest written + fsynced)") \
            .add(dur.get("epochs_committed", 0), base)
        fam("wf_durability_checkpoint_ms", "gauge",
            "Wall cost of the last checkpoint (barrier + snapshot + "
            "manifest)") \
            .add(dur.get("last_checkpoint_ms") or 0, base)
        fam("wf_durability_checkpoint_bytes", "gauge",
            "Snapshot bytes written by the last checkpoint") \
            .add(dur.get("last_checkpoint_bytes", 0), base)
        fam("wf_durability_dedupe_hits_total", "counter",
            "Sink messages skipped by the exactly-once fence on replay") \
            .add(dur.get("dedupe_hits", 0), base)
        fam("wf_durability_restored", "gauge",
            "1 when this graph was rebuilt from a checkpoint epoch") \
            .add(0 if dur.get("restored_epoch") is None else 1, base)

    # -- reshard executor ----------------------------------------------------
    rsh = stats.get("Reshard") or {}
    if rsh.get("enabled") and "error" not in rsh:
        fam("wf_reshard_plans_applied_total", "counter",
            "Reshard plans (move_keys/split_hot_key) applied live") \
            .add(rsh.get("plans_applied", 0), base)
        fam("wf_reshard_keys_moved_total", "counter",
            "Keys re-placed by executor-applied move_keys actions") \
            .add(rsh.get("keys_moved", 0), base)
        fam("wf_reshard_preagg_folds_total", "counter",
            "Hot-key tuples absorbed into pre-aggregated partials "
            "(split_hot_key)") \
            .add(rsh.get("preagg_folds", 0), base)
        fam("wf_reshard_admission_factor", "gauge",
            "Source admission factor (1.0 = no throttle; halves while "
            "degraded with no applicable plan)") \
            .add(rsh.get("admission_factor", 1.0), base)
        fam("wf_reshard_quiesce_ms", "gauge",
            "Wall cost of the last reshard quiesce-and-re-place "
            "barrier") \
            .add(rsh.get("quiesce_ms") or 0, base)
        fam("wf_reshard_recovery_ms", "gauge",
            "Wall time from the last applied plan to the first OK "
            "verdict") \
            .add(rsh.get("recovery_ms") or 0, base)

    # -- latency histograms --------------------------------------------------
    lat = stats.get("Latency") or {}
    f_svc = fam("wf_service_latency_usec", "histogram",
                "Per-batch service span per operator (microseconds)")
    for name, q in (lat.get("service_usec_per_operator") or {}).items():
        _hist_from_stats(f_svc, q, dict(base, operator=name))
    f_e2e = fam("wf_end_to_end_latency_usec", "histogram",
                "Staged-to-sunk end-to-end latency (microseconds)")
    _hist_from_stats(f_e2e, lat.get("end_to_end_usec"), base)

    # -- latency plane (critical-path decomposition + SLO) -------------------
    lplane = stats.get("Latency_plane") or {}
    if lplane.get("enabled"):
        f_seg = fam("wf_latency_segment_usec", "histogram",
                    "Critical-path segment latency per operator "
                    "(latency-ledger decomposition; `segment` label is "
                    "one of the five staged->sunk hops)")
        f_fresh = fam("wf_latency_freshness_usec", "histogram",
                      "Window fire time minus window-close event time "
                      "on sampled fired batches (result freshness)")
        f_share = fam("wf_latency_budget_share", "gauge",
                      "Operator's share of graph-wide decomposed "
                      "latency (0..1)")
        f_busy = fam("wf_latency_device_busy_usec_total", "counter",
                     "Device-compute microseconds credited to the "
                     "operator (megastep group spans deflated by K)")
        f_floor = fam("wf_latency_freshness_floor_usec", "gauge",
                      "Megastep K x mean batch span: the freshness "
                      "floor the executor's group-wait imposes")
        for name, entry in (lplane.get("per_op") or {}).items():
            lab = dict(base, operator=name)
            for seg, q in (entry.get("segments_usec") or {}).items():
                _hist_from_stats(f_seg, q, dict(lab, segment=seg))
            _hist_from_stats(f_fresh, entry.get("freshness_usec"), lab)
            if isinstance(entry.get("budget_share"), (int, float)):
                f_share.add(entry["budget_share"], lab)
            if isinstance(entry.get("device_busy_usec"), (int, float)):
                f_busy.add(entry["device_busy_usec"], lab)
            if isinstance(entry.get("freshness_floor_usec"),
                          (int, float)):
                f_floor.add(entry["freshness_floor_usec"], lab)
        fam("wf_latency_traces_decomposed_total", "counter",
            "Sampled traces fully decomposed by the latency ledger") \
            .add(lplane.get("traces_decomposed", 0), base)
        fam("wf_latency_traces_dropped_total", "counter",
            "Open traces evicted before their sunk event arrived") \
            .add(lplane.get("traces_dropped", 0), base)
        fam("wf_latency_events_lost_total", "counter",
            "Span-ring events overwritten before harvest") \
            .add(lplane.get("events_lost", 0), base)
        slo = lplane.get("slo") or {}
        if slo.get("budget_ms"):
            fam("wf_slo_active", "gauge",
                "1 while the latched SLO_VIOLATED verdict holds") \
                .add(1 if slo.get("active") else 0, base)
            fam("wf_slo_entered_total", "counter",
                "SLO violation episodes entered") \
                .add(slo.get("entered", 0), base)
            fam("wf_slo_cleared_total", "counter",
                "SLO violation episodes cleared (hysteresis)") \
                .add(slo.get("cleared", 0), base)
            fam("wf_slo_budget_ms", "gauge",
                "Declared end-to-end p99 latency budget "
                "(Config.latency_slo_ms)") \
                .add(slo.get("budget_ms", 0), base)
            fam("wf_slo_recent_p99_ms", "gauge",
                "Rolling-window e2e p99 the SLO is judged against") \
                .add(slo.get("recent_p99_ms", 0), base)

    # -- tenant plane --------------------------------------------------------
    # per-tenant attribution across every graph in the process
    # (monitoring/tenant_ledger.py).  Each sample carries the report's
    # base labels PLUS the ROW's tenant label: the section is the whole
    # process table, so in a multi-app merge the `app` label keeps the
    # same tenant's rows from different reports distinct.
    if tenant_section.get("enabled"):
        f_thbm = fam("wf_tenant_hbm_bytes", "gauge",
                     "Resident device state bytes attributed to the "
                     "tenant (the budget basis)")
        f_tbud = fam("wf_tenant_hbm_budget_bytes", "gauge",
                     "Declared per-tenant HBM budget "
                     "(Config.hbm_budget_bytes)")
        f_tpr = fam("wf_tenant_budget_pressure", "gauge",
                    "Resident bytes over budget (1.0 = at budget)")
        f_tob = fam("wf_tenant_over_budget", "gauge",
                    "1 while the tenant's latched OVER_BUDGET verdict "
                    "holds")
        f_toe = fam("wf_tenant_over_budget_entered_total", "counter",
                    "OVER_BUDGET episodes entered (sustained overage)")
        f_tdis = fam("wf_tenant_dispatches_total", "counter",
                     "Jitted dispatches attributed to the tenant's "
                     "operators (per-wrapper counters)")
        f_tcms = fam("wf_tenant_compile_ms_total", "counter",
                     "Compile wall-ms attributed to the tenant since "
                     "its graphs registered")
        f_th2d = fam("wf_tenant_h2d_bytes_total", "counter",
                     "Host-to-device wire bytes staged by the tenant's "
                     "graphs")
        f_td2h = fam("wf_tenant_d2h_bytes_total", "counter",
                     "Device-to-host bytes fetched by the tenant's "
                     "sinks")
        f_tici = fam("wf_tenant_ici_bytes_per_tuple", "gauge",
                     "Modeled ICI collective bytes per tuple across "
                     "the tenant's sharded programs (shard ledger)")
        f_tlat = fam("wf_tenant_latency_share", "gauge",
                     "Tenant's share of the process's decomposed "
                     "latency (latency plane; 0..1)")
        for tname, agg in (tenant_section.get("tenants") or {}).items():
            if not isinstance(agg, dict):
                continue
            lab = dict(base, tenant=tname)
            f_thbm.add(agg.get("resident_state_bytes", 0), lab)
            f_tdis.add(agg.get("dispatches", 0), lab)
            f_tcms.add(agg.get("compile_ms", 0.0), lab)
            f_th2d.add(agg.get("h2d_bytes", 0), lab)
            f_td2h.add(agg.get("d2h_bytes", 0), lab)
            if isinstance(agg.get("ici_bytes_per_tuple"), (int, float)):
                # summed shard-plane model per tenant — same provenance
                # labeling stance as wf_shard_ici_bytes_per_tuple
                f_tici.add(agg["ici_bytes_per_tuple"],
                           dict(lab,
                                provenance=agg.get("ici_provenance")
                                or "modeled"))
            if isinstance(agg.get("latency_share"), (int, float)):
                f_tlat.add(agg["latency_share"], lab)
            budget = agg.get("budget") or {}
            if budget.get("budget_bytes"):
                f_tbud.add(budget["budget_bytes"], lab)
                if isinstance(budget.get("pressure"), (int, float)):
                    f_tpr.add(budget["pressure"], lab)
                f_tob.add(1 if budget.get("active") else 0, lab)
                f_toe.add(budget.get("entered", 0), lab)
        attributed = tenant_section.get("attributed") or {}
        if isinstance(attributed.get("staged_fraction"), (int, float)):
            fam("wf_tenant_attributed_staged_fraction", "gauge",
                "Tenants' attributed staged bytes over the process "
                "staged-transfer total (the CI reconciliation gate)") \
                .add(attributed["staged_fraction"], base)

    # -- roofline plane + calibration provenance -----------------------------
    # live achieved-vs-roofline gauge (monitoring/calibration.
    # RooflineLedger) plus the info family naming where every modeled
    # constant currently comes from — measured/modeled/calibrated(age)
    roofline = stats.get("Roofline") or {}
    if roofline.get("enabled"):
        f_rtps = fam("wf_roofline_achieved_tuples_per_sec", "gauge",
                     "Per-hop achieved throughput at monitor cadence "
                     "(measured: deltas over replica counters)")
        f_rbpt = fam("wf_roofline_bytes_per_tuple", "gauge",
                     "Per-hop bytes/tuple the roofline ratio uses "
                     "(sweep ledger cost tables; see provenance label)")
        f_rrat = fam("wf_roofline_ratio_vs_roofline", "gauge",
                     "Achieved bytes/sec over the calibrated bandwidth "
                     "ceiling (1.0 = at the roofline)")
        for name, hop in (roofline.get("per_hop") or {}).items():
            lab = dict(base, operator=name)
            if isinstance(hop.get("achieved_tuples_per_sec"),
                          (int, float)):
                f_rtps.add(hop["achieved_tuples_per_sec"], lab)
            if isinstance(hop.get("bytes_per_tuple"), (int, float)):
                f_rbpt.add(hop["bytes_per_tuple"],
                           dict(lab, provenance=hop.get(
                               "bytes_per_tuple_provenance", "modeled")))
            if isinstance(hop.get("ratio_vs_roofline"), (int, float)):
                f_rrat.add(hop["ratio_vs_roofline"], lab)
        fam("wf_roofline_degraded", "gauge",
            "1 while the latched ROOFLINE_DEGRADED advisory verdict "
            "holds (dominant hop collapsed vs its trailing baseline)") \
            .add(1 if roofline.get("verdict") else 0, base)
        calib = roofline.get("calibration") or {}
        consts = calib.get("constants") or {}
        if consts:
            # info-style family (value 1): one sample per modeled
            # constant with its current provenance as a label — the
            # queryable "is this number measured?" surface
            f_prov = fam("wf_provenance", "gauge",
                         "Provenance of each modeled constant (info "
                         "family: 1 per constant, see labels)")
            for key, slot in sorted(consts.items()):
                if isinstance(slot, dict) and slot.get("provenance"):
                    f_prov.add(1, dict(base, constant=key,
                                       provenance=slot["provenance"]))

    # -- device plane --------------------------------------------------------
    device = stats.get("Device") or {}
    jit = device.get("jit") or {}
    f_cmp = fam("wf_jit_compiles_total", "counter",
                "XLA compiles per op (compile watcher)")
    f_rcmp = fam("wf_jit_recompiles_total", "counter",
                 "Signature-change recompiles per op")
    f_cms = fam("wf_jit_compile_ms_total", "counter",
                "Cumulative compile wall milliseconds per op")
    f_flops = fam("wf_jit_cost_flops", "gauge",
                  "XLA cost analysis: FLOPs per execution")
    f_bytes = fam("wf_jit_cost_bytes_accessed", "gauge",
                  "XLA cost analysis: bytes accessed per execution")
    for name, e in jit.items():
        lab = dict(base, op=name)
        f_cmp.add(e.get("compiles", 0), lab)
        f_rcmp.add(e.get("recompiles", 0), lab)
        f_cms.add(e.get("compile_ms_total", 0.0), lab)
        cost = e.get("cost") or {}
        if isinstance(cost.get("flops"), (int, float)):
            f_flops.add(cost["flops"], lab)
        if isinstance(cost.get("bytes_accessed"), (int, float)):
            f_bytes.add(cost["bytes_accessed"], lab)
    f_mem = fam("wf_device_memory_bytes", "gauge",
                "device.memory_stats() gauges per local device")
    for dev in device.get("memory") or []:
        st = dev.get("stats")
        if not isinstance(st, dict):
            continue    # CPU backend: memory_stats() is None
        for stat, v in st.items():
            f_mem.add(v, dict(base, device=dev.get("device", "?"),
                              stat=stat))
    live = device.get("live_buffers") or {}
    f_lb = fam("wf_live_buffer_bytes", "gauge",
               "Bytes of live jax arrays per device ('all' = total)")
    f_lc = fam("wf_live_buffer_count", "gauge",
               "Count of live jax arrays per device ('all' = total)")
    if "bytes" in live:
        f_lb.add(live["bytes"], dict(base, device="all"))
        f_lc.add(live.get("count", 0), dict(base, device="all"))
    for dev, slot in (live.get("per_device") or {}).items():
        lab = dict(base, device=dev)
        f_lb.add(slot.get("bytes", 0), lab)
        f_lc.add(slot.get("count", 0), lab)
    staging = device.get("staging") or {}
    if "staged_device_bytes_total" in staging:
        fam("wf_staged_device_bytes_total", "counter",
            "Cumulative packed bytes shipped host-to-device") \
            .add(staging["staged_device_bytes_total"], base)

    return fams


# ---------------------------------------------------------------------------
# strict parser (wf_metrics --check, golden-format tests)
# ---------------------------------------------------------------------------

_SUFFIXES = ("_bucket", "_sum", "_count")


def _unescape_label_value(raw: str, where: str) -> str:
    out = []
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == "\\":
            if i + 1 >= len(raw):
                raise ValueError(f"{where}: dangling escape")
            n = raw[i + 1]
            if n == "\\":
                out.append("\\")
            elif n == '"':
                out.append('"')
            elif n == "n":
                out.append("\n")
            else:
                raise ValueError(f"{where}: bad escape '\\{n}'")
            i += 2
        elif c == '"':
            raise ValueError(f"{where}: unescaped quote in label value")
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_labels(raw: str, where: str) -> dict:
    labels: Dict[str, str] = {}
    i = 0
    while i < len(raw):
        m = re.match(r'([a-zA-Z_][a-zA-Z0-9_]*)="', raw[i:])
        if not m:
            raise ValueError(f"{where}: malformed label at '{raw[i:]}'")
        name = m.group(1)
        i += m.end()
        # scan to the closing unescaped quote
        j = i
        while j < len(raw):
            if raw[j] == "\\":
                j += 2
                continue
            if raw[j] == '"':
                break
            j += 1
        if j >= len(raw):
            raise ValueError(f"{where}: unterminated label value")
        labels[name] = _unescape_label_value(raw[i:j], where)
        i = j + 1
        if i < len(raw):
            if raw[i] != ",":
                raise ValueError(f"{where}: expected ',' between labels")
            i += 1
    return labels


def _parse_value(raw: str, where: str) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    if raw == "NaN":
        return math.nan
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{where}: bad sample value {raw!r}") from None


def parse_exposition(text: str) -> dict:
    """Parse + validate Prometheus text exposition.  Returns
    ``{family: {"type": t, "help": h, "samples": [(name, labels, value)]}}``
    and raises ``ValueError`` on any format violation: samples without a
    preceding ``# TYPE``, bad metric/label names, broken escaping,
    non-monotonic histogram buckets, ``+Inf`` bucket disagreeing with
    ``_count``."""
    families: Dict[str, dict] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        where = f"line {lineno}"
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                continue        # free-form comment
            kind, name = parts[1], parts[2]
            if not _NAME_RE.match(name):
                raise ValueError(f"{where}: bad metric name {name!r}")
            f = families.setdefault(
                name, {"type": None, "help": None, "samples": []})
            if kind == "TYPE":
                value = parts[3].strip() if len(parts) > 3 else ""
                if value not in ("counter", "gauge", "histogram",
                                 "summary", "untyped"):
                    raise ValueError(f"{where}: bad TYPE {value!r}")
                if f["samples"]:
                    raise ValueError(
                        f"{where}: TYPE for {name} after its samples")
                f["type"] = value
            else:
                f["help"] = parts[3] if len(parts) > 3 else ""
            continue
        # sample line: name[{labels}] value [timestamp]
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)"
                     r"(\s+-?\d+)?$", line)
        if not m:
            raise ValueError(f"{where}: malformed sample {line!r}")
        name, _, rawlabels, rawvalue = m.group(1, 2, 3, 4)
        labels = _parse_labels(rawlabels, where) if rawlabels else {}
        value = _parse_value(rawvalue, where)
        family = name
        if family not in families:
            for suf in _SUFFIXES:
                if name.endswith(suf) and name[:-len(suf)] in families:
                    family = name[:-len(suf)]
                    break
        f = families.get(family)
        if f is None or f["type"] is None:
            raise ValueError(
                f"{where}: sample {name!r} without a preceding # TYPE")
        if f["type"] != "histogram" and family != name:
            raise ValueError(
                f"{where}: suffix sample {name!r} on non-histogram "
                f"family {family!r}")
        if f["type"] == "histogram" and family == name:
            raise ValueError(
                f"{where}: histogram {name!r} must expose only "
                "_bucket/_sum/_count samples")
        if f["type"] == "counter":
            if not (value >= 0 or math.isnan(value)):
                raise ValueError(f"{where}: negative counter {name!r}")
        if "le" in labels and not name.endswith("_bucket"):
            raise ValueError(f"{where}: 'le' label outside _bucket")
        f["samples"].append((name, labels, value))

    _validate_histograms(families)
    return families


def _series_key(labels: dict) -> tuple:
    return tuple(sorted((k, v) for k, v in labels.items() if k != "le"))


def _validate_histograms(families: dict) -> None:
    for fname, f in families.items():
        if f["type"] != "histogram":
            continue
        series: Dict[tuple, dict] = {}
        for name, labels, value in f["samples"]:
            s = series.setdefault(_series_key(labels),
                                  {"buckets": [], "sum": None,
                                   "count": None})
            if name.endswith("_bucket"):
                if "le" not in labels:
                    raise ValueError(
                        f"{fname}: _bucket sample without 'le'")
                s["buckets"].append((_parse_value(labels["le"],
                                                  fname), value))
            elif name.endswith("_sum"):
                s["sum"] = value
            elif name.endswith("_count"):
                s["count"] = value
        for key, s in series.items():
            if not s["buckets"] or s["count"] is None or s["sum"] is None:
                raise ValueError(
                    f"{fname}{dict(key)}: histogram series missing "
                    "_bucket/_sum/_count")
            s["buckets"].sort(key=lambda p: p[0])
            les = [le for le, _ in s["buckets"]]
            if les[-1] != math.inf:
                raise ValueError(f"{fname}{dict(key)}: no +Inf bucket")
            counts = [c for _, c in s["buckets"]]
            if any(prev > nxt for prev, nxt in zip(counts, counts[1:])):
                raise ValueError(
                    f"{fname}{dict(key)}: bucket counts decrease — "
                    "cumulative histogram broken")
            if counts[-1] != s["count"]:
                raise ValueError(
                    f"{fname}{dict(key)}: +Inf bucket {counts[-1]} != "
                    f"_count {s['count']}")
