"""PipeGraph diagram generation (reference graphviz hooks,
``/root/reference/wf/multipipe.hpp:694-795``, ``pipegraph.hpp:560-576``).

``to_dot`` renders the operator DAG as graphviz DOT text; ``to_svg`` shells
out to the ``dot`` binary when graphviz is installed and otherwise falls
back to a simple native SVG layout, so the dashboard registration payload
(monitoring protocol NEW_APP) always has a diagram to ship.
"""

from __future__ import annotations

import html
import shutil
import subprocess
from typing import List, Tuple


def _node_id(op) -> str:
    return f"op{id(op):x}"


def _graph_nodes_edges(graph) -> Tuple[List, List]:
    ops = list(graph._operators)
    edges = []
    for edge in graph._edges():
        if edge[0] == "op":
            _, a, b = edge
            edges.append((a, b, b.routing.name))
        else:  # split point: edges to every branch head
            _, mp = edge
            src = mp.operators[-1]
            for child in mp.split_children:
                head = child.operators[0]
                edges.append((src, head, "SPLIT"))
    return ops, edges


def _dot_escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"')


def _label(op) -> str:
    kind = type(op).__name__
    extra = " [TPU]" if getattr(op, "is_tpu", False) else ""
    return f"{_dot_escape(op.name)}\\n{kind}{extra} ({op.parallelism})"


def to_dot(graph) -> str:
    """Graphviz DOT text for a built PipeGraph."""
    ops, edges = _graph_nodes_edges(graph)
    lines = [f'digraph "{_dot_escape(graph.name)}" {{',
             "  rankdir=LR;",
             '  node [shape=box, style="rounded,filled", '
             'fillcolor=lightblue, fontname=Helvetica];']
    for op in ops:
        fill = "gold" if getattr(op, "is_tpu", False) else "lightblue"
        lines.append(f'  {_node_id(op)} [label="{_label(op)}", '
                     f'fillcolor={fill}];')
    for a, b, routing in edges:
        style = ' [label="KB"]' if routing == "KEYBY" else \
                ' [label="BC"]' if routing == "BROADCAST" else \
                ' [style=dashed]' if routing == "SPLIT" else ""
        lines.append(f"  {_node_id(a)} -> {_node_id(b)}{style};")
    lines.append("}")
    return "\n".join(lines)


def _fallback_svg(graph) -> str:
    """Minimal native SVG: operators laid out left-to-right in topological
    order with straight connector lines."""
    ops, edges = _graph_nodes_edges(graph)
    W, H, GAP = 150, 54, 40
    pos = {id(op): i for i, op in enumerate(ops)}
    width = len(ops) * (W + GAP) + GAP
    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" '
             f'width="{width}" height="{H + 60}">']
    for a, b, _routing in edges:
        x1 = GAP + pos[id(a)] * (W + GAP) + W
        x2 = GAP + pos[id(b)] * (W + GAP)
        y = 30 + H // 2
        parts.append(f'<line x1="{x1}" y1="{y}" x2="{x2}" y2="{y}" '
                     'stroke="black" marker-end="none"/>')
    for op in ops:
        x = GAP + pos[id(op)] * (W + GAP)
        fill = "#ffd700" if getattr(op, "is_tpu", False) else "#add8e6"
        name = html.escape(op.name)
        kind = html.escape(type(op).__name__)
        parts.append(
            f'<rect x="{x}" y="30" rx="8" width="{W}" height="{H}" '
            f'fill="{fill}" stroke="black"/>'
            f'<text x="{x + W // 2}" y="52" text-anchor="middle" '
            f'font-size="12">{name}</text>'
            f'<text x="{x + W // 2}" y="70" text-anchor="middle" '
            f'font-size="10">{kind} ({op.parallelism})</text>')
    parts.append("</svg>")
    return "".join(parts)


def to_svg(graph) -> str:
    dot = to_dot(graph)
    if shutil.which("dot"):
        try:
            out = subprocess.run(["dot", "-Tsvg"], input=dot.encode(),
                                 capture_output=True, timeout=10, check=True)
            return out.stdout.decode()
        except (OSError, subprocess.SubprocessError, UnicodeDecodeError):
            # graphviz missing/broken/timed out: the hand-rolled fallback
            # SVG below is always available
            pass
    return _fallback_svg(graph)
