"""Latency ledger: per-batch critical-path decomposition + SLO verdicts.

The flight recorder (monitoring/recorder.py) already stamps every sampled
batch's journey — ``staged``/``emitted`` at birth, ``dispatched`` at the
async enqueue, ``device_done`` on the sampled sync, ``collected`` at each
inbox pull, ``sunk`` at the sink — but nothing decomposes those stamps:
``stats()["Latency"]`` reports the staged→sunk total and per-operator
service times, so "p99 is 2 s" never says WHERE the 2 s went.  This
module is the measurement plane ROADMAP item 3's adaptive sizer needs
(the same ledger-then-executor sequence as PR 6→7 and PR 9→12): it
harvests the existing span rings at monitor/stats cadence — **zero new
hot-path work** — and lands every completed trace in five per-operator
segment histograms:

==========================  =============================================
segment                     meaning
==========================  =============================================
``staged_to_emitted``       ingest / staging-queue wait
``emitted_to_dispatched``   group-formation wait — under the megastep
                            executor this IS the K-wait
``dispatched_to_device_done``  device compute (sampled-sync traces only)
``device_done_to_collected``   D2H drain + downstream inbox wait
``collected_to_sunk``       sink-side processing
==========================  =============================================

Decomposition is a running-max boundary walk over the trace's events
(latest occurrence of each stage), so the five segments **telescope**:
their sum equals the trace's first→last event span exactly — the
segment-sum honesty tests/test_latency_plane.py pins at K=1/4/8.  A
``device_done`` stamp marked ``shared_k = K`` (a megastep group drains
once for K logical batches) keeps its full wall value in the histogram —
each batch really waited that long — but the per-operator
``device_busy_usec`` aggregate credits it at 1/K so group compute is
never double-counted.

On top sits the declarative SLO: when ``Config.latency_slo_ms`` is set,
the ledger evaluates the p99 of a rolling window of recent e2e spans at
watchdog cadence; over budget enters a latched ``SLO_VIOLATED`` verdict
attributed to the dominant (operator, segment) pair of the same window
("p99 budget 250 ms, e2e 309 ms, 61% in emitted→dispatched on op
`window` — megastep K-wait"), cleared only after ``clear_after``
consecutive in-budget evaluations.  The health plane surfaces the
verdict (monitoring/health.py), OpenMetrics exports ``wf_slo_*`` /
``wf_latency_segment_*`` families, the postmortem bundle gains
``latency.json`` (tools/wf_doctor.py renders it), and
``analysis/latency.py`` / ``tools/wf_slo.py`` turn the decomposition
into the per-operator megastep/tick-chunk plan contract the PR-18
adaptive sizer implements.

Off (``Config.latency_ledger = False`` or no flight recorder) the plane
is never built: every call site keeps one ``is not None`` check
(micro-asserted by tests/test_latency_plane.py, same stance as the
other planes).
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

import numpy as np

from windflow_tpu.analysis.hotpath import hot_path
from windflow_tpu.basic import current_time_usecs
from windflow_tpu.monitoring.recorder import (COLLECTED, DEVICE_DONE,
                                              DISPATCHED, EMITTED,
                                              LatencyHistogram, SUNK)

#: the five critical-path segments, in pipeline order; index i's segment
#: ends at the boundary stage ``_SEG_STAGE[i]``
SEGMENTS = (
    "staged_to_emitted",
    "emitted_to_dispatched",
    "dispatched_to_device_done",
    "device_done_to_collected",
    "collected_to_sunk",
)

_SEG_STAGE = (EMITTED, DISPATCHED, DEVICE_DONE, COLLECTED, SUNK)

#: human form for verdict messages ("61% in emitted→dispatched ...")
SEGMENT_ARROWS = {
    "staged_to_emitted": "staged→emitted",
    "emitted_to_dispatched": "emitted→dispatched",
    "dispatched_to_device_done": "dispatched→device_done",
    "device_done_to_collected": "device_done→collected",
    "collected_to_sunk": "collected→sunk",
}


def _p99(values: List[float]) -> float:
    if not values:
        return 0.0
    s = sorted(values)
    return s[min(len(s) - 1, int(0.99 * (len(s) - 1) + 0.999))]


class _OpLatency:
    """Per-operator accumulation: one histogram per segment, wall totals,
    the shared_k-deflated device-busy credit, and fire freshness."""

    __slots__ = ("segments", "total_usec", "device_busy_usec",
                 "shared_k_traces", "freshness")

    def __init__(self) -> None:
        self.segments: Dict[str, LatencyHistogram] = {}
        self.total_usec = 0.0
        self.device_busy_usec = 0.0
        self.shared_k_traces = 0
        self.freshness: Optional[LatencyHistogram] = None

    def add_segment(self, seg: str, dt: float, shared: int) -> None:
        h = self.segments.get(seg)
        if h is None:
            h = self.segments[seg] = LatencyHistogram()
        h.add(dt)
        self.total_usec += dt
        if seg == "dispatched_to_device_done":
            if shared > 1:
                self.device_busy_usec += dt / shared
                self.shared_k_traces += 1
            else:
                self.device_busy_usec += dt

    def dominant_segment(self) -> Optional[str]:
        best, best_sum = None, 0.0
        for seg, h in self.segments.items():
            if h.total > best_sum:
                best, best_sum = seg, h.total
        return best


class LatencyLedger:
    """Graph-scoped latency plane.  Built by ``PipeGraph._build`` when
    ``Config.latency_ledger`` AND the flight recorder are on; harvests the
    recorder's rings incrementally (per-ring cursors) at monitor/stats
    cadence and never touches the hot path."""

    #: bound on traces held open awaiting their ``sunk`` event; beyond it
    #: the oldest are dropped (counted, not silently)
    MAX_OPEN = 2048
    #: recently-finalized trace ids remembered so a late event (second
    #: sink of a multicast, ring stragglers) cannot re-open a trace
    DONE_RECENT = 4096

    def __init__(self, recorder, slo_ms: float = 0.0, window: int = 512,
                 clear_after: int = 3, min_samples: int = 8) -> None:
        self.recorder = recorder
        self.slo_usec = float(slo_ms) * 1000.0
        self.clear_after = max(1, int(clear_after))
        self.min_samples = max(1, int(min_samples))
        self._cursors: Dict[int, int] = {}      # id(ring) -> consumed n
        self._open: Dict[int, list] = {}        # trace -> [(op, st, t, sh)]
        self._done_recent = deque(maxlen=self.DONE_RECENT)
        self._done_set = set()
        # rolling evaluation window: (e2e_usec, [(op, seg, dt), ...])
        self._recent = deque(maxlen=max(16, int(window)))
        self.per_op: Dict[str, _OpLatency] = {}
        self.e2e = LatencyHistogram()
        self.segment_totals = {seg: 0.0 for seg in SEGMENTS}
        self.traces_decomposed = 0
        self.traces_dropped = 0
        self.events_lost = 0
        # megastep plane (set by PipeGraph._build after plane attach):
        # source of the per-edge K and freshness floor
        self.megastep_plane = None
        # SLO verdict state machine (enter / latch / clear)
        self.slo_active = False
        self.slo_entered = 0
        self.slo_cleared = 0
        self._ok_ticks = 0
        self._recent_p99_usec = 0.0
        self.verdict: Optional[dict] = None
        self.last_verdict: Optional[dict] = None

    # -- harvest (cadence only; reads the rings the hot path writes) --------
    @hot_path
    def harvest(self) -> None:
        """Consume new ring events since the last harvest, then finalize
        every trace whose ``sunk`` arrived.  All rings are drained before
        any finalization so a trace's upstream events (written earlier in
        wall time) are in hand when its sink event is."""
        sunk_now = []
        for ring in self.recorder.rings:
            n_now = ring.n        # snapshot: writers may advance under us
            key = id(ring)
            n0 = self._cursors.get(key, 0)
            if n_now - n0 > ring.size:
                # the ring wrapped past unconsumed events: count the loss
                # (spans missing their middle still telescope — the
                # boundary walk skips absent stages)
                self.events_lost += (n_now - n0) - ring.size
                n0 = n_now - ring.size
            for j in range(n0, n_now):
                i = j % ring.size
                trace = int(ring.trace[i])
                stage = int(ring.stage[i])
                if trace in self._done_set:
                    continue
                ev = self._open.get(trace)
                if ev is None:
                    ev = self._open[trace] = []
                ev.append((ring.op_name, stage, int(ring.t[i]),
                           int(ring.shared_k[i])))
                if stage == SUNK:
                    sunk_now.append(trace)
            self._cursors[key] = n_now
        for trace in sunk_now:
            ev = self._open.pop(trace, None)
            if ev is not None:
                self._finalize(ev)
                self._remember_done(trace)
        if len(self._open) > self.MAX_OPEN:
            # oldest-first (dict insertion order), no snapshot list of
            # every open trace just to drop a few
            drop = len(self._open) - self.MAX_OPEN
            for _ in range(drop):
                trace = next(iter(self._open))
                del self._open[trace]
                self._remember_done(trace)
            self.traces_dropped += drop

    @hot_path
    def _remember_done(self, trace: int) -> None:
        if len(self._done_recent) == self._done_recent.maxlen:
            self._done_set.discard(self._done_recent[0])
        self._done_recent.append(trace)
        self._done_set.add(trace)

    @hot_path
    def _finalize(self, events: list) -> None:
        """Running-max boundary walk: for each stage in pipeline order
        take its LATEST occurrence (the sink-side ``collected`` of a
        multi-hop trace, the last hop's ``dispatched``); the segment is
        the boundary delta, attributed to the operator that recorded the
        boundary event.  Segments telescope to last−first event time by
        construction — the sum-honesty property the tests pin."""
        events.sort(key=lambda e: e[2])
        t0 = events[0][2]
        prev = t0
        segs = []
        for si, stage in enumerate(_SEG_STAGE):
            best = None
            for e in events:
                if e[1] == stage and (best is None or e[2] >= best[2]):
                    best = e
            if best is None:
                continue        # stage absent (e.g. unsampled device sync)
            b = best[2] if best[2] > prev else prev
            segs.append((best[0], SEGMENTS[si], float(b - prev), best[3]))
            prev = b
        e2e = float(prev - t0)
        for op_name, seg, dt, shared in segs:
            track = self.per_op.get(op_name)
            if track is None:
                track = self.per_op[op_name] = _OpLatency()
            track.add_segment(seg, dt, shared)
            self.segment_totals[seg] += dt
        self.e2e.add(e2e)
        self.traces_decomposed += 1
        brief = []
        for op_name, seg, dt, _shared in segs:
            brief.append((op_name, seg, dt))
        self._recent.append((e2e, brief))

    # -- freshness gauges (called from sampled-sync sites only) -------------
    def note_window_fire(self, op_name: str, ts, valid,
                         now_usec: Optional[int] = None) -> None:
        """Fire-time minus window-close event time over the fired records
        of one sampled (already-synced) window batch.  ``ts``/``valid``
        may be device or host arrays — callers only reach here from sites
        that already paid the sync (1 in sample_every * device_sync_every
        batches), so the ``np.asarray`` is not a new blocking sync."""
        v = np.asarray(valid)
        if not v.any():
            return
        close = int(np.asarray(ts)[v].max())
        if close <= 0:
            return
        if now_usec is None:
            now_usec = current_time_usecs()
        track = self.per_op.get(op_name)
        if track is None:
            track = self.per_op[op_name] = _OpLatency()
        if track.freshness is None:
            track.freshness = LatencyHistogram()
        track.freshness.add(max(0.0, float(now_usec - close)))

    # -- SLO evaluation (watchdog cadence) ----------------------------------
    def tick(self) -> None:
        """One cadence step: harvest, then evaluate the SLO against the
        rolling window.  Enter is immediate, the verdict latches, and
        clear needs ``clear_after`` consecutive in-budget evaluations —
        the same hysteresis stance as the health stall latch."""
        self.harvest()
        if self.slo_usec <= 0:
            return
        e2es = [e for e, _segs in self._recent]
        if len(e2es) < self.min_samples:
            return
        p99 = _p99(e2es)
        self._recent_p99_usec = p99
        if p99 > self.slo_usec:
            if not self.slo_active:
                self.slo_active = True
                self.slo_entered += 1
            self._ok_ticks = 0
            self.verdict = self._build_verdict(p99)
            self.last_verdict = self.verdict
        elif self.slo_active:
            self._ok_ticks += 1
            if self._ok_ticks >= self.clear_after:
                self.slo_active = False
                self.slo_cleared += 1
                self.verdict = None

    def _build_verdict(self, p99_usec: float) -> dict:
        """Attribute the violation to the dominant (operator, segment)
        pair of the SAME rolling window the p99 came from."""
        sums: Dict[tuple, float] = {}
        total = 0.0
        for _e2e, segs in self._recent:
            for op_name, seg, dt in segs:
                sums[(op_name, seg)] = sums.get((op_name, seg), 0.0) + dt
                total += dt
        dom_op, dom_seg, share = None, None, 0.0
        if sums:
            (dom_op, dom_seg), dom_sum = max(sums.items(),
                                             key=lambda kv: kv[1])
            share = dom_sum / total if total else 0.0
        p99_ms = round(p99_usec / 1000.0, 3)
        budget_ms = round(self.slo_usec / 1000.0, 3)
        arrow = SEGMENT_ARROWS.get(dom_seg, dom_seg or "?")
        msg = (f"p99 budget {budget_ms:g} ms, e2e {p99_ms:g} ms, "
               f"{share:.0%} in {arrow} on op `{dom_op}`")
        if dom_seg == "emitted_to_dispatched" and self._megastep_k(dom_op):
            msg += " — megastep K-wait"
        return {
            "state": "SLO_VIOLATED",
            "p99_ms": p99_ms,
            "budget_ms": budget_ms,
            "dominant_op": dom_op,
            "dominant_segment": dom_seg,
            "share": round(share, 4),
            "message": msg,
        }

    def _megastep_k(self, op_name: Optional[str]) -> int:
        plane = self.megastep_plane
        if plane is None or op_name is None:
            return 0
        for edge in plane.edges:
            if edge.op.name == op_name:
                return edge.k
        return 0

    def _megastep_floor(self, op_name: str) -> Optional[float]:
        plane = self.megastep_plane
        if plane is None:
            return None
        for edge in plane.edges:
            if edge.op.name == op_name:
                return edge.freshness_floor_usec()
        return None

    # -- export --------------------------------------------------------------
    def section(self) -> dict:
        """The ``stats()["Latency_plane"]`` payload — also the postmortem
        ``latency.json`` body and the input contract of
        ``analysis/latency.py`` / ``tools/wf_slo.py``."""
        graph_total = sum(self.segment_totals.values()) or 0.0
        per_op = {}
        for op_name, track in sorted(self.per_op.items()):
            entry = {
                "segments_usec": {seg: h.quantiles()
                                  for seg, h in sorted(
                                      track.segments.items())},
                "total_usec": round(track.total_usec, 3),
                "budget_share": round(track.total_usec / graph_total, 4)
                if graph_total else 0.0,
                "dominant_segment": track.dominant_segment(),
                "device_busy_usec": round(track.device_busy_usec, 3),
                "shared_k_traces": track.shared_k_traces,
            }
            if track.freshness is not None:
                entry["freshness_usec"] = track.freshness.quantiles()
            k = self._megastep_k(op_name)
            if k:
                entry["megastep_k"] = k
                entry["freshness_floor_usec"] = self._megastep_floor(
                    op_name)
            per_op[op_name] = entry
        return {
            "enabled": True,
            "slo_ms": round(self.slo_usec / 1000.0, 3),
            "traces_decomposed": self.traces_decomposed,
            "traces_open": len(self._open),
            "traces_dropped": self.traces_dropped,
            "events_lost": self.events_lost,
            "e2e_usec": self.e2e.quantiles(),
            "segments_total_usec": {s: round(v, 3) for s, v
                                    in self.segment_totals.items()},
            "per_op": per_op,
            "slo": {
                "active": self.slo_active,
                "entered": self.slo_entered,
                "cleared": self.slo_cleared,
                "recent_p99_ms": round(self._recent_p99_usec / 1000.0, 3),
                "budget_ms": round(self.slo_usec / 1000.0, 3),
                "window": len(self._recent),
                "verdict": self.verdict,
                "last_verdict": self.last_verdict,
            },
        }
