"""Per-replica statistics (reference ``/root/reference/wf/stats_record.hpp:47-165``).

The reference records inputs/outputs/bytes and service times per replica, plus
GPU kernel-launch counts and H2D/D2H byte counts for device replicas
(``stats_record.hpp:80-82,152-160``).  The TPU equivalents map one-to-one:
compiled-program dispatches for kernel launches, stage/fetch bytes for the
transfer counters.  On top of the reference's lifetime counters and running
average, every replica keeps **log-bucketed latency histograms**
(monitoring/recorder.py): ``service_hist`` distributes the per-batch service
spans the average used to flatten, and sinks fill ``e2e_hist`` with
staged→sunk latencies from the flight recorder's trace lane — both surface
as ``p50/p95/p99`` here and aggregated in ``PipeGraph.stats()``.
"""

from __future__ import annotations

import dataclasses
import time

from windflow_tpu.analysis import debug_concurrency as _dbg
from windflow_tpu.basic import current_time_usecs
from windflow_tpu.monitoring.recorder import LatencyHistogram


@dataclasses.dataclass
class StatsRecord:
    operator_name: str = ""
    replica_index: int = 0
    is_tpu: bool = False
    start_time_usec: int = dataclasses.field(default_factory=current_time_usecs)
    inputs_received: int = 0
    inputs_ignored: int = 0   # e.g. late tuples at window operators
    outputs_sent: int = 0
    # Service-time accounting (reference startStatsRecording/endStatsRecording,
    # basic_operator.hpp:133-158).
    service_time_usec: float = 0.0
    num_service_samples: int = 0
    # Device-side counters (reference GPU extensions of Stats_Record).
    # h2d_bytes is credited by the staging plane through the owning
    # replica's emitter (parallel/emitters.py bind_observability); d2h_bytes
    # by the TPU→host boundary (DeviceToHostEmitter) and columnar sinks.
    device_programs_launched: int = 0
    h2d_bytes: int = 0
    #: decoded (pre-compression) bytes behind h2d_bytes: the wire plane
    #: (windflow_tpu/wire.py) makes the two diverge — h2d_bytes is the
    #: actual transfer, this is what the decoded lanes occupy.  Counting
    #: only one of them would let compression silently inflate every
    #: bytes-derived ratio (roofline attributed_fraction, MB/s legs).
    h2d_logical_bytes: int = 0
    d2h_bytes: int = 0
    #: actual replica termination state (reference Stats_Record terminated
    #: flag); set by Replica._terminate — live dashboard reports show the
    #: truth instead of a hardcoded True
    is_terminated: bool = False
    #: per-batch service-span distribution (every start/end sample pair)
    service_hist: LatencyHistogram = dataclasses.field(
        default_factory=LatencyHistogram)
    #: staged→sunk end-to-end latency; filled only at terminal (sink)
    #: replicas from the flight recorder's trace lane
    e2e_hist: LatencyHistogram = dataclasses.field(
        default_factory=LatencyHistogram)
    _t0: float = 0.0

    def start_sample(self) -> None:
        if _dbg.ENABLED:
            # a stats record belongs to one replica whose processing is
            # single-consumer; an overlapping sample bracket from another
            # thread means two threads are driving the same replica
            _dbg.enter(self, "StatsRecord.start_sample")
        self._t0 = time.perf_counter()

    def end_sample(self) -> None:
        dur = (time.perf_counter() - self._t0) * 1e6
        self.service_time_usec += dur
        self.num_service_samples += 1
        self.service_hist.add(dur)
        if _dbg.ENABLED:
            _dbg.exit_(self)

    def avg_service_time_usec(self) -> float:
        if self.num_service_samples == 0:
            return 0.0
        return self.service_time_usec / self.num_service_samples

    def to_json(self) -> dict:
        """Schema kept close to the reference's per-replica JSON dump
        (``basic_operator.hpp:292-317``) for dashboard compatibility."""
        out = {
            "Replica_id": self.replica_index,
            "Starting_time_usec": self.start_time_usec,
            "Inputs_received": self.inputs_received,
            "Inputs_ignored": self.inputs_ignored,
            "Outputs_sent": self.outputs_sent,
            "Service_time_usec": round(self.avg_service_time_usec(), 3),
            "Service_latency_usec": self.service_hist.quantiles(),
            "Is_terminated": self.is_terminated,
            "Device_programs_launched": self.device_programs_launched,
            "Bytes_H2D": self.h2d_bytes,
            "Bytes_H2D_logical": self.h2d_logical_bytes,
            "Bytes_D2H": self.d2h_bytes,
        }
        if self.e2e_hist.count:
            out["End_to_end_latency_usec"] = self.e2e_hist.quantiles()
        return out
