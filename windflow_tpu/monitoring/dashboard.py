"""Dashboard server: receives monitoring reports and serves them over HTTP.

In-process re-design of the reference's out-of-process dashboard (Java
Spring + custom NIO TCP server on 20207 + React SPA, ``dashboard/Server/...
ServerWF.java:93-160``): the TCP side speaks the same length-prefixed
protocol as :mod:`windflow_tpu.monitoring.monitor` (NEW_APP / NEW_REPORT /
END_APP), keeps per-application diagram + report history, and a small HTTP
endpoint serves what the reference exposes via REST
(``SpringServer/RequestController.java:38-52``):

* ``GET /``                  — single-page UI (app list, per-operator
  throughput sparklines, graph diagram; reference React SPA equivalent)
* ``GET /apps``              — application list (id, name, alive, #reports)
* ``GET /apps/<id>``         — full report history (JSON)
* ``GET /apps/<id>/latest``  — most recent report
* ``GET /apps/<id>/diagram`` — the registered SVG diagram
* ``GET /metrics``           — Prometheus text exposition of every app's
  latest report (monitoring/openmetrics.py; point a Prometheus scrape job
  or ``tools/wf_metrics.py --check`` at it)

Run standalone: ``python -m windflow_tpu.monitoring.dashboard [tcp_port
[http_port]]``.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from windflow_tpu.monitoring.monitor import recv_exact


class AppRecord:
    def __init__(self, ident: int, diagram: str) -> None:
        self.ident = ident
        self.diagram = diagram
        self.reports: List[dict] = []
        self.ended = False

    @property
    def name(self) -> str:
        if self.reports:
            return self.reports[-1].get("PipeGraph_name", "?")
        return "?"

    def summary(self) -> dict:
        return {"id": self.ident, "name": self.name,
                "alive": not self.ended, "num_reports": len(self.reports)}


class DashboardServer:
    def __init__(self, tcp_port: int = 20207, http_port: int = 20208,
                 host: str = "127.0.0.1", max_reports: int = 3600) -> None:
        self.host = host
        self.max_reports = max_reports
        self.apps: Dict[int, AppRecord] = {}
        self._next_id = 1
        self._lock = threading.Lock()
        self._tcp = socket.socket()
        self._tcp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._tcp.bind((host, tcp_port))
        self._tcp.listen(16)
        self.tcp_port = self._tcp.getsockname()[1]
        self._http = ThreadingHTTPServer((host, http_port),
                                         self._make_handler())
        self.http_port = self._http.server_address[1]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- TCP protocol side ---------------------------------------------------
    def _serve_client(self, conn: socket.socket) -> None:
        app: Optional[AppRecord] = None
        try:
            mtype, length = struct.unpack(">ii", recv_exact(conn, 8))
            if mtype != 0:
                return
            diagram = recv_exact(conn, length).rstrip(b"\0").decode(
                "utf-8", "replace")
            with self._lock:
                ident = self._next_id
                self._next_id += 1
                app = self.apps[ident] = AppRecord(ident, diagram)
            conn.sendall(struct.pack(">ii", 0, ident))
            while not self._stop.is_set():
                mtype, ident_in, length = struct.unpack(
                    ">iii", recv_exact(conn, 12))
                payload = recv_exact(conn, length).rstrip(b"\0")
                try:
                    report = json.loads(payload)
                except json.JSONDecodeError:
                    report = {"malformed": True}
                with self._lock:
                    app.reports.append(report)
                    del app.reports[:-self.max_reports]
                    if mtype == 2:  # END_APP
                        app.ended = True
                conn.sendall(struct.pack(">ii", 0, 0))
                if mtype == 2:
                    break
        except (ConnectionError, struct.error, OSError):
            pass
        finally:
            if app is not None and not app.ended:
                with self._lock:
                    app.ended = True  # connection dropped = app gone
            conn.close()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._tcp.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_client, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    # -- HTTP side -----------------------------------------------------------
    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                parts = [p for p in self.path.split("/") if p]
                if not parts or parts == ["index.html"]:
                    # the single-page UI (reference React SPA equivalent)
                    from windflow_tpu.monitoring.webui import INDEX_HTML
                    body = INDEX_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if parts == ["metrics"]:
                    # snapshot under the lock, render OUTSIDE it (same
                    # stance as the JSON endpoints below)
                    with server._lock:
                        latest = [(a.ident, a.name, a.reports[-1])
                                  for a in server.apps.values()
                                  if a.reports]
                    from windflow_tpu.monitoring.openmetrics import \
                        render_openmetrics_multi
                    body = render_openmetrics_multi(
                        [({"app": name, "app_id": str(ident)}, report)
                         for ident, name, report in latest]).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                # Snapshot under the lock, serialize and write OUTSIDE it: a
                # stalled HTTP client must never block TCP report ingestion
                # (monitors time out and switch off for good).
                obj, code, svg = None, 200, None
                with server._lock:
                    if parts == ["apps"]:
                        obj = [a.summary() for a in server.apps.values()]
                    elif len(parts) >= 2 and parts[0] == "apps":
                        try:
                            app = server.apps[int(parts[1])]
                        except (KeyError, ValueError):
                            obj, code = {"error": "unknown app"}, 404
                        else:
                            if len(parts) == 2:
                                obj = {**app.summary(),
                                       "reports": list(app.reports)}
                            elif parts[2] == "latest":
                                obj = app.reports[-1] if app.reports else {}
                            elif parts[2] == "diagram":
                                svg = app.diagram
                if svg is not None:
                    body = svg.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "image/svg+xml")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if obj is None:
                    obj, code = {"error": "not found"}, 404
                self._json(obj, code)

        return Handler

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "DashboardServer":
        for target in (self._accept_loop, self._http.serve_forever):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._tcp.close()
        except OSError:
            pass
        self._http.shutdown()
        self._http.server_close()


def main(argv=None) -> None:
    import sys
    args = list(argv if argv is not None else sys.argv[1:])
    tcp_port = int(args[0]) if args else 20207
    http_port = int(args[1]) if len(args) > 1 else 20208
    server = DashboardServer(tcp_port=tcp_port, http_port=http_port,
                             host="0.0.0.0")
    server.start()
    print(f"windflow_tpu dashboard: TCP {server.tcp_port} / "
          f"HTTP {server.http_port} (open http://localhost:"
          f"{server.http_port}/ for the UI; GET /apps for JSON)")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
