"""Flight recorder: per-batch span tracing + log-bucketed latency histograms.

The reference's observability layer records per-replica counters and service
times (``stats_record.hpp``) — enough when every operator blocks on its own
work.  Here dispatch is asynchronous (JAX enqueues; the chip crunches later),
so a per-operator running average no longer says where a batch spends its
time.  This module adds the missing batch-granular layer:

* **Span events.**  A sampled batch carries a trace id (``HostBatch.trace``
  / ``DeviceBatch.trace`` = ``(trace_id, t_origin_usec)``) from its birth at
  a source emitter or the staging plane all the way to the sink.  Hooks on
  the hot path append ``(trace_id, stage, t)`` records — stages ``staged``,
  ``emitted``, ``dispatched``, ``device_done``, ``collected``, ``sunk`` —
  into a preallocated per-replica **ring buffer** (:class:`ReplicaRing`):
  no allocation, no locking, no syscalls on the hot path; old events are
  overwritten when the ring wraps.

* **Sampling.**  One batch in ``Config.trace_sample_every`` is traced
  (default 64); untraced batches carry ``trace=None`` and every hook
  degenerates to one attribute check.  ``device_done`` additionally calls
  ``block_until_ready`` — a real sync — so it fires only every
  ``Config.trace_device_sync_every``-th *traced* batch (default 8, i.e.
  1 in 512 batches at the default sampling): the recorder's documented
  overhead budget is **< 2%** on the bench chain
  (tests/test_observability.py asserts it with generous slack).

* **Histograms.**  :class:`LatencyHistogram` buckets values by log2 —
  64 buckets cover 1 usec..centuries in constant memory — and reports
  ``p50/p95/p99`` by geometric interpolation inside the bucket, clamped to
  the exact observed ``[min, max]`` (so a single sample reports itself, not
  its bucket's midpoint).  Per-operator service-time histograms live in
  ``StatsRecord``; the staged→sunk end-to-end histogram is fed by sinks
  from the trace lane.

* **Export.**  :func:`chrome_trace_from_events` renders the merged rings as
  Chrome-trace JSON (the ``traceEvents`` array format) loadable in
  ``chrome://tracing`` or Perfetto next to a ``jax.profiler`` capture;
  ``PipeGraph.dump_trace()`` and ``tools/trace_export.py`` wrap it.

When ``Config.flight_recorder`` is off, ``PipeGraph`` binds no recorder at
all: replicas hold ``ring = None`` and emitters ``flight = None``, so the
hot path's only residue is a ``is not None`` check per batch.
"""

from __future__ import annotations

import itertools
import json
from typing import List, Optional

import numpy as np

from windflow_tpu.analysis import debug_concurrency as _dbg
from windflow_tpu.analysis.hotpath import hot_path
from windflow_tpu.basic import current_time_usecs

#: span stage codes (ring buffers store the code, exports the name)
STAGED = 0      # host rows fixed into a device batch (staging plane)
EMITTED = 1     # host batch formed/shipped by an emitter
DISPATCHED = 2  # device program enqueued for the batch (async!)
DEVICE_DONE = 3  # device results ready (block_until_ready, sampled subset)
COLLECTED = 4   # batch pulled from a replica inbox for processing
SUNK = 5        # batch reached a terminal (sink) replica

STAGE_NAMES = ("staged", "emitted", "dispatched", "device_done",
               "collected", "sunk")


class LatencyHistogram:
    """Log2-bucketed latency histogram (microseconds).

    ``add`` costs one ``int.bit_length`` and one array increment — no
    allocation, safe on the hot path.  Percentiles interpolate
    geometrically within the winning bucket and clamp to the observed
    ``[min, max]``, which makes the empty / single-sample / boundary edge
    cases exact (tests/test_observability.py pins them).
    """

    __slots__ = ("counts", "count", "total", "min", "max")

    NBUCKETS = 64

    def __init__(self) -> None:
        self.counts = np.zeros(self.NBUCKETS, np.int64)
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    @hot_path
    def add(self, usec: float) -> None:
        if usec < 0:
            usec = 0.0
        # bucket b holds values in [2^(b-1), 2^b); 0 lands in bucket 0
        b = int(usec).bit_length()
        if b >= self.NBUCKETS:
            b = self.NBUCKETS - 1
        self.counts[b] += 1
        self.count += 1
        self.total += usec
        if usec < self.min:
            self.min = usec
        if usec > self.max:
            self.max = usec

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        self.counts += other.counts
        self.count += other.count
        self.total += other.total
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentile(self, p: float) -> float:
        """Value at quantile ``p`` in [0, 1].  Empty histogram -> 0.0."""
        if self.count == 0:
            return 0.0
        rank = p * self.count
        cum = 0
        for b in range(self.NBUCKETS):
            c = int(self.counts[b])
            if c == 0:
                continue
            if cum + c >= rank:
                lo = 0.0 if b == 0 else float(1 << (b - 1))
                hi = float(1 << b)
                # geometric position of the rank inside this bucket
                frac = (rank - cum) / c
                val = lo + frac * (hi - lo)
                return min(max(val, self.min), self.max)
            cum += c
        return self.max

    def bucket_counts(self) -> list:
        """Nonzero ``[upper_bound_usec, count]`` pairs (bucket ``b`` holds
        values below ``2^b``): the raw series behind the Prometheus
        ``_bucket`` exposition (monitoring/openmetrics.py), where the
        quantile summary below is not enough."""
        return [[float(1 << b) if b else 1.0, int(c)]
                for b, c in enumerate(self.counts.tolist()) if c]

    def quantiles(self) -> dict:
        """The ``p50/p95/p99`` dict shipped by ``StatsRecord.to_json`` and
        ``PipeGraph.stats()`` (empty -> all zeros, count 0); ``sum`` and
        the raw ``buckets`` ride along for the OpenMetrics histogram
        exposition."""
        return {
            "count": self.count,
            "mean": round(self.mean(), 3),
            "p50": round(self.percentile(0.50), 3),
            "p95": round(self.percentile(0.95), 3),
            "p99": round(self.percentile(0.99), 3),
            "max": round(self.max, 3) if self.count else 0.0,
            "sum": round(self.total, 3),
            "buckets": self.bucket_counts(),
        }


class ReplicaRing:
    """Preallocated span-event ring for one replica.

    ``record`` writes three scalars into preallocated numpy arrays at a
    wrapping index — no allocation, no lock.  The driver loop and the host
    worker pool never share a ring (one per replica, and a replica's drain
    is single-threaded by construction), so the lock-free write is safe;
    the monitoring thread reads a possibly-torn snapshot, which is
    acceptable for telemetry (same stance as the lock-free backpressure
    reads, graph/pipegraph.py)."""

    __slots__ = ("op_name", "replica_index", "size", "trace", "stage", "t",
                 "shared_k", "n")

    def __init__(self, op_name: str, replica_index: int, size: int) -> None:
        self.op_name = op_name
        self.replica_index = replica_index
        self.size = max(8, int(size))
        self.trace = np.zeros(self.size, np.int64)
        self.stage = np.zeros(self.size, np.int8)
        self.t = np.zeros(self.size, np.int64)
        # K of the megastep group the event's timestamp is shared with
        # (0 = the stamp is this batch's own).  The latency ledger uses it
        # to divide group-shared device time by K instead of crediting the
        # whole group's compute to every member batch (latency_ledger.py).
        self.shared_k = np.zeros(self.size, np.int16)
        self.n = 0          # total events ever recorded (wraps the index)

    @hot_path
    def record(self, trace_id: int, stage: int, t_usec: int,
               shared: int = 0) -> None:
        if _dbg.ENABLED:
            # the lock-free write is safe ONLY because one thread drains a
            # replica at a time; overlapping record()s are the race the
            # debug mode turns into a diagnostic (context-managed so an
            # exception cannot leave a stale guard entry)
            with _dbg.entry_guard(self, "ReplicaRing.record"):
                return self._record_impl(trace_id, stage, t_usec, shared)
        return self._record_impl(trace_id, stage, t_usec, shared)

    @hot_path
    def _record_impl(self, trace_id: int, stage: int, t_usec: int,
                     shared: int = 0) -> None:
        i = self.n % self.size
        self.trace[i] = trace_id
        self.stage[i] = stage
        self.t[i] = t_usec
        self.shared_k[i] = shared
        self.n += 1

    def events(self) -> List[dict]:
        """Retained events, oldest first (ring order reconstructed)."""
        k = min(self.n, self.size)
        start = self.n % self.size if self.n > self.size else 0
        out = []
        for j in range(k):
            i = (start + j) % self.size
            out.append({
                "op": self.op_name,
                "replica": self.replica_index,
                "trace": int(self.trace[i]),
                "stage": STAGE_NAMES[int(self.stage[i])],
                "t_usec": int(self.t[i]),
                "shared_k": int(self.shared_k[i]),
            })
        return out


class FlightRecorder:
    """Graph-scoped recorder: owns the per-replica rings, the trace-id
    counter and the sampling decision.  Built by ``PipeGraph._build`` when
    ``Config.flight_recorder`` is on; replicas and emitters hold direct
    references to their ring (no indirection on the hot path)."""

    def __init__(self, sample_every: int = 64, ring_events: int = 65536,
                 device_sync_every: int = 8,
                 expected_rings: int = 1) -> None:
        self.sample_every = max(1, int(sample_every))
        self.ring_events = max(8, int(ring_events))
        self.device_sync_every = max(0, int(device_sync_every))
        self.expected_rings = max(1, int(expected_rings))
        self.rings: List[ReplicaRing] = []
        # itertools.count: __next__ is C-implemented and atomic under the
        # GIL, so concurrently-staging host-pool replicas never mint the
        # same trace id (a plain += would race and alias two batches'
        # spans in the Chrome export)
        self._seq = itertools.count(1)
        self.traces_started = 0

    # -- trace assignment (batch-birth sites: emitters, staging plane) ------
    def maybe_trace(self) -> Optional[tuple]:
        """Sampling decision for one new batch: ``(trace_id, t_origin)``
        for the 1-in-N sampled batch, None otherwise.  One counter tick +
        one modulo when not sampled."""
        seq = next(self._seq)
        if seq % self.sample_every:
            return None
        self.traces_started += 1
        return (seq, current_time_usecs())

    # -- ring registry -------------------------------------------------------
    def ring_for(self, op_name: str, replica_index: int) -> ReplicaRing:
        # ring_events splits evenly over the graph's replicas (the builder
        # passes the replica count), so total retained events stay bounded
        # regardless of graph width; the floor keeps narrow rings useful
        per = max(64, self.ring_events // self.expected_rings)
        ring = ReplicaRing(op_name, replica_index, per)
        self.rings.append(ring)
        return ring

    # -- export --------------------------------------------------------------
    def events(self) -> List[dict]:
        ev = [e for ring in self.rings for e in ring.events()]
        ev.sort(key=lambda e: e["t_usec"])
        return ev

    def summary(self) -> dict:
        return {
            "enabled": True,
            "sample_every": self.sample_every,
            "device_sync_every": self.device_sync_every,
            "traces_started": self.traces_started,
            "events_recorded": sum(r.n for r in self.rings),
            "events_retained": sum(min(r.n, r.size) for r in self.rings),
            "rings": len(self.rings),
        }

    def to_chrome_trace(self) -> dict:
        return chrome_trace_from_events(self.events())


def chrome_trace_from_events(events: List[dict],
                             metadata: Optional[dict] = None) -> dict:
    """Render raw span events as Chrome-trace JSON (``traceEvents`` array
    format), loadable in ``chrome://tracing`` and Perfetto.
    ``metadata`` entries are merged into ``otherData`` — the profiler
    bridge (graph/pipegraph.py ``profile()``) records the annotation
    format and capture directory there so this file and a
    ``jax.profiler`` capture cross-reference in one Perfetto session.

    Layout: one *thread* track per ``(op, replica)`` carrying instant
    events for every record, plus one *async* span per traced batch and
    stage pair (``b``/``e`` events keyed by the trace id) so a batch's
    staged→...→sunk journey reads as a nested bar across the pipeline.
    Timestamps are the recorder's wall-clock microseconds — the same
    domain as a ``jax.profiler`` capture, so the two files line up when
    opened side by side."""
    trace_events: List[dict] = []
    tids = {}
    for e in events:
        key = (e["op"], e["replica"])
        if key not in tids:
            tids[key] = len(tids)
            trace_events.append({
                "name": "thread_name", "ph": "M", "pid": 1,
                "tid": tids[key],
                "args": {"name": f"{e['op']}[{e['replica']}]"},
            })
    per_trace = {}
    for e in events:
        trace_events.append({
            "name": e["stage"], "ph": "i", "s": "t",
            "ts": e["t_usec"], "pid": 1, "tid": tids[(e["op"],
                                                      e["replica"])],
            "args": {"trace": e["trace"]},
        })
        per_trace.setdefault(e["trace"], []).append(e)
    for trace_id, evs in per_trace.items():
        evs.sort(key=lambda e: e["t_usec"])
        for a, b in zip(evs, evs[1:]):
            span = {"cat": "batch", "id": trace_id, "pid": 1, "tid": 0,
                    "name": f"{a['stage']}→{b['stage']}"}
            trace_events.append(dict(span, ph="b", ts=a["t_usec"]))
            trace_events.append(dict(span, ph="e", ts=b["t_usec"]))
    other = {"source": "windflow_tpu flight recorder", "clock": "wall_usec"}
    if metadata:
        other.update(metadata)
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def write_chrome_trace(events: List[dict], path: str,
                       metadata: Optional[dict] = None) -> str:
    with open(path, "w") as f:
        json.dump(chrome_trace_from_events(events, metadata), f)
    return path
