"""Health plane: per-operator watchdog, stall attribution, postmortems.

The monitoring layers so far *report* — counters (stats.py), spans
(recorder.py), compiles (jit_registry.py) — but never *judge*: a stalled
shard or a backpressured operator surfaced only as a bare
``"PipeGraph stalled ... (routing bug?)"`` and a dashboard that kept
showing the app alive.  This module closes that gap (the DrJAX stance:
silent degradation on a large mesh must be a first-class, machine-readable
signal):

* **State machine.**  :class:`HealthPlane` derives one of
  ``OK / BACKPRESSURED / STALLED / FAILED`` per operator from the gauges
  the monitor cadence already samples — queue-depth, watermark-frontier
  advancement, per-op input progress, and recompile storms from the
  compile watcher.  Evaluation runs at *cadence* (the 1 Hz monitoring
  thread, ``stats()`` reads, the stall path) — never on the per-batch hot
  path; with ``Config.health_watchdog`` off, ``PipeGraph`` binds no plane
  at all and every call site degenerates to one ``is not None`` check.

* **Stall attribution.**  On a stall (the driver loop made no progress,
  or the watchdog saw an operator's frontier frozen past the grace
  period), :meth:`HealthPlane.diagnose_stall` walks the operator list in
  reverse topological order and names the first operator still holding
  pending input whose progress counters stopped — the root cause whose
  refusal to drain explains every upstream symptom.  The diagnosis (per-op
  queue depth, frontier, last-advance age) is embedded in the raised
  ``WindFlowError`` instead of "routing bug?".

* **Verdict timeline.**  State *changes* append to a bounded deque, so a
  postmortem shows when each operator degraded, not just the final frame.

Thresholds live in ``Config`` (``WF_TPU_HEALTH_*`` env knobs,
docs/OBSERVABILITY.md "Health plane").  The plane never imports jax at
module scope; the black-box bundle it feeds (``PipeGraph.dump_postmortem``)
is rendered offline by ``tools/wf_doctor.py`` with no jax either.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

from windflow_tpu.basic import current_time_usecs

#: operator health states, worst last (graph verdict = max by this order).
#: SLO_VIOLATED sits between OK and BACKPRESSURED: the pipeline is
#: draining fine, it is just slower than the declared latency budget
#: (Config.latency_slo_ms; monitoring/latency_ledger.py) — with no SLO
#: configured the state is unreachable and every transition matches the
#: pre-SLO plane verbatim.
OK = "OK"
#: the roofline plane's advisory verdict (monitoring/calibration.py
#: RooflineLedger): the dominant hop's achieved throughput collapsed
#: against its own trailing baseline for ENTER_AFTER consecutive ticks.
#: The lowest non-OK notch — purely advisory (nothing is failing, the
#: pipeline just got slower than itself), so every harder state
#: outranks it; with the plane off the state is unreachable and every
#: transition matches the pre-roofline plane verbatim.
ROOFLINE_DEGRADED = "ROOFLINE_DEGRADED"
SLO_VIOLATED = "SLO_VIOLATED"
#: the tenant plane's budget verdict (monitoring/tenant_ledger.py):
#: the tenant this operator belongs to holds more resident device state
#: than Config.hbm_budget_bytes for ENTER_AFTER consecutive ticks.  One
#: notch above SLO_VIOLATED (memory overage starves co-resident tenants;
#: a slow pipeline only starves itself) and below BACKPRESSURED — with
#: no budget declared the state is unreachable and every transition
#: matches the pre-tenant plane verbatim.
OVER_BUDGET = "OVER_BUDGET"
BACKPRESSURED = "BACKPRESSURED"
STALLED = "STALLED"
FAILED = "FAILED"
STATES = (OK, ROOFLINE_DEGRADED, SLO_VIOLATED, OVER_BUDGET, BACKPRESSURED,
          STALLED, FAILED)
_SEVERITY = {s: i for i, s in enumerate(STATES)}

#: postmortem bundle schema tag (tools/wf_doctor.py validates against it)
POSTMORTEM_SCHEMA = "wf-postmortem/1"


class _OpTrack:
    """Watchdog memory for one operator: the previous sample's counters
    and the timestamps the state machine derives ages from."""

    __slots__ = ("name", "state", "since_usec", "last_advance_usec",
                 "last_inputs", "last_frontier", "queue_depth", "frontier",
                 "compile_storm", "failure", "stall_latched", "hot_shard",
                 "slo", "over_budget", "roofline")

    def __init__(self, name: str, now: int) -> None:
        self.name = name
        self.state = OK
        self.since_usec = now          # when the current state was entered
        self.last_advance_usec = now   # inputs/frontier last moved
        self.last_inputs = -1
        self.last_frontier: Optional[int] = None
        self.queue_depth = 0
        self.frontier: Optional[int] = None
        self.compile_storm = False
        self.failure: Optional[str] = None
        #: set by diagnose_stall's attribution: STALLED stays latched
        #: until the operator makes progress again (a later cadence
        #: sample inside the grace window must not flip a confirmed
        #: root cause back to OK)
        self.stall_latched = False
        #: shard-plane attribution (monitoring/shard_ledger.py): the
        #: specific replica holding the backlog when the operator is
        #: degraded and runs at parallelism > 1 — a BACKPRESSURED/
        #: STALLED verdict names the hot SHARD, not just the operator
        self.hot_shard: Optional[dict] = None
        #: latency-ledger attribution when this operator dominates an
        #: active SLO violation (monitoring/latency_ledger.py verdict)
        self.slo: Optional[dict] = None
        #: tenant-ledger attribution when this operator is the heaviest
        #: op of a tenant in active budget overage
        #: (monitoring/tenant_ledger.py verdict)
        self.over_budget: Optional[dict] = None
        #: roofline-ledger attribution when this operator is the
        #: dominant hop of an active throughput-collapse verdict
        #: (monitoring/calibration.RooflineLedger)
        self.roofline: Optional[dict] = None

    def verdict(self, now: int) -> dict:
        v = {
            "state": self.state,
            "since_usec": self.since_usec,
            "queue_depth": self.queue_depth,
            "watermark_frontier_usec": self.frontier,
            "last_advance_age_usec": max(0, now - self.last_advance_usec),
            "compile_storm": self.compile_storm,
            "failure": self.failure,
        }
        if self.hot_shard is not None:
            v["hot_shard"] = self.hot_shard
        if self.slo is not None:
            v["slo"] = self.slo
        if self.over_budget is not None:
            v["over_budget"] = self.over_budget
        if self.roofline is not None:
            v["roofline"] = self.roofline
        return v


class HealthPlane:
    """Graph-scoped watchdog.  Built by ``PipeGraph._build`` when
    ``Config.health_watchdog`` is on; every entry point is cadence-rate
    (1 Hz monitor thread, ``stats()``, the stall/crash paths) and takes
    the plane's own lock — nothing here runs per batch."""

    def __init__(self, graph) -> None:
        self.graph = graph
        cfg = graph.config
        self.stall_grace_usec = max(0, int(cfg.health_stall_grace_usec))
        self.backpressure_depth = int(cfg.health_backpressure_depth) \
            or max(1, cfg.max_inbox_messages // 2)
        self.recompile_storm = max(1, int(cfg.health_recompile_storm))
        now = current_time_usecs()
        self._tracks: Dict[str, _OpTrack] = {
            op.name: _OpTrack(op.name, now) for op in graph._operators}
        #: state-change timeline: {"t_usec", "changes": {op: state}}
        self.timeline: deque = deque(maxlen=max(8, int(cfg.health_history)))
        self.stall_events = 0
        self.last_stall: Optional[dict] = None
        self.samples_taken = 0
        self.sample_usec_total = 0.0   # watchdog self-cost (bench overhead)
        self._stall_bundle_written = False   # cadence auto-bundle: once
        #: thread id of a bundle write in progress (set by the graph's
        #: bundle writer around its locked write): an auto-bundle fired
        #: from the re-entrant stats sample on the SAME thread would
        #: deadlock the non-reentrant postmortem lock — another thread's
        #: auto-bundle just serializes behind the lock and must proceed
        self._bundle_thread = None
        self._lock = threading.Lock()
        #: latency ledger (monitoring/latency_ledger.py), bound by
        #: PipeGraph._build when Config.latency_ledger is on; its active
        #: SLO verdict turns the dominant operator's OK into
        #: SLO_VIOLATED (None = one attribute check per sample)
        self.latency = None
        #: tenant handle (monitoring/tenant_ledger.GraphTenantHandle),
        #: bound by PipeGraph._build when Config.tenant_ledger is on;
        #: its active OVER_BUDGET verdict turns the heaviest operator's
        #: OK into OVER_BUDGET (None = one attribute check per sample —
        #: the kill-switch contract, micro-asserted by
        #: tests/test_tenant_plane.py)
        self.tenant = None
        #: roofline ledger (monitoring/calibration.RooflineLedger),
        #: bound by PipeGraph._build when Config.roofline_plane is on;
        #: its active collapse verdict turns the dominant hop's OK into
        #: the advisory ROOFLINE_DEGRADED (None = one attribute check
        #: per sample, micro-asserted by tests/test_calibration.py)
        self.roofline = None
        #: the jit registry is process-global and never resets: baseline
        #: its per-op recompile counts now so a storm verdict reflects
        #: THIS graph's run, not a prior graph sharing operator names
        self._recompile_base = self._recompile_counts()

    # -- sampling (the watchdog tick) ---------------------------------------
    def sample(self, now: Optional[int] = None) -> dict:
        """One watchdog evaluation over the live graph.  Returns the
        per-operator verdict map.  Reads of replica counters are lock-free
        (same telemetry stance as ``PipeGraph._backpressured``); the
        plane's own bookkeeping is serialized — the monitor thread and a
        ``stats()`` caller may tick concurrently."""
        t0 = time.perf_counter()
        now = now if now is not None else current_time_usecs()
        storms = self._compile_storms()
        # snapshot the ledger's SLO verdict once, outside the lock — the
        # ledger ticks on the same monitor thread, so this is a plain
        # read of its latest published verdict, not a re-evaluation
        lat = self.latency
        slo_v = lat.verdict if lat is not None and lat.slo_active else None
        # same stance for the tenant ledger's budget verdict: the ledger
        # ticks at the same cadence, this is a read of its latest
        # published verdict (None unless THIS graph holds the tenant's
        # heaviest op — only that graph paints the verdict)
        ten = self.tenant
        ob_v = ten.health_verdict() if ten is not None else None
        # and the roofline ledger's collapse verdict — same plain-read
        # stance (the ledger ticks on the same monitor thread)
        rfl = self.roofline
        rf_v = rfl.health_verdict() if rfl is not None else None
        with self._lock:
            changes = {}
            for op in self.graph._operators:
                track = self._tracks.get(op.name)
                if track is None:   # operator added post-build: track late
                    track = self._tracks[op.name] = _OpTrack(op.name, now)
                state = self._evaluate_op(op, track, now,
                                          storms.get(op.name, False),
                                          slo_v, ob_v, rf_v)
                if state != track.state:
                    track.state = state
                    track.since_usec = now
                    changes[op.name] = state
            if changes:
                self.timeline.append({"t_usec": now, "changes": changes})
            verdicts = {name: t.verdict(now)
                        for name, t in self._tracks.items()}
            self.samples_taken += 1
            self.sample_usec_total += (time.perf_counter() - t0) * 1e6
            newly_stalled = [op for op, s in changes.items()
                             if s == STALLED]
            write_bundle = False
            if newly_stalled:
                # watchdog-confirmed stall (cadence detection — streaming
                # deployments driving step() never reach wait_end's hard
                # stall); count the event, auto-bundle once per graph
                # (wait_end's hard-stall path dumps its own fresher frame
                # regardless — bundle writes are serialized by the
                # graph's postmortem lock)
                self.stall_events += 1
                if not self._stall_bundle_written \
                        and self._bundle_thread != threading.get_ident() \
                        and self.graph.config.health_postmortem_on_crash:
                    self._stall_bundle_written = True
                    write_bundle = True
        if write_bundle:
            # outside the lock: dump_postmortem re-enters section()/sample()
            self.graph._safe_postmortem(
                "watchdog: stalled operator(s) " + ", ".join(newly_stalled))
        return verdicts

    def _evaluate_op(self, op, track: _OpTrack, now: int,
                     storm: bool, slo_v: Optional[dict] = None,
                     ob_v: Optional[dict] = None,
                     rf_v: Optional[dict] = None) -> str:
        # the queue-depth/min-frontier walk is the graph's (shared with
        # gauges(): the watchdog must judge exactly what the lag gauge
        # reports, or the two drift)
        depth, frontier = self.graph.op_frontier_and_depth(op)
        inputs = 0
        alive = False
        for rep in op.replicas:
            inputs += rep.stats.inputs_received
            if not rep.done:
                alive = True
        advanced = inputs != track.last_inputs \
            or (frontier is not None and frontier != track.last_frontier)
        if advanced:
            track.last_advance_usec = now
        track.last_inputs = inputs
        track.last_frontier = frontier
        track.queue_depth = depth
        track.frontier = frontier
        track.compile_storm = storm
        track.slo = None   # re-attached below only while the violation holds
        track.over_budget = None   # ditto for the budget verdict
        track.roofline = None      # ditto for the roofline collapse
        # hot-shard attribution: the replica holding the deepest backlog
        # (ties broken by the most-lagged frontier) — per-replica reads
        # only, so it works with the shard ledger off too; the ledger's
        # hot-KEY table joins in at diagnose_stall
        track.hot_shard = None
        if len(op.replicas) > 1 and depth > 0:
            from windflow_tpu.batch import WM_MAX, WM_NONE
            worst, w_depth, w_front = None, -1, None
            for rep in op.replicas:
                d = len(rep.inbox)
                wm = rep.current_wm
                f = wm if (wm != WM_NONE and wm < WM_MAX) else None
                if d > w_depth or (d == w_depth and f is not None
                                   and (w_front is None or f < w_front)):
                    worst, w_depth, w_front = rep.index, d, f
            if worst is not None and w_depth > 0:
                track.hot_shard = {
                    "shard": worst,
                    "queue_depth": w_depth,
                    "watermark_frontier_usec": w_front,
                }
        if advanced:
            track.stall_latched = False
        if track.failure is not None:
            return FAILED
        if not alive:
            # terminated cleanly — but a still-latched SLO verdict keeps
            # naming the run's latency story for post-run stats() and
            # postmortem readers (the ledger stops ticking with the
            # graph, so the latch is the final word)
            state = OK
            if rf_v is not None and rf_v.get("dominant_op") == op.name:
                # advisory and lowest-severity: attached first so a
                # latched SLO/budget verdict takes the state slot
                track.roofline = rf_v
                state = ROOFLINE_DEGRADED
            if slo_v is not None and slo_v.get("dominant_op") == op.name:
                track.slo = slo_v
                state = SLO_VIOLATED
            if ob_v is not None and ob_v.get("heaviest_op") == op.name:
                # resident state outlives the run — a latched budget
                # verdict is post-run truth, same as the SLO latch
                track.over_budget = ob_v
                state = OVER_BUDGET
            return state
        if track.stall_latched:
            return STALLED
        if depth > 0 and not advanced \
                and now - track.last_advance_usec >= self.stall_grace_usec:
            # latch here too: a grace-window detection IS a confirmed
            # stall — diagnose_stall reads the latch to avoid counting
            # the same stall a second time at wait_end
            track.stall_latched = True
            return STALLED
        if depth >= self.backpressure_depth or storm:
            return BACKPRESSURED
        # SLO check LAST: a violation only upgrades an otherwise-OK
        # operator (FAILED/STALLED/BACKPRESSURED already name a harder
        # problem and the latency verdict rides along in track.slo
        # regardless via the ledger section) — and only the verdict's
        # dominant operator carries the state, so one slow op does not
        # paint the whole graph red
        state = OK
        # roofline check FIRST among the verdict upgrades: advisory and
        # lowest-severity, so an SLO/budget verdict on the same operator
        # overwrites the state slot (the attribution stays in
        # track.roofline regardless), and only the collapse verdict's
        # dominant hop carries the state
        if rf_v is not None and rf_v.get("dominant_op") == op.name:
            track.roofline = rf_v
            state = ROOFLINE_DEGRADED
        if slo_v is not None and slo_v.get("dominant_op") == op.name:
            track.slo = slo_v
            state = SLO_VIOLATED
        # budget check after SLO: both verdicts attach to their tracks,
        # and when one operator carries both, OVER_BUDGET (the more
        # severe state) wins the state slot — the co-resident tenants
        # it starves are a harder problem than its own latency
        if ob_v is not None and ob_v.get("heaviest_op") == op.name:
            track.over_budget = ob_v
            state = OVER_BUDGET
        return state

    def _recompile_counts(self) -> dict:
        """Summed compile-watcher recompiles per operator.  A registry
        entry maps by exact name or a "."-suffixed variant (wf_jit sites
        key "{op}.mesh"/"{op}.dense"/…) — a bare prefix would let
        operator 'agg' absorb 'agg2's recompiles.  Guarded: the watchdog
        must never die on a telemetry probe."""
        try:
            from windflow_tpu.monitoring.jit_registry import default_registry
            snap = default_registry().snapshot()
        except Exception:  # lint: broad-except-ok (the registry imports
            # jax; on an exotic/dead backend the storm signal degrades to
            # "none", the rest of the verdict still computes)
            return {}
        counts = {}
        for op in self.graph._operators:
            counts[op.name] = sum(
                entry.get("recompiles", 0)
                for name, entry in snap.items()
                if name == op.name or name.startswith(op.name + "."))
        return counts

    def _compile_storms(self) -> dict:
        """Per-operator recompilation-storm flags: recompiles accumulated
        SINCE this plane's construction (the process-global registry never
        resets — raw totals would leak a prior graph's storm into a fresh
        graph sharing operator names)."""
        counts = self._recompile_counts()
        return {name: True for name, n in counts.items()
                if n - self._recompile_base.get(name, 0)
                >= self.recompile_storm}

    # -- failure / stall notifications --------------------------------------
    def note_failure(self, exc: BaseException) -> Optional[str]:
        """Crash-path attribution: walk the traceback for the innermost
        replica frame and mark its operator FAILED.  Returns the operator
        name (None when no replica frame exists — e.g. a failure in the
        driver loop itself)."""
        op_name = None
        tb = getattr(exc, "__traceback__", None)
        while tb is not None:
            me = tb.tb_frame.f_locals.get("self")
            op = getattr(getattr(me, "op", None), "name", None)
            if op is not None and hasattr(me, "inbox"):
                op_name = op               # keep the innermost replica
            tb = tb.tb_next
        now = current_time_usecs()
        with self._lock:
            target = self._tracks.get(op_name) if op_name else None
            if target is not None:
                target.failure = f"{type(exc).__name__}: {exc}"[:300]
                if target.state != FAILED:
                    target.state = FAILED
                    target.since_usec = now
                    self.timeline.append({"t_usec": now,
                                          "changes": {op_name: FAILED}})
        return op_name

    def diagnose_stall(self) -> dict:
        """Attribution for a confirmed stall: sample once more, then walk
        the operator list in REVERSE topological order and name the first
        operator still holding pending input — the deepest consumer that
        stopped draining, whose refusal explains every upstream backlog.
        Records the stall event and returns the diagnosis dict (also kept
        as ``last_stall`` for the postmortem)."""
        now = current_time_usecs()
        verdicts = self.sample(now)
        root = None
        already_counted = False
        with self._lock:
            for op in reversed(self.graph._operators):
                track = self._tracks[op.name]
                live = any(not r.done for r in op.replicas)
                if live and track.queue_depth > 0:
                    # a cadence tick may have latched (and counted) this
                    # stall already — confirm, don't double-count
                    already_counted = track.stall_latched
                    if track.state != STALLED:
                        track.since_usec = now
                    track.state = STALLED
                    track.stall_latched = True
                    root = op.name
                    break
            if root is not None and not already_counted:
                verdicts[root] = self._tracks[root].verdict(now)
                self.timeline.append({"t_usec": now,
                                      "changes": {root: STALLED}})
            if not already_counted:
                self.stall_events += 1
            diag = {
                "t_usec": now,
                "root_cause": root,
                "verdicts": verdicts,
            }
            self.last_stall = diag
        if root is not None:
            # shard-plane join: the root operator's per-shard load and
            # hot-key table, so the diagnosis names the hot SHARD (and
            # the key pinning it) rather than just the operator
            led = getattr(self.graph, "_shard", None)
            if led is not None:
                try:
                    diag["shard"] = led.op_summary(root)
                except Exception:  # lint: broad-except-ok (same stance
                    # as every other health read: a ledger bug must not
                    # replace the stall diagnosis)
                    pass
        return diag

    @staticmethod
    def format_diagnosis(diag: dict) -> str:
        """The human half of a stall diagnosis — the text embedded in the
        raised ``WindFlowError`` so a stall is debuggable from the
        exception alone."""
        root = diag.get("root_cause")
        verdicts = diag.get("verdicts") or {}
        if root:
            v = verdicts.get(root, {})
            head = (f"root cause '{root}': stopped draining with "
                    f"{v.get('queue_depth', '?')} message(s) pending "
                    f"(frontier={v.get('watermark_frontier_usec')}, "
                    f"last advance "
                    f"{(v.get('last_advance_age_usec') or 0) / 1e6:.3f}s "
                    "ago)")
            hs = v.get("hot_shard")
            if hs:
                head += (f"; hot shard {hs.get('shard')} holds "
                         f"{hs.get('queue_depth')} of them")
            sh = diag.get("shard") or {}
            hot = (sh.get("hot_keys") or [{}])[0]
            if hot.get("key") is not None:
                head += (f" — key {hot['key']} alone carries "
                         f"{100 * (hot.get('share') or 0):.0f}% of the "
                         f"stream (shard ledger, {sh.get('basis')})")
        else:
            head = ("no operator holds pending input — sources idle but "
                    "the graph never terminated (source starvation or a "
                    "lost EOS)")
        per_op = "; ".join(
            f"{name}={v.get('state')}"
            f"(queue={v.get('queue_depth')}, "
            f"age={(v.get('last_advance_age_usec') or 0) / 1e6:.1f}s)"
            for name, v in verdicts.items())
        return f"{head}. Per-operator: {per_op}"

    # -- reporting -----------------------------------------------------------
    def section(self, sample_first: bool = True) -> dict:
        """The ``stats()["Health"]`` payload (one fresh watchdog tick by
        default — ``stats()`` reads are cadence-rate by contract)."""
        now = current_time_usecs()
        if sample_first:
            self.sample(now)
        with self._lock:
            return {
                "enabled": True,
                "graph_state": max(
                    (t.state for t in self._tracks.values()),
                    key=_SEVERITY.__getitem__) if self._tracks else OK,
                "verdicts": {name: t.verdict(now)
                             for name, t in self._tracks.items()},
                "stall_events": self.stall_events,
                "last_stall": self.last_stall,
                "samples_taken": self.samples_taken,
                "watchdog_usec_total": round(self.sample_usec_total, 1),
                "thresholds": {
                    "stall_grace_usec": self.stall_grace_usec,
                    "backpressure_depth": self.backpressure_depth,
                    "recompile_storm": self.recompile_storm,
                },
                "timeline": list(self.timeline),
            }
