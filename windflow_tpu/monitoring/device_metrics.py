"""Device-plane gauges: HBM occupancy, live buffers, compile telemetry.

The host plane reports through the flight recorder; this module is the
device half of ``PipeGraph.stats()`` — the ``"Device"`` section shipped
in every dashboard ``NEW_REPORT`` and rendered by the OpenMetrics layer:

* **jit** — the compile watcher's per-op table (compile count, cumulative
  compile wall-ms, recompiles, first-compile cost analysis) from
  :mod:`windflow_tpu.monitoring.jit_registry`.
* **memory** — ``device.memory_stats()`` per local device.  TPU runtimes
  report ``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit``;
  the CPU backend returns ``None`` — surfaced as-is (the documented
  guard, pinned by tests/test_device_metrics.py), never a crash.
* **live_buffers** — count and total bytes of live ``jax.Array``s per
  device (``jax.live_arrays()``): the HBM number the allocator stats
  can't give on backends without ``memory_stats``.  Multi-device arrays
  are attributed to a ``"sharded:N"`` pseudo-device rather than
  double-counted per shard-holding device.
* **staging** — the staging plane's device-byte accounting: cumulative
  packed bytes shipped host→device (``staging.device_bytes``) next to
  the pool's retained host bytes, so HBM growth can be told apart from
  host-pool growth at a glance.

Everything here runs at stats cadence (the 1 Hz monitor thread, test
dumps) — never on the per-batch path — and every backend probe is
guarded: a metrics read must not take the pipeline down.
"""

from __future__ import annotations

from typing import Optional


def memory_stats_per_device() -> list:
    """``device.memory_stats()`` for every local device, ``stats=None``
    where the backend has no allocator stats (CPU)."""
    import jax
    out = []
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except (AttributeError, RuntimeError, NotImplementedError):
            stats = None
        if isinstance(stats, dict):
            stats = {k: v for k, v in stats.items()
                     if isinstance(v, (int, float))}
        out.append({"device": str(d), "platform": d.platform,
                    "stats": stats})
    return out


def live_buffer_gauges() -> dict:
    """Count/bytes of live device arrays, grouped per device."""
    import jax
    per_device: dict = {}
    count = 0
    total = 0
    try:
        arrays = jax.live_arrays()
    except (AttributeError, RuntimeError):
        return {"count": 0, "bytes": 0, "per_device": {},
                "note": "live_arrays unavailable on this backend"}
    for a in arrays:
        try:
            nbytes = int(a.nbytes)
            devs = a.devices()
        except (AttributeError, RuntimeError):
            continue    # deleted/donated out from under the iteration
        count += 1
        total += nbytes
        key = str(next(iter(devs))) if len(devs) == 1 \
            else f"sharded:{len(devs)}"
        slot = per_device.setdefault(key, {"count": 0, "bytes": 0})
        slot["count"] += 1
        slot["bytes"] += nbytes
    return {"count": count, "bytes": total, "per_device": per_device}


def device_section(graph: Optional[object] = None) -> dict:
    """The ``stats()["Device"]`` payload.  ``graph`` supplies the config
    for the profiler-bridge pointer; the jit/memory/live-buffer gauges
    are process-scoped (one XLA client per process — same stance as the
    staging pool)."""
    from windflow_tpu import staging
    from windflow_tpu.monitoring.jit_registry import default_registry
    reg = default_registry()
    section = {
        "jit": reg.snapshot(),
        "jit_totals": reg.totals(),
        "memory": memory_stats_per_device(),
        "live_buffers": live_buffer_gauges(),
        "staging": {
            "pool_host_held_bytes":
                staging.default_pool().stats()["held_bytes"],
            "staged_device_bytes_total":
                staging.device_bytes.staged_bytes_total,
            # decoded bytes behind the transfers — diverges from the
            # wire total exactly by the wire plane's compression
            "staged_logical_bytes_total":
                staging.device_bytes.logical_bytes_total,
            "staged_device_batches_total":
                staging.device_bytes.staged_batches_total,
        },
    }
    if graph is not None:
        cfg = getattr(graph, "config", None)
        section["profiler_dir"] = getattr(cfg, "profiler_dir", "") or None
    return section
