"""Tenant plane — per-tenant attribution across every PipeGraph in the
process (docs/OBSERVABILITY.md "Tenant plane").

ROADMAP item 2 (multi-tenant serving: N PipeGraphs sharing one mesh
under HBM budgets) needs tenant labels threaded through the ledger /
OpenMetrics / postmortems and per-tenant HBM budgets enforced from
device telemetry.  This module is that measurement plane: a
process-level :class:`TenantLedger` registry that every built graph
joins (``Config.tenant``, default = the app name), attributing — at
monitor/stats cadence, with ZERO per-batch hot-path work —

- per-op jitted **dispatches** from per-wrapper counters (``WfJit``
  instances are per-operator-instance, so two graphs reusing an op
  name never cross-credit; the sweep ledger's baseline-and-diff
  stance),
- **compile wall-ms** from the process jit registry, diffed against a
  per-graph baseline snapshotted at register (per-NAME table, so two
  graphs sharing an op name split ambiguously — documented, and the
  bench/tests use distinct names per tenant),
- **H2D/D2H wire + logical bytes** from the per-replica transfer
  counters (the same counters ``stats()["Bytes_H2D_total"]`` sums, so
  per-tenant attribution sums to the graph totals by construction),
- **resident HBM state bytes** from a guarded, depth-limited walk of
  each operator/replica's instance dict for live device arrays — the
  budget basis (cumulative staged bytes would exceed any budget by
  design; what a tenant *holds* is what a budget constrains),
- modeled **ICI bytes** from the shard ledger and the tenant's
  **latency share** from the latency plane.

``Config.hbm_budget_bytes`` declares a per-tenant budget; *sustained*
overage (``ENTER_AFTER`` consecutive over-budget ticks) enters a
latched ``OVER_BUDGET`` health verdict attributed to the tenant's
heaviest op — the latency plane's SLO_VIOLATED contract applied to
memory (enter / hold while over / clear after ``CLEAR_AFTER``
consecutive under-budget ticks, ``last_verdict`` kept for postmortems).

Kill switch: ``Config.tenant_ledger`` / ``WF_TPU_TENANT_LEDGER=0``.
Off, the graph never registers and every call site keeps exactly one
``is not None`` check (micro-asserted by tests/test_tenant_plane.py).

The section feeds ``stats()["Tenant"]``, the ``wf_tenant_*``
OpenMetrics families, postmortem ``tenant.json`` (wf_doctor renders it
jax-free), ``analysis/tenancy.py`` and ``tools/wf_tenant.py`` — and is
the plan contract PR 20's tenant scheduler executes.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional

#: consecutive over-budget ticks before OVER_BUDGET enters ("sustained
#: overage" — one transient spike at stats cadence is not a verdict)
ENTER_AFTER = 2
#: consecutive under-budget ticks before an active verdict clears (the
#: latency ledger's hysteresis constant, applied to memory)
CLEAR_AFTER = 3

#: max recursion depth of the resident-state walk (operator dict →
#: container → state object dict → array covers every shipped op)
_WALK_DEPTH = 4


def _resident_state_bytes(objs, per_obj: Optional[dict] = None) -> int:
    """Sum ``nbytes`` of live device arrays reachable from the instance
    dicts of ``objs`` (operators + replicas), deduplicated by ``id``.

    Device arrays are recognised structurally (``nbytes`` + ``devices``
    attributes — jax arrays on every backend, never numpy).  The walk
    recurses plain containers and object ``__dict__``s to a fixed depth
    and never triggers properties (instance dicts only), so it is safe
    to run against arbitrary operator state at stats cadence."""
    #: id -> the remaining depth the node was last visited with.  A
    #: node first reached through a LONG path (exhausted depth) must be
    #: revisited when a short path reaches it with budget left — a
    #: plain seen-set would let the operator's `replicas` back-reference
    #: truncation-poison the later direct visit of its state dicts.
    seen: Dict[int, int] = {}
    counted = set()   # leaf arrays count once, ever
    total = 0

    def walk(v, depth: int) -> int:
        nonlocal total
        i = id(v)
        if seen.get(i, -1) >= depth:
            return 0
        seen[i] = depth
        got = 0
        if hasattr(v, "nbytes") and hasattr(v, "devices"):
            if i in counted:
                return 0
            counted.add(i)
            try:
                got = int(v.nbytes)
            except Exception:  # lint: broad-except-ok (a deleted buffer
                # raising from .nbytes must not take telemetry down)
                got = 0
            total += got
            return got
        if depth <= 0:
            return 0
        if isinstance(v, dict):
            for x in v.values():
                got += walk(x, depth - 1)
        elif isinstance(v, (list, tuple, set, frozenset, deque)):
            for x in v:
                got += walk(x, depth - 1)
        else:
            d = getattr(v, "__dict__", None)
            if isinstance(d, dict):
                for x in d.values():
                    got += walk(x, depth - 1)
        return got

    for o in objs:
        d = getattr(o, "__dict__", None)
        if not isinstance(d, dict):
            continue
        got = 0
        for v in d.values():
            got += walk(v, _WALK_DEPTH)
        if per_obj is not None:
            name = getattr(o, "name", None)
            if name is not None:
                per_obj[name] = per_obj.get(name, 0) + got
    return total


class _TenantTrack:
    """Per-tenant budget state machine (latency ledger's SLO machine
    with a sustained-entry twist: ``ENTER_AFTER`` consecutive over
    ticks before the verdict enters)."""

    __slots__ = ("tenant", "budget_bytes", "active", "entered", "cleared",
                 "verdict", "last_verdict", "_over_ticks", "_ok_ticks")

    def __init__(self, tenant: str, budget_bytes: int) -> None:
        self.tenant = tenant
        self.budget_bytes = int(budget_bytes)
        self.active = False
        self.entered = 0
        self.cleared = 0
        self.verdict: Optional[dict] = None
        self.last_verdict: Optional[dict] = None
        self._over_ticks = 0
        self._ok_ticks = 0

    def tick(self, hbm_bytes: int, graph: Optional[str],
             heaviest_op: Optional[str]) -> None:
        if self.budget_bytes <= 0:
            return
        over = hbm_bytes > self.budget_bytes
        if over:
            self._over_ticks += 1
            self._ok_ticks = 0
            if self.active or self._over_ticks >= ENTER_AFTER:
                if not self.active:
                    self.active = True
                    self.entered += 1
                self.verdict = {
                    "state": "OVER_BUDGET",
                    "tenant": self.tenant,
                    "hbm_bytes": int(hbm_bytes),
                    "budget_bytes": self.budget_bytes,
                    "overage_bytes": int(hbm_bytes - self.budget_bytes),
                    "graph": graph,
                    "heaviest_op": heaviest_op,
                    "message": (
                        f"tenant '{self.tenant}' holds {int(hbm_bytes)} B "
                        f"resident device state against an HBM budget of "
                        f"{self.budget_bytes} B "
                        f"(+{int(hbm_bytes - self.budget_bytes)} B); "
                        f"heaviest op: {heaviest_op} (graph {graph}) — "
                        "see tools/wf_tenant.py for the shed plan"),
                }
                self.last_verdict = self.verdict
        else:
            self._over_ticks = 0
            if self.active:
                self._ok_ticks += 1
                if self._ok_ticks >= CLEAR_AFTER:
                    self.active = False
                    self.cleared += 1
                    self.verdict = None
                    self._ok_ticks = 0

    def budget_json(self, hbm_bytes: int) -> dict:
        pressure = (round(hbm_bytes / self.budget_bytes, 4)
                    if self.budget_bytes > 0 else None)
        return {
            "budget_bytes": self.budget_bytes,
            "hbm_bytes": int(hbm_bytes),
            "pressure": pressure,
            "active": self.active,
            "entered": self.entered,
            "cleared": self.cleared,
            "verdict": self.verdict,
            "last_verdict": self.last_verdict,
        }


class _GraphEntry:
    """One registered graph: weakref + the attribution baselines taken
    at register (per-wrapper dispatch counters, per-name compile-ms)."""

    __slots__ = ("ref", "name", "tenant", "wbase", "cbase", "frozen")

    def __init__(self, graph, tenant: str) -> None:
        self.ref = weakref.ref(graph)
        self.name = graph.name
        self.tenant = tenant
        from windflow_tpu.monitoring.sweep_ledger import _op_wrappers
        self.wbase: Dict[int, int] = {}
        for op in graph._operators:
            for w in _op_wrappers(op):
                self.wbase[id(w)] = w.dispatches
        from windflow_tpu.monitoring.jit_registry import default_registry
        self.cbase: Dict[str, float] = {
            name: e["compile_ms_total"]
            for name, e in default_registry().snapshot().items()}
        #: final attribution snapshot taken at graph shutdown
        #: (_finalize), so a tenant's history survives its graph
        self.frozen: Optional[dict] = None

    def collect(self) -> Optional[dict]:
        """Per-graph attribution row; ``frozen`` after shutdown, live
        otherwise, ``None`` once the graph object itself is gone and no
        snapshot was frozen."""
        g = self.ref()
        if g is None or self.frozen is not None:
            return self.frozen
        from windflow_tpu.monitoring.sweep_ledger import _op_wrappers
        from windflow_tpu.monitoring.jit_registry import default_registry
        per_op: Dict[str, dict] = {}
        dispatches = 0
        for op in g._operators:
            n = 0
            for w in _op_wrappers(op):
                n += w.dispatches - self.wbase.get(id(w), 0)
            per_op[op.name] = {"dispatches": n}
            dispatches += n
        # compile wall-ms: per-NAME registry diff against the register
        # baseline, credited to the op whose name matches (the health
        # plane's prefix rule).  Two graphs sharing an op name split
        # this ambiguously — per-wrapper compile timing does not exist.
        compile_ms = 0.0
        snap = default_registry().snapshot()
        for op in g._operators:
            ms = 0.0
            for name, e in snap.items():
                if name == op.name or name.startswith(op.name + "."):
                    ms += (e["compile_ms_total"]
                           - self.cbase.get(name, 0.0))
            if ms > 0:
                per_op[op.name]["compile_ms"] = round(ms, 3)
                compile_ms += ms
        # resident device state: the budget basis
        per_obj: Dict[str, int] = {}
        resident = _resident_state_bytes(
            list(g._operators) + list(g._all_replicas), per_obj)
        for name, b in per_obj.items():
            if name in per_op:
                per_op[name]["resident_bytes"] = b
        heaviest = None
        if per_op:
            heaviest = max(
                per_op,
                key=lambda n: (per_op[n].get("resident_bytes", 0),
                               per_op[n]["dispatches"]))
        row = {
            "graph": g.name,
            "tenant": self.tenant,
            "dispatches": dispatches,
            "compile_ms": round(compile_ms, 3),
            "h2d_bytes": sum(r.stats.h2d_bytes for r in g._all_replicas),
            "h2d_logical_bytes": sum(r.stats.h2d_logical_bytes
                                     for r in g._all_replicas),
            "d2h_bytes": sum(r.stats.d2h_bytes for r in g._all_replicas),
            "resident_state_bytes": resident,
            "per_op": per_op,
            "heaviest_op": heaviest,
        }
        # modeled ICI bytes (shard plane) and latency share (latency
        # plane) — both optional planes, both read guarded
        try:
            if g._shard is not None:
                totals = g._shard.section()["totals"]
                row["ici_bytes_per_tuple"] = totals["ici_bytes_per_tuple"]
                # the shard plane's collective model, never a counter —
                # carried so tenant aggregation stays honest about it
                row["ici_provenance"] = totals.get("ici_provenance",
                                                   "modeled")
        except Exception:  # lint: broad-except-ok (optional plane)
            pass
        try:
            if g._latency is not None:
                row["latency_usec_total"] = round(
                    sum(g._latency.segment_totals.values()), 3)
        except Exception:  # lint: broad-except-ok (optional plane)
            pass
        return row


class TenantLedger:
    """Process-level multi-graph tenant registry.  One instance per
    process (:func:`default_ledger`); every graph built with
    ``Config.tenant_ledger`` on registers itself at build and freezes
    its attribution at shutdown."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._graphs: Dict[int, _GraphEntry] = {}   # id(graph) -> entry
        self._tracks: Dict[str, _TenantTrack] = {}  # tenant -> track
        # process staged-bytes baseline: the denominator of the
        # attributed-fraction reconciliation.  staging.device_bytes is
        # cumulative across every graph the process ever ran, so the
        # fraction must be computed over the delta since this ledger
        # first saw an empty registry (or reset()).
        self._staged_base = self._snap_staged()
        self.collects = 0
        self.collect_ms_total = 0.0
        self.last_collect_ms = 0.0
        #: tick throttle: health_tick() forwards every monitor-cadence
        #: call here, and N co-resident graphs each tick at their own
        #: cadence — the wall-clock floor keeps the budget machine's
        #: collect cost at cadence rate no matter how many graphs (or
        #: how hot a stats loop) drive it.  Per-tenant timestamps: one
        #: tenant's tick must not starve another's machine.
        self.tick_min_interval_s = 0.25
        self._last_tick: Dict[str, float] = {}

    @staticmethod
    def _snap_staged() -> dict:
        from windflow_tpu import staging
        db = staging.device_bytes
        return {"staged_bytes_total": db.staged_bytes_total,
                "logical_bytes_total": db.logical_bytes_total,
                "staged_batches_total": db.staged_batches_total}

    # -- registration --------------------------------------------------------
    def register(self, graph, tenant: str,
                 budget_bytes: int = 0) -> "GraphTenantHandle":
        with self._lock:
            if not self._graphs:
                # first graph of this accounting epoch: re-anchor the
                # process staged-bytes baseline so earlier (finished +
                # unregistered) graphs don't dilute the fraction
                self._staged_base = self._snap_staged()
            self._graphs[id(graph)] = _GraphEntry(graph, tenant)
            track = self._tracks.get(tenant)
            if track is None:
                track = self._tracks[tenant] = _TenantTrack(
                    tenant, budget_bytes)
            elif budget_bytes and not track.budget_bytes:
                track.budget_bytes = int(budget_bytes)
            return GraphTenantHandle(self, graph, tenant)

    def freeze(self, graph) -> None:
        """Capture the graph's final attribution (called from
        ``PipeGraph._finalize``) so the tenant roll-up survives the
        graph's replicas being torn down."""
        with self._lock:
            entry = self._graphs.get(id(graph))
        if entry is None or entry.frozen is not None:
            return
        try:
            frozen = entry.collect()
        except Exception:  # lint: broad-except-ok (shutdown telemetry)
            frozen = None
        with self._lock:
            if frozen is not None:
                entry.frozen = frozen

    def reset(self) -> None:
        """Drop every registration and re-anchor the process baselines
        (tests + bench legs: staged-byte totals are cumulative)."""
        with self._lock:
            self._graphs.clear()
            self._tracks.clear()
            self._staged_base = self._snap_staged()
            self.collects = 0
            self.collect_ms_total = 0.0
            self.last_collect_ms = 0.0

    # -- collection ----------------------------------------------------------
    def _collect_rows(self) -> List[dict]:
        with self._lock:
            entries = list(self._graphs.values())
        rows = []
        for e in entries:
            try:
                row = e.collect()
            except Exception as ex:  # lint: broad-except-ok (one broken
                # graph must not hide every other tenant's numbers)
                row = {"graph": e.name, "tenant": e.tenant,
                       "error": f"{type(ex).__name__}: {ex}"[:200]}
            if row is not None:
                rows.append(row)
        return rows

    def tick(self, tenant: Optional[str] = None,
             force: bool = False) -> None:
        """Advance the budget state machine(s) from a fresh collection
        — called from ``PipeGraph.health_tick()`` at monitor cadence,
        never on the batch path.  Wall-clock throttled per tenant
        (``tick_min_interval_s``) so a hot ``stats()`` loop cannot turn
        cadence work into per-call work; ``force`` bypasses (tests)."""
        now_s = time.monotonic()
        if not force:
            names = ([tenant] if tenant is not None
                     else list(self._tracks))
            if all(now_s - self._last_tick.get(n, 0.0)
                   < self.tick_min_interval_s for n in names):
                return
        with self._lock:
            for n in ([tenant] if tenant is not None
                      else list(self._tracks)):
                self._last_tick[n] = now_s
        t0 = time.perf_counter()
        rows = self._collect_rows()
        by_tenant: Dict[str, List[dict]] = {}
        for r in rows:
            by_tenant.setdefault(r["tenant"], []).append(r)
        with self._lock:
            tracks = dict(self._tracks)
        for name, track in tracks.items():
            if tenant is not None and name != tenant:
                continue
            trows = by_tenant.get(name, [])
            hbm = sum(r.get("resident_state_bytes", 0) for r in trows)
            graph, heaviest = None, None
            best = -1
            for r in trows:
                po = r.get("per_op") or {}
                h = r.get("heaviest_op")
                if h is None:
                    continue
                score = po.get(h, {}).get("resident_bytes", 0)
                if score > best:
                    best, graph, heaviest = score, r["graph"], h
            track.tick(hbm, graph, heaviest)
        dt = (time.perf_counter() - t0) * 1000.0
        self.collects += 1
        self.collect_ms_total += dt
        self.last_collect_ms = dt

    def verdict_for(self, graph_name: str) -> Optional[dict]:
        """The active OVER_BUDGET verdict whose heaviest op lives in
        ``graph_name`` — the one graph whose health plane paints the
        verdict (the latency plane's dominant-op contract)."""
        with self._lock:
            tracks = list(self._tracks.values())
        for t in tracks:
            v = t.verdict
            if t.active and v is not None and v.get("graph") == graph_name:
                return v
        return None

    # -- export --------------------------------------------------------------
    def section(self, focus_graph: Optional[str] = None,
                focus_tenant: Optional[str] = None) -> dict:
        """The ``stats()["Tenant"]`` payload — also the postmortem
        ``tenant.json`` body and the input contract of
        ``analysis/tenancy.py`` / ``tools/wf_tenant.py``.  The whole
        process table is reported from every graph: any one tenant's
        stats dump is enough for the advisor to plan across tenants."""
        t0 = time.perf_counter()
        rows = self._collect_rows()
        by_tenant: Dict[str, List[dict]] = {}
        for r in rows:
            by_tenant.setdefault(r["tenant"], []).append(r)
        total_latency = sum(r.get("latency_usec_total", 0.0) for r in rows)
        tenants: Dict[str, dict] = {}
        with self._lock:
            tracks = dict(self._tracks)
        for name in sorted(by_tenant):
            trows = by_tenant[name]
            agg = {
                "graphs": sorted(r["graph"] for r in trows),
                "dispatches": sum(r.get("dispatches", 0) for r in trows),
                "compile_ms": round(sum(r.get("compile_ms", 0.0)
                                        for r in trows), 3),
                "h2d_bytes": sum(r.get("h2d_bytes", 0) for r in trows),
                "h2d_logical_bytes": sum(r.get("h2d_logical_bytes", 0)
                                         for r in trows),
                "d2h_bytes": sum(r.get("d2h_bytes", 0) for r in trows),
                "resident_state_bytes": sum(
                    r.get("resident_state_bytes", 0) for r in trows),
                "ici_bytes_per_tuple": round(
                    sum(r.get("ici_bytes_per_tuple", 0.0)
                        for r in trows), 2),
                # the summed ICI column is the shard plane's structural
                # model in every contributing graph (calibration.py
                # vocabulary; the time column's bandwidth may still be
                # calibrated — see stats()["Shard"] totals)
                "ici_provenance": next(
                    (r["ici_provenance"] for r in trows
                     if "ici_provenance" in r), None),
                "latency_usec_total": round(
                    sum(r.get("latency_usec_total", 0.0)
                        for r in trows), 3),
            }
            agg["latency_share"] = (
                round(agg["latency_usec_total"] / total_latency, 4)
                if total_latency > 0 else None)
            per_op: Dict[str, dict] = {}
            for r in trows:
                for op, d in (r.get("per_op") or {}).items():
                    cur = per_op.setdefault(
                        op, {"dispatches": 0, "graph": r["graph"]})
                    cur["dispatches"] += d.get("dispatches", 0)
                    if "resident_bytes" in d:
                        cur["resident_bytes"] = (
                            cur.get("resident_bytes", 0)
                            + d["resident_bytes"])
                    if "compile_ms" in d:
                        cur["compile_ms"] = round(
                            cur.get("compile_ms", 0.0) + d["compile_ms"],
                            3)
            agg["per_op"] = per_op
            agg["heaviest_op"] = (max(
                per_op, key=lambda n: (per_op[n].get("resident_bytes", 0),
                                       per_op[n]["dispatches"]))
                if per_op else None)
            track = tracks.get(name)
            if track is not None:
                agg["budget"] = track.budget_json(
                    agg["resident_state_bytes"])
            tenants[name] = agg
        # reconciliation: tenants' attributed staged (H2D wire) bytes
        # over the process staged-transfer delta since the baseline —
        # the CI-gated hbm_attributed_fraction (>= 0.9)
        staged_now = self._snap_staged()
        process_delta = (staged_now["staged_bytes_total"]
                         - self._staged_base["staged_bytes_total"])
        tenants_total = sum(t["h2d_bytes"] for t in tenants.values())
        dt = (time.perf_counter() - t0) * 1000.0
        self.collect_ms_total += dt
        self.last_collect_ms = dt
        out = {
            "enabled": True,
            "tenants": tenants,
            "attributed": {
                "staged_bytes_tenants_total": tenants_total,
                "staged_bytes_process_total": process_delta,
                "staged_fraction": (
                    round(tenants_total / process_delta, 4)
                    if process_delta > 0 else None),
            },
            "overhead": {
                "collects": self.collects,
                "collect_ms_total": round(self.collect_ms_total, 3),
                "last_collect_ms": round(self.last_collect_ms, 3),
            },
        }
        if focus_graph is not None:
            for r in rows:
                if r["graph"] == focus_graph:
                    out["graph"] = r
                    break
        if focus_tenant is not None:
            out["tenant"] = focus_tenant
        return out


class GraphTenantHandle:
    """One graph's view of the shared ledger — what ``PipeGraph._tenant``
    holds.  The kill switch leaves this ``None`` and every call site
    keeps exactly one ``is not None`` check."""

    __slots__ = ("ledger", "tenant", "_graph_name", "_graph_ref")

    def __init__(self, ledger: TenantLedger, graph, tenant: str) -> None:
        self.ledger = ledger
        self.tenant = tenant
        self._graph_name = graph.name
        self._graph_ref = weakref.ref(graph)

    def tick(self) -> None:
        """Advance this tenant's budget machine (health_tick cadence)."""
        self.ledger.tick(self.tenant)

    def health_verdict(self) -> Optional[dict]:
        """The active OVER_BUDGET verdict iff its heaviest op lives in
        THIS graph (only the heaviest op's graph paints the verdict —
        the latency plane's dominant-op contract)."""
        return self.ledger.verdict_for(self._graph_name)

    def section(self) -> dict:
        return self.ledger.section(focus_graph=self._graph_name,
                                   focus_tenant=self.tenant)

    def freeze(self) -> None:
        """Snapshot this graph's final attribution at shutdown
        (``PipeGraph._finalize``)."""
        g = self._graph_ref()
        if g is not None:
            self.ledger.freeze(g)


_default_ledger: Optional[TenantLedger] = None
_default_lock = threading.Lock()


def default_ledger() -> TenantLedger:
    """The process-wide tenant ledger (the jit registry's singleton
    pattern): every graph in the process registers here, which is what
    makes cross-tenant attribution possible at all."""
    global _default_ledger
    with _default_lock:
        if _default_ledger is None:
            _default_ledger = TenantLedger()
        return _default_ledger
