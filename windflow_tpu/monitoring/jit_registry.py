"""Compile watcher: :func:`wf_jit`, a drop-in ``jax.jit`` with telemetry.

The flight recorder (monitoring/recorder.py) made the HOST plane legible;
the ~20 ``jax.jit`` sites across ops/, windows/, parallel/ and the staging
plane stayed a black box: nothing reported how often a program compiled,
how long compilation stalled the driver, or — the #1 silent streaming
killer — when a shape/dtype drift put an operator into a **recompilation
storm** (every batch pays a multi-ms trace+compile instead of a µs cache
hit, and the pipeline's latency SLO dies without a single error).

:func:`wf_jit` wraps ``jax.jit`` and feeds a process-wide
:class:`JitRegistry` (one aggregate entry per ``op_name``, the same
process-scope stance as ``staging.default_pool``):

* **compile count + wall time** — a call whose input signature (pytree
  structure + per-leaf shape/dtype) was never seen by this wrapper is
  timed end to end; the delta is trace+lower+backend-compile (dispatch of
  a cached program is µs — the timing is dominated by the compile).
* **recompile events** — a NEW signature after the wrapper's first
  compile increments the per-op recompile counter and, once per op name,
  raises a ``RuntimeWarning`` naming the op and both signatures.
* **dispatch count + donation audit** — every call bumps the op's
  dispatch counter (one lock-free integer add — the per-hop numerator of
  the sweep ledger, monitoring/sweep_ledger.py), and the first compile
  records which positional args were donated plus how many non-donated
  input leaves match an output leaf shape/dtype — each such leaf is a
  whole-buffer copy donation would elide (the ledger's donation-miss
  tripwire).
* **cost table** — on the first compile of an op name the watcher
  captures XLA cost analysis (FLOPs, bytes accessed) and, in ``compiled``
  mode, the executable's memory footprint.  ``WF_TPU_COST_ANALYSIS``
  picks the mode: ``lowered`` (default) uses the client-side
  ``Lowered.cost_analysis()`` estimate — a few ms, no second backend
  compile; ``compiled`` runs ``lowered.compile().cost_analysis()`` for
  optimized-HLO numbers plus ``memory_analysis()`` (one extra backend
  compile per op name per process — bench.py opts in, the test gate's
  tight wall budget keeps the default cheap); ``off`` disables capture.

Steady-state cost per call (the hot path): one pytree flatten, one
shape/dtype tuple, one set hash-compare — the ``@hot_path`` contract
``tools/wf_lint.py`` enforces on :meth:`WfJit._signature` /
:meth:`WfJit.__call__`.  ``WF_TPU_JIT_WATCH=0`` removes even that:
:func:`wf_jit` then returns the plain ``jax.jit`` callable.

``PipeGraph.stats()["Device"]`` ships the registry snapshot (see
monitoring/device_metrics.py); ``tools/wf_metrics.py`` and the dashboard
``GET /metrics`` render it in Prometheus text exposition format.
"""

from __future__ import annotations

import os
import threading
import time
import warnings
from typing import Callable, Dict, Optional

import jax

from windflow_tpu.analysis.hotpath import hot_path

#: cost-analysis capture mode on an op name's first compile (see module
#: docstring): "lowered" | "compiled" | "off"
COST_MODE = os.environ.get("WF_TPU_COST_ANALYSIS", "lowered")
#: kill switch: WF_TPU_JIT_WATCH=0 turns wf_jit into plain jax.jit
WATCH_ENABLED = os.environ.get("WF_TPU_JIT_WATCH", "1").lower() \
    not in ("0", "", "false", "off")


def _leaf_sig(x):
    """Hashable (shape, dtype) of one argument leaf.  Python numeric
    scalars key by TYPE, mirroring ``jax.jit``'s cache: jit traces a
    weak-typed scalar once per dtype, not per value, so keying by value
    would fabricate a recompile (and a storm warning) for every distinct
    int while JAX never re-traces.  str/bytes keep their value — they are
    only legal as static args, where the value IS the cache key."""
    dt = getattr(x, "dtype", None)
    if dt is not None:
        return (getattr(x, "shape", ()), dt)
    if isinstance(x, (str, bytes)):
        return x
    return type(x)


def format_sig(sig) -> str:
    """Human-readable signature for the recompile warning:
    ``f32[4096],i32[4096]``-style, structure elided."""
    if sig is None:
        return "<none>"
    _, leaves = sig
    parts = []
    for leaf in leaves:
        if isinstance(leaf, tuple) and len(leaf) == 2 \
                and isinstance(leaf[0], tuple):
            shape, dt = leaf
            parts.append(f"{dt}[{','.join(str(d) for d in shape)}]")
        elif isinstance(leaf, type):
            parts.append(leaf.__name__)
        else:
            parts.append(repr(leaf))
    return ",".join(parts) if parts else "<no args>"


class OpCompileEntry:
    """Aggregate compile telemetry for one op name (process-wide; several
    wrapper instances — one per operator instance or cached capacity —
    may feed the same entry)."""

    __slots__ = ("op_name", "compiles", "recompiles", "compile_ms_total",
                 "last_compile_ms", "cost", "cost_by_sig", "memory",
                 "warned", "lock", "dispatches", "donation",
                 "donation_attempted", "capture_warned")

    def __init__(self, op_name: str) -> None:
        self.op_name = op_name
        self.compiles = 0
        self.recompiles = 0
        self.compile_ms_total = 0.0
        self.last_compile_ms = 0.0
        self.cost: Optional[dict] = None     # captured on first compile
        #: cost tables per input signature: one op name may compile
        #: genuinely different programs (another graph's operator reusing
        #: the name, a different record structure) — the sweep ledger
        #: attributes each wrapper's dispatches with ITS program's bytes,
        #: not whichever program happened to compile first in the process
        self.cost_by_sig: Dict[object, Optional[dict]] = {}
        #                                      membership doubles as the
        #                                      one-attempt-per-signature
        #                                      claim (None = attempt
        #                                      failed, stays failed)
        self.memory: Optional[dict] = None   # "compiled" mode only
        self.warned = False                  # one-time recompile warning
        self.lock = threading.Lock()
        #: total jitted dispatches through every wrapper feeding this
        #: entry — the per-hop denominator of the sweep ledger
        #: (monitoring/sweep_ledger.py).  Bumped lock-free on the hot path
        #: (a torn concurrent add may undercount by a call; the ledger
        #: reads it at stats cadence, never as an exact invariant).
        self.dispatches = 0
        #: buffer-donation audit captured once, on the first compile:
        #: which positional args were donated, and how many non-donated
        #: input leaves match an output leaf shape/dtype (each one is a
        #: whole-buffer copy XLA could elide with donation — the sweep
        #: ledger's donation-miss tripwire).
        self.donation: Optional[dict] = None
        self.donation_attempted = False
        #: one-time "lowering/cost capture failed" warning (an audit
        #: skip must never be mistaken for an audit pass)
        self.capture_warned = False

    def to_json(self) -> dict:
        return {
            "compiles": self.compiles,
            "recompiles": self.recompiles,
            "compile_ms_total": round(self.compile_ms_total, 3),
            "last_compile_ms": round(self.last_compile_ms, 3),
            "dispatches": self.dispatches,
            "cost": self.cost,
            "memory": self.memory,
            "donation": self.donation,
        }


class JitRegistry:
    """Process-wide op-name → :class:`OpCompileEntry` table."""

    def __init__(self) -> None:
        self._entries: Dict[str, OpCompileEntry] = {}
        self._lock = threading.Lock()

    def entry(self, op_name: str) -> OpCompileEntry:
        with self._lock:
            e = self._entries.get(op_name)
            if e is None:
                e = self._entries[op_name] = OpCompileEntry(op_name)
            return e

    def snapshot(self) -> dict:
        """JSON-serializable per-op table (``stats()["Device"]["jit"]``).
        Ops that never compiled (entry created, no call yet) are skipped."""
        with self._lock:
            entries = dict(self._entries)
        return {name: e.to_json() for name, e in sorted(entries.items())
                if e.compiles or e.recompiles}

    def totals(self) -> dict:
        """Graph-agnostic aggregates (bench.py's ``device`` section)."""
        with self._lock:
            entries = tuple(self._entries.values())
        return {
            "ops_compiled": sum(1 for e in entries if e.compiles),
            "compiles": sum(e.compiles for e in entries),
            "recompiles": sum(e.recompiles for e in entries),
            "compile_ms_total": round(sum(e.compile_ms_total
                                          for e in entries), 3),
        }

    def dispatch_counts(self) -> Dict[str, int]:
        """op name -> cumulative jitted dispatches.  The sweep ledger
        snapshots this at graph build and diffs at stats time, so one
        graph's per-hop dispatch counts exclude every earlier graph that
        reused the same op names in this process."""
        with self._lock:
            entries = dict(self._entries)
        return {name: e.dispatches for name, e in entries.items()}

    def reset(self) -> None:
        """Drop every entry (tests).  Live wrappers re-create their entry
        lazily on the next compile; until then their cached dispatch
        counter feeds the detached entry, so dispatch-count tests must
        build fresh operators (fresh wrappers) after a reset."""
        with self._lock:
            self._entries.clear()


_default_registry = JitRegistry()


def default_registry() -> JitRegistry:
    """The process-wide compile registry every :func:`wf_jit` wrapper
    reports into (same sharing stance as ``staging.default_pool``)."""
    return _default_registry


class WfJit:
    """One watched ``jax.jit`` callable.  The seen-signature set is
    per-wrapper (a fresh operator instance compiling its first batch is a
    compile, not a recompile); counters aggregate per op name in the
    process-wide registry."""

    __slots__ = ("op_name", "_jit", "_fn", "_seen", "_last_sig", "_lock",
                 "_entry", "_donate", "dispatches", "cost")

    def __init__(self, fn: Callable, op_name: str, jit_kwargs: dict) -> None:
        self.op_name = op_name
        #: the undecorated traced body — wfverify (analysis/tracecheck.py)
        #: statically analyzes it through this handle
        self._fn = fn
        self._jit = jax.jit(fn, **jit_kwargs)
        self._seen = set()
        self._last_sig = None
        #: per-WRAPPER dispatch count next to the entry's per-NAME total:
        #: the sweep ledger attributes by wrapper so two graphs reusing
        #: one op name never pollute each other's per-hop numbers
        self.dispatches = 0
        #: cost table of THIS wrapper's compiled program (bound from the
        #: entry's per-signature table at compile time — same reason)
        self.cost: Optional[dict] = None
        # cached so the hot path's dispatch count is one attribute add —
        # no registry lookup per call; refreshed on every compile so a
        # registry reset() re-binds at the next compile
        self._entry = default_registry().entry(op_name)
        da = jit_kwargs.get("donate_argnums", ())
        self._donate = frozenset((da,) if isinstance(da, int) else da)
        # serializes the cold compile path only: replicas of one operator
        # share one wrapper and may first-call concurrently from the host
        # worker pool — without this, both would count a compile and the
        # loser could mint a spurious same-signature "recompile" (which
        # would trip check_bench_keys' recompile tripwire).  The hot path
        # stays lock-free; a racy miss there lands here and re-checks.
        self._lock = threading.Lock()

    # -- hot path ------------------------------------------------------------
    @hot_path
    def _signature(self, args, kwargs):
        """Input signature: pytree structure + per-leaf shape/dtype.  The
        whole per-batch cost of the compile watcher is building this tuple
        and one set hash-compare in :meth:`__call__`."""
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        return (treedef, tuple(_leaf_sig(x) for x in leaves))

    @hot_path
    def __call__(self, *args, **kwargs):
        # sweep-ledger hook: TWO lock-free integer adds per dispatch —
        # the wrapper's own count (per-hop attribution) and the entry's
        # per-name process total; everything else the ledger reads comes
        # from counters that already exist
        self.dispatches += 1
        self._entry.dispatches += 1
        sig = self._signature(args, kwargs)
        if sig in self._seen:       # hash-compare only: steady state
            return self._jit(*args, **kwargs)
        return self._compile_call(sig, args, kwargs)

    # -- cold path: a compile is happening -----------------------------------
    def _compile_call(self, sig, args, kwargs):
        with self._lock:
            return self._compile_call_locked(sig, args, kwargs)

    def _compile_call_locked(self, sig, args, kwargs):
        if sig in self._seen:
            # lost the race: another replica thread compiled this
            # signature while we waited — plain cached dispatch (but
            # adopt the winner's cost table for the sweep ledger)
            entry = default_registry().entry(self.op_name)
            with entry.lock:
                self.cost = entry.cost_by_sig.get(sig)
            return self._jit(*args, **kwargs)
        entry = default_registry().entry(self.op_name)
        self._entry = entry     # re-bind after a registry reset()
        is_recompile = bool(self._seen)
        prev_sig = self._last_sig
        with entry.lock:
            capture_cost = sig not in entry.cost_by_sig \
                and COST_MODE != "off"
            if capture_cost:
                entry.cost_by_sig[sig] = None   # claimed: one attempt
                #                                 per (op name, signature),
                #                                 even if the backend
                #                                 fails it
        if capture_cost:
            # BEFORE the dispatch: donated buffers are dead afterwards
            self._capture_cost(entry, sig, args, kwargs)
        with entry.lock:
            # the cost table of THIS wrapper's program (may come from an
            # earlier wrapper that compiled the same signature)
            self.cost = entry.cost_by_sig.get(sig)
        t0 = time.perf_counter()
        out = self._jit(*args, **kwargs)
        dt_ms = (time.perf_counter() - t0) * 1e3
        self._seen.add(sig)
        self._last_sig = sig
        with entry.lock:
            capture_donation = not entry.donation_attempted
            entry.donation_attempted = True
        if capture_donation:
            self._capture_donation(entry, args, kwargs, out)
        warn = False
        with entry.lock:
            entry.compiles += 1
            entry.compile_ms_total += dt_ms
            entry.last_compile_ms = dt_ms
            if is_recompile:
                entry.recompiles += 1
                if not entry.warned:
                    entry.warned = True
                    warn = True
        if warn:
            warnings.warn(
                f"wf_jit('{self.op_name}'): input signature changed from "
                f"[{format_sig(prev_sig)}] to [{format_sig(sig)}] — the "
                "operator recompiled.  A signature that keeps drifting is "
                "a recompilation storm (every batch pays trace+compile "
                "instead of a cache hit); pad batches to a fixed capacity "
                "or split the op per shape.  Counted in "
                'stats()["Device"]["jit"]; warning shown once per op.',
                RuntimeWarning, stacklevel=3)
        return out

    def _capture_cost(self, entry: OpCompileEntry, sig, args,
                      kwargs) -> None:
        """Best-effort XLA cost capture, once per (op name, input
        signature) (module docstring: 'lowered' estimate vs 'compiled'
        optimized-HLO numbers + memory footprint)."""
        cost_src = None
        memory = None
        capture_err: Optional[BaseException] = None
        try:
            lowered = self._jit.lower(*args, **kwargs)
            try:
                # IR auditor (analysis/ir_audit.py): parse this SAME
                # lowering's StableHLO into the process-wide program
                # store — zero extra compiles; one flag check when the
                # WF_TPU_IR_AUDIT kill switch is off
                from windflow_tpu.analysis import ir_audit
                ir_audit.record_lowered(self.op_name, sig, lowered)
            except Exception as e:  # lint: broad-except-ok (audit
                # capture must degrade like cost capture — warn below,
                # never break dispatch or lose the cost table)
                capture_err = e
            if COST_MODE == "compiled":
                compiled = lowered.compile()
                cost_src = compiled.cost_analysis()
                if isinstance(cost_src, (list, tuple)):
                    cost_src = cost_src[0] if cost_src else None
                mem = compiled.memory_analysis()
                if mem is not None:
                    memory = {
                        "argument_bytes":
                            getattr(mem, "argument_size_in_bytes", None),
                        "output_bytes":
                            getattr(mem, "output_size_in_bytes", None),
                        "temp_bytes":
                            getattr(mem, "temp_size_in_bytes", None),
                        "generated_code_bytes":
                            getattr(mem, "generated_code_size_in_bytes",
                                    None),
                    }
            else:
                cost_src = lowered.cost_analysis()
                if isinstance(cost_src, (list, tuple)):
                    cost_src = cost_src[0] if cost_src else None
        except Exception as e:  # lint: broad-except-ok (cost analysis is
            # a best-effort probe of backend-specific AOT APIs — any
            # failure must degrade to "no cost table", never break
            # dispatch)
            cost_src = None
            capture_err = e
        if capture_err is not None:
            # Surface the skip once per op name: a silently-missing cost
            # table / IR record used to be indistinguishable from a
            # program that audited clean.
            warn_capture = False
            with entry.lock:
                if not entry.capture_warned:
                    entry.capture_warned = True
                    warn_capture = True
            if warn_capture:
                warnings.warn(
                    f"wf_jit('{self.op_name}'): lowering capture failed "
                    f"({type(capture_err).__name__}: {capture_err}) — "
                    "this program has no cost table and no IR-audit "
                    "record (WF_TPU_COST_ANALYSIS="
                    f"{COST_MODE}); wfir reports it as pending, not "
                    "clean.  Warning shown once per op.",
                    RuntimeWarning, stacklevel=2)
        cost = None
        if isinstance(cost_src, dict):
            cost = {"mode": COST_MODE}
            for key, out_key in (("flops", "flops"),
                                 ("bytes accessed", "bytes_accessed"),
                                 ("transcendentals", "transcendentals")):
                v = cost_src.get(key)
                if isinstance(v, (int, float)):
                    cost[out_key] = float(v)
        with entry.lock:
            entry.cost_by_sig[sig] = cost
            if entry.cost is None and cost is not None:
                # the entry-level table (snapshot/bench back-compat)
                # stays first-come; per-program consumers read the
                # signature-keyed table through their wrapper
                entry.cost = cost
                entry.memory = memory
            # a failed capture stays failed: the signature's claim in
            # cost_by_sig stops every later compile of this (op name,
            # signature) from re-paying the probe — in "compiled" mode
            # that would be a whole extra backend compile per compile

    def current_cost(self) -> Optional[dict]:
        """Cost table of this wrapper's compiled program (sweep-ledger
        read path, stats cadence).  Re-reads the entry's per-signature
        table when the bound value is still ``None``: a concurrent first
        compile of the same signature may have claimed the slot before
        its capture finished, leaving this wrapper's compile-time read
        empty."""
        if self.cost is None and self._last_sig is not None:
            with self._entry.lock:
                self.cost = self._entry.cost_by_sig.get(self._last_sig)
        return self.cost

    def _capture_donation(self, entry: OpCompileEntry, args, kwargs,
                          out) -> None:
        """Buffer-donation audit, once per op name on the first compile
        (cold path): count non-donated input leaves whose shape/dtype
        matches an output leaf — each one is a whole-buffer copy XLA
        could elide with ``donate_argnums``/aliasing.  Shape/dtype
        metadata survives donation, so reading it off already-donated
        inputs is safe; everything degrades to ``None`` on failure."""
        try:
            out_pool: dict = {}
            out_bytes = 0
            for leaf in jax.tree_util.tree_leaves(out):
                nb = getattr(leaf, "nbytes", None)
                if nb is None:
                    continue
                out_bytes += int(nb)
                sig = (tuple(getattr(leaf, "shape", ())),
                       str(getattr(leaf, "dtype", None)))
                out_pool[sig] = out_pool.get(sig, 0) + 1
            cand_leaves = 0
            cand_bytes = 0
            arg_bytes = 0
            # kwargs leaves are donation candidates too: jax.jit cannot
            # donate keyword arguments at all
            operands = [(i in self._donate, a) for i, a in enumerate(args)]
            operands += [(False, v) for v in kwargs.values()]
            for donated, a in operands:
                for leaf in jax.tree_util.tree_leaves(a):
                    nb = getattr(leaf, "nbytes", None)
                    if nb is None:
                        continue
                    arg_bytes += int(nb)
                    if donated:
                        continue
                    sig = (tuple(getattr(leaf, "shape", ())),
                           str(getattr(leaf, "dtype", None)))
                    if out_pool.get(sig, 0) > 0:
                        out_pool[sig] -= 1
                        cand_leaves += 1
                        cand_bytes += int(nb)
            donation = {
                "donated_argnums": sorted(self._donate),
                "candidate_leaves": cand_leaves,
                "candidate_bytes": cand_bytes,
                "arg_bytes": arg_bytes,
                "out_bytes": out_bytes,
            }
        except Exception:  # lint: broad-except-ok (the audit walks
            # arbitrary user pytrees right after a compile — any failure
            # must degrade to "no donation table", never break dispatch)
            donation = None
        if donation is not None:
            with entry.lock:
                if entry.donation is None:
                    entry.donation = donation

    # -- AOT passthroughs (parity with jax.jit's stages API) -----------------
    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)


def wf_jit(fn: Optional[Callable] = None, *, op_name: str,
           **jit_kwargs) -> Callable:
    """Drop-in ``jax.jit`` replacement reporting compiles / recompiles /
    compile wall time / first-compile cost into the process-wide
    :class:`JitRegistry` under ``op_name``.  All other keyword arguments
    pass straight through to ``jax.jit`` (``donate_argnums`` etc.).

    Usable both as a call (``step = wf_jit(step_fn, op_name=...)``) and a
    decorator (``@wf_jit(op_name=...)``)."""
    if fn is None:
        return lambda f: wf_jit(f, op_name=op_name, **jit_kwargs)
    if not WATCH_ENABLED:
        return jax.jit(fn, **jit_kwargs)
    return WfJit(fn, op_name, jit_kwargs)
