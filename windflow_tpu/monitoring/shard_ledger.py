"""Shard plane: per-shard load/ICI attribution + key-skew sketches.

ROADMAP item 4 (elastic serving: "dynamic key re-sharding over ICI/DCN
on skew") assumes the hot shard can be *pinpointed* — but every gauge
shipped so far (watermark lag, queue depth, health verdicts, sweep-
ledger bytes) aggregates per OPERATOR: a keyed operator at parallelism
8 whose replica 3 holds the hot key shows one flat row, and skew stays
invisible until it becomes a stall.  This module is the measurement
plane a PR-10 resharding executor will act on (the PR 6 pattern: sweep
ledger → fusion advisor → fusion executor):

* **Key-skew sketches on the keyed edges.**  A fixed-size count-min
  sketch plus a hot-key candidate table, computed where the keys lane
  already exists:

  - *in-program* on device keyed edges and fused chains — the sketch
    state is threaded through the existing ``wf_jit`` programs (the
    keyby split, the fused chain's downstream key extraction) as one
    donated extra operand, so the update costs **zero extra
    dispatches**; the accumulated device state is merged to host only
    at monitor/stats cadence (the Julia-GPU-primitives stance: keep the
    measurement on device, never pull keys to host per batch);
  - *host-side numpy* at the keyed staging boundary, where
    ``native.keyby_partition`` already materializes the key lane and
    per-destination counts (the counts are free; the count-min rows are
    ``np.bincount`` passes);
  - *dense exact histograms* where the consumer declares a bounded key
    space (``withMaxKeys`` / dense ``withNumKeySlots``) — exact per-key
    counts, and on a mesh the per-key-SHARD load falls out of the key
    ranges chip *i* owns.

* **Per-shard attribution** of the per-operator-only gauges: queue
  depth, watermark frontier/lag, service-latency quantiles, HBM bytes
  (the hop's steady XLA-cost bytes × the replica's own dispatches), and
  a documented ICI model for mesh collectives (all_gather over ``data``
  for key-sharded FFAT/stateful state, psum of the dense reduce tables,
  all_to_all for arbitrary-key reduces — XLA cost tables carry no
  collective terms on the CPU backend, so the model is derived from the
  program structure ``parallel/mesh.py`` compiles and labeled as such).

Surfaces: ``PipeGraph.stats()["Shard"]``, ``wf_shard_*`` OpenMetrics
families, the webui per-shard drill-down, ``dump_trace()`` metadata,
the postmortem bundle's ``shard.json`` (``tools/wf_doctor.py`` renders
it jax-free), and the reshard advisor (``analysis/resharding.py`` /
``tools/wf_shard.py``).  ``Config.shard_ledger`` off builds no plane:
no sketch attaches anywhere and each read/update site keeps one
``is not None`` check (micro-asserted by tests/test_shard_plane.py,
same stance as the health/sweep/durability planes).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

#: count-min geometry: DEPTH independent rows of WIDTH counters.  WIDTH
#: is a power of two <= 2^16 so each row's index is one 16-bit field of
#: the 64-bit splitmix hash (4 x 16 = the whole hash, rows independent).
SKETCH_DEPTH = 4
SKETCH_WIDTH = 2048
#: device-side hot-key candidate ring: CAND_PER_BATCH strided lanes per
#: batch overwrite a CAND_RING-slot ring — a key carrying x% of the
#: stream appears among the candidates with probability ~x per batch,
#: so over a monitor cadence a hot key is caught with near-certainty.
CAND_RING = 64
CAND_PER_BATCH = 8
#: declared key spaces up to this bound keep an EXACT dense histogram
#: instead of the sketch (a [K] int64 row per keyed edge)
EXACT_KEYS_LIMIT = 1 << 16
#: cap on the host candidate set between prunes (CMS edges)
_CAND_POOL_LIMIT = 1024

#: nominal per-chip ICI bandwidth for the collective TIME model
#: (bytes/sec; ~90 GB/s per direction is the TPU-v4-class figure).  The
#: model is structural — the CPU backend moves nothing over ICI — so
#: the time is labeled with the assumption and overridable for other
#: fabrics.
ICI_BYTES_PER_SEC = float(os.environ.get("WF_TPU_ICI_BYTES_PER_SEC",
                                         str(90e9)))


def _splitmix64_np(k: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 over an int64 key column — bit-identical to
    ``parallel.emitters.splitmix64_int`` / ``_splitmix64_dev`` and the
    native ``wf_hash64`` (the sketch row hashes and the shard placement
    must agree across the host, device, and native paths)."""
    with np.errstate(over="ignore"):
        x = k.astype(np.int64).view(np.uint64) \
            + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def _key32_np(k: np.ndarray) -> np.ndarray:
    """int64 -> device int32 truncation (the key space the consuming
    operator's state table collapses to — sketch exactly what routing
    and state see, ``KeyedDeviceStageEmitter._key32``)."""
    return np.asarray(k).astype(np.int64).astype(np.int32).astype(np.int64)


# ---------------------------------------------------------------------------
# device-side sketch state: threaded through existing wf_jit programs
# ---------------------------------------------------------------------------

def device_sketch_init(n_shards: int):
    """Fresh on-device sketch state for one keyed program site.  Built
    lazily at the site's first sketched batch (never at import: this
    module must not pull jax at module scope)."""
    import jax.numpy as jnp
    return {
        "cms": jnp.zeros((SKETCH_DEPTH, SKETCH_WIDTH), jnp.int64),
        "counts": jnp.zeros(max(1, n_shards), jnp.int64),
        "cand": jnp.full(CAND_RING, np.iinfo(np.int32).min, jnp.int32),
        "batches": jnp.zeros((), jnp.int32),
        "total": jnp.zeros((), jnp.int64),
    }


def device_sketch_update(state, keys, valid, n_shards: int, dest=None):
    """The in-program sketch update, TRACED INTO the host program (the
    keyby split / the fused chain step) — zero extra dispatches, a few
    fused scatter-adds.  ``dest`` is the per-lane destination the keyby
    split already computed (invalid lanes == ``n_shards``); ``None``
    derives it from the same splitmix placement the emitters use."""
    import jax
    import jax.numpy as jnp
    from windflow_tpu.parallel.emitters import _splitmix64_dev
    k32 = keys.astype(jnp.int32)
    h = _splitmix64_dev(k32)
    vi = valid.astype(jnp.int64)
    cms = state["cms"]
    for i in range(SKETCH_DEPTH):
        idx = ((h >> jnp.uint64(16 * i))
               % jnp.uint64(SKETCH_WIDTH)).astype(jnp.int32)
        cms = cms.at[i, idx].add(vi)
    if dest is None:
        dest = jnp.where(valid,
                         (h % jnp.uint64(max(1, n_shards))).astype(jnp.int32),
                         jnp.int32(n_shards))
    counts = jnp.zeros(max(1, n_shards) + 1, jnp.int64) \
        .at[dest].add(1, mode="drop")[:max(1, n_shards)]
    cap = int(k32.shape[0])
    c = min(CAND_PER_BATCH, cap)
    stride = max(1, cap // c)
    cand_new = jnp.where(valid[::stride][:c], k32[::stride][:c],
                         jnp.int32(np.iinfo(np.int32).min))
    slots = max(1, CAND_RING // c)
    start = (state["batches"] % jnp.int32(slots)) * jnp.int32(c)
    cand = jax.lax.dynamic_update_slice(state["cand"], cand_new, (start,))
    return {"cms": cms, "counts": state["counts"] + counts, "cand": cand,
            "batches": state["batches"] + 1, "total": state["total"]
            + jnp.sum(vi)}


# ---------------------------------------------------------------------------
# the per-edge sketch: host accumulators + registered device states
# ---------------------------------------------------------------------------

class ShardSketch:
    """Key-skew sketch for ONE keyed consumer operator.  Host update
    paths accumulate numpy state; device program sites register a state
    getter and are merged only when :meth:`summary` runs (stats /
    monitor cadence — the only device sync the plane ever pays).
    Counter updates are deliberately lock-free (same telemetry stance as
    the replica counters): a torn concurrent add may undercount a batch,
    and the section reads are never exact invariants."""

    def __init__(self, n_shards: int, topk: int = 8,
                 max_keys: Optional[int] = None,
                 key_axis: int = 1,
                 placement: str = "splitmix") -> None:
        self.n_shards = max(1, n_shards)
        self.topk = max(1, topk)
        #: "splitmix" (device/keyed-staging routing), "stable_hash"
        #: (host KeyByEmitter), "dense_range" (mesh key-axis ownership)
        self.placement = placement
        #: reshard-executor key→shard override (windflow_tpu/serving):
        #: set when the executor re-places keys so hot-key shard
        #: attribution follows the LIVE routing, not the derived hash
        self.override: Optional[dict] = None
        self.key_axis = max(1, key_axis)
        self.shard_counts = np.zeros(self.n_shards, np.int64)
        self.total = 0
        self.batches = 0
        self.update_usec = 0.0
        self.max_keys = max_keys if (max_keys
                                     and max_keys <= EXACT_KEYS_LIMIT) \
            else None
        if self.max_keys is not None:
            # exact dense histogram; row K is the out-of-range overflow
            self.hist = np.zeros(self.max_keys + 1, np.int64)
            self.cms = None
        else:
            self.hist = None
            self.cms = np.zeros((SKETCH_DEPTH, SKETCH_WIDTH), np.int64)
        #: CMS hot-key candidates (key -> 0); pruned by estimate
        self._cands: Dict[int, int] = {}
        #: sampled-flush weights for host KeyByEmitter edges (no key
        #: column exists there — per-tuple hashing would blow the <2%
        #: budget, so the flush path samples one key per shipped batch)
        self._sampled: Dict[int, int] = {}
        self._sampled_n = 0
        #: device program sites: callables returning the live state dict
        self._device_states: List = []
        self._lock = threading.Lock()   # candidate-dict prune only

    # -- update paths --------------------------------------------------------
    def update_host(self, keys: np.ndarray,
                    counts: Optional[np.ndarray] = None) -> None:
        """Bulk host update from a materialized key column (the keyed
        staging boundary / the staging-probe sites).  ``counts`` are the
        per-destination totals ``native.keyby_partition`` already
        computed (free when present; derived placements otherwise)."""
        t0 = time.perf_counter()
        keys = np.asarray(keys, np.int64)
        n = keys.size
        if n == 0:
            return
        self.batches += 1
        self.total += n
        if counts is not None:
            self.shard_counts += np.asarray(counts, np.int64)
        elif self.placement == "dense_range" or self.n_shards == 1:
            pass    # derived from the histogram key ranges at summary
        elif self.placement == "mod":
            # mesh arbitrary-key owner hash (uint32(key) % n — the
            # all_to_all routing in mesh.make_sharded_reduce_arbitrary)
            d = ((keys & 0xFFFFFFFF) % self.n_shards).astype(np.intp)
            self.shard_counts += np.bincount(d, minlength=self.n_shards)
        else:
            h = _splitmix64_np(keys)
            d = (h % np.uint64(self.n_shards)).astype(np.intp)
            self.shard_counts += np.bincount(d, minlength=self.n_shards)
        if self.hist is not None:
            k = np.where((keys < 0) | (keys >= self.max_keys),
                         self.max_keys, keys)
            self.hist += np.bincount(k.astype(np.intp),
                                     minlength=self.max_keys + 1)
        else:
            h = _splitmix64_np(keys)
            for i in range(SKETCH_DEPTH):
                idx = ((h >> np.uint64(16 * i))
                       % np.uint64(SKETCH_WIDTH)).astype(np.intp)
                self.cms[i] += np.bincount(idx, minlength=SKETCH_WIDTH)
            step = max(1, n // CAND_PER_BATCH)
            # per-batch rotating offset: a FIXED stride over periodic
            # key layouts (every 8th lane is the cold key, say) would
            # alias and sample the same phase forever, blinding the
            # candidate pool to the other keys entirely
            off = int((self.batches * 7) % step)
            with self._lock:
                # candidate dict writes share the prune's lock: sibling
                # replicas' emitters may update one consumer's sketch
                # concurrently, and an unlocked insert during a prune's
                # iteration would raise into the staging path
                for k in keys[off::step][:CAND_PER_BATCH]:
                    self._cands[int(k)] = 0
            if len(self._cands) > _CAND_POOL_LIMIT:
                self._prune_cands()
        self.update_usec += (time.perf_counter() - t0) * 1e6

    def note_flush(self, shard: int, n: int, sample_key=None) -> None:
        """Host KeyByEmitter hook, batch-flush granularity: exact shard
        load from the flushed batch size + one sampled key per batch
        (approximate hot-key weights — the ``"sampled"`` basis).  Never
        raises: the load counters must stay single-counted even when
        the sampled user key defeats the dict (unhashable)."""
        self.batches += 1
        self.total += n
        self.shard_counts[shard] += n
        if sample_key is None:
            return
        try:
            with self._lock:
                self._sampled[sample_key] = \
                    self._sampled.get(sample_key, 0) + n
                self._sampled_n += n
                if len(self._sampled) > _CAND_POOL_LIMIT:
                    keep = sorted(self._sampled.items(),
                                  key=lambda kv: kv[1],
                                  reverse=True)[:_CAND_POOL_LIMIT // 2]
                    self._sampled = dict(keep)
        except TypeError:
            pass    # unhashable user key: the load above still counted

    def register_device_state(self, getter) -> None:
        """Register an in-program sketch site; ``getter()`` returns its
        live (cumulative) device state dict, or None before the first
        sketched batch.  Merged fresh on every summary — cumulative
        state is never folded into the host accumulators twice."""
        self._device_states.append(getter)

    # -- read path (stats / monitor cadence) ---------------------------------
    def _prune_cands(self) -> None:
        with self._lock:
            est = [(k, self._estimate(k)) for k in self._cands]
            est.sort(key=lambda kv: kv[1], reverse=True)
            self._cands = {k: 0 for k, _ in est[:_CAND_POOL_LIMIT // 2]}

    def _estimate(self, key: int, cms: Optional[np.ndarray] = None) -> int:
        c = self.cms if cms is None else cms
        h = _splitmix64_np(np.asarray([key], np.int64))[0]
        return int(min(
            c[i][int((h >> np.uint64(16 * i)) % np.uint64(SKETCH_WIDTH))]
            for i in range(SKETCH_DEPTH)))

    def hot_candidates(self, limit: int) -> list:
        """Top candidate keys with their load estimates, for the
        key-compaction reseed (parallel/compaction.py): exact-histogram
        sketches rank their dense counts; CMS sketches merge the host
        candidate pool with every in-program site's ring and estimate
        over the merged CMS.  Returns ``[(key, est_tuples), ...]``
        ranked hottest-first, at most ``limit`` entries."""
        if self.hist is not None:
            body = self.hist[:self.max_keys]
            order = np.argsort(body)[::-1][:limit]
            return [(int(k), int(body[k])) for k in order if body[k] > 0]
        cms = self.cms.copy()
        with self._lock:
            cands = set(self._cands)
            cands.update(k for k in self._sampled
                         if isinstance(k, (int, np.integer)))
        for getter in self._device_states:
            try:
                st = getter()
                if st is None:
                    continue
                cms = cms + np.asarray(st["cms"], np.int64)
                ring = np.asarray(st["cand"], np.int64)
            except Exception:  # lint: broad-except-ok (donated operand
                # read racing the in-flight dispatch — skip the site for
                # this read, the summary() stance)
                continue
            cands.update(int(k) for k in ring
                         if k != np.iinfo(np.int32).min)
        est = [(int(k), self._estimate(int(k), cms)) for k in cands]
        est.sort(key=lambda kv: kv[1], reverse=True)
        return est[:limit]

    def shard_of(self, key: int) -> int:
        from windflow_tpu.basic import stable_hash
        from windflow_tpu.parallel.emitters import splitmix64_int
        if self.override:
            d = self.override.get(key)
            if isinstance(d, int) and 0 <= d < self.n_shards:
                return d
        if self.placement == "dense_range" and self.max_keys:
            per = max(1, self.max_keys // self.key_axis)
            return min(self.key_axis - 1, max(0, int(key)) // per)
        if self.placement == "mod":
            return (int(key) & 0xFFFFFFFF) % self.n_shards
        if self.placement == "stable_hash":
            return stable_hash(key) % self.n_shards
        k = int(key) & 0xFFFFFFFF
        k = k - (1 << 32) if k >= (1 << 31) else k
        return splitmix64_int(k) % self.n_shards

    def summary(self) -> dict:
        """Merge host + device accumulators into the section payload:
        per-shard loads, the hot-key top-K table, and the basis tag
        ("exact" | "cms" | "sampled")."""
        counts = self.shard_counts.copy()
        total = self.total
        batches = self.batches
        hist = self.hist.copy() if self.hist is not None else None
        cms = self.cms.copy() if self.cms is not None else None
        with self._lock:    # driver threads insert concurrently
            cands = set(self._cands)
        dev_fed = False
        for getter in self._device_states:
            try:
                st = getter()
                if st is None:
                    continue
                # monitor-cadence device sync: the ONLY sync the plane
                # pays
                dev_counts = np.asarray(st["counts"], np.int64)
                dev_total = int(st["total"])
                dev_batches = int(st["batches"])
                dev_cms = np.asarray(st["cms"], np.int64)
                ring = np.asarray(st["cand"], np.int64)
            except Exception:  # lint: broad-except-ok (the state is a
                # DONATED program operand: a read racing the in-flight
                # dispatch sees a deleted array — skip this site for
                # THIS read, the next cadence sees the fresh state)
                continue
            if dev_counts.size == counts.size:
                counts = counts + dev_counts
            total += dev_total
            batches += dev_batches
            if cms is None:
                # a bounded-key edge fed by an in-program site: the
                # device state carries a CMS (the program has no dense
                # histogram), so the merge view needs one
                cms = np.zeros((SKETCH_DEPTH, SKETCH_WIDTH), np.int64)
            cms = cms + dev_cms
            cands.update(int(k) for k in ring
                         if k != np.iinfo(np.int32).min)
            dev_fed = True
        if self.placement == "dense_range" and hist is not None \
                and self.key_axis > 1:
            per = max(1, self.max_keys // self.key_axis)
            counts = hist[:per * self.key_axis] \
                .reshape(self.key_axis, per).sum(axis=1)
        out = {
            "n_shards": int(counts.size),
            "placement": self.placement,
            "total_tuples": int(total),
            "batches": int(batches),
            "tuples": [int(c) for c in counts],
        }
        if total > 0 and counts.size > 1 and counts.sum() > 0:
            mean = counts.sum() / counts.size
            out["imbalance_ratio"] = round(float(counts.max() / mean), 4)
            out["hot_shard"] = int(counts.argmax())
        top: List[dict] = []
        if hist is not None and hist[:self.max_keys].sum() > 0:
            out["basis"] = "exact"
            body = hist[:self.max_keys]
            order = np.argsort(body)[::-1][:4 * self.topk]
            est_map = {int(k): int(body[k]) for k in order if body[k] > 0}
            if dev_fed and cms is not None:
                # mixed feed: an in-program site contributed tuples the
                # dense histogram never saw — join its CMS estimates so
                # shares stay honest against the merged total
                out["basis"] = "mixed"
                for k in cands:
                    est_map[k] = est_map.get(k, 0) \
                        + self._estimate(k, cms)
            ranked = sorted(est_map.items(), key=lambda kv: kv[1],
                            reverse=True)
            top = [{"key": k, "est_tuples": v}
                   for k, v in ranked[:self.topk] if v > 0]
            if hist[self.max_keys]:
                out["out_of_range_tuples"] = int(hist[self.max_keys])
        elif cms is not None and cands:
            out["basis"] = "cms"
            est = [(k, self._estimate(k, cms)) for k in cands]
            est.sort(key=lambda kv: kv[1], reverse=True)
            top = [{"key": int(k), "est_tuples": int(v)}
                   for k, v in est[:self.topk] if v > 0]
        elif self._sampled:
            out["basis"] = "sampled"
            est = sorted(self._sampled.items(), key=lambda kv: kv[1],
                         reverse=True)
            top = [{"key": k, "est_tuples": v}
                   for k, v in est[:self.topk]]
        else:
            out["basis"] = "cms" if cms is not None else "exact"
        for t in top:
            if total > 0:
                t["share"] = round(t["est_tuples"] / total, 4)
            try:
                t["shard"] = self.shard_of(t["key"])
            except (TypeError, ValueError):
                pass
        out["hot_keys"] = top
        if top and total > 0:
            out["hot_key_share"] = round(top[0]["est_tuples"] / total, 4)
        if self.update_usec:
            out["host_update_usec"] = round(self.update_usec, 1)
        return out


class HostKeyProbe:
    """Key probe on a plain (non-keyed) staging emitter feeding a keyed
    device consumer whose key extraction runs in-program (mesh FFAT /
    dense reduce / stateful): the emitter's columnar or record path
    already materializes the fields on host, so the consumer's extractor
    applies host-side at batch granularity.  Any extractor failure
    disables the probe permanently (speculative-vectorization stance of
    ``KeyedDeviceStageEmitter.emit_columns``) — the pipeline must never
    pay for a probe that cannot see.

    Doubles as the key-compaction admission point (``compactor``,
    parallel/compaction.py): a host-fed compacted consumer admits every
    key at this boundary, so its batches ship with a miss-free remap.
    A probe failure deactivates the compactor too — the consumer falls
    back to its legacy path instead of silently starving the table."""

    __slots__ = ("sketch", "key_fn", "dead", "compactor")

    def __init__(self, sketch: Optional[ShardSketch], key_fn,
                 compactor=None) -> None:
        self.sketch = sketch
        self.key_fn = key_fn
        self.compactor = compactor
        self.dead = False

    def _fail(self) -> None:
        self.dead = True
        if self.compactor is not None:
            self.compactor.deactivate()

    def columns(self, cols, n: int) -> None:
        if self.dead or n == 0:
            return
        try:
            k = np.asarray(self.key_fn(cols))
            if k.shape != (n,):
                raise ValueError("extractor is not elementwise")
            k32 = _key32_np(k)
            if self.compactor is not None:
                self.compactor.observe(k32)
            if self.sketch is not None:
                self.sketch.update_host(k32)
        except Exception:  # lint: broad-except-ok (speculative probe of
            # an arbitrary user extractor over SoA columns — ANY failure
            # means "cannot see", and telemetry must never take the
            # staging path down)
            self._fail()

    def items(self, items) -> None:
        if self.dead or not items:
            return
        try:
            keys = np.fromiter((int(self.key_fn(it)) for it in items),
                               np.int64, count=len(items))
            k32 = _key32_np(keys)
            if self.compactor is not None:
                self.compactor.observe(k32)
            if self.sketch is not None:
                self.sketch.update_host(k32)
        except Exception:  # lint: broad-except-ok (same stance as
            # columns(): a non-numeric or throwing extractor disables
            # the probe, never the staging path)
            self._fail()


# ---------------------------------------------------------------------------
# the graph-scoped ledger
# ---------------------------------------------------------------------------

def _steady_cost_bytes(op) -> Optional[float]:
    """Steady per-dispatch HBM bytes of the hop's dominant program (the
    sweep ledger's ``steady_bytes_per_tuple`` numerator, re-read here so
    per-REPLICA attribution scales it by each replica's own dispatch
    count)."""
    from windflow_tpu.monitoring.sweep_ledger import _op_wrappers
    best_d, best_ba = 0, None
    for w in _op_wrappers(op):
        if w.dispatches <= 0:
            continue
        cost = w.current_cost() or {}
        ba = cost.get("bytes_accessed")
        if isinstance(ba, (int, float)) and w.dispatches >= best_d:
            best_d, best_ba = w.dispatches, float(ba)
    return best_ba


class ShardLedger:
    """Graph-scoped shard plane: built by ``PipeGraph._build`` when
    ``Config.shard_ledger`` is on.  Construction attaches the key-skew
    sketches to the keyed edges (and the in-program sites); everything
    else is read-cadence — ``section()`` walks live replica counters and
    merges the sketches, never touching the per-batch path."""

    def __init__(self, graph) -> None:
        self._graph = graph
        self.topk = max(1, int(getattr(graph.config, "shard_topk", 8)))
        #: id(consumer op) -> ShardSketch (one per keyed consumer; all
        #: edges feeding that consumer share it)
        self._sketches: Dict[int, ShardSketch] = {}
        self._statics: Optional[dict] = None
        self._attach()

    # -- sketch attachment (build time) --------------------------------------
    def _sketch_for(self, consumer, n_shards: int,
                    placement: str) -> ShardSketch:
        sk = self._sketches.get(id(consumer))
        if sk is None:
            mesh = getattr(consumer, "mesh", None)
            key_axis = 1
            if mesh is not None:
                from windflow_tpu.parallel.mesh import DATA_AXIS, KEY_AXIS
                if consumer.key_space() is not None:
                    # bounded: chip i owns keys [i*K/kk, (i+1)*K/kk)
                    key_axis = mesh.shape[KEY_AXIS]
                    placement = "dense_range"
                    n_shards = key_axis
                else:
                    # arbitrary keys hash-shard to their owner chip by
                    # uint32(key) % n (mesh.make_sharded_reduce_arbitrary)
                    placement = "mod"
                    n_shards = mesh.shape[DATA_AXIS] \
                        * mesh.shape[KEY_AXIS]
            sk = ShardSketch(n_shards, topk=self.topk,
                             max_keys=consumer.key_space(),
                             key_axis=key_axis, placement=placement)
            self._sketches[id(consumer)] = sk
        return sk

    def _attach(self) -> None:
        from windflow_tpu.parallel.emitters import (AlignedMeshStageEmitter,
                                                    DeviceKeyByEmitter,
                                                    DeviceStageEmitter,
                                                    DeviceToHostEmitter,
                                                    KeyByEmitter,
                                                    KeyedDeviceStageEmitter,
                                                    SplittingEmitter)
        g = self._graph

        def visit(em):
            if em is None:
                return
            if isinstance(em, SplittingEmitter):
                for b in em.branches:
                    visit(b)
                return
            if isinstance(em, DeviceToHostEmitter):
                visit(em.inner)
                return
            if not em.dests:
                return
            consumer = em.dests[0][0].op
            if isinstance(em, AlignedMeshStageEmitter):
                # key-aligned mesh ingest: the keys are host-visible at
                # this boundary (the emitter routed by them), so the
                # probe sees exactly the placement the columns realize
                # (dense_range ownership — _sketch_for detects the mesh)
                kx = consumer.key_extractor
                if consumer.is_keyed and kx is not None:
                    sk = self._sketch_for(consumer, consumer.parallelism,
                                          "splitmix")
                    em._shard_probe = HostKeyProbe(sk, kx)
            elif isinstance(em, KeyedDeviceStageEmitter):
                em._sketch = self._sketch_for(consumer, len(em.dests),
                                              "splitmix")
            elif isinstance(em, DeviceKeyByEmitter):
                sk = self._sketch_for(consumer, len(em.dests), "splitmix")
                em.attach_shard_sketch(sk)
            elif isinstance(em, KeyByEmitter):
                em._sketch = self._sketch_for(consumer, len(em.dests),
                                              "stable_hash")
            elif isinstance(em, DeviceStageEmitter):
                # plain staging into a keyed device consumer whose key
                # extraction runs in-program (mesh / dense / windowed):
                # probe the host-visible records with that extractor.
                # Skipped for fused-segment hosts: their extractor
                # expects POST-prelude records, but this edge stages the
                # chain HEAD's inputs — probing them would sketch keys
                # the routing never computes.
                kx = consumer.key_extractor
                if consumer.is_keyed and kx is not None \
                        and consumer.is_tpu \
                        and consumer._fused_prelude is None:
                    sk = self._sketch_for(consumer, consumer.parallelism,
                                          "splitmix")
                    em._shard_probe = HostKeyProbe(sk, kx)

        for op in g._operators:
            for rep in op.replicas:
                visit(rep.emitter)
        # fused chains / chained pairs extracting a downstream consumer's
        # keys in-program: fold the sketch into that same program
        edges = [e for e in g._edges() if e[0] == "op"]
        downstream = {id(a): b for _, a, b in edges}
        for op in g._operators:
            for exec_ in (op._fusion_exec,
                          getattr(op, "_chain", None)):
                if exec_ is None or exec_._key_extractor is None:
                    continue
                consumer = downstream.get(id(op))
                if consumer is None or not consumer.is_keyed:
                    continue
                if consumer.parallelism > 1:
                    # the edge is a DeviceKeyByEmitter whose split
                    # program already sketches this stream (attached
                    # above) — a second update in the chain program
                    # would double-count every tuple
                    continue
                sk = self._sketch_for(consumer, consumer.parallelism,
                                      "splitmix")
                exec_.attach_shard_sketch(sk, consumer.parallelism)
                break

    # -- statics: record bytes, upstream ops, effective capacities -----------
    def _compute_statics(self) -> dict:
        """Everything derivable from the built graph, computed ONCE and
        cached (the section reads at monitor/webui cadence must not
        re-walk the edge list per operator per read)."""
        from windflow_tpu.analysis.preflight import (_effective_caps,
                                                     _upstream_map,
                                                     propagate_specs,
                                                     record_nbytes)
        g = self._graph
        edges = g._edges()
        upstreams = _upstream_map(edges)
        try:
            in_specs, _ = propagate_specs(g, edges=edges,
                                          upstreams=upstreams)
        except Exception:  # lint: broad-except-ok (abstract eval of
            # arbitrary user kernels; a failure degrades the ICI model
            # to "unknown", it must never take a stats read down)
            in_specs = {}
        ups: Dict[int, list] = {}
        for edge in edges:
            if edge[0] == "op":
                _, a, b = edge
                ups.setdefault(id(b), []).append(a)
        statics = {}
        for op in g._operators:
            caps = sorted(c for c in _effective_caps(op, upstreams) if c)
            statics[id(op)] = {
                "bpt": record_nbytes(in_specs.get(id(op))),
                "ups": ups.get(id(op), []),
                "cap": getattr(op, "output_batch_size", 0)
                or (caps[0] if caps else 0),
            }
        return statics

    # -- ICI model (mesh programs) -------------------------------------------
    def _ici_model(self, op, bpt: Optional[float],
                   cap: int) -> Optional[dict]:
        """Documented model of the ICI bytes one dispatch of ``op``'s
        sharded program moves, derived from the collective structure
        ``parallel/mesh.py`` compiles (XLA cost tables carry no
        collective terms on CPU).  ``bpt`` = payload+lane bytes/tuple;
        ``cap`` = the effective batch capacity (cached statics)."""
        mesh = getattr(op, "mesh", None)
        if mesh is None or bpt is None or not cap:
            return None
        from windflow_tpu.parallel.mesh import DATA_AXIS, KEY_AXIS
        dd = mesh.shape[DATA_AXIS]
        kk = mesh.shape[KEY_AXIS]
        n = dd * kk
        from windflow_tpu.ops.tpu import ReduceTPU
        if getattr(op, "_ingest_mode", None) == "aligned":
            # key-aligned ingest (parallel/emitters.
            # AlignedMeshStageEmitter): the host pre-placed each tuple
            # on its key-owner column; only the within-column data-axis
            # gather remains, for EVERY aligned consumer kind — FFAT
            # windows, dense ReduceTPU (whose [K]-table psum/all_gather
            # vanishes entirely), dense-key stateful (whose psum lane
            # merge vanishes too)
            total = cap * bpt * (dd - 1)
            kind = "all_gather(data|key-aligned)"
        elif isinstance(op, ReduceTPU):
            if op.max_keys is not None:
                k = op.max_keys if op.key_extractor is not None else 1
                table = k * bpt
                # ring all-reduce: each of n devices sends+receives
                # ~2(n-1)/n of the table
                total = 2.0 * (n - 1) * table
                kind = f"psum([{k}] table)"
            else:
                # hash-sharded all_to_all: (n-1)/n of the lanes cross ICI
                total = cap * bpt * (n - 1) / n
                kind = "all_to_all(lanes)"
        else:
            # key-sharded state (FFAT / stateful): every key shard
            # all_gathers the data-sharded batch — each of the kk*dd
            # devices receives the cap*(dd-1)/dd lanes it lacks
            total = kk * cap * bpt * (dd - 1)
            kind = "all_gather(data)"
        # the TIME half divides by the link bandwidth — a probe-measured
        # value while a fresh calibration store covers it (provenance
        # `calibrated(<age>)`), the nominal WF_TPU_ICI_BYTES_PER_SEC
        # default otherwise (`modeled`)
        from windflow_tpu.monitoring import calibration
        ici_bps, ici_prov = calibration.constant("ici_bytes_per_sec",
                                                 ICI_BYTES_PER_SEC)
        return {
            "collective": kind,
            "mesh": {"data": dd, "key": kk},
            "ici_bytes_per_dispatch": round(total, 1),
            "ici_bytes_per_tuple": round(total / cap, 2),
            # the TIME half of the model: per-dispatch collective bytes
            # over the fabric, serialized through each chip's share at
            # the calibrated-or-nominal link bandwidth
            "ici_usec_per_dispatch": round(
                (total / n) / ici_bps * 1e6, 3),
            "ici_bandwidth_assumed_bps": ici_bps,
            "ici_bandwidth_provenance": ici_prov,
            # the BYTES half is always structural — the collective shape
            # is derived, never measured on CPU
            "provenance": calibration.MODELED,
            "model": "structural (XLA cost tables carry no collective "
                     "terms; see docs/OBSERVABILITY.md shard plane)",
        }

    # -- read paths ----------------------------------------------------------
    def op_summary(self, op_name: str) -> Optional[dict]:
        """Load + hot-key summary for one operator by name (the health
        plane's stall-diagnosis hook)."""
        for op in self._graph._operators:
            if op.name == op_name:
                sk = self._sketches.get(id(op))
                return sk.summary() if sk is not None else None
        return None

    def section(self) -> dict:
        from windflow_tpu.basic import current_time_usecs
        from windflow_tpu.monitoring.sweep_ledger import \
            LANE_BYTES_PER_TUPLE
        if self._statics is None:
            self._statics = self._compute_statics()
        g = self._graph
        now = current_time_usecs()
        per_op: Dict[str, dict] = {}
        worst = (0.0, None)     # (imbalance ratio, op name)
        hot = (0.0, None)       # (hot key share, op name)
        ici_bpt_total = 0.0
        ici_time_prov = None    # provenance of the ICI TIME model
        sketch_usec = 0.0
        for op in g._operators:
            ba = _steady_cost_bytes(op) if op.is_tpu else None
            replicas = []
            lags = []
            for rep in op.replicas:
                from windflow_tpu.batch import WM_MAX, WM_NONE
                wm = rep.current_wm
                front = wm if (wm != WM_NONE and wm < WM_MAX) else None
                lag = max(0, now - front) if front is not None else None
                if lag is not None:
                    lags.append(lag)
                q = rep.stats.service_hist.quantiles()
                slot = {
                    "shard": rep.index,
                    "queue_depth": len(rep.inbox),
                    "watermark_frontier_usec": front,
                    "watermark_lag_usec": lag,
                    "inputs": rep.stats.inputs_received,
                    "outputs": rep.stats.outputs_sent,
                    "dispatches": rep.stats.device_programs_launched,
                    "service_usec": {k: q.get(k)
                                     for k in ("p50", "p95", "p99")
                                     if isinstance(q, dict)},
                }
                if ba is not None:
                    slot["hbm_bytes"] = round(
                        ba * rep.stats.device_programs_launched, 1)
                replicas.append(slot)
            entry: dict = {
                "parallelism": op.parallelism,
                "keyed": op.is_keyed,
                "replicas": replicas,
            }
            if len(lags) > 1:
                entry["lag_spread_usec"] = max(lags) - min(lags)
            sk = self._sketches.get(id(op))
            if sk is not None:
                load = sk.summary()
                entry["load"] = load
                sketch_usec += load.get("host_update_usec", 0.0)
                r = load.get("imbalance_ratio")
                if isinstance(r, (int, float)) and r > worst[0]:
                    worst = (r, op.name)
                s = load.get("hot_key_share")
                if isinstance(s, (int, float)) and s > hot[0]:
                    hot = (s, op.name)
            comp = op._compactor
            if comp is not None:
                # key compaction (parallel/compaction.py): remap table
                # hit rate / overflow share / slot churn ride the shard
                # section — the same per-consumer granularity as load
                entry["compaction"] = comp.summary()
            st = self._statics.get(id(op)) or {}
            spec_bpt = st.get("bpt")
            bpt = (spec_bpt + LANE_BYTES_PER_TUPLE) \
                if spec_bpt is not None else None
            basis = "record spec"
            if bpt is None and getattr(op, "mesh", None) is not None:
                # no declared record spec: fall back to the measured
                # staging bytes per tuple of the feeding edges (padded
                # batch bytes over received tuples — an upper-ish bound)
                h2d = sum(r.stats.h2d_bytes for u in st.get("ups", ())
                          for r in u.replicas)
                inputs = sum(r.stats.inputs_received
                             for r in op.replicas)
                if h2d > 0 and inputs > 0:
                    bpt = h2d / inputs
                    basis = "measured H2D bytes/tuple"
            ici = self._ici_model(op, bpt, st.get("cap", 0))
            if ici is not None:
                ici["bytes_per_tuple_basis"] = basis
                entry["ici"] = ici
                # per key-shard slice of the collective volume (each
                # shard participates symmetrically in the gather/psum)
                ici_bpt_total += ici["ici_bytes_per_tuple"]
                ici_time_prov = ici["ici_bandwidth_provenance"]
            per_op[op.name] = entry
        from windflow_tpu.monitoring import calibration
        return {
            "enabled": True,
            "per_op": per_op,
            "totals": {
                "max_imbalance_ratio": round(worst[0], 4) if worst[1]
                else None,
                "max_imbalance_op": worst[1],
                "hot_key_share": round(hot[0], 4) if hot[1] else None,
                "hot_key_op": hot[1],
                "ici_bytes_per_tuple": round(ici_bpt_total, 2),
                # the collective-shape bytes are structural everywhere;
                # the time column inherits the bandwidth's provenance
                "ici_provenance": calibration.MODELED,
                "ici_time_provenance": ici_time_prov,
                "sketch_host_update_usec": round(sketch_usec, 1),
                "keyed_edges_sketched": len(self._sketches),
            },
        }
