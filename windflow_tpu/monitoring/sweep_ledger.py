"""Sweep ledger: per-operator-hop dispatch & HBM-traffic attribution.

The roofline block in ``bench.py`` measures ~8x more HBM traffic per
tuple than the declared record model, and the staged e2e rate sits well
below the raw kernel — but until now nothing said *which hop* pays it.
Every operator hop in the PipeGraph sweep is its own jitted dispatch
that round-trips HBM; whole-chain fusion (ROADMAP item 1) cannot be
planned, sized, or verified without per-hop accounting.

This module cashes in counters the earlier planes already maintain —
it adds **zero per-batch work of its own**:

* **dispatches per batch per hop** — the compile watcher
  (monitoring/jit_registry.py) bumps per-wrapper and per-name dispatch
  counters on every jitted call (two lock-free integer adds,
  ``@hot_path``-linted); the ledger baselines each wrapper at graph
  build and diffs at stats cadence, divided by the replicas'
  ``device_programs_launched`` batch counts.  Chained ops
  (ops/chained.py) therefore show their REAL dispatch count: one for
  the fused ``a|b`` hop where the unchained pair pays two.
* **per-hop HBM bytes** — XLA cost-analysis bytes-accessed per compiled
  op (captured at first compile) scaled by that op's dispatches, split
  into payload vs overhead against the declared record spec (the
  pre-flight spec walk, analysis/preflight.propagate_specs — the same
  shared walk the fusion advisor reuses).
* **donation misses** — compiled ops whose non-donated input buffers
  match an output buffer shape/dtype: each dispatch pays a whole-batch
  copy that ``donate_argnums`` would elide (audit captured by the
  compile watcher at first compile).
* **hop-boundary residency** — hops whose output batches stay on device
  and are immediately re-consumed by the next TPU hop: the bytes a
  fused program would never materialize in HBM (the advisor's "fusion
  fuel").

Surfaces: ``PipeGraph.stats()["Sweep"]``, the OpenMetrics exposition
(``wf_sweep_*`` families), ``dump_trace()`` metadata, the webui per-op
columns, and the postmortem bundle's ``sweep.json``
(``tools/wf_doctor.py`` renders it jax-free).  ``Config.sweep_ledger``
off leaves one ``is not None`` check at each read site — the per-batch
path is untouched either way (the dispatch counter belongs to the
compile watcher and rides its ``WF_TPU_JIT_WATCH`` kill switch).
"""

from __future__ import annotations

from typing import Dict, Optional

#: bytes per tuple of the runtime lanes every device batch carries next
#: to the payload: int64 timestamp + bool validity mask
LANE_BYTES_PER_TUPLE = 9


def _op_wrappers(op):
    """Every :class:`~windflow_tpu.monitoring.jit_registry.WfJit` wrapper
    an operator instance (or one of its replicas) holds — directly
    (``_jit_step``, ``_extract``, a replica's ``_jit``) or in a
    per-capacity cache dict (``_jit_steps``, ``_steps``).  Wrappers are
    per instance, so this is what makes per-hop attribution graph-scoped
    where the registry aggregates per op name process-wide."""
    from windflow_tpu.monitoring.jit_registry import WfJit
    holders = [op] + list(getattr(op, "replicas", ()))
    for holder in holders:
        # list() snapshots: the monitor thread reads stats while the
        # driver may still be creating lazy per-capacity wrappers
        for v in list(vars(holder).values()):
            if isinstance(v, WfJit):
                yield v
            elif isinstance(v, dict):
                for w in list(v.values()):
                    if isinstance(w, WfJit):
                        yield w
            elif isinstance(getattr(v, "_jit", None), WfJit):
                # a fused stateless segment's chain program lives on the
                # host op's FusedStatelessExec (windflow_tpu/fusion) —
                # the fused hop's dispatches attribute here
                yield v._jit


class SweepLedger:
    """Per-graph view over the process-wide compile registry: built at
    ``PipeGraph._build`` (baseline dispatch snapshot), read at stats /
    trace / postmortem cadence — never on the per-batch path."""

    def __init__(self, graph) -> None:
        from windflow_tpu.monitoring.jit_registry import default_registry
        self._graph = graph
        # per-name registry baseline (for the non-hop infrastructure
        # programs) and per-wrapper baseline (for the hops: wrappers are
        # per operator instance, so two graphs reusing an op name never
        # cross-credit; wrappers built lazily after this start at zero)
        self._base = default_registry().dispatch_counts()
        self._wbase = {id(w): w.dispatches
                       for op in graph._operators
                       for w in _op_wrappers(op)}
        self._statics: Optional[dict] = None    # computed on first read

    # -- static graph facts (specs, capacities, residency) -------------------
    def _compute_statics(self) -> dict:
        """Record specs (shared pre-flight walk), effective batch
        capacities, and hop-boundary residency — all derivable from the
        built graph, cached after the first stats read."""
        from windflow_tpu.analysis.preflight import (_effective_caps,
                                                     _upstream_map,
                                                     propagate_specs,
                                                     record_nbytes)
        g = self._graph
        edges = g._edges()
        upstreams = _upstream_map(edges)
        try:
            in_specs, out_specs = propagate_specs(g, edges=edges,
                                                  upstreams=upstreams)
        except Exception:  # lint: broad-except-ok (the spec walk
            # abstractly evaluates arbitrary user kernels; a failure
            # degrades the payload/overhead split to "unknown", it must
            # never take a stats read down)
            in_specs, out_specs = {}, {}
        # downstream consumers per op over the plain op edges; a split
        # point fans out on the host, so its source op is never resident
        downs: Dict[int, list] = {}
        for edge in edges:
            if edge[0] == "op":
                _, a, b = edge
                downs.setdefault(id(a), []).append(b)
            else:
                _, mp = edge
                downs.setdefault(id(mp.operators[-1]), []).append(None)
        statics = {}
        for op in g._operators:
            caps = sorted(c for c in _effective_caps(op, upstreams) if c)
            cap = caps[0] if caps else getattr(op, "capacity", None)
            consumers = downs.get(id(op), [])
            resident = bool(consumers) and all(
                c is not None and c.is_tpu for c in consumers)
            statics[id(op)] = {
                "capacity": cap,
                "in_bytes_per_tuple": record_nbytes(in_specs.get(id(op))),
                "out_bytes_per_tuple": record_nbytes(out_specs.get(id(op))),
                "resident_output": resident,
            }
        return statics

    # -- the stats()["Sweep"] payload ----------------------------------------
    def section(self) -> dict:
        from windflow_tpu.ops.source import Source
        from windflow_tpu.monitoring.jit_registry import default_registry
        if self._statics is None:
            self._statics = self._compute_statics()
        reg = default_registry()
        snapshot = reg.snapshot()
        g = self._graph
        # ops sharing one name merge into ONE joint hop (their wrapper
        # sets and replica batch counts sum) — the surfaces key hops by
        # operator name, same per-name stance as the registry
        groups: Dict[str, list] = {}
        for op in g._operators:
            groups.setdefault(op.name, []).append(op)
        # whole-chain fusion (windflow_tpu/fusion): member hops are
        # marked fused_into and host hops carry the member list — the
        # "how fused hops appear" contract docs/OBSERVABILITY.md pins
        fused_member_of: Dict[str, str] = {}
        fused_hosts: Dict[str, dict] = {}
        for seg in getattr(g, "_fused_segments", ()):
            for n in seg["member_names"][:-1]:
                fused_member_of[n] = seg["name"]
            fused_hosts[seg["host_name"]] = seg
        per_hop: Dict[str, dict] = {}
        claimed = set()
        tot_bpt = 0.0
        tot_dpb = 0.0
        tot_miss = 0.0
        tot_disp = 0
        tot_attr_disp = 0
        for op in g._operators:
            key = op.name
            if key in per_hop:
                continue
            siblings = groups[key]
            wrappers = [w for sib in siblings for w in _op_wrappers(sib)]
            if not op.is_tpu and not wrappers:
                continue
            claimed.update(w.op_name for w in wrappers)
            batches = sum(r.stats.device_programs_launched
                          for sib in siblings for r in sib.replicas)
            # dispatch + byte tally from THIS graph's own wrappers
            # (per-instance counters and per-program cost tables,
            # baselined at build); donation audits are per op name
            disp = 0
            attr_disp = 0
            bytes_total = 0.0
            miss_bytes = 0.0
            miss_leaves = 0
            donated_any = False
            name_disp: Dict[str, int] = {}
            # the hop's dominant program (most dispatches): its bytes
            # are the steady-state per-dispatch cost, undiluted by
            # one-shot programs like the FFAT EOS flush
            primary_d = 0
            primary_ba = None
            for w in wrappers:
                d = w.dispatches - self._wbase.get(id(w), 0)
                if d <= 0:
                    continue
                disp += d
                name_disp[w.op_name] = name_disp.get(w.op_name, 0) + d
                cost = w.current_cost() \
                    or (snapshot.get(w.op_name) or {}).get("cost") or {}
                ba = cost.get("bytes_accessed")
                if isinstance(ba, (int, float)):
                    attr_disp += d
                    bytes_total += d * float(ba)
                    if d > primary_d:
                        primary_d = d
                        primary_ba = float(ba)
            # donation audits are per program name, weighted by every
            # dispatch that name saw in this graph
            for name, nd in name_disp.items():
                don = (snapshot.get(name) or {}).get("donation") or {}
                if don.get("donated_argnums"):
                    donated_any = True
                if don.get("candidate_leaves"):
                    miss_leaves += don["candidate_leaves"]
                    miss_bytes += nd * float(don.get("candidate_bytes", 0))
            st = self._statics.get(id(op), {})
            cap = st.get("capacity")
            hop = {
                "kind": type(op).__name__,
                "batches": batches,
                "dispatches": disp,
                "dispatches_per_batch":
                    round(disp / batches, 3) if batches else None,
                "capacity": cap,
                "resident_output": st.get("resident_output", False),
            }
            if key in fused_member_of and all(
                    sib._fused_into is not None for sib in siblings):
                # inert member of a fused segment: its execution (and
                # its dispatches/bytes) live in the fused hop below.
                # Guarded sibling-wise: hops aggregate per NAME, so an
                # unfused op sharing the name must keep its real
                # dispatch numbers unmasked (the per-wrapper attribution
                # stance — never cross-credit name collisions).
                hop["fused_into"] = fused_member_of[key]
            elif key in fused_hosts:
                seg = fused_hosts[key]
                hop["fused_program"] = seg["name"]
                hop["fused_members"] = seg["member_names"]
            if batches and attr_disp:
                bpb = bytes_total / batches
                hop["bytes_per_batch"] = round(bpb, 1)
                hop["bytes_per_tuple"] = round(bpb / cap, 2) if cap \
                    else None
                # XLA cost-table estimates, not byte counters — tagged
                # so downstream joins (roofline, tenant) name their
                # basis (monitoring/calibration.py vocabulary)
                hop["bytes_provenance"] = "modeled"
                if primary_ba is not None and cap:
                    # steady-state number: a short run's EOS flush or
                    # other one-shot programs dilute the amortized
                    # average above; this is what one more data batch
                    # would cost (the roofline comparison's domain)
                    hop["steady_bytes_per_tuple"] = \
                        round(primary_ba / cap, 2)
                if disp > attr_disp:
                    hop["unattributed_dispatches"] = disp - attr_disp
            payload = st.get("in_bytes_per_tuple")
            if payload is not None:
                model = payload + LANE_BYTES_PER_TUPLE
                hop["payload_bytes_per_tuple"] = model
                bpt = hop.get("bytes_per_tuple")
                if bpt is not None:
                    hop["overhead_bytes_per_tuple"] = round(bpt - model, 2)
                    hop["excess_vs_model"] = round(bpt / model, 2)
            if miss_leaves:
                hop["donation_miss"] = {
                    "candidate_leaves": miss_leaves,
                    "bytes_per_batch":
                        round(miss_bytes / batches, 1) if batches else None,
                    "donates_some_args": donated_any,
                }
            if st.get("resident_output") \
                    and st.get("out_bytes_per_tuple") is not None and cap:
                # what a fused chain would never materialize in HBM
                hop["fusion_fuel_bytes_per_batch"] = \
                    (st["out_bytes_per_tuple"] + LANE_BYTES_PER_TUPLE) * cap
            per_hop[key] = hop
            if hop.get("bytes_per_tuple") is not None:
                tot_bpt += hop["bytes_per_tuple"]
            if hop["dispatches_per_batch"] is not None \
                    and not isinstance(op, Source):
                tot_dpb += hop["dispatches_per_batch"]
            if miss_leaves and batches:
                tot_miss += miss_bytes / batches
            tot_disp += disp
            tot_attr_disp += attr_disp
        # infrastructure programs that dispatched but belong to no hop
        # (staging pack/unpack, emitter splits): reported so the bytes
        # accounting can reach 100% of the sweep's traffic
        non_hop = {}
        for name, e in snapshot.items():
            if name in claimed:
                continue
            d = e.get("dispatches", 0) - self._base.get(name, 0)
            if d <= 0:
                continue
            slot = {"dispatches": d}
            ba = (e.get("cost") or {}).get("bytes_accessed")
            if isinstance(ba, (int, float)):
                slot["bytes_per_dispatch"] = float(ba)
            non_hop[name] = slot
            tot_disp += d
        # fusion summary: realized dispatch savings (N member hops now
        # pay the host hop's single program) plus the projected interior
        # boundary bytes a fused chain never materializes — write + re-
        # read per boundary, the advisor's formula (analysis/fusion.plan)
        # evaluated over the segments that actually fused
        fusion_chains = []
        fusion_dsaved = 0.0
        fusion_bsaved = 0.0
        for seg in getattr(g, "_fused_segments", ()):
            n_members = len(seg["member_names"])
            host_hop = per_hop.get(seg["host_name"]) or {}
            dpb = host_hop.get("dispatches_per_batch")
            bsum = 0.0
            for mn in seg["member_names"][:-1]:
                fuel = (per_hop.get(mn) or {}) \
                    .get("fusion_fuel_bytes_per_batch")
                if fuel:
                    bsum += 2 * fuel
            entry = {
                "name": seg["name"],
                "members": seg["member_names"],
                "host": seg["host_name"],
                "donated_inputs": bool(seg.get("donate_inputs")),
                "dispatches_per_batch": dpb,
                "unfused_dispatches_per_batch": float(n_members),
                "bytes_saved_per_batch": round(bsum, 1),
            }
            if dpb is not None:
                entry["dispatches_saved_per_batch"] = \
                    round(n_members - dpb, 3)
                fusion_dsaved += n_members - dpb
            fusion_bsaved += bsum
            fusion_chains.append(entry)
        # wire plane (windflow_tpu/wire.py): THIS HOST's share of the
        # graph's staged traffic, wire vs logical — on a multi-host DCN
        # feed each process packs and stages only its local chips'
        # shard, and this is where that per-host attribution surfaces
        # (per-replica splits live in the replica stats' Bytes_H2D /
        # Bytes_H2D_logical pair)
        import jax as _jax
        wire_h2d = sum(r.stats.h2d_bytes for r in g._all_replicas)
        logical_h2d = sum(r.stats.h2d_logical_bytes
                          for r in g._all_replicas)
        wire_host = {
            "process_index": _jax.process_index(),
            "process_count": _jax.process_count(),
            "wire_bytes": wire_h2d,
            "logical_bytes": logical_h2d,
            "compression_ratio": round(logical_h2d / wire_h2d, 4)
            if wire_h2d else None,
            # real byte counters on the staged path, not a model
            "bytes_provenance": "measured",
        }
        return {
            "enabled": True,
            "per_hop": per_hop,
            "non_hop": non_hop,
            "wire": wire_host,
            "fusion": {
                "enabled": bool(fusion_chains),
                "fused_chains": [c["name"] for c in fusion_chains],
                "chains": fusion_chains,
                "dispatches_saved_per_batch": round(fusion_dsaved, 3),
                "bytes_saved_per_batch": round(fusion_bsaved, 1),
            },
            "totals": {
                "bytes_per_tuple": round(tot_bpt, 2),
                # the hop bytes are cost-table attributions (modeled);
                # the wire bytes above are real counters (measured)
                "bytes_provenance": "modeled",
                "dispatches_per_batch": round(tot_dpb, 3),
                "donation_miss_bytes_per_batch": round(tot_miss, 1),
                "dispatches": tot_disp,
                "cost_attributed_dispatch_fraction":
                    round(tot_attr_disp / tot_disp, 4) if tot_disp
                    else None,
            },
        }
