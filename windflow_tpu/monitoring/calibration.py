"""Calibration plane: measured-vs-modeled provenance + the live roofline.

Three planes (wire, Pallas kernels, megastep) default auto-on for TPU
backends, yet every number the repo holds for them is a *model* — the
structural ICI collective model (``WF_TPU_ICI_BYTES_PER_SEC``), the XLA
cost-table bytes the sweep ledger attributes per hop, the ~19 MB/s
tunnel figure ``bench.py``'s gap diagnosis compares against — or an
*interpret-mode* run.  Nothing in stats()/OpenMetrics/bench said which,
so a stale model read exactly like ground truth (ROADMAP item 1).

This module closes that gap in the PR 6/9/17/19 plane mold:

* **Provenance vocabulary.**  Every surfaced quantity that is not a
  direct measurement carries one of four tags: ``measured`` (a clock or
  byte counter on the live path), ``modeled`` (a constant or cost-table
  estimate), ``calibrated(<age>)`` (a modeled constant replaced by a
  probe measurement from ``tools/wf_calibrate.py``, with the
  measurement's age), or ``interpret`` (a Pallas interpreter run — a
  correctness vehicle, never a perf number).

* **Calibration store.**  ``tools/wf_calibrate.py`` runs a short seeded
  probe suite on the live backend and writes a versioned
  ``calibration.json`` keyed by device kind + jax version.
  ``Config.calibration`` / ``WF_TPU_CALIBRATION`` names the file; every
  modeled-constant read site goes through :func:`constant`, which
  returns ``(value, provenance)`` — the calibrated value while the
  store is fresh and matches the live device kind, the modeled default
  (with a one-time warning) once it goes stale past
  ``WF_TPU_CALIBRATION_TTL_S`` or mismatches.  ``WF_TPU_CALIBRATION=0``
  is the kill switch: no store loads anywhere and every read site
  degrades to its modeled default in one check.

* **Live roofline.**  :class:`RooflineLedger` promotes the bench-only
  roofline decomposition to a monitor-cadence gauge: per-hop achieved
  tuples/sec (a delta over counters the replicas already keep — zero
  per-batch work) joined with the sweep ledger's bytes/tuple and the
  calibrated memory bandwidth into ``stats()["Roofline"]`` +
  ``wf_roofline_*`` OpenMetrics families, plus a latched
  ``ROOFLINE_DEGRADED`` advisory health verdict when the dominant
  hop's throughput collapses against its own trailing baseline (the
  SLO plane's enter/latch/clear hysteresis).  ``Config.roofline_plane``
  off leaves one ``is not None`` check per call site (micro-asserted
  by tests/test_calibration.py).

The module never imports jax at module scope (``tools/wf_doctor.py``
renders the postmortem's ``calibration.json`` with no jax at all).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
import warnings
from collections import deque
from typing import Dict, Optional, Tuple

# ---------------------------------------------------------------------------
# provenance vocabulary
# ---------------------------------------------------------------------------

#: a direct measurement on the live path (clocks, byte counters)
MEASURED = "measured"
#: a constant, structural model, or XLA cost-table estimate
MODELED = "modeled"
#: a Pallas interpreter run — correctness vehicle, never a perf number
INTERPRET = "interpret"
#: prefix of the aged calibrated tag (see :func:`calibrated_tag`)
CALIBRATED_PREFIX = "calibrated("

#: schema tag of calibration.json (tools/wf_calibrate.py writes it,
#: tools/wf_doctor.py validates the postmortem copy against it)
SCHEMA = "wf-calibration/1"

#: calibration freshness TTL in seconds (default 7 days): past it the
#: store degrades to the modeled defaults with a one-time warning —
#: last week's tunnel measurement must not masquerade as today's
TTL_S = float(os.environ.get("WF_TPU_CALIBRATION_TTL_S", str(7 * 86400)))

#: the constants a calibration store may carry, with their modeled
#: defaults (each default env-overridable at its historical knob where
#: one exists).  Every read site names its key here so wf_calibrate,
#: the doctor validation, and the provenance summary agree on the set.
MODELED_DEFAULTS = {
    # ICI bandwidth the shard ledger's structural collective model
    # divides by (shard_ledger.ICI_BYTES_PER_SEC keeps the env knob)
    "ici_bytes_per_sec": 90e9,
    # host->device tunnel bandwidth of the staged path — the ~19 MB/s
    # remote-link figure bench.py's gap_diagnosis compares against
    "h2d_tunnel_bytes_per_sec": float(os.environ.get(
        "WF_TPU_TUNNEL_BYTES_PER_SEC", str(19e6))),
    # memory bandwidth the roofline ceiling divides by (v5e peak HBM;
    # on the CPU fallback the probe measures effective host bandwidth)
    "hbm_bytes_per_sec": float(os.environ.get(
        "WF_TPU_HBM_BYTES_PER_SEC", str(819e9))),
    # per-dispatch overhead of a cached jitted program (µs)
    "dispatch_overhead_usec": 100.0,
    # cost of one sampled block_until_ready device sync (µs) — what the
    # trace lane's trace_device_sync_every batches pay
    "sampled_sync_usec": 100.0,
    # one fused FFAT kernel step at the bench shape (µs/step) — the
    # per-device-kind step timing the roofline cross-checks
    "kernel_step_usec": 0.0,
}

#: calibration keys whose probe is meaningful only on a multi-device
#: mesh — absent on single-device stores by design, not corruption
MESH_ONLY_KEYS = ("ici_bytes_per_sec",)


def calibrated_tag(age_s: float) -> str:
    """The aged provenance tag: ``calibrated(3h)`` / ``calibrated(2d)``."""
    age_s = max(0.0, float(age_s))
    if age_s < 120:
        human = f"{int(age_s)}s"
    elif age_s < 2 * 3600:
        human = f"{int(age_s // 60)}m"
    elif age_s < 2 * 86400:
        human = f"{int(age_s // 3600)}h"
    else:
        human = f"{int(age_s // 86400)}d"
    return f"{CALIBRATED_PREFIX}{human})"


def is_calibrated(tag: str) -> bool:
    return isinstance(tag, str) and tag.startswith(CALIBRATED_PREFIX)


def legal_provenance(tag) -> bool:
    """True for any tag of the four-value vocabulary (the bench checker
    and wf_doctor validate surfaced tags against this)."""
    return tag in (MEASURED, MODELED, INTERPRET) or is_calibrated(tag)


# ---------------------------------------------------------------------------
# the calibration store
# ---------------------------------------------------------------------------

class CalibrationError(ValueError):
    """calibration.json failed validation (corrupt, wrong schema, bad
    constant types) — a corrupt store must never silently read as
    calibrated truth."""


class CalibrationStore:
    """One validated calibration.json: measured constants keyed by the
    device kind + jax version they were probed on."""

    __slots__ = ("path", "recorded_at", "device_kind", "backend",
                 "jax_version", "constants", "probes")

    def __init__(self, doc: dict, path: Optional[str] = None) -> None:
        if not isinstance(doc, dict):
            raise CalibrationError("calibration document is not an object")
        if doc.get("schema") != SCHEMA:
            raise CalibrationError(
                f"schema {doc.get('schema')!r} != {SCHEMA!r}")
        rec = doc.get("recorded_at")
        if not isinstance(rec, (int, float)) or not math.isfinite(rec) \
                or rec <= 0:
            raise CalibrationError(f"bad recorded_at {rec!r}")
        kind = doc.get("device_kind")
        jv = doc.get("jax_version")
        if not isinstance(kind, str) or not kind:
            raise CalibrationError(f"bad device_kind {kind!r}")
        if not isinstance(jv, str) or not jv:
            raise CalibrationError(f"bad jax_version {jv!r}")
        consts = doc.get("constants")
        if not isinstance(consts, dict) or not consts:
            raise CalibrationError("constants missing or empty")
        for k, v in consts.items():
            if k not in MODELED_DEFAULTS:
                raise CalibrationError(f"unknown constant {k!r}")
            if not isinstance(v, (int, float)) or not math.isfinite(v) \
                    or v < 0:
                raise CalibrationError(f"constant {k!r} not a finite "
                                       f"non-negative number: {v!r}")
        self.path = path
        self.recorded_at = float(rec)
        self.device_kind = kind
        self.backend = doc.get("backend")
        self.jax_version = jv
        self.constants = {k: float(v) for k, v in consts.items()}
        self.probes = doc.get("probes") if isinstance(doc.get("probes"),
                                                      dict) else {}

    def age_s(self, now: Optional[float] = None) -> float:
        return max(0.0, (now if now is not None else time.time())
                   - self.recorded_at)

    def fresh(self, now: Optional[float] = None) -> bool:
        return self.age_s(now) <= TTL_S

    def to_json(self) -> dict:
        return {
            "schema": SCHEMA,
            "recorded_at": self.recorded_at,
            "device_kind": self.device_kind,
            "backend": self.backend,
            "jax_version": self.jax_version,
            "constants": dict(self.constants),
            "probes": dict(self.probes),
        }


def load(path: str) -> CalibrationStore:
    """Read + validate one calibration.json.  Raises
    :class:`CalibrationError` on any corruption (a bad store must fail
    loudly at load, never flip numbers silently)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise CalibrationError(f"unreadable: {e}") from e
    except ValueError as e:
        raise CalibrationError(f"not JSON: {e}") from e
    return CalibrationStore(doc, path=path)


# -- process-default store (the shard/tenant/bench read path) ---------------

_lock = threading.Lock()
_store: Optional[CalibrationStore] = None
_store_resolved = False
_warned: set = set()          # one-time warning keys


def _warn_once(key: str, msg: str) -> None:
    with _lock:
        if key in _warned:
            return
        _warned.add(key)
    warnings.warn(msg, RuntimeWarning, stacklevel=3)


def killed() -> bool:
    """The kill switch: ``WF_TPU_CALIBRATION=0`` (or ``off``) disables
    calibration loading process-wide — every read site returns its
    modeled default in one check (wf_calibrate --check exits 2 under
    it, the wf_ir refuse-to-report-clean stance)."""
    return os.environ.get("WF_TPU_CALIBRATION", "").lower() in ("0", "off",
                                                                "false")


def default_store() -> Optional[CalibrationStore]:
    """The process-wide store: installed by :func:`set_default_store`
    (PipeGraph._build on ``Config.calibration``) or resolved lazily from
    ``WF_TPU_CALIBRATION`` (a path).  None = uncalibrated."""
    global _store, _store_resolved
    if _store is not None or _store_resolved:
        return _store
    with _lock:
        if _store is not None or _store_resolved:
            return _store
        _store_resolved = True
    env = os.environ.get("WF_TPU_CALIBRATION", "")
    if not env or killed():
        return None
    try:
        store = load(env)
    except CalibrationError as e:
        _warn_once(f"load:{env}",
                   f"WF_TPU_CALIBRATION={env!r} failed to load ({e}) — "
                   "running uncalibrated, every modeled constant keeps "
                   "its default")
        return None
    with _lock:
        _store = store
    return _store


def set_default_store(store: Optional[CalibrationStore]) -> None:
    """Install (or clear, with re-resolution from the env) the
    process-wide store.  ``PipeGraph._build`` calls this when
    ``Config.calibration`` names a file; tests use it directly."""
    global _store, _store_resolved
    with _lock:
        _store = store
        _store_resolved = store is not None
        if store is None:
            _warned.clear()


_device_kind_cache: Optional[str] = None


def live_device_kind() -> Optional[str]:
    """Device kind of the default backend (cached; None when the
    backend cannot answer — the store's kind gate then passes, same
    degrade-to-available stance as the device plane's memory probes)."""
    global _device_kind_cache
    if _device_kind_cache is not None:
        return _device_kind_cache
    try:
        import jax
        d = jax.devices()[0]
        _device_kind_cache = str(getattr(d, "device_kind", None)
                                 or d.platform)
    except Exception:  # lint: broad-except-ok (a dead/exotic backend
        # must degrade the kind gate to "unknown", never break a stats
        # read that only wanted a provenance tag)
        return None
    return _device_kind_cache


def constant(key: str, default: Optional[float] = None,
             now: Optional[float] = None) -> Tuple[float, str]:
    """THE modeled-constant read path: ``(value, provenance)``.

    Calibrated value + aged ``calibrated(...)`` tag while the default
    store is fresh, carries ``key``, and was recorded on this device
    kind; the modeled default + ``modeled`` otherwise (stale or
    kind-mismatched stores warn once and degrade — a dead measurement
    must never outrank a live model silently).  Called at stats/bench
    cadence only, never per batch."""
    if default is None:
        default = MODELED_DEFAULTS[key]
    store = default_store()
    if store is None:
        return float(default), MODELED
    if key not in store.constants:
        return float(default), MODELED
    kind = live_device_kind()
    if kind is not None and store.device_kind != kind:
        _warn_once(f"kind:{store.path}",
                   f"calibration {store.path or '<installed>'} was "
                   f"recorded on device kind {store.device_kind!r} but "
                   f"this process runs {kind!r} — ignoring it, every "
                   "modeled constant keeps its default")
        return float(default), MODELED
    if not store.fresh(now):
        _warn_once(f"stale:{store.path}",
                   f"calibration {store.path or '<installed>'} is "
                   f"{store.age_s(now) / 86400:.1f} days old (TTL "
                   f"{TTL_S / 86400:.1f}d) — degrading to the modeled "
                   "defaults; re-run tools/wf_calibrate.py")
        return float(default), MODELED
    return store.constants[key], calibrated_tag(store.age_s(now))


def provenance_summary(now: Optional[float] = None) -> dict:
    """One provenance frame for dump_trace metadata, the postmortem's
    ``calibration.json``, and the ``wf_provenance`` OpenMetrics family:
    where each modeled constant currently comes from."""
    store = default_store()
    out = {
        "schema": SCHEMA,
        "enabled": not killed(),
        "source": getattr(store, "path", None),
        "device_kind": live_device_kind(),
    }
    if store is not None:
        out["store"] = {
            "recorded_at": store.recorded_at,
            "device_kind": store.device_kind,
            "jax_version": store.jax_version,
            "age_s": round(store.age_s(now), 1),
            "fresh": store.fresh(now),
        }
    consts = {}
    for key in MODELED_DEFAULTS:
        v, prov = constant(key, now=now)
        consts[key] = {"value": v, "provenance": prov}
    out["constants"] = consts
    return out


# ---------------------------------------------------------------------------
# live roofline plane
# ---------------------------------------------------------------------------

#: throughput-collapse threshold: the dominant hop's current rate below
#: this fraction of its own trailing baseline is a breach tick
DEGRADE_RATIO = float(os.environ.get("WF_TPU_ROOFLINE_DEGRADE", "0.5"))


class RooflineLedger:
    """Monitor-cadence roofline gauge over counters that already exist.

    ``tick()`` (health_tick cadence) diffs each hop's cumulative
    processed-tuple counter against the previous tick — two integer
    reads per op per tick, zero per-batch work — into a bounded rate
    ring.  ``section()`` (stats cadence) joins the rings with the sweep
    ledger's per-hop bytes/tuple and the calibrated memory bandwidth
    into achieved-vs-roofline ratios.  The verdict state machine is the
    SLO plane's (latency_ledger.py): enter after ``ENTER_AFTER``
    consecutive collapse ticks once ``MIN_SAMPLES`` rates exist, latch
    while active, clear after ``CLEAR_AFTER`` consecutive OK ticks —
    judged against the hop's OWN trailing baseline, so it needs no
    absolute target."""

    ENTER_AFTER = 2
    CLEAR_AFTER = 3
    MIN_SAMPLES = 8
    WINDOW = 64
    #: wall-clock tick throttle (the tenant ledger's stance): headless
    #: runs call health_tick per sweep, and the counter walk must not
    #: become per-batch work through that path — ticks inside the
    #: interval are one compare
    TICK_MIN_INTERVAL_S = 0.2

    def __init__(self, graph) -> None:
        self._graph = graph
        self._last_tick_s: Optional[float] = None
        #: op name -> bounded ring of tuples/sec samples
        self._rings: Dict[str, deque] = {}
        #: op name -> (wall_s, cumulative inputs) at the previous tick
        self._prev: Dict[str, tuple] = {}
        self.ticks = 0
        self.entered = 0
        self.cleared = 0
        self._breach_ticks = 0
        self._ok_ticks = 0
        self.verdict: Optional[dict] = None
        self.last_verdict: Optional[dict] = None
        self._lock = threading.Lock()

    # -- cadence tick (zero per-batch work: reads existing counters) ---------
    def tick(self, now_s: Optional[float] = None) -> None:
        now_s = now_s if now_s is not None else time.monotonic()
        last = self._last_tick_s
        if last is not None and now_s - last < self.TICK_MIN_INTERVAL_S:
            return
        self._last_tick_s = now_s
        with self._lock:
            rates = {}
            for op in self._graph._operators:
                if not getattr(op, "is_tpu", False):
                    continue
                done = sum(r.stats.inputs_received for r in op.replicas)
                prev = self._prev.get(op.name)
                self._prev[op.name] = (now_s, done)
                if prev is None:
                    continue
                dt = now_s - prev[0]
                dn = done - prev[1]
                if dt <= 0 or dn <= 0:
                    # idle tick: no sample — degradation means the rate
                    # collapsed while tuples still flow, not that the
                    # run ended (a drained graph must not latch a
                    # verdict from its own completion)
                    continue
                rate = dn / dt
                ring = self._rings.get(op.name)
                if ring is None:
                    ring = self._rings[op.name] = deque(maxlen=self.WINDOW)
                ring.append(rate)
                rates[op.name] = rate
            self.ticks += 1
            self._evaluate(rates)

    def _dominant(self) -> Optional[str]:
        """The hop carrying the most cumulative tuples — the one whose
        collapse is the pipeline's story."""
        best, best_n = None, -1
        for name, (_, n) in self._prev.items():
            if n > best_n:
                best, best_n = name, n
        return best

    def _evaluate(self, rates: Dict[str, float]) -> None:
        """The enter/latch/clear machine over the dominant hop (caller
        holds the lock)."""
        dom = self._dominant()
        ring = self._rings.get(dom) if dom else None
        if not ring or len(ring) < self.MIN_SAMPLES or dom not in rates:
            # no fresh evidence this tick: an active verdict stays
            # latched (the SLO stance — silence is not recovery)
            return
        trailing = sorted(list(ring)[:-1])
        baseline = trailing[len(trailing) // 2]
        current = ring[-1]
        breach = baseline > 0 and current < DEGRADE_RATIO * baseline
        if breach:
            self._breach_ticks += 1
            self._ok_ticks = 0
            if self.verdict is None \
                    and self._breach_ticks >= self.ENTER_AFTER:
                self.entered += 1
                self.verdict = self.last_verdict = {
                    "state": "ROOFLINE_DEGRADED",
                    "dominant_op": dom,
                    "current_tuples_per_sec": round(current, 1),
                    "baseline_tuples_per_sec": round(baseline, 1),
                    "ratio_vs_baseline": round(current / baseline, 4),
                    "degrade_ratio": DEGRADE_RATIO,
                    "entered_tick": self.ticks,
                }
        else:
            self._breach_ticks = 0
            if self.verdict is not None:
                self._ok_ticks += 1
                if self._ok_ticks >= self.CLEAR_AFTER:
                    self.cleared += 1
                    self.verdict = None
                    self._ok_ticks = 0

    def health_verdict(self) -> Optional[dict]:
        """Plain read of the latest published verdict (the health
        plane's per-sample hook — same stance as the SLO/budget reads)."""
        return self.verdict

    # -- stats()["Roofline"] --------------------------------------------------
    def section(self) -> dict:
        """Per-hop achieved vs roofline (stats cadence).  Bytes/tuple
        joins from the sweep ledger (cost-table numbers — tagged
        ``modeled``); the bandwidth ceiling is the calibrated
        ``hbm_bytes_per_sec`` (tagged with ITS provenance), so the
        achieved/roofline ratio names its own trustworthiness."""
        bw, bw_prov = constant("hbm_bytes_per_sec")
        led = self._graph._ledger
        sweep_hops = {}
        if led is not None:
            try:
                sweep_hops = led.section().get("per_hop") or {}
            except Exception:  # lint: broad-except-ok (the sweep join
                # is telemetry enrichment — a ledger bug degrades the
                # roofline to rates-only, it must not take stats down)
                sweep_hops = {}
        with self._lock:
            per_hop = {}
            for name, ring in self._rings.items():
                if not ring:
                    continue
                rs = sorted(ring)
                tps = rs[len(rs) // 2]
                hop = {
                    "achieved_tuples_per_sec": round(tps, 1),
                    "samples": len(ring),
                    "tuples_per_sec_provenance": MEASURED,
                }
                sh = sweep_hops.get(name) or {}
                bpt = sh.get("steady_bytes_per_tuple") \
                    or sh.get("bytes_per_tuple")
                if bpt:
                    hop["bytes_per_tuple"] = bpt
                    hop["bytes_per_tuple_provenance"] = \
                        sh.get("bytes_provenance", MODELED)
                    achieved_bps = tps * float(bpt)
                    hop["achieved_bytes_per_sec"] = round(achieved_bps, 1)
                    if bw > 0:
                        hop["roofline_tuples_per_sec"] = \
                            round(bw / float(bpt), 1)
                        hop["ratio_vs_roofline"] = \
                            round(achieved_bps / bw, 6)
                per_hop[name] = hop
            return {
                "enabled": True,
                "per_hop": per_hop,
                "dominant_op": self._dominant(),
                "bandwidth_bytes_per_sec": bw,
                "bandwidth_provenance": bw_prov,
                "ticks": self.ticks,
                "entered": self.entered,
                "cleared": self.cleared,
                "verdict": self.verdict,
                "last_verdict": self.last_verdict,
                "thresholds": {
                    "degrade_ratio": DEGRADE_RATIO,
                    "enter_after": self.ENTER_AFTER,
                    "clear_after": self.CLEAR_AFTER,
                    "min_samples": self.MIN_SAMPLES,
                },
                "calibration": provenance_summary(),
            }
