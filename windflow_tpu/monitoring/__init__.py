"""Observability: per-replica stats records, the flight recorder (span
tracing + latency histograms), the dashboard monitoring thread + TCP
protocol, and graph diagram generation (reference ``stats_record.hpp``,
``monitoring.hpp``, graphviz hooks — SURVEY.md §2.8/§5.1; recorder design
in docs/OBSERVABILITY.md)."""

from windflow_tpu.monitoring.dashboard import DashboardServer
from windflow_tpu.monitoring.diagram import to_dot, to_svg
from windflow_tpu.monitoring.monitor import MonitoringThread
from windflow_tpu.monitoring.recorder import (FlightRecorder,
                                              LatencyHistogram,
                                              chrome_trace_from_events)
from windflow_tpu.monitoring.stats import StatsRecord
