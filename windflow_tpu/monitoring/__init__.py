"""Observability: per-replica stats records, the flight recorder (span
tracing + latency histograms), the dashboard monitoring thread + TCP
protocol, and graph diagram generation (reference ``stats_record.hpp``,
``monitoring.hpp``, graphviz hooks — SURVEY.md §2.8/§5.1; recorder design
in docs/OBSERVABILITY.md)."""

from windflow_tpu.monitoring.dashboard import DashboardServer
from windflow_tpu.monitoring.diagram import to_dot, to_svg
from windflow_tpu.monitoring.health import HealthPlane
from windflow_tpu.monitoring.monitor import MonitoringThread
from windflow_tpu.monitoring.openmetrics import (parse_exposition,
                                                 render_openmetrics)
from windflow_tpu.monitoring.recorder import (FlightRecorder,
                                              LatencyHistogram,
                                              chrome_trace_from_events)
from windflow_tpu.monitoring.stats import StatsRecord

# The compile watcher (jit_registry.wf_jit), device gauges
# (device_metrics) and sweep ledger (sweep_ledger.SweepLedger) are
# intentionally NOT re-exported here: the first two import jax at
# module scope and the ledger pulls them in lazily — import them by
# full path from code that already owns a backend.  openmetrics stays
# pure stdlib so tools/wf_metrics.py can load it file-direct without
# importing the package (no jax on a scrape host).
