from windflow_tpu.monitoring.stats import StatsRecord
