"""Monitoring thread speaking the reference dashboard protocol.

Wire-compatible re-implementation of the reference ``MonitoringThread``
(``/root/reference/wf/monitoring.hpp:160-295``): a background thread samples
the graph once per second and ships reports to the dashboard server over a
length-prefixed TCP protocol (default ``localhost:20207``):

* ``NEW_APP``  (type 0): preamble ``[type, len]`` (two big-endian int32) +
  NUL-terminated SVG diagram; ack ``[status, identifier]``.
* ``NEW_REPORT`` (type 1): preamble ``[type, identifier, len]`` + NUL-
  terminated JSON stats; ack ``[status, _]``.
* ``END_APP`` (type 2): same framing as NEW_REPORT, sent once at the end.

Each NEW_REPORT payload is ``PipeGraph.stats()``, which since the flight
recorder (monitoring/recorder.py) also carries the ``Latency`` histograms
(per-operator + end-to-end p50/p95/p99) and the ``Gauges`` section —
watermark lag, queue depths, staging-pool occupancy, rolling 1s/10s
throughput — sampled by THIS thread's once-per-second cadence (the
rolling-rate window is fed by ``PipeGraph.sample_gauges``).

Like the reference (``monitoring.hpp:197-200``), the thread ships no more
reports once the dashboard is unreachable or any send fails — monitoring
must never take the pipeline down.  Unlike the reference, SAMPLING is
decoupled from SHIPPING: the rolling 1s/10s throughput gauges and the
health watchdog (``PipeGraph.sample_gauges`` / ``health_tick``,
monitoring/health.py) are fed by this thread's cadence, so a headless
run — no dashboard listening, or a dashboard that died mid-run — keeps
sampling on the same cadence and only stops sending.  (Before this split
the gauges starved whenever the TCP connection was down: ``stats()``
read at the end of a run saw a throughput window that had never
advanced.)

Termination is best-effort on BOTH paths: normal completion and an
aborted run (``wait_end`` raised) each ship a final report + ``END_APP``
(``_send_final``), degrading from full stats to a minimal
name+``Aborted`` payload when ``stats()`` itself is broken — before
this, a crashed app stayed "live" on the dashboard forever.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time

SAMPLE_INTERVAL_SEC = 1.0
TYPE_NEW_APP = 0
TYPE_NEW_REPORT = 1
TYPE_END_APP = 2


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes (shared by both protocol ends)."""
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed the connection")
        buf += chunk
    return buf


class MonitoringThread:
    def __init__(self, graph,
                 interval: float = SAMPLE_INTERVAL_SEC) -> None:
        self.graph = graph
        self.interval = interval
        self.identifier = -1
        self._sock = None
        self._thread = None
        self._stop = threading.Event()
        self.active = False      # a dashboard connection is up
        self.samples_taken = 0   # gauge samples on cadence (shipped or not)
        self.aborted = False     # abnormal termination (wait_end raised)
        self.end_app_sent = False

    # -- protocol ------------------------------------------------------------
    def _register_app(self) -> None:
        from windflow_tpu.monitoring.diagram import to_svg
        payload = to_svg(self.graph).encode() + b"\0"
        self._sock.sendall(struct.pack(">ii", TYPE_NEW_APP, len(payload)))
        self._sock.sendall(payload)
        status, ident = struct.unpack(">ii", recv_exact(self._sock, 8))
        if status != 0:
            raise ConnectionError(f"dashboard rejected NEW_APP: {status}")
        self.identifier = ident

    def _send_report(self, msg_type: int,
                     report: dict | None = None) -> None:
        payload = json.dumps(report if report is not None
                             else self.graph.stats()).encode() + b"\0"
        self._sock.sendall(struct.pack(">iii", msg_type, self.identifier,
                                          len(payload)))
        self._sock.sendall(payload)
        status, _ = struct.unpack(">ii", recv_exact(self._sock, 8))
        if status != 0:
            raise ConnectionError(f"dashboard rejected report: {status}")

    def _send_final(self) -> None:
        """Final report + END_APP, best-effort on BOTH termination paths.
        Before this existed, a wait_end crash left the dashboard showing
        the app live forever: stats() on a dead backend raised a
        non-OSError past the loop's handler and the thread died without
        END_APP.  Now the final report degrades (full stats → minimal
        name+Aborted payload) instead of vanishing."""
        if not self.active:
            return
        try:
            report = self.graph.stats()
            if self.aborted:
                report["Aborted"] = True
        except Exception:  # lint: broad-except-ok (crash-path stats()
            # may touch a dead backend; END_APP must still reach the
            # dashboard with whatever payload survives)
            # the degraded payload still names the tenant, so an aborted
            # app keeps its attribution on the dashboard's tenant roll-up
            report = {"PipeGraph_name": self.graph.name, "Aborted": True,
                      "Tenant": {"enabled": False, "tenant":
                                 getattr(self.graph.config, "tenant", "")
                                 or self.graph.name},
                      "stats_error": "stats() raised during termination"}
        try:
            self._send_report(TYPE_END_APP, report)
            self.end_app_sent = True
        except Exception:  # lint: broad-except-ok (monitoring must never
            # take termination down — a dead socket here is a no-op)
            pass

    # -- thread --------------------------------------------------------------
    def _run(self) -> None:
        try:
            self._sock = socket.create_connection(
                (self.graph.config.dashboard_host,
                 self.graph.config.dashboard_port), timeout=2.0)
            self._register_app()
            self.active = True
        except OSError:
            # reference: "Monitoring thread switched off" — but only the
            # SHIPPING half: the sampling loop below still runs, because
            # the rolling-throughput gauges are fed by this cadence and
            # must not starve on a headless run
            self.active = False
        try:
            last = time.monotonic()
            # Check ~20×/s: fine-grained enough for END_APP latency without
            # stealing GIL time from the dispatch loop (the reference's
            # usleep(100) spin is cheap only because its poll is off-GIL).
            while not self._stop.wait(0.05) and not self.graph.is_done():
                now = time.monotonic()
                if now - last >= self.interval:
                    last = now
                    self.samples_taken += 1
                    if self.active:
                        # stats() inside _send_report samples the gauges
                        # AND the health watchdog, so the shipped report,
                        # the rolling window and the verdicts advance on
                        # the same tick
                        try:
                            self._send_report(TYPE_NEW_REPORT)
                        except OSError:
                            # socket/protocol dead: keep sampling headless
                            self._disconnect()
                        except Exception:  # lint: broad-except-ok (a
                            # transient stats() failure raises BEFORE any
                            # bytes hit the wire — the report serializes
                            # first — so the protocol is still in sync:
                            # keep the connection, skip this tick, and
                            # END_APP still goes out at termination)
                            pass
                    else:
                        try:
                            self.graph.sample_gauges()
                            self.graph.health_tick()
                        except Exception:  # lint: broad-except-ok (a
                            # headless sampling failure must not kill the
                            # thread — the final report still goes out)
                            pass
            self._send_final()
        except OSError:
            pass
        finally:
            self._disconnect()

    def _disconnect(self) -> None:
        self.active = False
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="wf-monitoring")
        self._thread.start()

    def stop(self, timeout: float = 5.0, aborted: bool = False) -> None:
        if aborted:
            self.aborted = True   # final report carries the crash marker
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
