"""Single-page dashboard UI (reference: React SPA under
``dashboard/web_client/src/Pages/Dashboard.js`` — app list, graph view,
per-operator charts).  Served by :mod:`windflow_tpu.monitoring.dashboard`
at ``GET /`` as one static page of vanilla HTML+JS polling the JSON
endpoints; no build step, no external assets (works offline)."""

INDEX_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>windflow_tpu dashboard</title>
<style>
  body { font-family: system-ui, sans-serif; margin: 0; display: flex;
         height: 100vh; color: #222; }
  #apps { width: 220px; border-right: 1px solid #ddd; padding: 12px;
          overflow-y: auto; }
  #apps h2, #main h2 { font-size: 15px; margin: 4px 0 10px; }
  .app { padding: 6px 8px; border-radius: 6px; cursor: pointer;
         margin-bottom: 4px; font-size: 13px; }
  .app:hover { background: #f0f4ff; }
  .app.sel { background: #dbe7ff; }
  .dead { color: #999; }
  #main { flex: 1; padding: 14px 18px; overflow-y: auto; }
  table { border-collapse: collapse; font-size: 12px; margin-top: 6px; }
  td, th { border: 1px solid #e3e3e3; padding: 3px 8px; text-align: right; }
  th { background: #f7f7f7; }
  td:first-child, th:first-child { text-align: left; }
  .spark { vertical-align: middle; }
  .hOK { color: #1a7f37; font-weight: 600; }
  .hSLO_VIOLATED { color: #c2571a; font-weight: 600; }
  .hOVER_BUDGET { color: #8e44ad; font-weight: 600; }
  .hBACKPRESSURED { color: #b8860b; font-weight: 600; }
  .hSTALLED, .hFAILED { color: #c0392b; font-weight: 600; }
  .bud { display: inline-block; width: 60px; height: 9px;
         background: #eceff4; vertical-align: middle; }
  .bud > div { height: 9px; background: #c2571a; }
  #meta { font-size: 12px; color: #555; margin-bottom: 8px;
          white-space: pre-line; }
  pre { background: #f7f7f7; padding: 8px; font-size: 11px;
        overflow-x: auto; }
  details { margin-top: 12px; }
</style>
</head>
<body>
<div id="apps"><h2>Applications</h2><div id="applist">loading…</div></div>
<div id="main"><h2 id="title">select an application</h2>
  <div id="meta"></div>
  <div id="tenants"></div>
  <div id="ops"></div>
  <details><summary>graph diagram</summary><div id="diagram"></div></details>
</div>
<script>
let sel = null;

// every server-supplied string passes through esc() before innerHTML:
// app names, operator names, and diagrams arrive from arbitrary TCP
// clients and must never execute as markup in the viewer's browser
function esc(s) {
  return String(s).replace(/&/g, "&amp;").replace(/</g, "&lt;")
                  .replace(/>/g, "&gt;").replace(/"/g, "&quot;");
}

function spark(values, w, h) {
  if (values.length < 2) return "";
  const max = Math.max(...values, 1e-9);
  const pts = values.map((v, i) =>
    `${(i / (values.length - 1) * w).toFixed(1)},` +
    `${(h - v / max * (h - 2)).toFixed(1)}`).join(" ");
  return `<svg class="spark" width="${w}" height="${h}">` +
         `<polyline points="${pts}" fill="none" stroke="#4169e1" ` +
         `stroke-width="1.5"/></svg>`;
}

async function poll() {
  try {
    const apps = await (await fetch("/apps")).json();
    const el = document.getElementById("applist");
    el.innerHTML = apps.map(a =>
      `<div class="app ${a.id === sel ? "sel" : ""} ${a.alive ? "" : "dead"}"
            onclick="select(${a.id})">#${a.id} ${esc(a.name)}` +
      `${a.alive ? "" : " (ended)"}<br><small>${a.num_reports} reports` +
      `</small></div>`).join("") || "no applications yet";
    if (sel !== null) await render(sel);
  } catch (e) { /* server restarting */ }
  setTimeout(poll, 1000);
}

function select(id) { sel = id; render(id); loadDiagram(id); }

async function render(id) {
  const app = await (await fetch(`/apps/${id}`)).json();
  const reports = app.reports || [];
  document.getElementById("title").textContent =
    `#${id} ${app.name} — ${reports.length} reports`;  // textContent: safe
  if (!reports.length) return;
  const last = reports[reports.length - 1];
  // device/HBM line next to the host-side meta: compile-watcher totals
  // plus per-device allocator bytes (CPU backends report no memory_stats
  // — shown as host-only so the gap is explicit, not blank)
  const dev = last.Device || {};
  const jt = dev.jit_totals || {};
  const hbm = (dev.memory || [])
    .filter(d => d.stats && d.stats.bytes_in_use !== undefined)
    .map(d => `${d.device}=${(d.stats.bytes_in_use / 1048576).toFixed(1)}MB`)
    .join(" ");
  const live = dev.live_buffers || {};
  // health plane: graph verdict + stall counter in the meta line, a
  // per-operator state column in the table below
  const health = last.Health || {};
  // latency plane: rolling-p99-vs-budget headline when an SLO is
  // declared, and the per-op budget-bar column in the table below
  const lplane = last.Latency_plane || {};
  const slo = lplane.slo || {};
  const sloLine = slo.budget_ms
    ? `  slo=${slo.active ? "VIOLATED" : "ok"} ` +
      `p99=${slo.recent_p99_ms}ms/${slo.budget_ms}ms`
    : "";
  const hLine = (health.enabled
    ? `health=${health.graph_state || "?"} ` +
      `stalls=${health.stall_events ?? 0}`
    : "health=off") + sloLine + (last.Aborted ? "  ABORTED" : "");
  // wire plane: compression ratio of the staged ingest (logical over
  // wire bytes) — "off"/"raw" make the no-compression cases explicit
  const wire = (last.Staging || {}).Wire || {};
  const wLine = wire.enabled
    ? (wire.compression_ratio != null
       ? `wire=${wire.compression_ratio}x` : "wire=raw")
    : "wire=off";
  document.getElementById("meta").textContent =
    `mode=${last.Mode}  operators=${last.Operator_number}  ` +
    `dropped=${last.Dropped_tuples}  rss=${last.rss_size_kb} kB  ` +
    `throttle_events=${last.Backpressure_throttle_events}  ` +
    `${wLine}  ${hLine}\n` +
    `device: compiles=${jt.compiles ?? "?"} ` +
    `recompiles=${jt.recompiles ?? "?"} ` +
    `compile_ms=${jt.compile_ms_total ?? "?"}  ` +
    `live_buffers=${live.count ?? "?"} ` +
    `(${((live.bytes || 0) / 1048576).toFixed(1)}MB)  ` +
    `hbm: ${hbm || "(no allocator stats — host-only backend)"}`;
  // tenant plane (monitoring/tenant_ledger.py): process-wide roll-up —
  // one row per tenant with a budget bar (resident bytes vs declared
  // HBM budget; the bar overflows red past 1.0) and the attribution
  // fraction headline.  Rendered from this app's report, which carries
  // the WHOLE process table.
  const tplane = last.Tenant || {};
  const tEl = document.getElementById("tenants");
  if (tplane.enabled && tplane.tenants &&
      Object.keys(tplane.tenants).length) {
    const frac = (tplane.attributed || {}).staged_fraction;
    const fmtB = b => b >= 1048576 ? `${(b / 1048576).toFixed(1)}MB`
      : b >= 1024 ? `${(b / 1024).toFixed(1)}kB` : `${b}B`;
    tEl.innerHTML =
      `<table><tr><th>tenant` +
      `${frac != null ? ` (attributed ${(frac * 100).toFixed(0)}%)`
                      : ""}</th>` +
      `<th>graphs</th><th>resident</th><th>budget</th>` +
      `<th>dispatches</th><th>H2D</th><th>verdict</th></tr>` +
      Object.entries(tplane.tenants).map(([name, t]) => {
        const bud = t.budget || {};
        const pr = bud.pressure;
        const over = bud.active;
        const budCell = !bud.budget_bytes ? "–"
          : `<span class="bud"><div style="width:` +
            `${Math.round(Math.min(1, pr || 0) * 60)}px` +
            `${over ? ";background:#c0392b" : ""}"></div></span> ` +
            `${fmtB(bud.budget_bytes)} (${(pr || 0).toFixed(2)}x)`;
        const vCell = over
          ? `<span class="hOVER_BUDGET">OVER_BUDGET</span>` +
            ` → ${esc((bud.verdict || {}).heaviest_op || "?")}`
          : "ok";
        return `<tr><td>${esc(name)}</td>` +
               `<td>${(t.graphs || []).map(esc).join(", ")}</td>` +
               `<td>${fmtB(t.resident_state_bytes || 0)}</td>` +
               `<td>${budCell}</td><td>${t.dispatches ?? 0}</td>` +
               `<td>${fmtB(t.h2d_bytes || 0)}</td>` +
               `<td>${vCell}</td></tr>`;
      }).join("") + "</table>";
  } else {
    tEl.innerHTML = "";
  }
  // per-operator history: throughput (delta Outputs_sent) and
  // watermark-lag gauge between reports
  const hist = {}, lagHist = {};
  let prev = null;
  for (const r of reports) {
    const byOp = {};
    for (const op of (r.Operators || [])) {
      let out = 0;
      for (const rep of (op.Replicas || [])) out += rep.Outputs_sent || 0;
      byOp[op.Operator_name || op.Name || "?"] = out;
    }
    const gops = (r.Gauges || {}).operators || {};
    for (const [name, g] of Object.entries(gops)) {
      if (g.watermark_lag_usec != null)
        (lagHist[name] = lagHist[name] || []).push(g.watermark_lag_usec);
    }
    if (prev) {
      for (const [name, out] of Object.entries(byOp)) {
        (hist[name] = hist[name] || []).push(
          Math.max(0, out - (prev[name] || 0)));
      }
    }
    prev = byOp;
  }
  const lastOps = reports[reports.length - 1].Operators || [];
  const lat = (last.Latency || {}).service_usec_per_operator || {};
  const gops = (last.Gauges || {}).operators || {};
  const fmtUs = v => v == null ? "–" :
    (v >= 1e6 ? `${(v / 1e6).toFixed(1)}s` :
     v >= 1e3 ? `${(v / 1e3).toFixed(1)}ms` : `${Math.round(v)}µs`);
  const verdicts = health.verdicts || {};
  // sweep ledger (monitoring/sweep_ledger.py): per-hop dispatch + HBM
  // attribution columns — "B/tuple" is XLA cost-analysis bytes accessed
  // per tuple for the hop, "disp/batch" its jitted dispatches per
  // staged batch; a flagged hop ("!don") has donation-miss copies
  const sweepHops = (last.Sweep || {}).per_hop || {};
  // shard plane (monitoring/shard_ledger.py): per-shard drill-down
  // under each op row — click the operator name to expand its shards
  // (queue/lag/load per replica, hot-key table for keyed edges)
  const shardOps = (last.Shard || {}).per_op || {};
  // latency ledger (monitoring/latency_ledger.py): each op's share of
  // the graph-wide decomposed critical path, drawn as a budget bar;
  // hover names the op's dominant segment (where its share is spent)
  const latOps = lplane.per_op || {};
  const shardRow = (name, i) => {
    const sh = shardOps[name];
    if (!sh) return "";
    const reps = sh.replicas || [];
    const load = sh.load || {};
    const tuples = load.tuples || [];
    if (reps.length < 2 && !tuples.length) return "";
    const rows = reps.map(r => {
      const q = r.service_usec || {};
      const t = tuples[r.shard];
      const hotMark = load.hot_shard === r.shard ? " 🔥" : "";
      return `<tr><td>shard ${r.shard}${hotMark}</td>` +
             `<td>${r.queue_depth}</td><td>${fmtUs(r.watermark_lag_usec)}` +
             `</td><td>${t == null ? "–" : t}</td>` +
             `<td>${fmtUs(q.p50)}</td><td>${fmtUs(q.p99)}</td>` +
             `<td>${r.dispatches}</td>` +
             `<td>${r.hbm_bytes == null ? "–" : r.hbm_bytes}</td></tr>`;
    }).join("");
    const hot = (load.hot_keys || []).slice(0, 4).map(h =>
      `${esc(h.key)}→shard ${h.shard ?? "?"} ` +
      `(${((h.share || 0) * 100).toFixed(1)}%)`).join(", ");
    const imb = load.imbalance_ratio != null
      ? ` imbalance=${load.imbalance_ratio}` : "";
    // calibration provenance (monitoring/calibration.py): the ICI
    // column is the shard plane's structural model, never a counter —
    // marked "~" with the provenance in the hover title so a modeled
    // number can never read as ground truth
    const ici = (sh.ici || {}).ici_bytes_per_tuple;
    const iciProv = (sh.ici || {}).ici_bandwidth_provenance || "modeled";
    const open = (window._openShards || new Set()).has(i);
    return `<tr id="shard_${i}" style="display:${open ? "" : "none"}">` +
           `<td colspan="14">` +
           `<table><tr><th>shard</th><th>queue</th><th>wm lag</th>` +
           `<th>tuples</th><th>p50</th><th>p99</th><th>disp</th>` +
           `<th>HBM B</th></tr>${rows}</table>` +
           `<small>${load.basis ? `load basis=${esc(load.basis)}` : ""}` +
           `${imb}${hot ? ` hot keys: ${hot}` : ""}` +
           `${ici != null ? ` <span title="provenance: modeled ` +
             `(structural collective model; bandwidth ${esc(iciProv)})">` +
             `ICI≈${ici} B/tuple</span>` : ""}</small>` +
           `</td></tr>`;
  };
  window._openShards = window._openShards || new Set();
  window.toggleShard = i => {
    const el = document.getElementById(`shard_${i}`);
    if (!el) return;
    const hidden = el.style.display === "none";
    el.style.display = hidden ? "" : "none";
    // survives the 1 Hz re-render: membership drives the next render
    if (hidden) window._openShards.add(i);
    else window._openShards.delete(i);
  };
  document.getElementById("ops").innerHTML =
    `<table><tr><th>operator</th><th>health</th><th>replicas</th>` +
    `<th>outputs</th>` +
    `<th>ignored</th><th>p50</th><th>p95</th><th>p99</th>` +
    `<th>disp/batch</th><th>B/tuple</th><th>wire</th>` +
    `<th>budget</th>` +
    `<th>wm lag</th><th>throughput (tuples/report)</th></tr>` +
    lastOps.map(op => {
      const name = op.Operator_name || op.Name || "?";
      const reps = (op.Replicas || []);
      const outs = reps.reduce((s, r) => s + (r.Outputs_sent || 0), 0);
      const ign = reps.reduce((s, r) => s + (r.Inputs_ignored || 0), 0);
      const h = hist[name] || [];
      const cur = h.length ? h[h.length - 1] : 0;
      const q = lat[name] || {};
      const lag = (gops[name] || {}).watermark_lag_usec;
      const lh = lagHist[name] || [];
      const state = (verdicts[name] || {}).state;
      const hCell = state
        ? `<span class="h${esc(state)}">${esc(state)}</span>`
        : "–";
      const hop = sweepHops[name] || {};
      const don = hop.donation_miss ? " <b>!don</b>" : "";
      // "~" marks a modeled cell (XLA cost-table attribution, not a
      // byte counter) — hover for the provenance tag (calibration.py)
      const bpt = hop.bytes_per_tuple == null ? "–"
        : `<span title="provenance: ` +
          `${esc(hop.bytes_provenance || "modeled")} ` +
          `(XLA cost-table estimate)">~${hop.bytes_per_tuple}</span>${don}`;
      // whole-chain fusion: a member hop dispatches nothing — its
      // program folded into the fused host hop it names here
      const dpb = hop.fused_into
        ? `⇒ ${esc(hop.fused_into)}`
        : (hop.dispatches_per_batch == null ? "–"
           : hop.dispatches_per_batch);
      // wire plane: per-op compression ratio of the staged transfers
      // this op's replicas shipped (Bytes_H2D_logical over Bytes_H2D —
      // "raw" when the op stages uncompressed, "–" when it stages
      // nothing)
      const wSent = reps.reduce((s, r) => s + (r.Bytes_H2D || 0), 0);
      const wLog = reps.reduce(
        (s, r) => s + (r.Bytes_H2D_logical || 0), 0);
      const wCell = !wSent ? "–"
        : (wLog > wSent ? `${(wLog / wSent).toFixed(2)}x` : "raw");
      const lp = latOps[name] || {};
      const bsh = lp.budget_share;
      const budCell = bsh == null ? "–"
        : `<span class="bud" title="${esc(lp.dominant_segment || "")}">` +
          `<div style="width:${Math.round(bsh * 60)}px"></div></span> ` +
          `${(bsh * 100).toFixed(0)}%`;
      const idx = lastOps.indexOf(op);
      const sub = shardRow(name, idx);
      const nameCell = sub
        ? `<td style="cursor:pointer" onclick="toggleShard(${idx})">` +
          `▸ ${esc(name)}</td>`
        : `<td>${esc(name)}</td>`;
      return `<tr>${nameCell}<td>${hCell}</td>` +
             `<td>${reps.length}</td>` +
             `<td>${outs}</td><td>${ign}</td>` +
             `<td>${fmtUs(q.p50)}</td><td>${fmtUs(q.p95)}</td>` +
             `<td>${fmtUs(q.p99)}</td>` +
             `<td>${dpb}</td><td>${bpt}</td><td>${wCell}</td>` +
             `<td>${budCell}</td>` +
             `<td>${spark(lh.slice(-60), 80, 26)} ${fmtUs(lag)}</td>` +
             `<td>${spark(h.slice(-60), 160, 26)} ${cur}</td></tr>` + sub;
    }).join("") + "</table>";
}

async function loadDiagram(id) {
  const txt = await (await fetch(`/apps/${id}/diagram`)).text();
  const el = document.getElementById("diagram");
  if (txt.trimStart().startsWith("<svg")) {
    // embed via <img>: SVG in an img element never runs scripts
    el.innerHTML = `<img src="/apps/${id}/diagram" alt="graph">`;
  } else {
    el.innerHTML = `<pre>${esc(txt)}</pre>`;   // DOT source
  }
}

poll();
</script>
</body>
</html>
"""
