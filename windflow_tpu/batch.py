"""Data plane: stream messages as batches.

TPU-first re-design of the reference message layer
(``/root/reference/wf/single_t.hpp``, ``batch_cpu_t.hpp``, ``batch_gpu_t.hpp``):

* The reference's host-side unit is ``Single_t``/``Batch_CPU_t`` — a vector of
  ``{tuple, ts}`` plus watermark slots.  Here :class:`HostBatch` plays that
  role: a list of arbitrary Python records with parallel timestamp list and a
  scalar watermark.

* The reference's device unit is ``Batch_GPU_t`` — a device array of
  ``batch_item_gpu_t{tuple, ts}`` with keyby support arrays and a per-batch
  CUDA stream (``batch_gpu_t.hpp:51-229``).  Here :class:`DeviceBatch` holds a
  **structure-of-arrays pytree** of JAX arrays (leading dim = static capacity),
  an ``int64`` timestamp lane, and a validity mask.  Static capacity + mask is
  the XLA answer to ragged batches: every compiled program sees one shape, so
  it is traced and tiled once.  Asynchronous dispatch replaces CUDA streams —
  JAX ops enqueue without blocking, so the host driver naturally keeps several
  batches in flight (the reference's 2-deep double buffering,
  ``forward_emitter_gpu.hpp:254-300``).

Watermarks are host metadata: the reference embeds per-destination watermark
slots in every message (``single_t.hpp:159-178``) because messages are shared
pointers multicast across thread queues.  Here routing is done by a host
driver that tracks watermarks per channel, so one scalar per batch suffices.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from windflow_tpu import staging
from windflow_tpu.monitoring.jit_registry import wf_jit

TS_DTYPE = jnp.int64
#: Watermark value meaning "no watermark yet".
WM_NONE = -1
#: Watermark value attached to the end-of-stream punctuation.
WM_MAX = (1 << 62)


@dataclasses.dataclass
class Punctuation:
    """Control message carrying only a watermark (reference: punctuation flag
    on ``Single_t``/``Batch_t``, ``single_t.hpp:54``).  ``watermark == WM_MAX``
    marks end-of-stream."""

    watermark: int

    @property
    def is_eos(self) -> bool:
        return self.watermark >= WM_MAX


@dataclasses.dataclass
class HostBatch:
    """A batch of host-resident records (reference ``Batch_CPU_t``,
    ``batch_cpu_t.hpp:51-205``).

    ``items[i]`` is an arbitrary Python object; ``tss[i]`` its timestamp in
    microseconds.  ``watermark`` is the minimum watermark folded over the
    inputs that produced this batch (the reference folds min-watermark in
    ``Batch_CPU_t::addTuple``)."""

    items: list
    tss: list
    watermark: int = WM_NONE
    #: optional per-item ORIGIN ids (tuples: source ordinal, replica, seq,
    #: expansion...) — assigned at sources and relayed by one-to-one /
    #: one-to-many host stages so DETERMINISTIC ordering can break
    #: timestamp ties config-independently (reference Single_t id field,
    #: ``single_t.hpp:50-183``); None when unavailable (aggregates emit
    #: fresh streams, device edges strip them — TPU ops are DEFAULT-only)
    ids: list = None
    #: True when this batch object is multicast to several inboxes
    #: (BROADCAST edges); in-place-capable consumers must copy before
    #: mutating (reference ``copyOnWrite`` + ``delete_counter`` multicast,
    #: ``map.hpp:57-215``, ``single_t.hpp:54``).
    shared: bool = False
    #: flight-recorder trace lane: ``(trace_id, t_origin_usec)`` on the
    #: 1-in-N sampled batch, None otherwise (monitoring/recorder.py).
    #: Relayed by whole-batch paths; host per-tuple stages start fresh
    #: traces at their emitter — lineage across a record explosion is not
    #: a single batch's journey.
    trace: tuple = None

    def __len__(self) -> int:
        return len(self.items)

    def ids_or_nones(self):
        """Per-item origin ids, None-filled when the batch carries none."""
        return self.ids if self.ids is not None \
            else (None,) * len(self.items)


class DeviceBatch:
    """A batch resident in TPU HBM (reference ``Batch_GPU_t``,
    ``batch_gpu_t.hpp:51-229``) as a structure-of-arrays pytree.

    Attributes
    ----------
    payload : pytree of jnp arrays, each with leading dimension ``capacity``.
    ts      : int64 [capacity] timestamps (microseconds).
    valid   : bool [capacity] mask; padding slots are False.  The reference
              carries an exact ``size``; a mask keeps shapes static for XLA.
    keys    : optional int32 [capacity] dense key-slot ids, attached by the
              keyby boundary (reference: ``dist_keys_cpu`` + per-key index
              chains built by ``keyby_emitter_gpu.hpp:519-583``; here key
              grouping is done with XLA sorts/segment ops at use sites).
    watermark, size : host-side metadata.  ``watermark`` is the min-folded
              stamp safe to propagate downstream (a host edge may re-split
              the batch per tuple).  ``frontier`` is the NEWEST watermark
              observed when the batch content was fixed at staging; it is
              only valid for the consuming operator's own firing decision
              *after* placing all the batch's tuples (place-then-fire), so
              it never propagates past the consumer — it saves time windows
              one batch of firing lag over the conservative stamp.
              ``ts_min``/``ts_max`` are the DATA timestamp extrema of
              the staged lanes (host-known at staging for free; ``None``
              for device-born batches) — outer bounds that stay valid
              through mask-only stages (map/filter/split can only shrink
              the valid set), letting the TB ring size itself to the
              batch pane spread and the data-vs-watermark lag without
              any device sync.
    """

    __slots__ = ("payload", "ts", "valid", "keys", "watermark", "_frontier",
                 "_size", "ts_max", "ts_min", "trace")

    def __init__(self, payload, ts, valid, keys=None, watermark: int = WM_NONE,
                 size: Optional[int] = None, frontier: Optional[int] = None,
                 ts_max: Optional[int] = None,
                 ts_min: Optional[int] = None,
                 trace: Optional[tuple] = None):
        self.payload = payload
        self.ts = ts
        self.valid = valid
        self.keys = keys
        self.watermark = watermark
        self._frontier = frontier
        self._size = size
        self.ts_max = ts_max
        self.ts_min = ts_min
        #: flight-recorder trace lane (monitoring/recorder.py):
        #: ``(trace_id, t_origin_usec)`` when this batch is the 1-in-N
        #: sampled one, else None.  Host metadata only — never transferred.
        self.trace = trace

    @property
    def frontier(self) -> int:
        """Newest known watermark at batch-content fix time; falls back to
        the propagated stamp.  Never below ``watermark``."""
        if self._frontier is None:
            return self.watermark
        return max(self._frontier, self.watermark)

    @property
    def size(self) -> int:
        """Number of valid items.  Lazily counted: reading it after a filter
        forces a device sync, so hot paths use :attr:`known_size` instead."""
        if self._size is None:
            self._size = int(self.valid.sum())
        return self._size

    @property
    def known_size(self) -> Optional[int]:
        return self._size

    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])

    def __len__(self) -> int:
        return self.size


def transfer_nbytes(batch: DeviceBatch) -> int:
    """Whole-batch transfer size (payload + ts + valid lanes): the ONE
    definition behind the H2D/D2H byte counters (stats_record.hpp parity)
    wherever no packed staging buffer exists to measure exactly — shared
    by the staging emitters, the TPU→host boundary, and columnar sinks so
    the two directions can never drift apart."""
    return sum(getattr(l, "nbytes", 0)
               for l in jax.tree.leaves(batch.payload)) \
        + getattr(batch.ts, "nbytes", 0) + getattr(batch.valid, "nbytes", 0)


# ---------------------------------------------------------------------------
# Host <-> device conversion (the reference's pinned-staging H2D/D2H path,
# forward_emitter_gpu.hpp:254-300 and Batch_GPU_t::transfer2CPU).
# ---------------------------------------------------------------------------

def _stack_records(items: Sequence[Any]):
    """Convert a list of per-tuple pytrees (scalars, tuples, dicts, ...) into
    one structure-of-arrays pytree of numpy arrays."""
    treedef = jax.tree.structure(items[0])
    leaves = [jax.tree.leaves(it) for it in items]
    cols = [np.asarray(col) for col in zip(*leaves)]
    return jax.tree.unflatten(treedef, cols)


def _pad_leading(arr: np.ndarray, capacity: int) -> np.ndarray:
    n = arr.shape[0]
    if n == capacity:
        return arr
    pad = [(0, capacity - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


#: cached unpack programs for packed staging, keyed by
#: (leaf treedef/dtypes, capacity) — one trace per batch shape
_UNPACK_CACHE: dict = {}

# 32-bit word packing: host↔device links are dominated by per-TRANSFER
# latency, not bandwidth (the reference stages one contiguous pinned array
# of batch_item_gpu_t for the same reason, forward_emitter_gpu.hpp:254-300),
# so all lanes of a batch ride ONE uint32 buffer.  Only 32-bit bitcasts are
# used on device — the TPU X64-rewrite pass implements no 64-bit bitcast —
# int64 lanes travel as arithmetic lo/hi word pairs; float64 lanes make a
# batch unpackable (TPU has no native f64 anyway: stage f32).  Packing,
# layout, and the host-buffer recycling pool live in windflow_tpu/staging.

_words = staging.lane_words
_packable_dtype = staging.packable_dtype


def unpack_body(dtypes, capacity: int, wire=None):
    """The raw (un-jitted) unpack closure behind :func:`_get_unpack`:
    ``b -> (payload_cols, ts, valid, n_valid)``.  Exposed separately so
    the megastep executor (windflow_tpu/megastep.py) can inline the
    SAME decode — wire decompression included — into its K-sweep scan
    body instead of paying one unpack dispatch per batch."""
    if wire is not None:
        from windflow_tpu.wire import build_wire_decode
        decode = build_wire_decode(wire, dtypes, capacity)

        def unpack_fn(b):
            cols = decode(b)
            n_valid = b[-1].astype(jnp.int32)
            return cols[:-1], cols[-1], \
                jnp.arange(capacity, dtype=jnp.int32) < n_valid, \
                n_valid
    else:
        def unpack_fn(b):
            cols, off = [], 0
            for dt in dtypes + ("int64",):
                d = np.dtype(dt)
                if d.itemsize == 8:
                    seg = b[off:off + 2 * capacity]
                    lo = seg[0::2].astype(jnp.int64)
                    hi = seg[1::2].astype(jnp.int64)
                    cols.append(((hi << 32) | lo).astype(d))
                    off += 2 * capacity
                else:
                    cols.append(jax.lax.bitcast_convert_type(
                        b[off:off + capacity], d))
                    off += capacity
            n_valid = b[-1].astype(jnp.int32)
            return cols[:-1], cols[-1], \
                jnp.arange(capacity, dtype=jnp.int32) < n_valid, n_valid
    return unpack_fn


def _get_unpack(treedef, dtypes, capacity: int, wire=None):
    """Cached device program re-typing one packed uint32 staging buffer
    into payload columns + ts lane + validity mask (derived on device from
    the trailing fill-count word — never transferred separately, and cached
    per capacity, not per fill level).  The extra scalar output is the
    pool's recycling GATE: it depends on the transferred buffer like every
    other output, but it is never handed to a consumer, so no downstream
    ``donate_argnums`` (ops/chained.py, windflow_tpu/fusion) can delete it
    out from under ``StagingPool.acquire``'s readiness sync.

    ``wire`` (a ``wire.WireFormat``) switches the program to the wire-
    compressed layout: the columnar decode (``wire.build_wire_decode``)
    is inlined AHEAD of the mask derivation inside this SAME program —
    decompression costs zero extra dispatches, and each distinct wire
    descriptor keys its own cached program (a fresh compile, never a
    re-trace of an existing one)."""
    key = (treedef, dtypes, capacity, wire)
    unpack = _UNPACK_CACHE.get(key)
    if unpack is None:
        unpack = wf_jit(unpack_body(dtypes, capacity, wire=wire),
                        op_name="staging.unpack")
        _UNPACK_CACHE[key] = unpack
    return unpack


def stage_packed(buf: np.ndarray, treedef, dtypes, capacity: int, n: int,
                 watermark: int = WM_NONE, device=None,
                 frontier: Optional[int] = None,
                 ts_max: Optional[int] = None, ts_min: Optional[int] = None,
                 pool=None, trace: Optional[tuple] = None,
                 wire=None, logical_nbytes: Optional[int] = None
                 ) -> DeviceBatch:
    """ONE host→device transfer of a packed staging buffer (built by
    ``staging.PackedBatchBuilder`` or the inline pack in ``_stage_soa``)
    into a DeviceBatch.  When ``pool`` is given, ``buf`` is recycled with
    the unpack output as its gate — the device owns the buffer until the
    unpack has executed, so reuse can never race the (asynchronous)
    transfer (staging.StagingPool).  ``wire`` marks ``buf`` as a wire-
    compressed buffer (windflow_tpu/wire.py): the matching columnar
    decode is inlined into the unpack program itself, and
    ``logical_nbytes`` keeps the byte accounting honest (wire bytes =
    the transfer, logical bytes = the decoded lanes)."""
    unpack = _get_unpack(treedef, dtypes, capacity, wire=wire)
    dbuf = jnp.asarray(buf) if device is None \
        else jax.device_put(buf, device)
    # device-plane accounting (monitoring/device_metrics): every fused
    # staging transfer credits the process-wide staged-byte gauge —
    # wire bytes as shipped, logical bytes as decoded
    staging.device_bytes.note(buf.nbytes, logical_nbytes)
    cols, ts, valid, gate = unpack(dbuf)
    if pool is not None:
        # gate on the unpack's private scalar output, NOT a lane the
        # consumer sees: a donated lane's deletion happens at the host's
        # (async) dispatch enqueue, which proves nothing about the H2D
        # DMA that is still reading `buf`
        pool.release(buf, gate=gate)
    return DeviceBatch(jax.tree.unflatten(treedef, cols), ts, valid,
                       watermark=watermark, size=n, frontier=frontier,
                       ts_max=ts_max, ts_min=ts_min, trace=trace)


def _stage_soa(soa, tss, n: int, capacity: int, watermark: int,
               device, frontier: Optional[int] = None,
               trace: Optional[tuple] = None) -> DeviceBatch:
    """Shared staging tail: pad an SoA numpy pytree + timestamps to
    ``capacity``, build the validity mask, optionally pin to a device.

    When every payload column is a 1-D packable lane (4-byte, or int64),
    all lanes plus timestamps ride ONE host→device transfer as a uint32
    buffer, re-typed on device by a cached program; the validity mask is
    derived on device from ``n``, never transferred."""
    # data-ts extrema of the real lanes: free host metadata for TB ring
    # sizing (DeviceBatch.ts_min/ts_max)
    _t = np.asarray(tss[:n])
    ts_max = int(np.max(_t)) if n else None
    ts_min = int(np.min(_t)) if n else None
    leaves, treedef = jax.tree.flatten(soa)
    if isinstance(device, jax.sharding.Sharding) and jax.process_count() > 1:
        # multi-host staging: `capacity` is the GLOBAL lane count; this
        # process contributes its local slice (capacity / process_count
        # lanes) and the global batch is assembled shard-locally — the
        # graph-level form of parallel/multihost.stage_local.  Every
        # process must stage batches in lockstep (same count, same order):
        # the sharded programs downstream are collective.
        nproc = jax.process_count()
        local_cap = capacity // nproc
        if n > local_cap:
            raise ValueError(
                f"local batch of {n} exceeds per-process capacity "
                f"{local_cap} (= {capacity}/{nproc})")

        def assemble(a):
            a = _pad_leading(np.ascontiguousarray(a), local_cap)
            return jax.make_array_from_process_local_data(
                device, a, (capacity,) + a.shape[1:])

        payload = jax.tree.map(assemble, soa)
        ts = assemble(np.asarray(tss, dtype=np.int64))
        valid = assemble(np.arange(local_cap) < n)
        # ts extrema deliberately NOT attached (ADVICE r5 medium): they
        # describe only this process's local slice of a globally sharded
        # batch, and attaching them would let windows/ffat_tpu
        # _regrow_for_span make DIFFERENT ring-growth decisions per
        # process, desynchronizing sharded state shapes.  The eviction-
        # cadence regrow (SPMD-consistent n_evicted sums) remains the
        # ring's growth path on multi-host meshes.
        out = DeviceBatch(payload, ts, valid, watermark=watermark,
                          size=None, frontier=frontier,
                          ts_max=None, ts_min=None, trace=trace)
        # device-plane accounting: this process's local shard share of the
        # assembled global batch (the packed path credits via stage_packed)
        staging.device_bytes.note(transfer_nbytes(out) // nproc)
        return out
    packable = (
        device is None or isinstance(device, jax.Device)
    ) and all(l.ndim == 1 and _packable_dtype(l.dtype) for l in leaves)
    if packable:
        dtypes = tuple(str(np.dtype(l.dtype)) for l in leaves)
        pool = staging.default_pool()
        # pooled buffer + streaming pack (staging.PackedBatchBuilder):
        # steady-state staging allocates no numpy buffers, and the final
        # word carries n, so the unpack program is cached per capacity,
        # not per fill level (no per-partial-batch recompiles, and no
        # extra scalar transfer)
        b = staging.PackedBatchBuilder(dtypes, capacity, pool=pool)
        b.append(leaves, np.asarray(tss, dtype=np.int64))
        return stage_packed(b.finish(), treedef, dtypes, capacity, n,
                            watermark=watermark, device=device,
                            frontier=frontier, ts_max=ts_max,
                            ts_min=ts_min, pool=pool, trace=trace)
    payload = jax.tree.map(
        lambda a: jnp.asarray(_pad_leading(np.ascontiguousarray(a),
                                           capacity)), soa)
    ts = jnp.asarray(_pad_leading(np.asarray(tss, dtype=np.int64), capacity),
                     dtype=TS_DTYPE)
    valid = jnp.asarray(np.arange(capacity) < n)
    if device is not None:
        payload = jax.device_put(payload, device)
        ts = jax.device_put(ts, device)
        valid = jax.device_put(valid, device)
    out = DeviceBatch(payload, ts, valid, watermark=watermark, size=n,
                      frontier=frontier, ts_max=ts_max, ts_min=ts_min,
                      trace=trace)
    # unpackable-lane fallback (per-lane transfers): still a staged batch
    # for the device-plane accounting stage_packed credits on the fused path
    staging.device_bytes.note(transfer_nbytes(out))
    return out


def host_to_device(batch: HostBatch, capacity: Optional[int] = None,
                   device=None, frontier: Optional[int] = None,
                   trace: Optional[tuple] = None) -> DeviceBatch:
    """Stage a HostBatch into device buffers, padding to ``capacity``."""
    n = len(batch)
    if n == 0:
        raise ValueError("cannot stage an empty batch")
    cap = capacity or n
    if n > cap:
        raise ValueError(f"batch of {n} items exceeds capacity {cap}")
    return _stage_soa(_stack_records(batch.items), batch.tss, n, cap,
                      batch.watermark, device, frontier,
                      trace=trace if trace is not None else batch.trace)


def columns_to_device(cols, tss, capacity: int, watermark: int = WM_NONE,
                      device=None, frontier: Optional[int] = None,
                      trace: Optional[tuple] = None) -> DeviceBatch:
    """Stage columnar (SoA numpy) data directly into a DeviceBatch — the
    zero-per-tuple-Python path used by bulk sources (windflow_tpu/io) and the
    columnar staging emitter.  ``cols`` is a dict of [n]-leading numpy
    arrays, ``tss`` an int64 [n] array; n must be <= capacity."""
    n = len(tss)
    if n == 0:
        raise ValueError("cannot stage an empty column batch")
    if n > capacity:
        raise ValueError(f"column batch of {n} exceeds capacity {capacity}")
    return _stage_soa(dict(cols), tss, n, capacity, watermark, device,
                      frontier, trace=trace)


#: cached pack programs for single-transfer egress, keyed by the payload's
#: (treedef, shape/dtype) signature
_EGRESS_PACK_CACHE: dict = {}


def device_to_columns(batch: DeviceBatch):
    """Transfer a DeviceBatch's valid lanes to host as SoA numpy columns —
    the egress twin of :func:`columns_to_device`: ONE device→host transfer
    for the whole batch and NO per-record Python object construction
    (VERDICT r2: the per-tuple dict build in ``device_to_host`` capped
    every TPU→Sink edge).  All 1-D lanes plus the timestamp and validity
    lanes are bitcast-packed into a single byte buffer on device (a cached
    program) and re-typed host-side with numpy views — per-transfer
    latency, not bandwidth, dominates host↔device links.  Returns
    ``(cols, tss)`` where ``cols`` mirrors the payload pytree with ``[n]``-
    leading numpy arrays and ``tss`` is an int64 ``[n]`` array.  Reference:
    the GPU→CPU boundary is also one bulk pinned D2H copy before any
    per-tuple work (``keyby_emitter_gpu.hpp:594-638``)."""
    r = device_to_columns_multi([batch])
    return r[0]


def _np_local(a):
    """Device→host view of an array that may span processes (multi-host
    run): a fully-addressable array transfers whole; otherwise this
    process reads ONLY its addressable shards — deduplicated by shard
    index (axis replication repeats content per device) and concatenated
    in index order.  Each host's sink thereby consumes the rows its own
    key shards produced (SURVEY §5.8: per-process sinks)."""
    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        seen = {}
        for s in a.addressable_shards:
            key = tuple((sl.start or 0, sl.stop) for sl in s.index)
            seen.setdefault(key, s.data)
        parts = [np.asarray(d) for _, d in sorted(seen.items())]
        return np.concatenate(parts, axis=0)
    return np.asarray(a)


def _egress_packable(batch: DeviceBatch):
    leaves, treedef = jax.tree.flatten(batch.payload)
    cap = batch.capacity
    # numpy-leaf batches (the megastep drain's zero-copy per-batch
    # slices) must take the host fallback: device-packing them would
    # round-trip already-host-resident lanes through HBM
    ok = all(getattr(l, "ndim", 0) == 1 and l.shape[0] == cap
             and (_packable_dtype(l.dtype) or l.dtype == jnp.bool_)
             and isinstance(l, jax.Array) and l.is_fully_addressable
             for l in leaves)
    return ok, leaves, treedef, cap


def _egress_pack(batch: DeviceBatch, leaves, treedef, cap):
    """Device program producing the batch's single uint32 egress buffer."""
    specs = tuple(str(np.dtype(l.dtype)) for l in leaves)
    key = (treedef, specs, cap)
    pack = _EGRESS_PACK_CACHE.get(key)
    if pack is None:
        def to_words(l):
            # only 32-bit device bitcasts (see packing note above):
            # 64-bit lanes leave as arithmetic lo/hi uint32 pairs
            if l.dtype == jnp.bool_:
                return [l.astype(jnp.uint32)]
            if np.dtype(l.dtype).itemsize == 8:
                v = l.astype(jnp.int64) if l.dtype != jnp.int64 else l
                lo = (v & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
                hi = ((v >> 32) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
                return [lo, hi]
            return [jax.lax.bitcast_convert_type(l, jnp.uint32)]

        def pack_fn(lvs, ts, vld):
            parts = []
            for l in lvs:
                parts.extend(to_words(l))
            parts.extend(to_words(ts))
            parts.append(vld.astype(jnp.uint32))
            return jnp.concatenate(parts)
        pack = wf_jit(pack_fn, op_name="staging.egress_pack")
        _EGRESS_PACK_CACHE[key] = pack
    return pack(leaves, batch.ts, batch.valid), specs


def _egress_unpack(raw, batch: DeviceBatch, treedef, specs, cap):
    def take(off, dt):
        d = np.dtype(dt)
        if d == np.bool_:
            return raw[off:off + cap].astype(np.bool_), off + cap
        if d.itemsize == 8:
            lo = raw[off:off + cap].astype(np.uint64)
            hi = raw[off + cap:off + 2 * cap].astype(np.uint64)
            return ((hi << np.uint64(32)) | lo).view(np.int64) \
                .astype(d, copy=False), off + 2 * cap
        return raw[off:off + cap].view(d), off + cap

    off = 0
    cols_flat = []
    for dt in specs:
        col, off = take(off, dt)
        cols_flat.append(col)
    tss, off = take(off, "int64")
    valid = raw[off:off + cap].astype(np.bool_)
    n = batch.known_size
    if n is not None and bool(valid[:n].all()):
        sel = slice(None, n)
    else:
        sel = np.nonzero(valid)[0]
    cols = jax.tree.unflatten(treedef, [c[sel] for c in cols_flat])
    return cols, tss[sel]


def device_to_columns_multi(batches):
    """Columnar egress for SEVERAL device batches in ONE device→host
    transfer: each batch's lanes are packed on device (cached program) and
    the packed buffers ride a single concatenated copy — per-transfer link
    latency is paid once per group instead of once per batch (the deferred
    columnar sink hands its whole queue here).  Returns a list of
    ``(cols, tss)`` in input order."""
    packed = []
    metas = []
    fallback = {}
    for i, b in enumerate(batches):
        ok, leaves, treedef, cap = _egress_packable(b)
        if ok:
            buf, specs = _egress_pack(b, leaves, treedef, cap)
            metas.append((i, b, treedef, specs, cap, buf.shape[0]))
            packed.append(buf)
        else:
            fallback[i] = _columns_fallback(b)
    out = [None] * len(batches)
    for i, v in fallback.items():
        out[i] = v
    if packed:
        raw_all = np.asarray(packed[0] if len(packed) == 1
                             else jnp.concatenate(packed))  # ONE transfer
        off = 0
        for i, b, treedef, specs, cap, nwords in metas:
            out[i] = _egress_unpack(raw_all[off:off + nwords], b, treedef,
                                    specs, cap)
            off += nwords
    return out


def _columns_fallback(batch: DeviceBatch):
    valid = _np_local(batch.valid)
    n = batch.known_size
    if n is not None and len(valid) == batch.capacity \
            and bool(valid[:n].all()):
        # staged batches carry prefix validity: slice, no gather
        cols = jax.tree.map(lambda a: _np_local(a)[:n], batch.payload)
        return cols, _np_local(batch.ts)[:n]
    idx = np.nonzero(valid)[0]
    cols = jax.tree.map(lambda a: _np_local(a)[idx], batch.payload)
    return cols, _np_local(batch.ts)[idx]


def device_to_host(batch: DeviceBatch) -> HostBatch:
    """Transfer a DeviceBatch back to host records (reference
    ``Batch_GPU_t::transfer2CPU``), dropping padding slots.

    The transfer itself is columnar — one bulk ``np.asarray`` per lane, like
    the reference's single pinned D2H copy — and record construction uses
    ``tolist()`` + ``dict(zip(...))`` on the common flat-dict payload shape
    rather than per-tuple pytree calls."""
    valid = _np_local(batch.valid)
    idx = np.nonzero(valid)[0]
    tss = _np_local(batch.ts)[idx].tolist()
    if isinstance(batch.payload, dict) and all(
            hasattr(a, "ndim") for a in batch.payload.values()):
        # flat dict of array lanes only: a nested pytree value (e.g. a
        # multi-leaf window aggregate) has no ndim and takes the generic
        # tree path below
        cols = {n: _np_local(a)[idx] for n, a in batch.payload.items()}
        if all(c.ndim == 1 for c in cols.values()):
            names = list(cols)
            items = [dict(zip(names, vals))
                     for vals in zip(*(cols[n].tolist() for n in names))]
            return HostBatch(items=items, tss=tss,
                             watermark=batch.watermark, trace=batch.trace)
    treedef = jax.tree.structure(batch.payload)
    cols = [_np_local(leaf)[idx] for leaf in jax.tree.leaves(batch.payload)]
    items = [jax.tree.unflatten(treedef, [c[i] for c in cols])
             for i in range(len(idx))]
    # Unwrap 0-d numpy scalars for ergonomic host-side records.
    items = [jax.tree.map(lambda v: v.item() if np.ndim(v) == 0 else v, it)
             for it in items]
    return HostBatch(items=items, tss=tss, watermark=batch.watermark,
                     trace=batch.trace)
