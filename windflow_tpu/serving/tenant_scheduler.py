"""Tenant scheduler (PR-20 stub): consume the tenancy advisor's plan.

The tenant ledger measures (monitoring/tenant_ledger.py), the tenancy
advisor plans (analysis/tenancy.py), and PR 20's scheduler will ACT —
the ledger→advisor→executor progression the reshard plane already
completed (shard_ledger → resharding → ReshardExecutor).  This module
pins the executor-facing half of that contract NOW so the advisor's
output shape is load-bearing before the executor exists:

* :meth:`TenantScheduler.ingest` accepts exactly what
  ``analysis.tenancy.plan(...)`` returns (``advisor: "tenancy/1"``),
  validates every action against :data:`ACTION_KINDS` and the fields
  each kind promises, and queues them per tenant.  A malformed plan is
  rejected loudly (``ValueError``) — PR 20 must not discover contract
  drift at apply time.
* :meth:`TenantScheduler.pending` / :meth:`TenantScheduler.section`
  expose the queue for stats/tests.
* :meth:`TenantScheduler.apply_next` is the PR-20 seam: today it pops
  the action, records it on a bounded timeline with ``applied: False``,
  and returns it — the real executor replaces the body, keeping the
  signature.  ``throttle_admission`` will reuse the reshard executor's
  admission machinery; ``drain_shards`` its move path; ``rescale_tenant``
  the rescale-on-restore path (docs/DURABILITY.md);
  ``rebalance_hot_tenant`` a placement change.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

#: the advisor revision this scheduler consumes (tenancy.plan "advisor")
PLAN_SCHEMA = "tenancy/1"

#: action kind -> the fields analysis.tenancy._actions promises for it
ACTION_KINDS = {
    "throttle_admission": ("factor",),
    "rescale_tenant": ("shed_bytes",),
    "drain_shards": ("op",),
    "rebalance_hot_tenant": ("latency_share",),
}

_TIMELINE_CAP = 64


class TenantScheduler:
    """Process-scoped consumer of tenancy plans (PR-20 executor seam)."""

    def __init__(self) -> None:
        self._queue: deque = deque()
        self.plans_ingested = 0
        self.actions_queued = 0
        self.rejected_plans = 0
        self.timeline: deque = deque(maxlen=_TIMELINE_CAP)

    # -- contract ------------------------------------------------------------
    def ingest(self, plan: dict) -> int:
        """Validate + queue one advisor plan; returns actions queued.
        Raises ``ValueError`` on contract drift so PR 20 cannot silently
        consume a plan shape the advisor no longer emits."""
        if not isinstance(plan, dict) \
                or plan.get("advisor") != PLAN_SCHEMA:
            self.rejected_plans += 1
            raise ValueError(
                f"not a {PLAN_SCHEMA} plan: advisor="
                f"{plan.get('advisor') if isinstance(plan, dict) else plan!r}")
        tenants = plan.get("tenants")
        if not isinstance(tenants, list):
            self.rejected_plans += 1
            raise ValueError("plan.tenants must be a list")
        queued = 0
        for row in tenants:
            tname = row.get("tenant")
            for act in row.get("actions") or []:
                kind = act.get("kind")
                if kind not in ACTION_KINDS:
                    self.rejected_plans += 1
                    raise ValueError(
                        f"tenant {tname!r}: unknown action kind {kind!r} "
                        f"(want one of {tuple(ACTION_KINDS)})")
                for field in ACTION_KINDS[kind]:
                    if field not in act:
                        self.rejected_plans += 1
                        raise ValueError(
                            f"tenant {tname!r}: {kind} action missing "
                            f"required field {field!r}")
                self._queue.append({"tenant": tname, **act})
                queued += 1
        self.plans_ingested += 1
        self.actions_queued += queued
        return queued

    # -- PR-20 seam ----------------------------------------------------------
    def apply_next(self) -> Optional[dict]:
        """Pop + record the next queued action.  PR-20 replaces this
        body with the real executors; until then every action lands on
        the timeline with ``applied: False`` so tests (and the eventual
        executor) see exactly what would have run."""
        if not self._queue:
            return None
        act = self._queue.popleft()
        entry = dict(act, applied=False)
        self.timeline.append(entry)
        return entry

    # -- introspection -------------------------------------------------------
    def pending(self) -> List[dict]:
        return list(self._queue)

    def section(self) -> dict:
        """JSON-able snapshot (future stats()["Tenant_scheduler"])."""
        return {
            "schema": PLAN_SCHEMA,
            "plans_ingested": self.plans_ingested,
            "rejected_plans": self.rejected_plans,
            "actions_queued": self.actions_queued,
            "pending": list(self._queue),
            "timeline": list(self.timeline),
        }


_default: Optional[TenantScheduler] = None


def default_scheduler() -> TenantScheduler:
    """Process singleton, mirroring tenant_ledger.default_ledger()."""
    global _default
    if _default is None:
        _default = TenantScheduler()
    return _default
