"""Serving plane: the reshard/failover executor.

The shard plane measures (monitoring/shard_ledger.py), the reshard
advisor plans (analysis/resharding.py), and this package ACTS: the
:class:`~windflow_tpu.serving.executor.ReshardExecutor` applies
``move_keys``/``split_hot_key`` plans to a LIVE graph — quiesce,
re-place the key→shard map (keyed state moving with the keys), resume,
with no process restart — and degrades admission at the sources when no
plan can help.  docs/OBSERVABILITY.md "Reshard executor".
"""

from windflow_tpu.serving.executor import ReshardExecutor
from windflow_tpu.serving.tenant_scheduler import TenantScheduler

__all__ = ["ReshardExecutor", "TenantScheduler"]
