"""Reshard/failover executor: apply the advisor's plans to a live graph.

PR 9's reshard advisor (analysis/resharding.py) emits ``move_keys`` /
``split_hot_key`` plans; PR 8's epoch protocol proves the graph can
quiesce to an aligned barrier with every operator's keyed state
host-visible.  This module is the missing verb: a reshard IS
"quiesce, re-place the key→shard map, resume" — the checkpoint
machinery minus the manifest.  Concretely, at executor-tick cadence
(``Config.reshard_check_sweeps`` driver sweeps — never per batch):

* **Triggers.**  A health-plane ``BACKPRESSURED``/``STALLED`` verdict on
  a keyed operator, or measured imbalance above
  ``Config.reshard_imbalance_threshold`` (the advisor's own
  actionability bound), sustained for ``reshard_trigger_ticks``.
* **move_keys.**  The graph quiesces (durability/checkpoint.quiesce —
  the same aligned barrier, so no record is in flight), the plan's
  key→shard overrides install on every keyed emitter feeding the
  operator (routing), the moved keys' STATE moves with them (host
  Reduce per-key dicts re-home; per-replica TB pane-ring rows re-home
  when the ring clocks agree; shared-table operators — dense/interned
  stateful, CB FFAT — need no state move at all: per-key rows are
  replica-independent), and the driver resumes.  No restart, no dropped
  or duplicated record: the barrier guarantees the moved key's tuples
  before the move were fully processed at the old shard and every tuple
  after it routes to the new one.
* **split_hot_key.**  Routing cannot balance a key hotter than a whole
  shard's fair share; the executor turns the split action into a
  PRE-AGGREGATING partial combine at the keyed staging boundary
  (parallel/emitters.KeyedDeviceStageEmitter.set_preagg): the hot key's
  tuples fold through the consumer's associative combiner before they
  ship, cutting its downstream load by the fold factor.  Applied only
  to consumers exposing an associative record combiner with a declared
  monoid (the WF405 contract class — ReduceTPU); per-batch partials
  coarsen, the final per-key aggregate is unchanged.
* **Admission control.**  When no plan can help (nothing actionable, or
  an applied plan did not recover), the executor degrades gracefully AT
  THE SOURCE: the per-sweep tick chunk scales down (halving to a 1/16
  floor) so inboxes stop growing, and recovers (doubling back to 1.0)
  once the graph holds OK.
* **Scale-down.**  Sustained OK for ``reshard_scale_down_ticks`` ticks
  (0 = record candidates only) drains the least-loaded shard's known
  keys onto its siblings through the same move path — the capacity-
  shrink half of elastic serving; the actual replica-count change is a
  rescale restore (docs/DURABILITY.md "rescale-on-restore").

Every action lands in ``stats()["Reshard"]`` (plans_applied,
keys_moved, quiesce_ms, recovery_ms, admission_factor, a bounded
timeline), the ``wf_reshard_*`` OpenMetrics families, and the
postmortem bundle's ``reshard.json`` (wf_doctor renders the timeline).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

from windflow_tpu.basic import current_time_usecs

#: per-operator executor states (stats()["Reshard"].ops[..].state)
E_OK = "OK"
E_TRIGGERED = "TRIGGERED"
E_RECOVERING = "RECOVERING"
E_DEGRADED = "DEGRADED"

#: admission-control floor: the source tick chunk never throttles below
#: this fraction — the graph keeps draining even fully degraded
_MIN_ADMISSION = 1.0 / 16.0


class _OpTrack:
    __slots__ = ("name", "state", "bad_ticks", "ok_ticks", "t_applied",
                 "last_action", "rounds")

    def __init__(self, name: str) -> None:
        self.name = name
        self.state = E_OK
        self.bad_ticks = 0
        self.ok_ticks = 0
        self.t_applied: Optional[float] = None
        self.last_action: Optional[str] = None
        #: plan applications in the current degradation episode — a
        #: reshard often takes several rounds (move the next-hottest
        #: keys off the still-hot shard) before admission control is
        #: the honest answer
        self.rounds = 0


class ReshardExecutor:
    """Graph-scoped executor (built by ``PipeGraph._build`` when
    ``Config.reshard_executor`` is on).  All work happens at tick
    cadence on the driver thread — ``on_sweep`` is one counter compare
    per sweep, and a tick that finds nothing bad reads two cached
    telemetry sections and returns."""

    def __init__(self, graph) -> None:
        self.graph = graph
        cfg = graph.config
        self.check_sweeps = max(1, int(cfg.reshard_check_sweeps))
        self.trigger_ticks = max(1, int(cfg.reshard_trigger_ticks))
        self.ok_ticks_needed = max(1, int(cfg.reshard_ok_ticks))
        self.threshold = float(cfg.reshard_imbalance_threshold)
        self.scale_down_ticks = max(0, int(cfg.reshard_scale_down_ticks))
        self._sweeps = 0
        # keyed targets: operators reached through override-capable
        # keyed emitters at parallelism > 1, plus split candidates
        # (monoid reduce consumers) at any parallelism
        from windflow_tpu.durability.checkpoint import keyed_emitters_into
        self._targets: Dict[str, dict] = {}
        for op in graph._operators:
            if op.key_extractor is None:
                continue
            ems = keyed_emitters_into(graph, op)
            if not ems:
                continue
            self._targets[op.name] = {"op": op, "emitters": ems}
        self._tracks = {name: _OpTrack(name) for name in self._targets}
        # counters (stats()["Reshard"] / wf_reshard_* / reshard.json)
        self.plans_applied = 0
        self.keys_moved = 0
        self.splits_applied = 0
        self.moves_skipped = 0
        self.admission_throttles = 0
        self.scale_down_events = 0
        self.last_quiesce_ms: Optional[float] = None
        self.quiesce_ms_total = 0.0
        self.last_recovery_ms: Optional[float] = None
        self.ticks = 0
        self._admission = 1.0
        self._all_ok_ticks = 0
        # per-op shard loads at the previous tick: the imbalance TRIGGER
        # judges the delta window (loads since last tick), because the
        # ledger's loads are cumulative — a successful move can never
        # repair the historical ratio, only the current one
        self._prev_loads: Dict[str, list] = {}
        self._last_delta: Dict[str, float] = {}
        self._last_window: Dict[str, list] = {}
        #: minimum delta-window tuples before the ratio means anything
        #: (idle graphs and end-of-stream must read as no-signal); the
        #: window ACCUMULATES across ticks until it is judgeable, so
        #: bursty per-shard flush cadences average out
        self._min_window = 256
        self.timeline: deque = deque(maxlen=max(
            8, int(getattr(cfg, "health_history", 64))))

    # -- sweep hook (the whole per-sweep cost) -------------------------------
    def on_sweep(self) -> None:
        self._sweeps += 1
        if self._sweeps % self.check_sweeps == 0:
            self.tick()

    # -- admission control ---------------------------------------------------
    def admit_chunk(self, chunk: int) -> int:
        """Scale the source tick chunk by the admission factor — the
        graceful-degradation valve ``PipeGraph._tick_chunk`` applies."""
        if self._admission >= 1.0:
            return chunk
        return max(1, int(chunk * self._admission))

    # -- the tick ------------------------------------------------------------
    def tick(self) -> None:
        """One executor evaluation: read health verdicts + the shard
        plan, advance each target's state machine, apply what fires."""
        self.ticks += 1
        verdicts = self._health_verdicts()
        pl = self._plan()
        by_op = {e["op"]: e for e in (pl.get("ops") or [])}
        all_ok = True
        for name, tr in self._tracks.items():
            entry = by_op.get(name) or {}
            bad = self._is_bad(name, entry, verdicts)
            self._advance(tr, bad, entry)
            if tr.state != E_OK or self._admission < 1.0:
                all_ok = False
        if all_ok:
            self._all_ok_ticks += 1
            if self.scale_down_ticks \
                    and self._all_ok_ticks >= self.scale_down_ticks:
                self._all_ok_ticks = 0
                self._scale_down(by_op)
        else:
            self._all_ok_ticks = 0

    def _is_bad(self, name: str, entry: dict,
                verdicts: dict) -> Optional[bool]:
        """Tri-state verdict: True (degraded), False (healthy), None
        (no information this tick — a delta window too small to judge;
        the state machine holds position instead of flapping)."""
        v = (verdicts.get(name) or {}).get("state")
        if v in ("BACKPRESSURED", "STALLED"):
            return True
        r = self._delta_imbalance(name, entry.get("loads") or [])
        if r is None:
            return None
        return r > self.threshold

    def _delta_imbalance(self, name: str, loads: list) -> Optional[float]:
        """Imbalance ratio of the CURRENT window: per-shard load growth
        since the previous tick.  The ledger's loads are cumulative —
        judging them directly would latch any historical skew forever;
        the delta is what an applied plan can actually repair."""
        prev = self._prev_loads.get(name)
        if prev is None or len(prev) != len(loads) or len(loads) < 2:
            self._prev_loads[name] = list(loads)
            return None
        delta = [max(0, b - a) for a, b in zip(prev, loads)]
        total = sum(delta)
        if total < self._min_window:
            # window too small to judge: keep the origin so the next
            # tick's window includes this one (no signal is discarded)
            return None
        self._prev_loads[name] = list(loads)
        self._last_window[name] = delta
        r = max(delta) / (total / len(delta))
        self._last_delta[name] = round(r, 4)
        return r

    def _advance(self, tr: _OpTrack, bad: Optional[bool],
                 entry: dict) -> None:
        if bad is None:
            return      # no signal this tick: hold position
        if tr.state == E_OK:
            if bad:
                tr.state = E_TRIGGERED
                tr.bad_ticks = 1
                self._note(tr.name, "triggered",
                           f"window imbalance="
                           f"{self._last_delta.get(tr.name)} "
                           f"(cumulative "
                           f"{entry.get('imbalance_ratio')})")
            return
        if tr.state == E_TRIGGERED:
            if not bad:
                # symmetric hysteresis: one balanced window must not
                # reset a building trigger — bursty per-shard flush
                # cadences make single-window ratios noisy
                tr.ok_ticks += 1
                if tr.ok_ticks >= self.ok_ticks_needed:
                    tr.state = E_OK
                    tr.bad_ticks = tr.ok_ticks = 0
                return
            tr.ok_ticks = 0
            tr.bad_ticks += 1
            if tr.bad_ticks >= self.trigger_ticks:
                self._fire(tr, entry)
            return
        if tr.state == E_RECOVERING:
            if not bad:
                if tr.ok_ticks == 0 and tr.t_applied is not None:
                    self.last_recovery_ms = round(
                        (time.perf_counter() - tr.t_applied) * 1e3, 3)
                tr.ok_ticks += 1
                if tr.ok_ticks >= self.ok_ticks_needed:
                    tr.state = E_OK
                    tr.bad_ticks = tr.ok_ticks = tr.rounds = 0
                    self._note(tr.name, "recovered",
                               f"after {tr.last_action}, "
                               f"{self.last_recovery_ms}ms to first OK")
                return
            tr.ok_ticks = 0
            tr.bad_ticks += 1
            if tr.bad_ticks >= 2 * self.trigger_ticks:
                if tr.rounds < 3:
                    # still degraded after the move: re-enter the
                    # trigger path — the advisor plans the NEXT move
                    # round (the next-hottest keys) before admission
                    # control becomes the honest answer
                    tr.state = E_TRIGGERED
                    tr.bad_ticks = self.trigger_ticks
                    return
                self._degrade(tr)
            return
        if tr.state == E_DEGRADED:
            if bad:
                self._throttle(tr)
                return
            tr.ok_ticks += 1
            if tr.ok_ticks >= self.ok_ticks_needed:
                tr.ok_ticks = 0
                self._admission = min(1.0, self._admission * 2.0)
                self._note(tr.name, "admission",
                           f"recovering to {self._admission:.3f}")
                if self._admission >= 1.0:
                    tr.state = E_OK
                    tr.bad_ticks = 0

    def _fire(self, tr: _OpTrack, entry: dict) -> None:
        """A trigger confirmed: apply the best available action."""
        actions = entry.get("actions") or []
        if not actions and entry.get("loads"):
            # the delta-window trigger can fire while the CUMULATIVE
            # ratio still looks balanced (a fresh skew on a long
            # history — the Zipf-shift case): synthesize the plan from
            # the WINDOW loads, with the hot-key estimates scaled to
            # the window so the greedy placement arithmetic stays in
            # one unit
            try:
                from windflow_tpu.analysis.resharding import \
                    rebalance_actions
                row = dict(entry)
                win = self._last_window.get(tr.name)
                if win and sum(win) > 0:
                    scale = sum(win) / max(1, sum(entry["loads"]))
                    row["loads"] = win
                    row["hot_keys"] = [
                        dict(h, est_tuples=max(1, int(
                            h.get("est_tuples", 0) * scale)))
                        for h in (entry.get("hot_keys") or [])]
                actions = rebalance_actions(row, self.threshold)
            except Exception:  # lint: broad-except-ok (plan synthesis
                # over telemetry rows — a failure degrades to the
                # admission path, never the pipeline)
                actions = []
        moves = [a for a in actions if a.get("kind") == "move_keys"]
        splits = [a for a in actions if a.get("kind") == "split_hot_key"]
        if moves and self._apply_moves(tr, moves[0]):
            return
        if splits and self._apply_split(tr, splits):
            return
        self._degrade(tr)

    def _degrade(self, tr: _OpTrack) -> None:
        tr.state = E_DEGRADED
        tr.bad_ticks = tr.ok_ticks = 0
        self._throttle(tr)

    def _throttle(self, tr: _OpTrack) -> None:
        if self._admission > _MIN_ADMISSION:
            self._admission = max(_MIN_ADMISSION, self._admission / 2.0)
            self.admission_throttles += 1
            self._note(tr.name, "admission",
                       f"no plan helps — throttled to "
                       f"{self._admission:.3f}")

    # -- actions -------------------------------------------------------------
    def _apply_moves(self, tr: _OpTrack, action: dict) -> bool:
        """move_keys: quiesce → re-place → move state → resume."""
        target = self._targets[tr.name]
        op = target["op"]
        moves = [m for m in (action.get("moves") or [])
                 if isinstance(m.get("to_shard"), int)
                 and 0 <= m["to_shard"] < op.parallelism]
        if not moves:
            return False
        from windflow_tpu.durability.checkpoint import quiesce
        t0 = time.perf_counter()
        quiesce(self.graph)
        moved = self._move_state(op, moves)
        # routing: merge the new moves over any earlier override
        for em in target["emitters"]:
            cur = dict(getattr(em, "_override", None) or {})
            cur.update({m["key"]: m["to_shard"] for m in moves})
            em.set_override(cur)
            sk = getattr(em, "_sketch", None)
            if sk is not None:
                # keep the ledger's derived-placement attribution honest
                try:
                    sk.override = dict(cur)
                except Exception:  # lint: broad-except-ok (telemetry
                    # attribution only — an exotic sketch must never
                    # fail the reshard itself)
                    pass
        ms = round((time.perf_counter() - t0) * 1e3, 3)
        self.last_quiesce_ms = ms
        self.quiesce_ms_total += ms
        self.plans_applied += 1
        self.keys_moved += len(moves)
        tr.state = E_RECOVERING
        tr.bad_ticks = tr.ok_ticks = 0
        tr.rounds += 1
        tr.t_applied = time.perf_counter()
        tr.last_action = "move_keys"
        self._note(tr.name, "move_keys",
                   f"{len(moves)} key(s) re-placed, {moved} state "
                   f"row(s) moved, quiesce {ms}ms")
        return True

    def _apply_split(self, tr: _OpTrack, splits: list) -> bool:
        """split_hot_key → pre-aggregating partial combine (only for
        consumers with an associative combiner and a declared monoid —
        the contract class where replacing m tuples by their fold is
        provably output-preserving)."""
        target = self._targets[tr.name]
        op = target["op"]
        comb = getattr(op, "comb", None)
        if comb is None or getattr(op, "monoid", None) is None:
            return False
        ems = [em for em in target["emitters"]
               if hasattr(em, "set_preagg")]
        if not ems:
            return False
        keys = [s["key"] for s in splits if s.get("key") is not None]
        if not keys:
            return False
        for em in ems:
            cur = set()
            pa = getattr(em, "_preagg", None)
            if pa:
                cur = set(pa["keys"])
            em.set_preagg(cur | set(keys), comb)
        self.splits_applied += 1
        self.plans_applied += 1
        tr.state = E_RECOVERING
        tr.bad_ticks = tr.ok_ticks = 0
        tr.rounds += 1
        tr.t_applied = time.perf_counter()
        tr.last_action = "split_hot_key"
        self._note(tr.name, "split_hot_key",
                   f"pre-aggregating {len(keys)} hot key(s) at the "
                   "staging boundary")
        return True

    def _scale_down(self, by_op: dict) -> None:
        """Sustained OK: drain the least-loaded shard's KNOWN keys onto
        its siblings (the ledger only knows the hot-key table; an
        honest scale-down reports what it could not find)."""
        for name, target in self._targets.items():
            op = target["op"]
            if op.parallelism < 2:
                continue
            entry = by_op.get(name) or {}
            loads = entry.get("loads") or []
            hot = entry.get("hot_keys") or []
            if len(loads) < 2:
                continue
            victim = min(range(len(loads)), key=lambda i: loads[i])
            keys_on = [h for h in hot if h.get("shard") == victim
                       and h.get("key") is not None]
            self.scale_down_events += 1
            if not keys_on:
                self._note(name, "scale_down",
                           f"shard {victim} is the drain candidate "
                           "(no known keys to move — rescale-restore "
                           "onto fewer shards to realize it)")
                continue
            others = [i for i in range(len(loads)) if i != victim]
            moves = [{"key": h["key"],
                      "to_shard": others[i % len(others)],
                      "from_shard": victim,
                      "est_tuples": h.get("est_tuples", 0)}
                     for i, h in enumerate(keys_on)]
            tr = self._tracks[name]
            self._apply_moves(tr, {"moves": moves})
            self._note(name, "scale_down",
                       f"drained {len(moves)} known key(s) off shard "
                       f"{victim}")
            return      # one consolidation per sustained-OK window

    # -- keyed state movement ------------------------------------------------
    def _move_state(self, op, moves: list) -> int:
        """Move the keyed state rows/entries of ``moves`` to their new
        shards.  Shared-table operators need nothing (every replica
        reads the same table); host Reduce re-homes dict entries;
        per-replica TB FFAT re-homes pane-ring rows when the ring
        clocks agree (skipped and counted otherwise — the keys still
        re-route, and the advisor re-plans if the imbalance returns)."""
        from windflow_tpu.ops.reduce_op import Reduce
        if isinstance(op, Reduce):
            moved = 0
            reps = op.replicas
            for m in moves:
                key, dst = m["key"], m["to_shard"]
                for r in reps:
                    if r.index != dst and key in r._states:
                        reps[dst]._states[key] = r._states.pop(key)
                        moved += 1
                        break
            return moved
        from windflow_tpu.windows.ffat_tpu import FfatWindowsTPU
        if isinstance(op, FfatWindowsTPU) and op._per_replica_state:
            return self._move_ffat_rows(op, moves)
        return 0    # shared state table: routing move is the whole move

    def _move_ffat_rows(self, op, moves: list) -> int:
        import jax.numpy as jnp
        import numpy as np
        comp = getattr(op, "_compactor", None)
        moved = 0
        from windflow_tpu.basic import int32_key
        for m in moves:
            try:
                k32 = int32_key(m["key"])
            except (TypeError, ValueError):
                self.moves_skipped += 1
                continue
            row = comp.slot_of(k32) if comp is not None else k32
            dst = m["to_shard"]
            src_i = m.get("from_shard")
            if row is None or not (0 <= row < op.max_keys) \
                    or src_i not in op._states \
                    or dst not in op._states:
                # a destination replica that never stepped has no state
                # to merge into — the re-route alone is still safe (its
                # first batch initializes a ring and the key's windows
                # restart from the barrier), but we refuse to move the
                # rows and say so
                self.moves_skipped += 1
                self._note(op.name, "move_skipped",
                           f"key {m['key']}: no ring state at shard "
                           f"{src_i}→{dst} (or no slot)")
                continue
            src, dstst = op._states[src_i], op._states[dst]
            if int(np.asarray(src["base"])) \
                    != int(np.asarray(dstst["base"])) \
                    or int(np.asarray(src["win_next"])) \
                    != int(np.asarray(dstst["win_next"])):
                self.moves_skipped += 1
                self._note(op.name, "move_skipped",
                           f"key {m['key']}: ring clocks disagree "
                           f"between shards {src_i} and {dst}")
                continue
            import jax
            for name in ("cells", "cell_valid", "horizon"):
                s_v, d_v = src[name], dstst[name]
                if name == "cells":
                    dstst[name] = jax.tree.map(
                        lambda d, s: d.at[row].set(s[row]), d_v, s_v)
                    src[name] = jax.tree.map(
                        lambda s: s.at[row].set(jnp.zeros_like(s[row])),
                        s_v)
                elif name == "cell_valid":
                    dstst[name] = d_v.at[row].set(s_v[row])
                    src[name] = s_v.at[row].set(False)
                else:   # horizon: per-key overflow taint travels along
                    dstst[name] = d_v.at[row].set(s_v[row])
                    src[name] = s_v.at[row].set(
                        jnp.int64(-(1 << 60)))
            moved += 1
        return moved

    # -- reporting -----------------------------------------------------------
    def _health_verdicts(self) -> dict:
        h = self.graph._health
        if h is None:
            return {}
        try:
            return h.sample()
        except Exception:  # lint: broad-except-ok (telemetry read — a
            # watchdog bug degrades the trigger to imbalance-only, it
            # must never take the executor or the pipeline down)
            return {}

    def _plan(self) -> dict:
        led = self.graph._shard
        if led is None:
            return {"ops": []}
        try:
            from windflow_tpu.analysis.resharding import plan
            return plan(led.section(), graph_name=self.graph.name,
                        threshold=self.threshold)
        except Exception:  # lint: broad-except-ok (planning reads the
            # shard ledger's merged sketches — telemetry; a failure
            # skips this tick's actions, never the pipeline)
            return {"ops": []}

    def _note(self, op: str, event: str, detail: str) -> None:
        self.timeline.append({"t_usec": current_time_usecs(),
                              "op": op, "event": event,
                              "detail": detail})

    def preagg_folds(self) -> int:
        total = 0
        for t in self._targets.values():
            for em in t["emitters"]:
                total += getattr(em, "preagg_folds", 0)
        return total

    def section(self) -> dict:
        """stats()["Reshard"] / OpenMetrics / postmortem payload."""
        return {
            "enabled": True,
            "ticks": self.ticks,
            "plans_applied": self.plans_applied,
            "keys_moved": self.keys_moved,
            "splits_applied": self.splits_applied,
            "moves_skipped": self.moves_skipped,
            "preagg_folds": self.preagg_folds(),
            "admission_factor": self._admission,
            "admission_throttles": self.admission_throttles,
            "scale_down_events": self.scale_down_events,
            "quiesce_ms": self.last_quiesce_ms,
            "quiesce_ms_total": round(self.quiesce_ms_total, 3),
            "recovery_ms": self.last_recovery_ms,
            "ops": {name: {"state": tr.state,
                           "last_action": tr.last_action,
                           "window_imbalance":
                               self._last_delta.get(name)}
                    for name, tr in self._tracks.items()},
            "timeline": list(self.timeline),
        }
