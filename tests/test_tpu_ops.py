"""TPU operator pipelines (reference ``tests/graph_tests_gpu``): device ops
mixed with host ops in one graph, validated with the same metamorphic oracle.
On the test backend these compile to CPU-XLA; the programs are identical to
what runs on a TPU chip."""

import random

import jax.numpy as jnp
import pytest

import windflow_tpu as wf


def stream(n_keys, length):
    return [{"key": i % n_keys, "value": float(i)} for i in range(length)]


class Acc:
    def __init__(self):
        self.total = 0.0
        self.count = 0

    def __call__(self, item):
        if item is not None:
            self.total += float(item["value"])
            self.count += 1


def run_tpu_linear(par, batch, length=1000, n_keys=7):
    acc = Acc()
    src = (wf.Source_Builder(lambda: iter(stream(n_keys, length)))
           .withOutputBatchSize(batch).build())
    m = (wf.MapTPU_Builder(
            lambda t: {"key": t["key"], "value": t["value"] * 3.0})
         .withParallelism(par[0]).build())
    f = (wf.FilterTPU_Builder(lambda t: t["value"] % 2.0 == 0.0)
         .withParallelism(par[1]).build())
    snk = wf.Sink_Builder(acc).withParallelism(par[2]).build()
    g = wf.PipeGraph("tpu_linear", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(m).add(f).add_sink(snk)
    g.run()
    return acc


def test_tpu_map_filter_metamorphic():
    rnd = random.Random(11)
    reference = None
    for run in range(4):
        par = [rnd.randint(1, 3) for _ in range(3)]
        batch = rnd.choice([16, 64, 128])
        acc = run_tpu_linear(par, batch)
        if reference is None:
            reference = (acc.total, acc.count)
        else:
            assert (acc.total, acc.count) == reference, \
                f"run {run} diverged par={par} batch={batch}"
    expected = sum(v * 3.0 for v in map(float, range(1000))
                   if (v * 3.0) % 2.0 == 0.0)
    assert reference == (expected, 500)


def test_tpu_chain_fuses_to_one_program():
    """chain() on TPU ops composes one XLA program (reference chaining is
    thread fusion, multipipe.hpp:553-569)."""
    acc = Acc()
    src = (wf.Source_Builder(lambda: iter(stream(3, 300)))
           .withOutputBatchSize(32).build())
    m1 = wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "value": t["value"] + 1.0}).build()
    m2 = wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "value": t["value"] * 2.0}).build()
    f1 = wf.FilterTPU_Builder(lambda t: t["value"] > 100.0).build()
    snk = wf.Sink_Builder(acc).build()
    g = wf.PipeGraph("tpu_chain", wf.ExecutionMode.DEFAULT)
    mp = g.add_source(src)
    mp.chain(m1)
    mp.chain(m2)
    mp.chain(f1)
    mp.add_sink(snk)
    # the three TPU ops fused into one operator stage
    assert len(mp.operators) == 3
    g.run()
    expected = [(v + 1) * 2 for v in range(300) if (v + 1) * 2 > 100]
    assert acc.count == len(expected)
    assert acc.total == sum(expected)


def test_tpu_keyed_reduce():
    """Keyed ReduceTPU shrinks each batch to one combined record per distinct
    key (reference Reduce_GPU reduce_by_key semantics)."""
    per_key = {}

    def sink_fn(item):
        if item is not None:
            per_key[item["key"]] = per_key.get(item["key"], 0.0) + item["value"]

    length, n_keys, batch = 640, 5, 64
    src = (wf.Source_Builder(lambda: iter(stream(n_keys, length)))
           .withOutputBatchSize(batch).build())
    red = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": a["key"], "value": a["value"] + b["value"]})
           .withKeyBy(lambda t: t["key"]).build())
    snk = wf.Sink_Builder(sink_fn).build()
    g = wf.PipeGraph("tpu_reduce", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(red).add_sink(snk)
    g.run()
    expected = {}
    for t in stream(n_keys, length):
        expected[t["key"]] = expected.get(t["key"], 0.0) + t["value"]
    assert per_key == expected


def test_tpu_rejects_non_default_mode():
    src = wf.Source_Builder(lambda: iter(stream(2, 10))) \
        .withOutputBatchSize(4).build()
    m = wf.MapTPU_Builder(lambda t: t).build()
    snk = wf.Sink_Builder(lambda t: None).build()
    g = wf.PipeGraph("bad", wf.ExecutionMode.DETERMINISTIC)
    g.add_source(src).add(m).add_sink(snk)
    with pytest.raises(wf.WindFlowError):
        g.run()


def test_tpu_requires_batching_upstream():
    src = wf.Source_Builder(lambda: iter(stream(2, 10))).build()  # no batching
    m = wf.MapTPU_Builder(lambda t: t).build()
    g = wf.PipeGraph("bad2", wf.ExecutionMode.DEFAULT)
    with pytest.raises(wf.WindFlowError):
        g.add_source(src).add(m)


def test_reduce_tpu_combiner_structure_contract():
    """A combiner that drops a record field raises a clear contract error
    (not an opaque pytree mismatch from inside the scan)."""
    src = (wf.Source_Builder(
            lambda: iter({"key": i % 4, "value": i, "extra": 1.0}
                         for i in range(64)))
           .withOutputBatchSize(32).build())
    red = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": a["key"],
                          "value": a["value"] + b["value"]})  # drops extra
           .withKeyBy(lambda t: t["key"]).build())
    snk = wf.Sink_Builder(lambda r: None).build()
    g = wf.PipeGraph("contract", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(red).add_sink(snk)
    with pytest.raises(wf.WindFlowError, match="same record structure"):
        g.run()


def test_reduce_tpu_combiner_leaf_contract():
    """Same treedef but a leaf whose dtype (or shape) drifts also raises
    the clear contract error — structure alone is not enough (the scan
    would fail with the same opaque mismatch)."""
    src = (wf.Source_Builder(
            lambda: iter({"key": i % 4, "value": float(i)}
                         for i in range(64)))
           .withOutputBatchSize(32).build())
    import jax.numpy as jnp
    red = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": a["key"],
                          "value": jnp.stack([a["value"], b["value"]])})
           .withKeyBy(lambda t: t["key"]).build())
    snk = wf.Sink_Builder(lambda r: None).build()
    g = wf.PipeGraph("leaf_contract", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(red).add_sink(snk)
    with pytest.raises(wf.WindFlowError, match="shape"):
        g.run()


def test_device_to_host_nested_pytree_payload():
    """Egress of a batch whose payload holds a NESTED pytree lane (e.g. a
    multi-leaf window aggregate like {"value": {"hi": ..., "lo": ...}}):
    the columnar flat-dict fast path must defer to the generic tree path
    instead of indexing the sub-dict (r5 regression, found by the
    market_ticker model)."""
    import jax.numpy as jnp
    from windflow_tpu.batch import DeviceBatch, device_to_host

    payload = {"key": jnp.arange(4, dtype=jnp.int32),
               "value": {"hi": jnp.asarray([1., 2., 3., 4.]),
                         "lo": jnp.asarray([-1., -2., -3., -4.])}}
    b = DeviceBatch(payload=payload,
                    ts=jnp.asarray([10, 20, 30, 40], jnp.int64),
                    valid=jnp.asarray([True, False, True, True]))
    hb = device_to_host(b)
    assert [it["key"] for it in hb.items] == [0, 2, 3]
    assert [it["value"]["hi"] for it in hb.items] == [1., 3., 4.]
    assert [it["value"]["lo"] for it in hb.items] == [-1., -3., -4.]
    assert hb.tss == [10, 30, 40]
