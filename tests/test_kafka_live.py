"""LIVE-broker Kafka e2e (VERDICT r4 item 8): the real-client adapters
(`kafka/client.py` ConfluentConsumer/ConfluentProducer) against an actual
broker — the four semantics the in-process fake cannot prove
(kafka/client.py "VALIDATION STATUS") get their first real exercise here.

Skips cleanly unless BOTH hold:
* ``confluent_kafka`` is importable (not in the zero-egress build image;
  installed in ``dockerimages/Dockerfile_cpu``);
* a broker answers at ``KAFKA_BOOTSTRAP`` (default ``localhost:9092``)
  within 5 s.

Run via the CPU docker image (which starts a single-node KRaft broker
before the suite) or against any reachable cluster:

    KAFKA_BOOTSTRAP=host:9092 python -m pytest tests/test_kafka_live.py
"""

import os
import time
import uuid

import pytest

import windflow_tpu as wf
from windflow_tpu.kafka import (KafkaSink_Builder, KafkaSinkMessage,
                                KafkaSource_Builder)

BOOTSTRAP = os.environ.get("KAFKA_BOOTSTRAP", "localhost:9092")
IDLE_USEC = 8_000_000          # 8 s of real-broker silence = end of stream


def _broker_available():
    if "KAFKA_BOOTSTRAP" not in os.environ:
        # no explicit opt-in: skip WITHOUT probing, so hosts that happen
        # to have confluent_kafka installed don't pay a 5 s dead-connect
        # stall on every collection of the normal suite
        return False, "set KAFKA_BOOTSTRAP to enable live-broker tests"
    try:
        from confluent_kafka.admin import AdminClient
    except ImportError:
        return False, "confluent_kafka not installed"
    try:
        admin = AdminClient({"bootstrap.servers": BOOTSTRAP,
                             "socket.timeout.ms": 4000})
        md = admin.list_topics(timeout=5)
        return True, f"broker {md.orig_broker_name}"
    except Exception as e:
        return False, f"no broker at {BOOTSTRAP}: {e}"


_OK, _WHY = _broker_available()
pytestmark = pytest.mark.skipif(not _OK, reason=_WHY)


def _fresh_topic(partitions: int) -> str:
    from confluent_kafka.admin import AdminClient, NewTopic
    name = f"wf-live-{uuid.uuid4().hex[:12]}"
    admin = AdminClient({"bootstrap.servers": BOOTSTRAP})
    fs = admin.create_topics([NewTopic(name, num_partitions=partitions,
                                       replication_factor=1)])
    for f in fs.values():
        f.result(timeout=15)
    time.sleep(0.5)            # let metadata propagate to the one broker
    return name


def _consume_all(topic: str, group: str, parallelism: int = 1):
    """Drain ``topic`` through a KafkaSource graph until the broker stays
    silent for IDLE_USEC; returns the int payloads seen."""
    got = []

    def deser(msg, shipper):
        if msg is None:
            return False           # idle: end the stream (reference EOS)
        shipper.push({"v": int(msg.value.decode())})
        return True

    src = (KafkaSource_Builder(deser).withBrokers(BOOTSTRAP)
           .withTopics(topic).withGroupID(group)
           .withIdleness(IDLE_USEC)
           .withParallelism(parallelism)
           .withOutputBatchSize(32).build())
    g = wf.PipeGraph(f"live_consume_{group}", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add_sink(
        wf.Sink_Builder(lambda t: got.append(t["v"])
                        if t is not None else None).build())
    g.run()
    return got


def test_live_sink_then_source_roundtrip():
    """Producer graph → real broker → consumer graph: every record
    arrives exactly once per group across 2 real partitions (real
    rebalance callbacks, auto-commit, the librdkafka poll loop —
    unverified items 1/2/4 of kafka/client.py)."""
    topic = _fresh_topic(partitions=2)
    n = 400

    def gen():
        for i in range(n):
            yield {"k": i % 7, "v": i}

    def ser(item):
        return KafkaSinkMessage(topic=topic,
                                payload=str(item["v"]).encode(),
                                key=str(item["k"]).encode())

    snk = KafkaSink_Builder(ser).withBrokers(BOOTSTRAP).build()
    g1 = wf.PipeGraph("live_producer", wf.ExecutionMode.DEFAULT)
    g1.add_source(wf.Source_Builder(gen).withOutputBatchSize(64).build()) \
      .add_sink(snk)
    g1.run()

    got = _consume_all(topic, f"wf-live-{uuid.uuid4().hex[:8]}")
    assert sorted(got) == list(range(n)), (len(got), len(set(got)))


def test_live_two_replicas_cover_partitions():
    """Two source replicas in one real consumer group must split the
    topic's partitions and together consume everything (real group
    coordinator + cooperative-sticky assignment — unverified item 1)."""
    topic = _fresh_topic(partitions=2)
    n = 200

    from confluent_kafka import Producer
    p = Producer({"bootstrap.servers": BOOTSTRAP})
    for i in range(n):
        # explicit partition: key hashing could land both key streams on
        # one partition and leave the second replica unexercised
        p.produce(topic, value=str(i).encode(), partition=i % 2)
    p.flush(15)

    got = _consume_all(topic, f"wf-live-{uuid.uuid4().hex[:8]}",
                       parallelism=2)
    # COVERAGE assertion, deliberately not exactly-once: the adapter is
    # at-least-once (auto-commit; a cooperative rebalance while the
    # second replica joins may re-deliver an uncommitted tail —
    # kafka/client.py VALIDATION STATUS item 2)
    assert set(got) == set(range(n)), (len(got), len(set(got)))


def test_live_offset_resume_after_commit():
    """A second run of the SAME group resumes past committed offsets
    (real offset persistence across consumer lifetimes — unverified
    item 2): it must see only the records produced after the first
    run."""
    topic = _fresh_topic(partitions=1)
    group = f"wf-live-{uuid.uuid4().hex[:8]}"

    from confluent_kafka import Producer
    p = Producer({"bootstrap.servers": BOOTSTRAP})
    for i in range(50):
        p.produce(topic, value=str(i).encode())
    p.flush(15)

    first = _consume_all(topic, group)
    assert sorted(first) == list(range(50))
    for i in range(50, 80):
        p.produce(topic, value=str(i).encode())
    p.flush(15)
    time.sleep(1)       # let the committed offsets land broker-side
    second = _consume_all(topic, group)
    assert sorted(second) == list(range(50, 80)), second[:10]
