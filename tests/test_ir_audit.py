"""wfir contracts (docs/ANALYSIS.md "wfir"): golden StableHLO substring
fixtures pin every WF90x detector against jaxlib text drift (one seeded
violation + one clean twin per code), real lowerings prove the
donation/callback markers on the jax this repo actually runs, the WF901
aligned/unaligned mesh reduce twin from the acceptance contract, the
preflight/stats/postmortem wiring, the wf_ir CLI round trip, the
zero-extra-compile pin (the audit parses the compile watcher's existing
first-compile lowering — registry counters must not move), the WF905
static/runtime donation-miss cross-validation, the registry
capture-failure one-time warning, and the kill-switch off-path budget."""

import dataclasses
import importlib.util
import json
import os
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu.analysis import ir_audit
from windflow_tpu.basic import default_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CAP = 256
N = 8 * CAP


def _spec():
    return {"key": np.int32(0), "v": np.float32(0.0)}


def _source(name="ira_src", n=N, cap=CAP):
    return (wf.Source_Builder(
        lambda: iter({"key": np.int32(i % 8), "v": np.float32(i)}
                     for i in range(n)))
        .withName(name).withOutputBatchSize(cap)
        .withRecordSpec(_spec()).build())


def _map_graph(app, map_name, src_name):
    m = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] * 2.0})
         .withName(map_name).build())
    snk = wf.Sink_Builder(lambda r: None).withName("snk").build()
    g = wf.PipeGraph(app, wf.ExecutionMode.DEFAULT,
                     config=dataclasses.replace(default_config))
    g.add_source(_source(src_name)).add(m).add_sink(snk)
    return g


@pytest.fixture(scope="module")
def run_graph():
    """One shared run: the audit/stats/postmortem/cross-validation
    contracts all read the same compiled programs."""
    g = _map_graph("ira_app", "ira_ma", "ira_src_shared")
    g.run()
    return g


# ---------------------------------------------------------------------------
# golden StableHLO fixtures: one seeded violation + one clean twin per code
# ---------------------------------------------------------------------------

CLEAN_TWIN = """module @jit_step {
  func.func public @main(%arg0: tensor<64xf32>) -> (tensor<64xf32>) {
    %0 = stablehlo.multiply %arg0, %arg0 : tensor<64xf32>
    %1 = "stablehlo.reduce_window"(%0) <{window = dense<1> : tensor<2xi64>}> : (tensor<64xf32>) -> tensor<64xf32>
    return %1 : tensor<64xf32>
  }
}"""

GOLD_COLLECTIVE = """module @jit_step {
  func.func public @main(%arg0: tensor<16x4xf32>) -> (tensor<128x4xf32>) {
    %0 = "stablehlo.all_gather"(%arg0) <{all_gather_dim = 0 : i64, replica_groups = dense<[[0, 1, 2, 3, 4, 5, 6, 7]]> : tensor<1x8xi64>, use_global_device_ids}> : (tensor<16x4xf32>) -> tensor<128x4xf32>
    return %0 : tensor<128x4xf32>
  }
}"""

#: region-bearing collective on a SCALAR operand (the drop-count psum
#: every mesh layout keeps): must parse numel from the region's closing
#: line, never from the replica_groups attribute tensor
GOLD_SCALAR_REDUCE = """module @jit_step {
  func.func public @main(%arg0: tensor<i64>) -> (tensor<i64>) {
    %0 = "stablehlo.all_reduce"(%arg0) <{replica_groups = dense<[[0, 1, 2, 3, 4, 5, 6, 7]]> : tensor<1x8xi64>, use_global_device_ids}> ({
    ^bb0(%arg1: tensor<i64>, %arg2: tensor<i64>):
      %1 = stablehlo.add %arg1, %arg2 : tensor<i64>
      stablehlo.return %1 : tensor<i64>
    }) : (tensor<i64>) -> tensor<i64>
    return %0 : tensor<i64>
  }
}"""

GOLD_CALLBACK = """module @jit_step {
  func.func public @main(%arg0: tensor<64xf32>) -> (tensor<64xf32>) {
    %0 = stablehlo.custom_call @xla_python_cpu_callback(%arg0) {api_version = 2 : i32} : (tensor<64xf32>) -> tensor<64xf32>
    return %0 : tensor<64xf32>
  }
}"""

GOLD_CALLBACK_ATTR = """module @jit_step {
  func.func public @main(%arg0: tensor<64xf32>) -> (tensor<64xf32>) {
    %0 = "stablehlo.custom_call"(%arg0) {call_target_name = "xla_ffi_python_gpu_callback"} : (tensor<64xf32>) -> tensor<64xf32>
    return %0 : tensor<64xf32>
  }
}"""

GOLD_WIDE = """module @jit_step {
  func.func public @main(%arg0: tensor<64xf32>) -> (tensor<64xf64>) {
    %0 = stablehlo.convert %arg0 : (tensor<64xf32>) -> tensor<64xf64>
    return %0 : tensor<64xf64>
  }
}"""

GOLD_DYNAMIC = """module @jit_step {
  func.func public @main(%arg0: tensor<?xf32>, %arg1: tensor<2xi32>) -> (tensor<?x4xf32>) {
    %0 = stablehlo.dynamic_reshape %arg0, %arg1 : (tensor<?xf32>, tensor<2xi32>) -> tensor<?x4xf32>
    return %0 : tensor<?x4xf32>
  }
}"""

GOLD_ALIASED = """module @jit_step {
  func.func public @main(%arg0: tensor<64xf32> {tf.aliasing_output = 0 : i32}) -> (tensor<64xf32>) {
    %0 = stablehlo.add %arg0, %arg0 : tensor<64xf32>
    return %0 : tensor<64xf32>
  }
}"""

GOLD_TRANSFER = """module @jit_step {
  func.func public @main(%arg0: tensor<f32>, %arg1: !stablehlo.token) -> (!stablehlo.token) {
    %0 = "stablehlo.send"(%arg0, %arg1) <{channel_handle = #stablehlo.channel_handle<handle = 1, type = 2>, is_host_transfer = true}> : (tensor<f32>, !stablehlo.token) -> !stablehlo.token
    return %0 : !stablehlo.token
  }
}"""

GOLD_MOSAIC = """module @jit_step {
  func.func public @main(%arg0: tensor<64xf32>) -> (tensor<64xf32>) {
    %0 = stablehlo.custom_call @tpu_custom_call(%arg0) {backend_config = ""} : (tensor<64xf32>) -> tensor<64xf32>
    return %0 : tensor<64xf32>
  }
}"""


def _codes(findings):
    return sorted({d.code for d in findings})


def test_wf901_collective_fixture_and_clean_twin():
    facts = ir_audit.extract_facts(GOLD_COLLECTIVE)
    assert facts["collectives"] == ["all_gather"]
    assert _codes(ir_audit.program_findings(
        "p", facts, promised_collective_free=True)) == ["WF901"]
    assert _codes(ir_audit.program_findings(
        "p", facts, alignable_unaligned=True)) == ["WF901"]
    # no graph context -> a collective is not a finding by itself
    assert ir_audit.program_findings("p", facts) == []
    clean = ir_audit.extract_facts(CLEAN_TWIN)
    assert clean["collectives"] == []
    assert ir_audit.program_findings(
        "p", clean, promised_collective_free=True) == []


def test_wf901_cross_key_classification():
    """Only NON-scalar collectives whose replica groups span >1 key
    coordinate count as the traffic aligned ingest eliminates: scalar
    counter psums and within-column data-axis gathers are excluded."""
    from windflow_tpu.parallel import mesh as M
    mesh = M.make_mesh(8, data=2)
    axis = mesh.axis_names.index(M.KEY_AXIS)
    key_of = {}
    for idx in np.ndindex(mesh.devices.shape):
        key_of[int(mesh.devices[idx].id)] = idx[axis]
    by_key = {}
    for dev, k in key_of.items():
        by_key.setdefault(k, []).append(dev)
    data_groups = sorted(sorted(v) for v in by_key.values())
    all_ids = sorted(key_of)

    def facts_for(groups, numel):
        return {"collectives": ["all_gather"],
                "collective_ops": [
                    {"op": "all_gather", "groups": groups, "numel": numel}]}

    # whole-mesh non-scalar gather: crossing
    assert ir_audit.cross_key_collectives(
        facts_for([all_ids], 16), mesh) == ["all_gather"]
    # data-axis (same-key-column) gather: NOT crossing
    assert ir_audit.cross_key_collectives(
        facts_for(data_groups, 16), mesh) == []
    # scalar reduce over the whole mesh (drop-count telemetry): excluded
    assert ir_audit.cross_key_collectives(
        facts_for([all_ids], 1), mesh) == []
    # unparseable groups: conservative — counted as crossing
    assert ir_audit.cross_key_collectives(
        facts_for(None, 16), mesh) == ["all_gather"]
    # the region-op fixture parses the operand from the closing line,
    # not the replica_groups attribute tensor
    scalar = ir_audit.extract_facts(GOLD_SCALAR_REDUCE)
    assert scalar["collective_ops"] == [
        {"op": "all_reduce", "groups": [[0, 1, 2, 3, 4, 5, 6, 7]],
         "numel": 1}]
    assert ir_audit.cross_key_collectives(scalar, mesh) == []
    # legacy facts without the detail fall back to every collective
    assert ir_audit.cross_key_collectives(
        {"collectives": ["all_to_all"]}, mesh) == ["all_to_all"]


def test_wf902_callback_fixture_and_clean_twin():
    for text in (GOLD_CALLBACK, GOLD_CALLBACK_ATTR):
        facts = ir_audit.extract_facts(text)
        assert len(facts["callbacks"]) == 1
        assert _codes(ir_audit.program_findings("p", facts)) == ["WF902"]
    clean = ir_audit.extract_facts(CLEAN_TWIN)
    assert clean["callbacks"] == []
    assert ir_audit.program_findings("p", clean) == []


def test_wf903_wide_dtype_fixture_and_clean_twin():
    facts = ir_audit.extract_facts(GOLD_WIDE, backend="tpu")
    assert facts["wide_dtypes"] == ["f64"]
    assert _codes(ir_audit.program_findings("p", facts)) == ["WF903"]
    # same program on a CPU backend: 64-bit is legal there
    cpu = ir_audit.extract_facts(GOLD_WIDE, backend="cpu")
    assert ir_audit.program_findings("p", cpu) == []
    # i64 in ATTRIBUTE position (dense window shapes etc.) never counts —
    # the clean twin carries one on purpose
    clean = ir_audit.extract_facts(CLEAN_TWIN, backend="tpu")
    assert clean["wide_dtypes"] == []
    assert ir_audit.program_findings("p", clean) == []


def test_wf904_dynamic_fixture_and_clean_twin():
    facts = ir_audit.extract_facts(GOLD_DYNAMIC)
    assert "dynamic_reshape" in facts["dynamic"]
    assert "dynamic_dimension" in facts["dynamic"]
    assert _codes(ir_audit.program_findings("p", facts)) == ["WF904"]
    assert ir_audit.extract_facts(CLEAN_TWIN)["dynamic"] == []


def test_wf905_donation_fixture_and_aliased_twin():
    # donated operand, zero aliasing attributes in the module: miss
    facts = ir_audit.extract_facts(CLEAN_TWIN, donated_leaves=2)
    assert facts["aliased_outputs"] == 0
    assert _codes(ir_audit.program_findings("p", facts)) == ["WF905"]
    # the twin carries jax's tf.aliasing_output marker: donation landed
    ok = ir_audit.extract_facts(GOLD_ALIASED, donated_leaves=1)
    assert ok["aliased_outputs"] == 1
    assert ir_audit.program_findings("p", ok) == []
    # nothing donated -> nothing to miss
    assert ir_audit.program_findings(
        "p", ir_audit.extract_facts(CLEAN_TWIN)) == []


def test_wf906_transfer_fixture_and_clean_twin():
    facts = ir_audit.extract_facts(GOLD_TRANSFER)
    assert facts["transfers"] == ["send"]
    assert _codes(ir_audit.program_findings("p", facts)) == ["WF906"]
    assert ir_audit.extract_facts(CLEAN_TWIN)["transfers"] == []


def test_wf907_mosaic_fixture_and_clean_twin():
    # Pallas resolved ON, TPU backend, no Mosaic custom call: downgrade
    facts = ir_audit.extract_facts(CLEAN_TWIN, backend="tpu")
    assert facts["mosaic_calls"] == 0
    assert _codes(ir_audit.program_findings(
        "p", facts, expect_mosaic=True)) == ["WF907"]
    # twin: the tpu_custom_call is present (and is NOT a WF902 callback)
    ok = ir_audit.extract_facts(GOLD_MOSAIC, backend="tpu")
    assert ok["mosaic_calls"] == 1 and ok["callbacks"] == []
    assert ir_audit.program_findings("p", ok, expect_mosaic=True) == []
    # on CPU the interpreter fallback is the contract, not a downgrade
    cpu = ir_audit.extract_facts(CLEAN_TWIN, backend="cpu")
    assert ir_audit.program_findings("p", cpu, expect_mosaic=True) == []


# ---------------------------------------------------------------------------
# real lowerings: the markers hold on the jax this repo runs
# ---------------------------------------------------------------------------

def test_real_lowering_donation_markers():
    """jax's aliasing attribute appears exactly when the donated operand
    can alias an output — extract_facts + record_lowered read the real
    thing, not just the golden fixtures."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # jax's own unused-donation warn
        ok = jax.jit(lambda s: s + 1.0, donate_argnums=(0,)) \
            .lower(jnp.zeros(64, jnp.float32))
        bad = jax.jit(lambda s: s.sum(), donate_argnums=(0,)) \
            .lower(jnp.zeros(64, jnp.float32))
    facts_ok = ir_audit.extract_facts(ok.as_text(), donated_leaves=1)
    assert facts_ok["aliased_outputs"] >= 1
    assert ir_audit.program_findings("p", facts_ok) == []
    facts_bad = ir_audit.extract_facts(bad.as_text(), donated_leaves=1)
    assert facts_bad["aliased_outputs"] == 0
    assert _codes(ir_audit.program_findings("p", facts_bad)) == ["WF905"]
    # record_lowered counts the donated leaves from args_info itself
    ir_audit.record_lowered("ira_real_don", ("sig",), bad)
    stored = ir_audit.store_snapshot()["ira_real_don"][0]
    assert stored["donated_leaves"] == 1
    assert stored["aliased_outputs"] == 0


def test_real_lowering_callback_marker():
    def cb(t):
        v = jax.pure_callback(lambda a: np.sin(a),
                              jax.ShapeDtypeStruct((), jnp.float32),
                              t["v"], vmap_method="sequential")
        return {"key": t["key"], "v": v}
    low = jax.jit(jax.vmap(cb)).lower(
        {"key": jax.ShapeDtypeStruct((64,), jnp.int32),
         "v": jax.ShapeDtypeStruct((64,), jnp.float32)})
    facts = ir_audit.extract_facts(low.as_text())
    assert facts["callbacks"], facts
    assert _codes(ir_audit.program_findings("p", facts)) == ["WF902"]


# ---------------------------------------------------------------------------
# graph-level wiring: audit_graph, stats, postmortem + wf_doctor
# ---------------------------------------------------------------------------

def test_run_graph_audits_clean(run_graph):
    report = ir_audit.audit_graph(run_graph, dry_lower=False)
    assert report.programs_audited >= 1
    assert report.findings == [] and report.pending == []
    assert "ira_ma" in report.op_names
    sec = run_graph.stats()["IR_audit"]
    assert sec["enabled"] is True
    assert sec["programs_audited"] >= 1 and sec["findings"] == []
    json.dumps(sec)


def _load_doctor():
    spec = importlib.util.spec_from_file_location(
        "wf_doctor", os.path.join(REPO, "tools", "wf_doctor.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_postmortem_ir_audit_section_roundtrips_wf_doctor(run_graph,
                                                          tmp_path):
    doctor = _load_doctor()
    d = run_graph.dump_postmortem(str(tmp_path / "bundle"),
                                  reason="wfir test")
    bundle = doctor.load_bundle(d)
    doctor.validate(bundle)
    sec = bundle["sections"]["ir_audit.json"]
    assert sec["enabled"] is True and sec["programs_audited"] >= 1
    diag = doctor.diagnose(bundle)
    assert diag["ir_audit"]["programs_audited"] >= 1
    assert "IR audit" in doctor.render_text(diag)
    # a corrupted section must fail --check, not render garbage
    path = os.path.join(d, "ir_audit.json")
    with open(path) as f:
        sec = json.load(f)
    sec["findings"] = [{"code": "OOPS"}]
    with open(path, "w") as f:
        json.dump(sec, f)
    with pytest.raises(doctor.BundleError):
        doctor.validate(doctor.load_bundle(d))


# ---------------------------------------------------------------------------
# WF901 acceptance twin: aligned vs unaligned mesh reduce
# ---------------------------------------------------------------------------

def _mesh_reduce_run(aligned, tag):
    from windflow_tpu.parallel import mesh as M
    mesh = M.make_mesh(8, data=1)
    kk = mesh.shape[M.KEY_AXIS]
    cap, K = 16 * 8, 4 * kk
    rng = np.random.default_rng(5)
    records = [{"key": int(k), "value": float(v)}
               for k, v in zip(rng.integers(0, K, 4 * cap),
                               rng.integers(0, 97, 4 * cap))]
    cfg = dataclasses.replace(default_config, mesh=mesh,
                              key_aligned_ingest=aligned)
    src = (wf.Source_Builder(lambda: iter(records))
           .withOutputBatchSize(cap).build())
    red = (wf.ReduceTPU_Builder(
            lambda a, b: {"key": jnp.maximum(a["key"], b["key"]),
                          "value": jnp.maximum(a["value"], b["value"])})
           .withKeyBy(lambda t: t["key"]).withMaxKeys(K)
           .withMonoidCombiner("max").withName(f"ira_red_{tag}").build())
    g = wf.PipeGraph(f"ira_mesh_{tag}", config=cfg)
    g.add_source(src).add(red).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        g.run()
    report = ir_audit.audit_graph(g, dry_lower=False)
    return red, report


def test_wf901_mesh_reduce_aligned_vs_unaligned_twin():
    """The acceptance contract: the aligned-ingest mesh program audits
    with ZERO WF901 (its only cross-key collective is the scalar
    drop-count psum every layout keeps) while the unaligned twin — whose
    [K]-table pmax combine rides the key axis — yields at least one."""
    red_a, rep_a = _mesh_reduce_run(True, "a")
    assert getattr(red_a, "_ingest_mode", None) == "aligned"
    assert [d for d in rep_a.findings if d.code == "WF901"] == []
    red_u, rep_u = _mesh_reduce_run(False, "u")
    assert getattr(red_u, "_ingest_mode", None) is None
    wf901 = [d for d in rep_u.findings if d.code == "WF901"]
    assert len(wf901) >= 1
    assert "aligned ingest" in wf901[0].message


# ---------------------------------------------------------------------------
# WF905 cross-validation: the static miss and the runtime counters agree
# ---------------------------------------------------------------------------

def test_wf905_static_and_runtime_donation_miss_cross_validate(run_graph):
    """Satellite contract: the IR-level donation audit and the sweep
    ledger's runtime counters are two views of one defect class — a
    donated-but-unaliasable program is flagged statically (WF905) while
    the ledger charges real bytes for undonated candidate buffers."""
    # runtime half: the map hop re-copies its undonated buffers
    sweep = run_graph.stats()["Sweep"]
    assert sweep["totals"]["donation_miss_bytes_per_batch"] > 0
    hop = next(h for name, h in sweep["per_hop"].items()
               if "ira_ma" in name)
    assert hop["donation_miss"]["bytes_per_batch"] > 0
    # static half: a donated operand no output can alias
    from windflow_tpu.monitoring.jit_registry import wf_jit
    step = wf_jit(lambda s, x: s.sum() + x.sum(),
                  op_name="ira_unaliasable", donate_argnums=(0,))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        step(jnp.ones(128, jnp.float32), jnp.ones(128, jnp.float32))
    facts = ir_audit.store_snapshot()["ira_unaliasable"][0]
    assert facts["donated_leaves"] == 1 and facts["aliased_outputs"] == 0
    assert "WF905" in _codes(
        ir_audit.program_findings("ira_unaliasable", facts))


# ---------------------------------------------------------------------------
# preflight integration: check() folds the dry-lower audit
# ---------------------------------------------------------------------------

def _cb_kernel(t):
    v = jax.pure_callback(lambda a: np.sin(a),
                          jax.ShapeDtypeStruct((), jnp.float32),
                          t["v"], vmap_method="sequential")
    return {"key": t["key"], "v": v}


# wfir shares wfverify's inline suppression; the token on the def line
# below is the seeded fixture test_preflight_suppression reads
def _cb_kernel_suppressed(t):  # wfverify: ok (seeded wfir suppression fixture)
    v = jax.pure_callback(lambda a: np.sin(a),
                          jax.ShapeDtypeStruct((), jnp.float32),
                          t["v"], vmap_method="sequential")
    return {"key": t["key"], "v": v}


def _unstarted_graph(app, fn, name):
    src = (wf.Source_Builder(lambda: iter(()))
           .withOutputBatchSize(64).withName(f"{name}_src")
           .withRecordSpec(_spec()).build())
    m = wf.MapTPU_Builder(fn).withName(name).build()
    g = wf.PipeGraph(app)
    g.add_source(src).add(m).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    return g


def test_preflight_check_folds_dry_lower_audit():
    """check() on an UNSTARTED graph dry-lowers the user kernels over
    the preflight record specs: a host callback inside one surfaces as
    WF902 before anything ever compiles; the clean twin stays silent."""
    g = _unstarted_graph("ira_pf_cb", _cb_kernel, "ira_pf_cb_map")
    ds = g.check()
    assert "WF902" in {d.code for d in ds}
    assert g._ir_audit_report.dry_lowered >= 1
    g2 = _unstarted_graph(
        "ira_pf_clean",
        lambda t: {"key": t["key"], "v": t["v"] * 2.0}, "ira_pf_clean_m")
    ds2 = g2.check()
    assert {d.code for d in ds2} & {"WF901", "WF902", "WF903", "WF904",
                                    "WF905", "WF906", "WF907"} == set()
    assert g2._ir_audit_report.dry_lowered >= 1


def test_preflight_suppression_shares_wfverify_syntax():
    g = _unstarted_graph("ira_pf_sup", _cb_kernel_suppressed,
                         "ira_pf_sup_map")
    ds = g.check()
    assert "WF902" not in {d.code for d in ds}
    assert g._ir_audit_report.suppressed >= 1


# ---------------------------------------------------------------------------
# CLI round trip
# ---------------------------------------------------------------------------

CLEAN_APP = """\
import numpy as np
import windflow_tpu as wf

def make_graph():
    src = (wf.Source_Builder(lambda: iter(()))
           .withOutputBatchSize(256).withName("cli_src")
           .withRecordSpec({"key": np.int32(0), "v": np.float32(0.0)})
           .build())
    m = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] * 2.0})
         .withName("cli_map").build())
    g = wf.PipeGraph("cli_clean")
    g.add_source(src).add(m).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    return g
"""

VIOLATING_APP = """\
import jax
import numpy as np
import windflow_tpu as wf

def _cb(t):
    v = jax.pure_callback(lambda a: np.sin(a),
                          jax.ShapeDtypeStruct((), np.float32),
                          t["v"], vmap_method="sequential")
    return {"key": t["key"], "v": v}

def make_graph():
    src = (wf.Source_Builder(lambda: iter(()))
           .withOutputBatchSize(256).withName("cli_bad_src")
           .withRecordSpec({"key": np.int32(0), "v": np.float32(0.0)})
           .build())
    m = wf.MapTPU_Builder(_cb).withName("cli_bad_map").build()
    g = wf.PipeGraph("cli_bad")
    g.add_source(src).add(m).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    return g
"""


def test_cli_json_strict_roundtrip(tmp_path):
    """tools/wf_ir.py: --drive runs the graphs, --json emits per-app
    reports, --strict propagates the seeded WF902 as exit 1 while the
    clean app audits 0 errors; WF_TPU_IR_AUDIT=0 is a usage error."""
    (tmp_path / "cli_clean_app.py").write_text(CLEAN_APP)
    (tmp_path / "cli_bad_app.py").write_text(VIOLATING_APP)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(tmp_path))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_ir.py"),
         "cli_clean_app", "cli_bad_app", "--drive", "512", "--json",
         "--strict"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert r.returncode == 1, (r.stdout, r.stderr)
    out = json.loads(r.stdout)
    clean = out["cli_clean_app"]
    assert clean["graph"] == "cli_clean"
    assert clean["errors"] == 0 and clean["programs_audited"] >= 1
    bad = out["cli_bad_app"]
    assert bad["errors"] >= 1
    assert "WF902" in {f["code"] for f in bad["findings"]}
    # the driven run compiles the framework staging programs too: the
    # orphan sweep covers them
    assert out["(framework programs)"]["programs_audited"] >= 1
    # kill switch refuses to pretend it audited anything
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_ir.py"),
         "cli_clean_app"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env=dict(env, WF_TPU_IR_AUDIT="0"))
    assert r2.returncode == 2
    assert "WF_TPU_IR_AUDIT=0" in r2.stderr


# ---------------------------------------------------------------------------
# zero extra compiles + kill switch + capture-failure warning
# ---------------------------------------------------------------------------

def test_audit_performs_zero_extra_compiles(run_graph):
    """The audit parses the compile watcher's existing first-compile
    lowering; auditing (including the dry-lower pass, which uses
    client-side ``jit().lower()`` only) must leave every registry
    compile counter untouched."""
    from windflow_tpu.monitoring.jit_registry import default_registry
    before = default_registry().totals()
    ir_audit.audit_graph(run_graph, dry_lower=False)
    ir_audit.process_report()
    ir_audit.audit_orphans(set())
    g = _unstarted_graph(
        "ira_zero_compiles",
        lambda t: {"key": t["key"], "v": t["v"] * 2.0}, "ira_zc_map")
    rep = ir_audit.audit_graph(g, dry_lower=True)
    assert rep.dry_lowered >= 1
    assert default_registry().totals() == before


def test_kill_switch_off_path_budget(monkeypatch):
    g = _map_graph("ira_kill_app", "ira_kill_ma", "ira_kill_src")
    g.config = dataclasses.replace(g.config, ir_audit=False)
    g.run()
    assert g.stats()["IR_audit"] == {"enabled": False}
    assert ir_audit.audit_graph(g).programs_audited == 0
    # off-path budget: the disabled section is ONE flag check
    t0 = time.perf_counter()
    for _ in range(10_000):
        g._ir_audit_section()
    per_call = (time.perf_counter() - t0) / 10_000
    assert per_call < 5e-6, \
        f"disabled ir_audit section costs {per_call * 1e6:.2f}us/call"
    # process switch: capture and every report become no-ops
    monkeypatch.setattr(ir_audit, "ENABLED", False)
    ir_audit.record_lowered("ira_kill_never", ("sig",), None)
    assert "ira_kill_never" not in ir_audit.store_snapshot()
    assert ir_audit.process_report().programs_audited == 0
    assert ir_audit.audit_orphans(set()).programs_audited == 0
    assert ir_audit.audit_graph(g).programs_audited == 0


def test_capture_failure_warns_once_and_reports_pending(monkeypatch):
    """Satellite contract: a lowering-capture failure inside the
    registry's cost path warns ONCE per op (naming the op and the
    consequence) instead of silently leaving a program that looks
    audited-clean — and the audit reports the op as pending."""
    def boom(op_name, sig, lowered):
        raise RuntimeError("seeded capture failure")
    monkeypatch.setattr(ir_audit, "record_lowered", boom)
    g = _map_graph("ira_capfail_app", "ira_capfail_ma", "ira_capfail_src")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        g.run()
    mine = [str(x.message) for x in w
            if "lowering capture failed" in str(x.message)
            and "ira_capfail_ma" in str(x.message)]
    assert len(mine) == 1, mine
    assert "pending" in mine[0] and "RuntimeError" in mine[0]
    report = ir_audit.audit_graph(g, dry_lower=False)
    assert "ira_capfail_ma" in report.pending
