"""Test configuration: run everything on a virtual 8-device CPU mesh so
multi-chip sharding paths compile and execute without TPU hardware (the
driver's dryrun does the same; real-chip benchmarking lives in bench.py)."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The environment may pin JAX_PLATFORMS to a hardware plugin at interpreter
# startup (sitecustomize), so an env-var setdefault is not enough: force the
# CPU backend through the config API before any backend is initialized.
import jax

jax.config.update("jax_platforms", "cpu")

import random

import pytest


@pytest.fixture
def rng():
    return random.Random(1234)


def tb_window_sums(points, win_us, slide_us):
    """Shared TB-window oracle: per-key sums of every time window containing
    at least one tuple.  ``points`` maps key -> [(ts_us, value), ...]."""
    exp = {}
    for k, pts in points.items():
        wids = set()
        for ts, _ in pts:
            last = ts // slide_us
            first = max(0, -(-(ts - win_us + 1) // slide_us))
            wids.update(range(first, last + 1))
        for w in wids:
            vals = [v for ts, v in pts
                    if w * slide_us <= ts < w * slide_us + win_us]
            if vals:
                exp[(k, w)] = sum(vals)
    return exp
