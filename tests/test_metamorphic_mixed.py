"""Randomized mixed host+device DAG sweep (reference ``graph_tests_gpu``
pattern, ``test_graph_1.cpp:84-206``): one DAG mixing TPU map/filter, host
map/filter, split/merge, and a host time-window stage, swept over random
per-operator parallelism and batch sizes.  Run 0 is the oracle; every other
configuration must reproduce it exactly.  A pure-Python oracle pins the
absolute values, and the two split branches run the same logic on host vs
device, so the sweep also cross-checks backend equivalence."""

import random

import jax.numpy as jnp

import windflow_tpu as wf

N_KEYS = 4
LENGTH = 600
TWIN, TSLIDE = 16_000, 8_000  # µs


def stream():
    return [{"key": i % N_KEYS, "value": i, "ts": i * 1000}
            for i in range(LENGTH)]


def py_oracle():
    """Both branches apply v*3 then drop v%5==0; branch is by parity of the
    original value, but both branches do the same thing, so the merged
    stream is just every surviving tuple; then per-key TB windows sum."""
    per_key = {}
    for t in stream():
        v = t["value"] * 3
        if v % 5 != 0:
            per_key.setdefault(t["key"], []).append((t["ts"], v))
    count = total = 0
    for items in per_key.values():
        max_ts = max(ts for ts, _ in items)
        w = 0
        while w * TSLIDE <= max_ts:
            in_win = [v for ts, v in items
                      if w * TSLIDE <= ts < w * TSLIDE + TWIN]
            if in_win:
                count += 1
                total += sum(in_win)
            w += 1
    return count, total


def run_config(rnd):
    acc = {"count": 0, "total": 0}

    def on_result(r):
        if r is not None:
            acc["count"] += 1
            acc["total"] += int(r.value if hasattr(r, "value") else r)

    batch = rnd.choice([16, 32, 64])
    g = wf.PipeGraph("meta_mixed", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    src = (wf.Source_Builder(lambda: iter(stream()))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(batch).build())
    prep = (wf.Map_Builder(lambda t: dict(t))
            .withParallelism(rnd.randint(1, 3))
            .withOutputBatchSize(batch).build())
    mp = g.add_source(src).add(prep)
    mp.split(lambda t: t["value"] % 2, 2)

    # branch 0 (even values): device map + filter
    b0 = mp.select(0) \
        .add(wf.MapTPU_Builder(
            lambda t: {"key": t["key"], "value": t["value"] * 3,
                       "ts": t["ts"]})
             .withParallelism(rnd.randint(1, 2)).build()) \
        .add(wf.FilterTPU_Builder(lambda t: (t["value"] % 5) != 0)
             .withParallelism(rnd.randint(1, 2)).build())
    # branch 1 (odd values): the same logic on host
    b1 = mp.select(1) \
        .add(wf.Map_Builder(
            lambda t: {"key": t["key"], "value": t["value"] * 3,
                       "ts": t["ts"]})
             .withParallelism(rnd.randint(1, 3)).build()) \
        .add(wf.Filter_Builder(lambda t: (t["value"] % 5) != 0)
             .withParallelism(rnd.randint(1, 3)).build())

    merged = b0.merge(b1)
    win = (wf.Keyed_Windows_Builder(
            lambda items: sum(t["value"] for t in items))
           .withTBWindows(TWIN, TSLIDE)
           .withKeyBy(lambda t: t["key"])
           .withParallelism(rnd.randint(1, 3)).build())
    merged.add(win).add_sink(wf.Sink_Builder(on_result).build())
    g.run()
    return acc["count"], acc["total"]


def test_mixed_dag_metamorphic_sweep():
    rnd = random.Random(42)
    expected = py_oracle()
    results = [run_config(rnd) for _ in range(5)]
    # run 0 is the oracle for the sweep; the python oracle pins the values
    assert results[0] == expected, (results[0], expected)
    for i, r in enumerate(results[1:], 1):
        assert r == results[0], f"config {i}: {r} != {results[0]}"
