"""The O(n) dense-key grouping permutation (windows/grouping.py) and its
wiring into the FFAT steps (Config.ffat_grouping).

Three layers of evidence, mirroring how the argsort path earned trust:
1. the permutation itself is bit-identical to ``jnp.argsort(stable=True)``
   across bucket widths (single-digit, radix), batch sizes (chunk-padding
   edges), and skews;
2. the CB and TB FFAT steps produce bit-identical outputs AND state under
   both groupings — including a NON-commutative combiner, which fails if
   arrival order within a key is ever perturbed;
3. a whole graph run under ``ffat_grouping="rank_scatter"`` matches the
   pure-Python oracle (the config plumbing, not just the kernel).

Reference anchor: the grouping the reference buys with
``thrust::sort_by_key`` (``keyby_emitter_gpu.hpp:519-583``).
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.windows.ffat_kernels import (agg_spec_for, make_ffat_state,
                                               make_ffat_step,
                                               make_ffat_tb_state,
                                               make_ffat_tb_step)
from windflow_tpu.windows.grouping import counting_order


# the two heaviest cells (~8s each: bench digit width, 2-digit radix)
# ride the nightly leg (wfverify-round headroom pass); the remaining
# cells keep every algorithm branch (radix digit counts, sub-chunk
# padding, degenerate buckets) in the tier-1 gate
@pytest.mark.parametrize("B,nbuckets", [
    pytest.param(4096, 257, marks=pytest.mark.slow),  # bench digit width
    (1000, 7),        # few buckets
    (64, 257),        # one chunk exactly
    (63, 3),          # sub-chunk + padding
    (31, 5),          # below one chunk
    (4096, 70000),    # radix (3 digits)
    (300, 1),         # all ids equal
    pytest.param(512, 300, marks=pytest.mark.slow),   # radix (2 digits)
])
def test_counting_order_matches_stable_argsort(B, nbuckets):
    rng = np.random.default_rng(B * 31 + nbuckets)
    ids = jnp.asarray(rng.integers(0, nbuckets, B), jnp.int32)
    got = jax.jit(lambda x: counting_order(x, nbuckets))(ids)
    want = jnp.argsort(ids, stable=True)
    assert (got == want).all()
    # the helpers built on it: auto_order picks an algorithm but must be
    # bit-identical; invert_perm must invert any permutation sort-free
    from windflow_tpu.windows.grouping import auto_order, invert_perm
    assert (auto_order(ids, nbuckets) == want).all()
    assert (invert_perm(got) == jnp.argsort(got)).all()


@pytest.mark.slow  # ~10s: the skew/sorted-input matrix rides the
# nightly leg (wfverify-round headroom pass); the parametrized
# stable-argsort equality above keeps counting_order covered in tier-1
def test_counting_order_skewed_and_sorted_inputs():
    for ids_np in [
        np.zeros(500, np.int32),                       # one hot bucket
        np.arange(500, dtype=np.int32) % 3,            # round-robin
        np.sort(np.random.default_rng(0).integers(0, 9, 500)).astype(
            np.int32),                                 # already grouped
        np.concatenate([np.full(499, 7, np.int32), [0]]),  # tail singleton
    ]:
        ids = jnp.asarray(ids_np)
        got = counting_order(ids, int(ids_np.max()) + 1)
        want = jnp.argsort(ids, stable=True)
        assert (got == want).all()


# -- kernel-level equivalence ----------------------------------------------

def _random_batches(rng, cap, K, n_batches, ts_jitter=False):
    for i in range(n_batches):
        n = rng.integers(cap // 2, cap + 1)
        keys = rng.integers(0, K + 2, cap)      # includes out-of-range keys
        vals = rng.random(cap).astype(np.float32)
        ts = np.arange(cap, dtype=np.int64) * 1000 + i * cap * 1000
        if ts_jitter:
            ts = ts + rng.integers(-2000, 2000, cap)
        valid = np.zeros(cap, bool)
        valid[:n] = True
        yield (jnp.asarray(keys, jnp.int32), jnp.asarray(vals),
               jnp.asarray(ts), jnp.asarray(valid))


# non-commutative, associative: 2x2 matrix product over (value, 1) lifts
def _mat_lift(x):
    v = x["v"]
    one = jnp.ones((), v.dtype)
    return {"a": one, "b": v, "c": jnp.zeros((), v.dtype), "d": one}


def _mat_comb(m1, m2):
    return {"a": m1["a"] * m2["a"] + m1["b"] * m2["c"],
            "b": m1["a"] * m2["b"] + m1["b"] * m2["d"],
            "c": m1["c"] * m2["a"] + m1["d"] * m2["c"],
            "d": m1["c"] * m2["b"] + m1["d"] * m2["d"]}


@pytest.mark.parametrize("comb_kind", ["sum", "noncommutative"])
def test_cb_step_bitwise_equal_across_groupings(comb_kind):
    cap, K, P, R, D = 96, 5, 4, 4, 1
    if comb_kind == "sum":
        lift, comb = (lambda x: x["v"]), (lambda a, b: a + b)
    else:
        lift, comb = _mat_lift, _mat_comb
    key_fn = lambda x: x["k"]
    steps = {
        g: jax.jit(make_ffat_step(cap, K, P, R, D, lift, comb, key_fn,
                                  grouping=g))
        for g in ("rank_scatter", "argsort")
    }
    spec = agg_spec_for(lift, {"k": jnp.zeros((cap,), jnp.int32),
                               "v": jnp.zeros((cap,), jnp.float32)})
    states = {g: make_ffat_state(spec, K, R) for g in steps}
    rngs = {g: np.random.default_rng(7) for g in steps}
    for _ in range(4):
        outs = {}
        for g, step in steps.items():
            keys, vals, ts, valid = next(
                _random_batches(rngs[g], cap, K, 1))
            states[g], out, fired, out_ts = step(
                states[g], {"k": keys, "v": vals}, ts, valid)
            outs[g] = (out, fired, out_ts)
        for (a, b) in zip(jax.tree.leaves(outs["rank_scatter"]),
                          jax.tree.leaves(outs["argsort"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for (a, b) in zip(jax.tree.leaves(states["rank_scatter"]),
                          jax.tree.leaves(states["argsort"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("comb_kind", ["sum", "noncommutative"])
def test_tb_step_bitwise_equal_across_groupings(comb_kind):
    cap, K, P_usec, R, D, NP = 96, 5, 1000, 4, 2, 32
    if comb_kind == "sum":
        lift, comb = (lambda x: x["v"]), (lambda a, b: a + b)
    else:
        lift, comb = _mat_lift, _mat_comb
    key_fn = lambda x: x["k"]
    steps = {
        g: jax.jit(make_ffat_tb_step(cap, K, P_usec, R, D, NP, lift, comb,
                                     key_fn, grouping=g))
        for g in ("rank_scatter", "argsort")
    }
    spec = agg_spec_for(lift, {"k": jnp.zeros((cap,), jnp.int32),
                               "v": jnp.zeros((cap,), jnp.float32)})
    states = {g: make_ffat_tb_state(spec, K, NP) for g in steps}
    rngs = {g: np.random.default_rng(11) for g in steps}
    for i in range(4):
        outs = {}
        for g, step in steps.items():
            keys, vals, ts, valid = next(
                _random_batches(rngs[g], cap, K, 1, ts_jitter=True))
            wm = jnp.int64((i + 1) * cap * 1000 // P_usec - R)
            states[g], out, fired, out_ts, n_adv = step(
                states[g], {"k": keys, "v": vals}, ts, valid, wm)
            outs[g] = (out, fired, out_ts, n_adv)
        for (a, b) in zip(jax.tree.leaves(outs["rank_scatter"]),
                          jax.tree.leaves(outs["argsort"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for (a, b) in zip(jax.tree.leaves(states["rank_scatter"]),
                          jax.tree.leaves(states["argsort"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cb_step_scatter_add_fast_path_matches_sorted():
    """sum_like + rank_scatter takes the scatter-add bypass (no
    permutation); with integer-valued floats addition is exact in any
    order, so outputs and state must EQUAL the argsort path's."""
    cap, K, P, R, D = 96, 5, 4, 4, 1
    lift, comb = (lambda x: x["v"]), (lambda a, b: a + b)
    key_fn = lambda x: x["k"]
    steps = {
        g: jax.jit(make_ffat_step(cap, K, P, R, D, lift, comb, key_fn,
                                  sum_like=True, grouping=g))
        for g in ("rank_scatter", "argsort")
    }
    spec = agg_spec_for(lift, {"k": jnp.zeros((cap,), jnp.int32),
                               "v": jnp.zeros((cap,), jnp.float32)})
    states = {g: make_ffat_state(spec, K, R) for g in steps}
    rng = np.random.default_rng(23)
    for _ in range(5):
        n = rng.integers(cap // 2, cap + 1)
        keys = rng.integers(0, K + 2, cap)
        vals = rng.integers(0, 1000, cap).astype(np.float32)
        valid = np.zeros(cap, bool)
        valid[:n] = True
        batch = ({"k": jnp.asarray(keys, jnp.int32),
                  "v": jnp.asarray(vals)},
                 jnp.asarray(np.arange(cap, dtype=np.int64)),
                 jnp.asarray(valid))
        outs = {}
        for g, step in steps.items():
            states[g], out, fired, out_ts = step(states[g], *batch)
            outs[g] = (out, fired, out_ts)
        for (a, b) in zip(jax.tree.leaves(outs["rank_scatter"]),
                          jax.tree.leaves(outs["argsort"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for (a, b) in zip(jax.tree.leaves(states["rank_scatter"]),
                          jax.tree.leaves(states["argsort"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cb_step_scatter_add_wide_keyspace():
    """K > one radix digit (256) still takes the scatter-add path (the
    rank pass is a single counting sweep whatever K is; gate is 4096)."""
    cap, K, P, R, D = 128, 300, 4, 4, 1
    lift, comb = (lambda x: x["v"]), (lambda a, b: a + b)
    key_fn = lambda x: x["k"]
    steps = {
        g: jax.jit(make_ffat_step(cap, K, P, R, D, lift, comb, key_fn,
                                  sum_like=True, grouping=g))
        for g in ("rank_scatter", "argsort")
    }
    spec = agg_spec_for(lift, {"k": jnp.zeros((cap,), jnp.int32),
                               "v": jnp.zeros((cap,), jnp.float32)})
    states = {g: make_ffat_state(spec, K, R) for g in steps}
    rng = np.random.default_rng(31)
    for _ in range(3):
        keys = rng.integers(0, K, cap)
        vals = rng.integers(0, 100, cap).astype(np.float32)
        batch = ({"k": jnp.asarray(keys, jnp.int32), "v": jnp.asarray(vals)},
                 jnp.asarray(np.arange(cap, dtype=np.int64)),
                 jnp.ones(cap, bool))
        outs = {}
        for g, step in steps.items():
            states[g], out, fired, out_ts = step(states[g], *batch)
            outs[g] = (out, fired, out_ts)
        for (a, b) in zip(jax.tree.leaves(outs["rank_scatter"]),
                          jax.tree.leaves(outs["argsort"])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cb_step_scatter_add_float_tolerance():
    """Random floats: scatter-add order may differ, so results are close,
    not bitwise (the psum tolerance the declaration implies)."""
    cap, K, P, R, D = 128, 7, 4, 8, 2
    lift, comb = (lambda x: x["v"]), (lambda a, b: a + b)
    key_fn = lambda x: x["k"]
    steps = {
        g: jax.jit(make_ffat_step(cap, K, P, R, D, lift, comb, key_fn,
                                  sum_like=True, grouping=g))
        for g in ("rank_scatter", "argsort")
    }
    spec = agg_spec_for(lift, {"k": jnp.zeros((cap,), jnp.int32),
                               "v": jnp.zeros((cap,), jnp.float32)})
    states = {g: make_ffat_state(spec, K, R) for g in steps}
    rng = np.random.default_rng(29)
    for i in range(4):
        keys = rng.integers(0, K, cap)
        vals = rng.random(cap).astype(np.float32)
        batch = ({"k": jnp.asarray(keys, jnp.int32), "v": jnp.asarray(vals)},
                 jnp.asarray(np.arange(cap, dtype=np.int64)),
                 jnp.ones(cap, bool))
        outs = {}
        for g, step in steps.items():
            states[g], out, fired, out_ts = step(states[g], *batch)
            outs[g] = (out, fired)
        np.testing.assert_array_equal(np.asarray(outs["rank_scatter"][1]),
                                      np.asarray(outs["argsort"][1]))
        np.testing.assert_allclose(
            np.asarray(outs["rank_scatter"][0]["value"]),
            np.asarray(outs["argsort"][0]["value"]), rtol=1e-5, atol=1e-4)


def test_tb_step_scatter_add_matches_grouped():
    """TB sum_like placement (sort-free scatter-add into the pane ring):
    integer-valued floats make addition order-exact, so outputs and state
    must EQUAL the grouped path's across batches with late/out-of-order
    timestamps."""
    cap, K, P_usec, R, D, NP = 96, 5, 1000, 4, 2, 32
    lift, comb = (lambda x: x["v"]), (lambda a, b: a + b)
    key_fn = lambda x: x["k"]
    steps = {
        sl: jax.jit(make_ffat_tb_step(cap, K, P_usec, R, D, NP, lift, comb,
                                      key_fn, sum_like=sl))
        for sl in (True, False)
    }
    spec = agg_spec_for(lift, {"k": jnp.zeros((cap,), jnp.int32),
                               "v": jnp.zeros((cap,), jnp.float32)})
    states = {sl: make_ffat_tb_state(spec, K, NP) for sl in steps}
    rng = np.random.default_rng(41)
    for i in range(5):
        n = rng.integers(cap // 2, cap + 1)
        keys = rng.integers(0, K + 2, cap)
        vals = rng.integers(0, 500, cap).astype(np.float32)
        ts = (np.arange(cap, dtype=np.int64) * 1000 + i * cap * 1000
              + rng.integers(-3000, 3000, cap))
        valid = np.zeros(cap, bool)
        valid[:n] = True
        wm = jnp.int64((i + 1) * cap - R)
        batch = ({"k": jnp.asarray(keys, jnp.int32), "v": jnp.asarray(vals)},
                 jnp.asarray(ts), jnp.asarray(valid))
        outs = {}
        for sl, step in steps.items():
            states[sl], out, fired, out_ts, n_adv = step(
                states[sl], *batch, wm)
            outs[sl] = (out, fired, out_ts, n_adv)
        # fired mask + non-value lanes must match exactly; value lanes
        # only where fired (non-fired rows carry path-dependent garbage,
        # gated by `fired` for every consumer)
        f_t, f_f = np.asarray(outs[True][1]), np.asarray(outs[False][1])
        np.testing.assert_array_equal(f_t, f_f)
        np.testing.assert_array_equal(np.asarray(outs[True][3]),
                                      np.asarray(outs[False][3]))
        for name in outs[True][0]:
            for la, lb in zip(jax.tree.leaves(outs[True][0][name]),
                              jax.tree.leaves(outs[False][0][name])):
                la, lb = np.asarray(la), np.asarray(lb)
                m = f_f.reshape(f_f.shape + (1,) * (la.ndim - 1))
                np.testing.assert_array_equal(np.where(m, la, 0),
                                              np.where(m, lb, 0))
        np.testing.assert_array_equal(
            np.where(f_f, np.asarray(outs[True][2]), 0),
            np.where(f_f, np.asarray(outs[False][2]), 0))
        # state equality is masked for "cells": the grouped path leaves
        # stale values in cell_valid==False slots where scatter-add
        # writes zeros — semantically identical (readers gate on
        # cell_valid); every other field must match exactly
        cv = np.asarray(states[False]["cell_valid"])
        for name in states[False]:
            a, b = states[True][name], states[False][name]
            if name == "cells":
                for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                    la, lb = np.asarray(la), np.asarray(lb)
                    np.testing.assert_array_equal(
                        np.where(cv.reshape(cv.shape + (1,) * (la.ndim - 2)),
                                 la, 0),
                        np.where(cv.reshape(cv.shape + (1,) * (lb.ndim - 2)),
                                 lb, 0))
            else:
                for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                    np.testing.assert_array_equal(np.asarray(la),
                                                  np.asarray(lb))


# -- graph-level: config plumbing + oracle ---------------------------------

N_KEYS = 3
LENGTH = 240


def _stream():
    return [{"key": i % N_KEYS, "value": i, "ts": i * 1000}
            for i in range(LENGTH)]


def _oracle_cb(win, slide):
    per_key = {}
    for t in _stream():
        per_key.setdefault(t["key"], []).append(t["value"])
    exp = {}
    for k, vals in per_key.items():
        w = 0
        while w * slide < len(vals):
            seg = vals[w * slide: w * slide + win]
            if seg:
                exp[(k, w)] = sum(seg)
            w += 1
    return exp


@pytest.mark.parametrize("grouping", ["rank_scatter", "argsort"])
def test_graph_ffat_grouping_config(grouping):
    import dataclasses

    got = {}
    src = (wf.Source_Builder(lambda: iter(_stream()))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(31).build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a, b: a + b)
          .withKeyBy(lambda t: t["key"]).withMaxKeys(N_KEYS)
          .withCBWindows(16, 4).build())
    snk = wf.Sink_Builder(
        lambda r: got.__setitem__((r["key"], r["wid"]), r["value"])
        if r is not None else None).build()
    cfg = dataclasses.replace(wf.default_config, ffat_grouping=grouping)
    g = wf.PipeGraph("grouping_cfg", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT, config=cfg)
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    assert got == _oracle_cb(16, 4)


def test_unknown_grouping_rejected():
    import dataclasses

    src = (wf.Source_Builder(lambda: iter(_stream()))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(31).build())
    op = (wf.Ffat_WindowsTPU_Builder(lambda t: t["value"],
                                     lambda a, b: a + b)
          .withKeyBy(lambda t: t["key"]).withMaxKeys(N_KEYS)
          .withCBWindows(16, 4).build())
    snk = wf.Sink_Builder(lambda r: None).build()
    cfg = dataclasses.replace(wf.default_config, ffat_grouping="bogus")
    g = wf.PipeGraph("grouping_bad", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT, config=cfg)
    g.add_source(src).add(op).add_sink(snk)
    with pytest.raises(wf.WindFlowError, match="ffat_grouping"):
        g.run()
