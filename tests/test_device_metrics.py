"""Device-plane observability contracts (docs/OBSERVABILITY.md "Device
plane"): compile-watcher counters + recompile detection + one-time storm
warning, first-compile cost capture, the CPU ``memory_stats() is None``
guard, the OpenMetrics exposition golden format (label escaping, bucket
monotonicity, counter-vs-gauge typing) with the ``wf_metrics --check``
round trip, the dashboard ``/metrics`` endpoint, gauge sampling without a
dashboard (starvation regression), the profiler bridge, and the
annotation off-path budget."""

import dataclasses
import json
import os
import subprocess
import sys
import time
import warnings

import jax
import jax.numpy as jnp
import pytest

import windflow_tpu as wf
from windflow_tpu.basic import default_config
from windflow_tpu.monitoring.jit_registry import (default_registry,
                                                  wf_jit)
from windflow_tpu.monitoring.openmetrics import (parse_exposition,
                                                 render_openmetrics)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _graph(name, n=3000, cap=512, **cfg_kw):
    cfg_kw.setdefault("flight_recorder", True)
    cfg_kw.setdefault("trace_sample_every", 2)
    cfg = dataclasses.replace(default_config, **cfg_kw)
    src = (wf.Source_Builder(
        lambda: iter({"key": i % 8, "v": float(i)} for i in range(n)))
        .withName("src").withOutputBatchSize(cap).build())
    m = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] * 2.0})
         .withName(f"{name}_map").build())
    seen = []
    snk = (wf.Sink_Builder(lambda t, ctx=None: seen.append(t))
           .withName("snk").build())
    g = wf.PipeGraph(name, wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(src).add(m).add_sink(snk)
    return g, seen


@pytest.fixture(scope="module")
def ran_stats():
    """One shared small traced run: (graph, stats dict)."""
    g, seen = _graph("dm_shared")
    g.run()
    assert seen
    return g, g.stats()


# ---------------------------------------------------------------------------
# compile watcher: counts, recompiles, warning, cost table
# ---------------------------------------------------------------------------

def test_wf_jit_counts_and_cost_table():
    f = wf_jit(lambda x: x * 2 + 1, op_name="dm_probe_basic")
    f(jnp.ones(16, jnp.float32))
    f(jnp.ones(16, jnp.float32))      # cache hit: no second compile
    e = default_registry().snapshot()["dm_probe_basic"]
    assert e["compiles"] == 1
    assert e["recompiles"] == 0
    assert e["compile_ms_total"] > 0
    # CPU backend provides cost analysis: FLOPs + bytes accessed captured
    # on the first compile (mode 'lowered' by default, see jit_registry)
    assert e["cost"] is not None
    assert e["cost"]["flops"] > 0
    assert e["cost"]["bytes_accessed"] > 0


def test_wf_jit_recompile_exactly_once_plus_one_time_warning():
    f = wf_jit(lambda x: x + 1, op_name="dm_probe_recompile")
    f(jnp.ones(8, jnp.float32))
    # forced shape change: exactly one recompile count + one warning
    with pytest.warns(RuntimeWarning, match="signature changed"):
        f(jnp.ones(12, jnp.float32))
    e = default_registry().snapshot()["dm_probe_recompile"]
    assert e["compiles"] == 2 and e["recompiles"] == 1
    # same shape again: nothing moves
    f(jnp.ones(12, jnp.float32))
    e = default_registry().snapshot()["dm_probe_recompile"]
    assert e["compiles"] == 2 and e["recompiles"] == 1
    # a THIRD signature recompiles again but warns no second time
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        f(jnp.ones(16, jnp.float32))
    assert not [w for w in rec if "wf_jit" in str(w.message)]
    e = default_registry().snapshot()["dm_probe_recompile"]
    assert e["compiles"] == 3 and e["recompiles"] == 2


def test_wf_jit_python_scalar_args_do_not_fabricate_recompiles():
    """jax.jit traces a weak-typed Python scalar once per dtype, not per
    value — the signature must key scalars by type or every distinct int
    would count as a recompile (and fire a false storm warning) while
    JAX never re-traces."""
    f = wf_jit(lambda x, k: x * k, op_name="dm_probe_scalar")
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        for k in range(4):
            f(jnp.arange(4), k)
    assert not [w for w in rec if "wf_jit" in str(w.message)]
    e = default_registry().snapshot()["dm_probe_scalar"]
    assert e["compiles"] == 1 and e["recompiles"] == 0


def test_wf_jit_fresh_instance_is_compile_not_recompile():
    a = wf_jit(lambda x: x - 1, op_name="dm_probe_instances")
    a(jnp.ones(8, jnp.float32))
    b = wf_jit(lambda x: x - 1, op_name="dm_probe_instances")
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        b(jnp.ones(24, jnp.float32))   # new instance, new shape: no storm
    assert not [w for w in rec if "wf_jit" in str(w.message)]
    e = default_registry().snapshot()["dm_probe_instances"]
    assert e["compiles"] == 2 and e["recompiles"] == 0


def test_operator_shape_change_recompiles():
    """The real op wiring: one MapTPU jit fed two capacities."""
    m = wf.MapTPU_Builder(lambda t: {"v": t["v"] * 2.0}) \
        .withName("dm_op_shape").build()
    m._jit_step({"v": jnp.ones(64, jnp.float32)}, jnp.ones(64, bool))
    with pytest.warns(RuntimeWarning, match="signature changed"):
        m._jit_step({"v": jnp.ones(128, jnp.float32)}, jnp.ones(128, bool))
    e = default_registry().snapshot()["dm_op_shape"]
    assert e["compiles"] == 2 and e["recompiles"] == 1


# ---------------------------------------------------------------------------
# stats()["Device"]: per-op table, CPU memory guard, staging accounting
# ---------------------------------------------------------------------------

def test_device_section_schema_and_cpu_guard(ran_stats):
    _, st = ran_stats
    dev = st["Device"]
    # per-op compile table covers the graph's device operator
    e = dev["jit"]["dm_shared_map"]
    assert e["compiles"] >= 1
    assert e["recompiles"] == 0
    assert e["compile_ms_total"] > 0
    assert e["cost"] is not None and e["cost"]["flops"] > 0
    totals = dev["jit_totals"]
    assert totals["compiles"] >= totals["ops_compiled"] >= 1
    # CPU guard: memory_stats() is None on the CPU backend — reported,
    # not crashed on
    assert dev["memory"], "no local devices reported"
    for d in dev["memory"]:
        assert d["platform"] == "cpu"
        assert d["stats"] is None
    assert dev["live_buffers"]["count"] >= 0
    # the staged run shipped real bytes through the staging accounting
    assert dev["staging"]["staged_device_bytes_total"] > 0
    assert dev["staging"]["staged_device_batches_total"] > 0
    json.dumps(dev)     # the whole section must ship in NEW_REPORT


# ---------------------------------------------------------------------------
# OpenMetrics exposition: golden format, escaping, typing, round trip
# ---------------------------------------------------------------------------

def test_openmetrics_golden_format_real_stats(ran_stats):
    _, st = ran_stats
    text = render_openmetrics(st)
    fams = parse_exposition(text)       # raises on any format violation
    assert fams["wf_operator_outputs_total"]["type"] == "counter"
    assert fams["wf_queue_depth"]["type"] == "gauge"
    assert fams["wf_throughput_tps"]["type"] == "gauge"
    assert fams["wf_jit_compiles_total"]["type"] == "counter"
    assert fams["wf_service_latency_usec"]["type"] == "histogram"
    # histogram really exposes buckets: _bucket/_sum/_count samples
    names = {n for n, _, _ in fams["wf_service_latency_usec"]["samples"]}
    assert names == {"wf_service_latency_usec_bucket",
                     "wf_service_latency_usec_sum",
                     "wf_service_latency_usec_count"}
    # every sample carries the app label
    for fam in fams.values():
        for _, labels, _ in fam["samples"]:
            assert labels.get("app") == "dm_shared"
    # watermark-lag gauge exists for the graph's operators
    lag_ops = {lab["operator"] for _, lab, _
               in fams["wf_watermark_lag_usec"]["samples"]}
    assert "dm_shared_map" in lag_ops or "snk" in lag_ops


def test_openmetrics_label_escaping_round_trips():
    nasty = 'evil"op\\name\nnewline'
    stats = {
        "PipeGraph_name": 'app"with\\quirks',
        "Operators": [{"Operator_name": nasty,
                       "Replicas": [{"Inputs_received": 3,
                                     "Outputs_sent": 2}]}],
    }
    text = render_openmetrics(stats)
    fams = parse_exposition(text)
    ops = [lab["operator"] for _, lab, _
           in fams["wf_operator_outputs_total"]["samples"]]
    assert ops == [nasty]     # escaped on the wire, intact after parsing


def test_openmetrics_parser_rejects_violations():
    ok = ("# TYPE wf_x_total counter\n"
          "wf_x_total 1\n")
    parse_exposition(ok)
    with pytest.raises(ValueError, match="without a preceding"):
        parse_exposition("wf_orphan 1\n")
    with pytest.raises(ValueError, match="decrease"):
        parse_exposition(
            "# TYPE wf_h histogram\n"
            'wf_h_bucket{le="1"} 5\n'
            'wf_h_bucket{le="2"} 3\n'
            'wf_h_bucket{le="+Inf"} 3\n'
            "wf_h_sum 4\n"
            "wf_h_count 3\n")
    with pytest.raises(ValueError, match="no \\+Inf"):
        parse_exposition(
            "# TYPE wf_h histogram\n"
            'wf_h_bucket{le="1"} 5\n'
            "wf_h_sum 4\n"
            "wf_h_count 5\n")
    with pytest.raises(ValueError, match="_count"):
        parse_exposition(
            "# TYPE wf_h histogram\n"
            'wf_h_bucket{le="+Inf"} 4\n'
            "wf_h_sum 4\n"
            "wf_h_count 5\n")
    with pytest.raises(ValueError, match="negative counter"):
        parse_exposition("# TYPE wf_c_total counter\nwf_c_total -1\n")


def test_wf_metrics_check_round_trip(ran_stats, tmp_path):
    g, st = ran_stats
    path = tmp_path / "stats.json"
    path.write_text(json.dumps(st))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_metrics.py"),
         str(path), "--check"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "OK" in proc.stdout
    # render mode emits parseable text too
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_metrics.py"),
         str(path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0
    parse_exposition(proc.stdout)


# ---------------------------------------------------------------------------
# dashboard /metrics endpoint
# ---------------------------------------------------------------------------

def test_dashboard_metrics_endpoint():
    import urllib.request
    from windflow_tpu.monitoring import DashboardServer
    server = DashboardServer(tcp_port=0, http_port=0).start()
    try:
        g, _ = _graph("dm_dash", tracing_enabled=True,
                      dashboard_host="127.0.0.1",
                      dashboard_port=server.tcp_port)
        g.run()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.http_port}/metrics",
                timeout=5) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        fams = parse_exposition(text)
        # the acceptance surface: throughput, latency histograms,
        # watermark lag, and the device plane, all scrapeable
        for family in ("wf_operator_outputs_total", "wf_throughput_tps",
                       "wf_service_latency_usec", "wf_watermark_lag_usec",
                       "wf_jit_compiles_total", "wf_live_buffer_bytes"):
            assert fams[family]["samples"], f"{family} empty"
        apps = {lab.get("app") for _, lab, _
                in fams["wf_operator_outputs_total"]["samples"]}
        assert "dm_dash" in apps
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# monitor gauge starvation (satellite): sample without a dashboard
# ---------------------------------------------------------------------------

def test_monitor_samples_without_dashboard():
    from windflow_tpu.monitoring.monitor import MonitoringThread
    # dashboard_port points at nothing: connection refused -> no shipping
    g, _ = _graph("dm_headless", n=20000, cap=256,
                  dashboard_port=1)     # port 1: guaranteed refused
    g.start()
    mt = MonitoringThread(g, interval=0.02)
    mt.start()
    deadline = time.monotonic() + 2.0
    while not g.is_done() and time.monotonic() < deadline:
        g.step()
        time.sleep(0.002)
    g.wait_end()
    mt.stop()
    assert mt.active is False           # never connected
    # the regression: before the split, zero samples were taken when the
    # TCP connection was down and the rolling windows never advanced
    assert mt.samples_taken >= 1
    assert len(g._thr_samples) >= 2


# ---------------------------------------------------------------------------
# profiler bridge + annotation off-path budget
# ---------------------------------------------------------------------------

@pytest.mark.slow   # jax.profiler start/stop serializes an xplane capture
#                     (~17s on CPU CI regardless of capture length)
def test_profile_bridge_writes_capture(tmp_path):
    g, seen = _graph("dm_prof", n=20000, cap=256)
    g.start()
    d = g.profile(duration_ms=150, log_dir=str(tmp_path / "xprof"))
    g.wait_end()
    assert seen
    assert os.path.isdir(d)
    prof = os.path.join(d, "plugins", "profile")
    assert os.path.isdir(prof) and os.listdir(prof)


def test_dump_trace_carries_profiler_cross_reference(tmp_path):
    g, _ = _graph("dm_xref")
    g.run()
    path = g.dump_trace(str(tmp_path / "dm_xref_trace.json"))
    with open(path) as f:
        trace = json.load(f)
    other = trace["otherData"]
    assert "trace:<trace_id>" in other["profiler_annotation_format"]
    assert other["profiler_dir"]


class _CountingAnnotation:
    count = 0

    def __init__(self, *a, **k):
        _CountingAnnotation.count += 1

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def test_annotation_off_path_is_one_attribute_check(monkeypatch):
    """Recorder off => no trace lane => the dispatch path must never even
    construct a TraceAnnotation (the documented off-path budget: one
    `is not None` check per batch)."""
    monkeypatch.setattr(jax.profiler, "TraceAnnotation",
                        _CountingAnnotation)
    _CountingAnnotation.count = 0
    g, _ = _graph("dm_annot_off", flight_recorder=False)
    g.run()
    assert _CountingAnnotation.count == 0
    # and with sampling on, the sampled batches ARE annotated
    _CountingAnnotation.count = 0
    g, _ = _graph("dm_annot_on", flight_recorder=True,
                  trace_sample_every=2)
    g.run()
    assert _CountingAnnotation.count > 0
