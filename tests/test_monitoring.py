"""Observability tests: diagram generation, stats JSON schema/dump, and the
dashboard TCP protocol against a stub server (the reference tests the
protocol with ``dashboard/Stub_Client``; here the stub is the server side)."""

import json
import socket
import struct
import sys
import threading

import windflow_tpu as wf
from windflow_tpu.monitoring import to_dot, to_svg


def build_graph(tracing=False, port=None):
    cfg = None
    if tracing:
        import dataclasses
        from windflow_tpu.basic import default_config
        cfg = dataclasses.replace(default_config, tracing_enabled=True,
                                  dashboard_host="127.0.0.1",
                                  dashboard_port=port)
    src = (wf.Source_Builder(
        lambda: iter({"key": i % 4, "value": i} for i in range(5000)))
        .withName("src").build())
    mp = (wf.Map_Builder(lambda t: {"key": t["key"], "value": t["value"] + 1})
          .withName("mapper").withParallelism(2).build())
    snk = wf.Sink_Builder(lambda t, ctx=None: None).withName("sink").build()
    g = wf.PipeGraph("monitored_app", wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(src).add(mp).add_sink(snk)
    return g


def test_dot_and_svg_diagram():
    g = build_graph()
    g.start()
    dot = to_dot(g)
    assert 'digraph "monitored_app"' in dot
    assert "src" in dot and "mapper" in dot and "sink" in dot
    assert dot.count("->") == 2
    svg = to_svg(g)
    assert svg.lstrip().startswith("<")
    assert "svg" in svg[:200]
    while not g.is_done():
        g.step()
    g._finalize()


def test_stats_schema_and_dump(tmp_path):
    g = build_graph()
    g.run()
    st = g.stats()
    for field in ("PipeGraph_name", "Mode", "Backpressure", "Dropped_tuples",
                  "Operator_number", "Thread_number", "rss_size_kb",
                  "Operators"):
        assert field in st, field
    assert st["Operator_number"] == 3
    if sys.platform == "linux":  # _rss_kb reads /proc/self/statm
        assert st["rss_size_kb"] > 0
    mapper = next(o for o in st["Operators"]
                  if o["Operator_name"] == "mapper")
    assert len(mapper["Replicas"]) == 2
    assert sum(r["Inputs_received"] for r in mapper["Replicas"]) == 5000
    path = g.dump_stats(str(tmp_path))
    with open(path) as f:
        assert json.load(f)["PipeGraph_name"] == "monitored_app"


class StubDashboard(threading.Thread):
    """Speaks the server side of the reference protocol
    (``monitoring.hpp:226-260``): ack every message with status 0, hand out
    app identifier 77."""

    def __init__(self):
        super().__init__(daemon=True)
        self.server = socket.socket()
        self.server.bind(("127.0.0.1", 0))
        self.server.listen(1)
        self.port = self.server.getsockname()[1]
        self.messages = []

    def _recv(self, conn, n):
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def run(self):
        conn, _ = self.server.accept()
        try:
            # NEW_APP: [type, len] + payload, ack [0, id]
            mtype, length = struct.unpack(">ii", self._recv(conn, 8))
            payload = self._recv(conn, length)
            self.messages.append((mtype, payload))
            conn.sendall(struct.pack(">ii", 0, 77))
            # reports until the client closes
            while True:
                try:
                    hdr = self._recv(conn, 12)
                except ConnectionError:
                    break
                mtype, ident, length = struct.unpack(">iii", hdr)
                payload = self._recv(conn, length)
                self.messages.append((mtype, ident, payload))
                conn.sendall(struct.pack(">ii", 0, 0))
        finally:
            conn.close()
            self.server.close()


def test_dashboard_protocol_roundtrip():
    stub = StubDashboard()
    stub.start()
    g = build_graph(tracing=True, port=stub.port)
    g.run()
    stub.join(timeout=5)
    assert stub.messages, "dashboard never contacted"
    # registration: type 0, NUL-terminated SVG payload
    mtype, payload = stub.messages[0]
    assert mtype == 0
    assert payload.endswith(b"\0")
    assert b"svg" in payload[:200].lower() or b"<" in payload[:10]
    # final message: END_APP (type 2) with the handed-out identifier and a
    # parseable JSON stats report
    mtype, ident, payload = stub.messages[-1]
    assert mtype == 2
    assert ident == 77
    report = json.loads(payload.rstrip(b"\0"))
    assert report["PipeGraph_name"] == "monitored_app"
    assert report["Operator_number"] == 3


def test_monitoring_switches_off_when_unreachable():
    """Reference behavior (monitoring.hpp:197-200): no dashboard, no harm."""
    # grab a port with nothing listening
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    g = build_graph(tracing=True, port=dead_port)
    g.run()  # must complete normally
    assert g.is_done()
    assert g._monitor is None  # stopped and cleared at finalize
