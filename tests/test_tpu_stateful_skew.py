"""Skew and async hardening of stateful TPU operators (VERDICT r1 item 8):
dense-key mode removes the per-batch host interning round-trip, and the
associative-update path replaces the rank wavefront (depth = max per-key
multiplicity) with a log-depth segmented scan, so a single-hot-key batch
costs about the same as a uniform one."""

import time

import pytest

import windflow_tpu as wf


def _run_running_sum(records, batch, *, dense=False, assoc=False,
                     num_slots=64):
    got = []
    src = (wf.Source_Builder(lambda: iter(records))
           .withOutputBatchSize(batch).build())
    b = (wf.MapTPU_Builder(
            lambda t, s: ({"key": t["key"], "value": s + t["value"]},
                          s + t["value"]))
         .withKeyBy(lambda t: t["key"]).withInitialState(0.0)
         .withNumKeySlots(num_slots))
    if dense:
        b = b.withDenseKeys()
    if assoc:
        b = b.withAssociativeUpdate(
            lift=lambda t: t["value"],
            comb=lambda a, b: a + b,
            project=lambda t, s: {"key": t["key"], "value": s})
    m = b.build()
    snk = wf.Sink_Builder(
        lambda t: got.append((t["key"], t["value"])) if t else None).build()
    g = wf.PipeGraph("skew", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(m).add_sink(snk)
    t0 = time.perf_counter()
    g.run()
    return got, time.perf_counter() - t0, m


def _oracle(records):
    run, out = {}, []
    for t in records:
        run[t["key"]] = run.get(t["key"], 0.0) + t["value"]
        out.append((t["key"], run[t["key"]]))
    return out


def _recs(n, n_keys):
    return [{"key": i % n_keys, "value": float(i % 7 + 1)} for i in range(n)]


def test_dense_keys_skips_interning():
    records = _recs(512, 8)
    got, _, op = _run_running_sum(records, 64, dense=True)
    assert sorted(got) == sorted(_oracle(records))
    assert len(op._interner) == 0, "dense-key path must not intern on host"


def test_dense_keys_out_of_range_masked():
    records = _recs(128, 8) + [{"key": 99, "value": 1.0}] * 16  # 99 >= 64
    got, _, op = _run_running_sum(records, 16, dense=True)
    assert sorted(got) == sorted(_oracle(_recs(128, 8)))


def test_assoc_running_sum_matches_wavefront():
    records = _recs(600, 6)
    for dense in (False, True):
        got, _, _ = _run_running_sum(records, 64, dense=dense, assoc=True)
        assert sorted(got) == sorted(_oracle(records))


@pytest.mark.slow   # 16k-capacity timing VERDICT (~6s): nightly leg; the fast assoc A/B above keeps tier-1 coverage
def test_assoc_single_hot_key_no_skew_penalty():
    """All tuples share ONE key at a large capacity: the wavefront would run
    `capacity` sequential sweeps; the associative scan must stay within ~2x
    the uniform-key time (VERDICT done-criterion, with CI slack)."""
    n, cap = 32768, 16384
    hot = [{"key": 3, "value": 1.0} for _ in range(n)]
    uniform = [{"key": i % 64, "value": 1.0} for i in range(n)]

    # warm both compile caches with one small run each
    _run_running_sum(hot[:cap], cap, dense=True, assoc=True)
    got_u, t_uniform, _ = _run_running_sum(uniform, cap, dense=True,
                                           assoc=True)
    got_h, t_hot, _ = _run_running_sum(hot, cap, dense=True, assoc=True)

    assert sorted(got_h) == sorted(_oracle(hot))
    assert sorted(got_u) == sorted(_oracle(uniform))
    assert t_hot <= 3.0 * t_uniform + 0.5, \
        f"hot-key {t_hot:.2f}s vs uniform {t_uniform:.2f}s"


def test_assoc_stateful_filter():
    """Associative stateful filter: keep the first 3 tuples of each key
    (state = count including self; project keeps count <= 3)."""
    records = _recs(240, 5)
    kept = []
    src = (wf.Source_Builder(lambda: iter(records))
           .withOutputBatchSize(32).build())
    f = (wf.FilterTPU_Builder(lambda t, s: (True, s))
         .withKeyBy(lambda t: t["key"]).withInitialState(0)
         .withNumKeySlots(16).withDenseKeys()
         .withAssociativeUpdate(
             lift=lambda t: 1,
             comb=lambda a, b: a + b,
             project=lambda t, s: s <= 3)
         .build())
    snk = wf.Sink_Builder(
        lambda t: kept.append(t["key"]) if t else None).build()
    g = wf.PipeGraph("assoc_filter", wf.ExecutionMode.DEFAULT)
    g.add_source(src).add(f).add_sink(snk)
    g.run()
    from collections import Counter
    assert Counter(kept) == Counter({k: 3 for k in range(5)})
