"""Wire plane (windflow_tpu/wire.py): columnar wire compression with
in-prelude device decode, key-aligned mesh ingest, and the byte-
accounting honesty split.

Contracts pinned here (docs/OBSERVABILITY.md "Wire plane", docs/PERF.md
round 13):

* every codec round-trips BIT-EXACTLY over adversarial lanes (constant,
  random, sorted-with-gaps, all-null, dtype extremes incl. int64
  min/max wrap-around deltas and float NaN payload bits);
* compressed and kill-switch runs are record-for-record identical
  across the chaos families, and a durability kill→restore→diff holds
  with compression on;
* decompression adds ZERO dispatches — the decode rides the existing
  ``staging.unpack`` program, pinned through the jit registry;
* spec-less edges downgrade to raw passthrough with a named WF606;
* the StagingPool keys wire buffers by SIZE CLASS, so codec churn
  cannot thrash it;
* key-aligned mesh ingest reproduces the all_gather path's outputs
  record for record while the modeled ICI bytes drop;
* a two-process DCN cell (slow) asserts each host stages only its
  local shard (tests/_multihost_worker.py carries the assertion —
  re-exercised here so this file owns the fast-gate entry point).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import windflow_tpu as wf
from windflow_tpu import staging, wire
from windflow_tpu.monitoring.jit_registry import default_registry


# ---------------------------------------------------------------------------
# per-codec encode/decode round trips (adversarial lanes)
# ---------------------------------------------------------------------------

def _roundtrip(lane: np.ndarray, cap: int, tss=None):
    """Encode one payload lane + ts lane through the wire and decode it
    with the traced program; returns (decoded_lane, decoded_ts, fmt)."""
    dt = str(lane.dtype)
    b = staging.PackedBatchBuilder((dt,), cap)
    tss = np.arange(cap, dtype=np.int64) * 17 if tss is None else tss
    b.append([lane], tss)
    buf = b.finish()
    enc = wire.WireEncoder((dt,), cap, reseed_every=4)
    wbuf, fmt = enc.encode(buf.copy())
    if fmt is None:
        return lane, tss, None     # compression lost: logical ships
    cols = jax.jit(wire.build_wire_decode(fmt, (dt,), cap))(
        jnp.asarray(wbuf))
    return np.asarray(cols[0]), np.asarray(cols[1]), fmt


_RNG = np.random.default_rng(0)
_CAP = 2048
ADVERSARIAL = {
    "constant_i32": np.full(_CAP, -7, np.int32),
    "all_null_i32": np.zeros(_CAP, np.int32),
    "all_null_f32": np.zeros(_CAP, np.float32),
    "random_i32": _RNG.integers(-2**31, 2**31, _CAP).astype(np.int32),
    "random_f32": _RNG.random(_CAP, dtype=np.float32),
    "nan_inf_f32": np.tile(np.array([np.nan, np.inf, -np.inf, -0.0],
                                    np.float32), _CAP // 4),
    "low_card_i32": _RNG.integers(0, 61, _CAP).astype(np.int32),
    "sorted_gaps_i64": np.sort(
        _RNG.integers(0, 10**9, _CAP)).astype(np.int64),
    "cadence_i64": np.arange(_CAP, dtype=np.int64) * 1_000 + 5,
    "extremes_i64": np.tile(np.array(
        [np.iinfo(np.int64).min, np.iinfo(np.int64).max, 0, -1],
        np.int64), _CAP // 4),
    "extremes_i32": np.tile(np.array(
        [np.iinfo(np.int32).min, np.iinfo(np.int32).max], np.int32),
        _CAP // 2),
    "big_u64": _RNG.integers(0, 2**63, _CAP).astype(np.uint64)
    + np.uint64(2**63 - 1),
    "uint32_full": _RNG.integers(0, 2**32, _CAP).astype(np.uint32),
}


@pytest.mark.parametrize("name", sorted(ADVERSARIAL))
def test_codec_round_trip_bit_exact(name):
    lane = ADVERSARIAL[name]
    got, got_ts, fmt = _roundtrip(lane, _CAP)
    # bit-exact: NaN payload bits and negative zero must survive, so
    # compare the raw bytes, not values
    assert np.array_equal(np.asarray(got).view(np.uint8),
                          lane.view(np.uint8)), name
    assert np.array_equal(got_ts, np.arange(_CAP, dtype=np.int64) * 17)


def test_codec_round_trip_partial_batch_zero_tail():
    """finish() zero-pads the tail; the decode must reproduce those
    zeros exactly (downstream equality depends on it)."""
    cap, n = 256, 100
    lane = _RNG.integers(0, 50, n).astype(np.int32)
    b = staging.PackedBatchBuilder(("int32",), cap)
    b.append([lane], np.arange(n, dtype=np.int64))
    buf = b.finish()
    enc = wire.WireEncoder(("int32",), cap, reseed_every=1)
    wbuf, fmt = enc.encode(buf.copy())
    assert fmt is not None
    cols = jax.jit(wire.build_wire_decode(fmt, ("int32",), cap))(
        jnp.asarray(wbuf))
    got = np.asarray(cols[0])
    assert np.array_equal(got[:n], lane) and not got[n:].any()
    assert int(wbuf[-1]) == n       # fill count survives the re-pack


def test_codec_misfit_degrades_to_raw_then_reseeds():
    """A lane whose data stops matching its codec ships raw for that
    batch (counted) and the next batch re-chooses."""
    cap = 512
    enc = wire.WireEncoder(("int32",), cap, reseed_every=100)

    def encode(lane):
        b = staging.PackedBatchBuilder(("int32",), cap)
        b.append([lane], np.zeros(cap, np.int64))
        return enc.encode(b.finish().copy())

    _, fmt1 = encode(np.full(cap, 3, np.int32))     # seeds CONST
    assert fmt1.codecs[0].kind == wire.CONST
    lane2 = _RNG.integers(-2**31, 2**31, cap).astype(np.int32)
    wbuf2, fmt2 = encode(lane2)
    assert enc.stats.fallback_lanes >= 1
    if fmt2 is not None:            # ts still compresses: wire may win
        assert fmt2.codecs[0].kind == wire.RAW
        cols = jax.jit(wire.build_wire_decode(fmt2, ("int32",), cap))(
            jnp.asarray(wbuf2))
        assert np.array_equal(np.asarray(cols[0]), lane2)
    _, fmt3 = encode(np.full(cap, 9, np.int32))     # forced reseed
    assert fmt3.codecs[0].kind == wire.CONST
    assert enc.stats.reseeds >= 2


# ---------------------------------------------------------------------------
# pool size-class keying (the codec-churn thrash fix)
# ---------------------------------------------------------------------------

def test_size_class_quantizes_and_bounds_waste():
    assert staging.size_class(1) == 256
    assert staging.size_class(256) == 256
    for n in (257, 1000, 5000, 65536, 100000):
        c = staging.size_class(n)
        assert c >= n and (c - n) / c <= 0.25
        assert staging.size_class(c) == c       # classes are fixpoints


def test_pool_reuses_across_codec_churn():
    """Two wire batches of DIFFERENT encoded sizes in the same size
    class must hit the pool, not mint a fresh slot per batch."""
    pool = staging.StagingPool(depth=4)
    a = pool.acquire(staging.size_class(5000))
    pool.release(a, None)
    hits0 = pool.hits
    b = pool.acquire(staging.size_class(5100))   # same class as 5000
    assert staging.size_class(5000) == staging.size_class(5100)
    assert pool.hits == hits0 + 1 and b is a


def test_wire_encoder_acquires_class_sized_buffers():
    cap = 4096
    enc = wire.WireEncoder(("int32",), cap, reseed_every=1)
    pool = staging.StagingPool(depth=4)
    lane = _RNG.integers(0, 200, cap).astype(np.int32)
    b = staging.PackedBatchBuilder(("int32",), cap, pool=pool)
    b.append([lane], np.arange(cap, dtype=np.int64))
    wbuf, fmt = enc.encode(b.finish(), pool=pool)
    assert fmt is not None
    assert wbuf.shape[0] == staging.size_class(
        wire.wire_words_total(fmt.codecs, ("int32", "int64"), cap))
    assert fmt.words == wbuf.shape[0]
    # the logical scratch went back to the pool (host-only, no gate)
    assert pool.releases >= 1


# ---------------------------------------------------------------------------
# graph-level A/B: compressed vs kill-switch, dispatch pin, stats
# ---------------------------------------------------------------------------

def _ab_graph(wire_on: bool, n=3000, cap=256):
    got = []
    rng = np.random.default_rng(11)
    ks = rng.integers(0, 64, n)
    vs = rng.integers(0, 1000, n)
    records = [{"key": int(k), "v": np.float32(v)}
               for k, v in zip(ks, vs)]
    cfg = dataclasses.replace(wf.default_config)
    cfg.wire_compression = wire_on
    src = (wf.Source_Builder(lambda: iter(records))
           .withOutputBatchSize(cap)
           .withRecordSpec({"key": np.int64(0), "v": np.float32(0.0)})
           .build())
    red = (wf.ReduceTPU_Builder(
        lambda a, b: {"key": b["key"], "v": a["v"] + b["v"]})
        .withKeyBy(lambda t: t["key"]).build())
    g = wf.PipeGraph("wire_ab", config=cfg)
    g.add_source(src).add(red).add_sink(
        wf.Sink_Builder(lambda r: got.append(r)
                        if r is not None else None).build())
    g.run()
    return got, g


def test_compressed_vs_killswitch_record_identical():
    on, g_on = _ab_graph(True)
    off, g_off = _ab_graph(False)
    key = lambda r: (r["key"], round(float(r["v"]), 6))
    assert sorted(map(key, on)) == sorted(map(key, off))
    ws = g_on.stats()["Staging"]["Wire"]
    assert ws["enabled"] and ws["batches"] > 0
    assert ws["compression_ratio"] > 1.5
    assert ws["wire_bytes"] < ws["logical_bytes"]
    assert isinstance(ws["codecs"], list) and ws["codecs"]
    ws_off = g_off.stats()["Staging"]["Wire"]
    assert ws_off["batches"] == 0 and ws_off["encoders"] == 0


def test_byte_accounting_wire_vs_logical_split():
    _, g_on = _ab_graph(True)
    st = g_on.stats()
    assert 0 < st["Bytes_H2D_total"] < st["Bytes_H2D_logical_total"]
    _, g_off = _ab_graph(False)
    st_off = g_off.stats()
    assert st_off["Bytes_H2D_total"] == st_off["Bytes_H2D_logical_total"]
    # per-host attribution in the sweep ledger's wire subsection
    w = st["Sweep"]["wire"]
    assert w["process_count"] == 1 and w["process_index"] == 0
    assert w["wire_bytes"] == st["Bytes_H2D_total"]
    assert w["logical_bytes"] == st["Bytes_H2D_logical_total"]
    assert w["compression_ratio"] > 1.0


def test_zero_extra_dispatches_decode_in_unpack():
    """The decode rides the existing staging.unpack program: dispatches
    per staged batch are IDENTICAL compressed vs kill-switch (the jit
    registry is the witness)."""
    reg = default_registry()

    def unpack_disp_per_batch(wire_on):
        base = reg.dispatch_counts().get("staging.unpack", 0)
        _, g = _ab_graph(wire_on)
        ws = g.stats()["Staging"]["Wire"]
        batches = sum(r.stats.device_programs_launched
                      for op in g._operators if op.name == "reduce_tpu"
                      for r in op.replicas)
        disp = reg.dispatch_counts().get("staging.unpack", 0) - base
        return disp, ws

    d_on, ws_on = unpack_disp_per_batch(True)
    d_off, _ = unpack_disp_per_batch(False)
    assert ws_on["batches"] > 0
    assert d_on == d_off, (d_on, d_off)     # decode added ZERO dispatches


def test_openmetrics_wire_families_round_trip():
    """The wf_wire_* families render the SAME numbers stats() carries
    and survive the strict parser round trip."""
    from windflow_tpu.monitoring.openmetrics import (parse_exposition,
                                                     render_openmetrics)
    _, g = _ab_graph(True)
    ws = g.stats()["Staging"]["Wire"]
    text = render_openmetrics(g.stats(), {"app": "wire_ab"})
    parse_exposition(text)      # strict: raises on any violation
    for fam in ("wf_wire_bytes", "wf_wire_logical_bytes",
                "wf_wire_batches", "wf_wire_compression_ratio"):
        assert fam in text, fam
    # same-numbers contract: the rendered sample carries stats()' value
    assert f"wf_wire_bytes_total{{" in text or "wf_wire_bytes" in text
    line = [ln for ln in text.splitlines()
            if ln.startswith("wf_wire_bytes")][0]
    assert float(line.rsplit(" ", 1)[1]) == float(ws["wire_bytes"])


def test_wire_auto_resolution():
    """The default is "auto": off on the CPU backend (host==device, a
    memcpy wire — compression is pure overhead), on for accelerators;
    explicit values force either way."""
    cfg = dataclasses.replace(wf.default_config)
    assert cfg.wire_compression == "auto" or isinstance(
        cfg.wire_compression, bool)
    cfg.wire_compression = "auto"
    assert wire.wire_enabled(cfg) is False      # CPU test backend
    cfg.wire_compression = True
    assert wire.wire_enabled(cfg) is True
    cfg.wire_compression = "0"
    assert wire.wire_enabled(cfg) is False


def test_wf606_specless_source_downgrades_named():
    _cfg = dataclasses.replace(wf.default_config, wire_compression=True)
    g = wf.PipeGraph("w606", config=_cfg)
    g.add_source(wf.Source_Builder(lambda: iter([]))
                 .withOutputBatchSize(8).build()) \
        .add(wf.MapTPU_Builder(lambda t: t).build()) \
        .add_sink(wf.Sink_Builder(lambda r: None).build())
    ds = [d for d in g.check() if d.code == "WF606"]
    assert len(ds) == 1 and ds[0].severity == "warning"
    assert "raw passthrough" in ds[0].message
    # declared spec: no WF606, and the kill switch also silences it
    g2 = wf.PipeGraph("w606_declared", config=_cfg)
    g2.add_source(wf.Source_Builder(lambda: iter([]))
                  .withOutputBatchSize(8)
                  .withRecordSpec({"v": np.float32(0)}).build()) \
        .add(wf.MapTPU_Builder(lambda t: t).build()) \
        .add_sink(wf.Sink_Builder(lambda r: None).build())
    assert not [d for d in g2.check() if d.code == "WF606"]
    cfg = dataclasses.replace(wf.default_config, wire_compression=False)
    g3 = wf.PipeGraph("w606_off", config=cfg)
    g3.add_source(wf.Source_Builder(lambda: iter([]))
                  .withOutputBatchSize(8).build()) \
        .add(wf.MapTPU_Builder(lambda t: t).build()) \
        .add_sink(wf.Sink_Builder(lambda r: None).build())
    assert not [d for d in g3.check() if d.code == "WF606"]


def test_specless_source_ships_raw_passthrough():
    """The WF606 downgrade is real: a spec-less source stages with no
    encoder attached even though wire compression is globally on."""
    got = []
    records = [{"key": i % 8, "v": np.float32(i)} for i in range(512)]
    src = (wf.Source_Builder(lambda: iter(records))
           .withOutputBatchSize(128).build())     # NO record spec
    g = wf.PipeGraph("wire_raw", config=dataclasses.replace(
        wf.default_config, wire_compression=True))
    g.add_source(src).add(
        wf.MapTPU_Builder(lambda t: {"key": t["key"],
                                     "v": t["v"] * 2.0}).build()) \
        .add_sink(wf.Sink_Builder(lambda r: got.append(r)
                                  if r is not None else None).build())
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")     # the named WF606
        g.run()
    ws = g.stats()["Staging"]["Wire"]
    assert ws["enabled"] and ws["encoders"] == 0 and ws["batches"] == 0
    assert len(got) == 512


# ---------------------------------------------------------------------------
# chaos families: compressed vs kill-switch A/B + kill→restore→diff
# ---------------------------------------------------------------------------

def _chaos_output(family, wire_on, tmp_path, tag, kill=False, n=1024):
    from windflow_tpu.durability import chaos
    import windflow_tpu.basic as basic
    ck = str(tmp_path / f"ck_{tag}")
    out = str(tmp_path / f"out_{tag}") \
        if family == "stateless_chain" else None
    cell = chaos.make_cell(family, ck, out_dir=out, n=n)
    old = basic.default_config.wire_compression
    basic.default_config.wire_compression = wire_on
    try:
        if kill:
            g = chaos.run_killed_and_restored(
                cell["factory"], chaos.default_kill(family, "mid_epoch"))
        else:
            g = chaos.run_baseline(cell["factory"])
        # wire really engaged on the compressed run of device families
        if wire_on and family != "reduce":
            ws = g.stats()["Staging"]["Wire"]
            assert ws["batches"] > 0, (family, ws)
    finally:
        basic.default_config.wire_compression = old
    return cell["read"]()


@pytest.mark.parametrize("family", ["window_cb", "window_tb", "reduce",
                                    "stateless_chain"])
def test_chaos_family_ab_compressed_vs_killswitch(family, tmp_path):
    from windflow_tpu.durability.chaos import diff_records
    on = _chaos_output(family, True, tmp_path, f"{family}_on")
    off = _chaos_output(family, False, tmp_path, f"{family}_off")
    assert diff_records(off, on) is None


def test_durability_kill_restore_diff_with_compression_on(tmp_path):
    """Exactly-once through a crash WITH wire compression active: the
    killed+restored run matches the uninterrupted baseline record for
    record (decode correctness across the restore boundary)."""
    from windflow_tpu.durability.chaos import diff_records
    base = _chaos_output("window_cb", True, tmp_path, "base", n=4096)
    chaosd = _chaos_output("window_cb", True, tmp_path, "killed",
                           kill=True, n=4096)
    assert diff_records(base, chaosd) is None


# ---------------------------------------------------------------------------
# key-aligned mesh ingest
# ---------------------------------------------------------------------------

def _mesh_window_run(aligned: bool, data=2):
    from windflow_tpu.parallel import mesh as M
    mesh = M.make_mesh(8, data=data)
    kk = mesh.shape[M.KEY_AXIS]
    cap, K = 16 * 8, 4 * kk
    rng = np.random.default_rng(2)
    n = 8 * cap
    records = [{"k": int(k), "v": np.float32(v)}
               for k, v in zip(rng.integers(0, K, n),
                               rng.integers(0, 100, n))]
    cfg = dataclasses.replace(wf.default_config, mesh=mesh,
                              key_aligned_ingest=aligned)
    fired = []
    src = (wf.Source_Builder(lambda: iter(records))
           .withOutputBatchSize(cap).build())
    win = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"],
                                      lambda a, b: a + b)
           .withCBWindows(8, 4).withKeyBy(lambda t: t["k"])
           .withMaxKeys(K).build())
    g = wf.PipeGraph(f"wire_mesh_{aligned}", config=cfg)
    g.add_source(src).add(win).add_sink(
        wf.Sink_Builder(lambda r: fired.append(r)
                        if r is not None else None).build())
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        g.run()
    sec = (g.stats().get("Shard") or {}).get("per_op") or {}
    ici = ((sec.get(win.name) or {}).get("ici") or {}) \
        .get("ici_bytes_per_tuple")
    wins = sorted((int(r["key"]), int(r["wid"]),
                   round(float(r["value"]), 4)) for r in fired)
    return wins, ici, getattr(win, "_ingest_mode", None)


def test_key_aligned_mesh_ingest_record_identical_and_ici_drops():
    wins_a, ici_a, mode_a = _mesh_window_run(True)
    wins_g, ici_g, mode_g = _mesh_window_run(False)
    assert mode_a == "aligned" and mode_g is None
    assert wins_a and wins_a == wins_g
    assert ici_a is not None and ici_g is not None and ici_a < ici_g


def test_key_aligned_refuses_executor_overrides():
    """Key ownership is COMPILED into the aligned consumer's sharded
    step, so an emitter-side executor move would stage the key onto a
    column whose shard silently drops it — set_override must refuse
    loudly (mesh reshard routes through rescale-on-restore, the PR-12
    executor-limits contract)."""
    from windflow_tpu.basic import WindFlowError
    from windflow_tpu.parallel import mesh as M
    from windflow_tpu.parallel.emitters import AlignedMeshStageEmitter

    class _Dest:
        def add_channel(self):
            return 0

        def receive(self, ch, msg):
            pass

    mesh = M.make_mesh(8, data=1)
    kk = mesh.shape[M.KEY_AXIS]
    em = AlignedMeshStageEmitter([(_Dest(), 0)], 8 * kk,
                                 lambda t: t["k"], mesh, 8 * kk)
    with pytest.raises(WindFlowError, match="rescale-on-restore"):
        em.set_override({5: kk - 1})
    em.set_override(None)       # clearing is a no-op, never a raise
    em.set_override({})


def test_key_aligned_skew_retention_caps_watermark():
    """A hot column that fills while others buffer must not let the
    shipped batch's watermark outrun the retained rows (retained min
    ts caps the stamp)."""
    from windflow_tpu.parallel import mesh as M
    from windflow_tpu.parallel.emitters import AlignedMeshStageEmitter

    class _Dest:
        def __init__(self):
            self.batches = []

        def add_channel(self):
            return 0

        def receive(self, ch, msg):
            self.batches.append(msg)

    mesh = M.make_mesh(8, data=1)
    kk = mesh.shape[M.KEY_AXIS]
    obs = 8 * kk
    col_cap = obs // kk
    dest = _Dest()
    em = AlignedMeshStageEmitter([(dest, 0)], obs, lambda t: t["k"],
                                 mesh, kk)      # K_local = 1: key==column
    # ONE chunk overfills column 0: the ship takes col_cap rows and
    # RETAINS the overflow (ts 100+col_cap..), so the shipped batch's
    # stamp must cap at the retained rows' min ts even though the
    # chunk's frontier ran to 10**6
    m = col_cap + 3
    em.emit_columns({"k": np.zeros(m, np.int64),
                     "v": np.arange(m, dtype=np.float32)},
                    np.arange(100, 100 + m, dtype=np.int64),
                    wm=10**6)
    assert dest.batches, "hot column must force a ship"
    db = dest.batches[0]
    retained_min_ts = 100 + col_cap
    assert db.watermark <= retained_min_ts
    assert db.frontier <= retained_min_ts
    em.flush(10**6)
    total = sum(int(np.asarray(b.valid).sum()) for b in dest.batches)
    assert total == m                           # nothing lost
    # once nothing is retained, the frontier stamp flows again
    assert dest.batches[-1].watermark == 10**6


# ---------------------------------------------------------------------------
# off-path budget + two-process DCN cell
# ---------------------------------------------------------------------------

def test_off_path_attaches_nothing():
    cfg = dataclasses.replace(wf.default_config, wire_compression=False)
    records = [{"key": i % 8, "v": np.float32(i)} for i in range(256)]
    src = (wf.Source_Builder(lambda: iter(records))
           .withOutputBatchSize(64)
           .withRecordSpec({"key": np.int64(0), "v": np.float32(0.0)})
           .build())
    g = wf.PipeGraph("wire_off", config=cfg)
    g.add_source(src).add(
        wf.MapTPU_Builder(lambda t: {"key": t["key"],
                                     "v": t["v"] * 2.0}).build()) \
        .add_sink(wf.Sink_Builder(lambda r: None).build())
    g.run()
    for _src, _route, em in wire.iter_stage_emitters(g):
        assert em._wire_on is False and not em._wire_encoders
    ws = g.stats()["Staging"]["Wire"]
    assert ws["enabled"] is False and ws["batches"] == 0


@pytest.mark.slow  # ~40s: spawns two OS processes + a TCP coordinator
def test_two_process_dcn_per_host_wire_attribution():
    """Each host packs and stages only its LOCAL chips' shard, with
    per-host wire/H2D bytes attributed in the sweep ledger — the
    assertions live in tests/_multihost_worker.py (per-host wire ledger
    leg); this cell owns running them.

    Retried once on the PRE-EXISTING Gloo infra abort (rc=-6,
    ``pair.cc preamble`` enforce — reproducible at the PR-12 seed with
    no wire changes applied): a box-load-dependent race in the CPU
    collective transport, not a product failure mode this cell tests."""
    import socket
    import subprocess
    import sys as _sys

    def one_round():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        worker = str(__import__("pathlib").Path(__file__).with_name(
            "_multihost_worker.py"))
        import os as _os
        env = {k: v for k, v in _os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        repo = str(
            __import__("pathlib").Path(__file__).resolve().parents[1])
        env["PYTHONPATH"] = repo + (_os.pathsep + env["PYTHONPATH"]
                                    if env.get("PYTHONPATH") else "")
        procs = [subprocess.Popen(
            [_sys.executable, worker, str(i), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env) for i in range(2)]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=300)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            raise AssertionError("two-process wire cell hung")
        return procs, outs

    for attempt in range(3):            # documented infra retries: the
        procs, outs = one_round()       # abort rate rises with box load
        infra = any(p.returncode == -6 for p in procs) and any(
            "gloo" in o or "Gloo" in o or "Coordination" in o
            for o in outs)
        if not infra:
            break
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0 and "per-host wire ledger OK" in out, \
            f"worker {i} failed (rc={p.returncode}):\n{out[-3000:]}"
