"""The bench's unrolled-chain fallback (bench.make_unrolled_chain) must
measure the SAME computation as sequential per-dispatch stepping: state
threads through every unrolled step and the fired-window accumulators
match the windows the sequential path fires.

The fallback exists because the axon remote-compile helper rejects any
``lax.scan`` around the FFAT step (HTTP 500 even at scan length 1 — r5
bisect) — so on that backend the chained kernel number comes from this
code path, and a silent divergence here would corrupt the headline
metric.  Distinct batches per unrolled step are part of the contract
(shared batches let XLA CSE the payload-only grouping stages and the
chain measures a several-times-lighter program)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bench
from windflow_tpu.windows.ffat_kernels import make_ffat_state, make_ffat_step

CAP, K, WIN, SLIDE = 2048, 16, 256, 32


def _mk_step():
    Pn = math.gcd(WIN, SLIDE)
    R, D = WIN // Pn, SLIDE // Pn
    step = make_ffat_step(CAP, K, Pn, R, D, lambda x: x["v"],
                          lambda a, b: a + b, lambda x: x["k"])
    state = make_ffat_state(jnp.zeros((), jnp.float32), K, R)
    return step, state


def _mk_batches(n, rng):
    out = []
    for i in range(n):
        valid = jnp.asarray(rng.random(CAP) > 0.1)   # some invalid lanes
        out.append((
            {"k": jnp.asarray(rng.integers(0, K, CAP), jnp.int32),
             "v": jnp.asarray(rng.random(CAP, dtype=np.float32))},
            jnp.asarray(np.arange(CAP) + i * CAP, jnp.int64),
            valid,
        ))
    return out


@pytest.mark.slow  # ~8s compile: validates the BENCH harness's unroll
# transform, not product semantics — rides the nightly leg
# (wfverify-round headroom pass)
def test_unrolled_chain_matches_sequential_steps():
    unroll = 3
    step_fn, state0 = _mk_step()
    rng = np.random.default_rng(7)
    batches = _mk_batches(unroll, rng)

    # sequential per-dispatch reference
    step = jax.jit(step_fn)
    st = state0
    n_ref = 0
    v_ref = 0.0
    for payload, ts, valid in batches:
        st, out, out_valid, _ = step(st, payload, ts, valid)
        n_ref += int(jnp.sum(out_valid))
        v_ref += float(jnp.sum(jnp.where(out_valid, out["value"], 0.0)))
    assert n_ref > 0, "shapes must fire windows or the test proves nothing"

    # one unrolled-chain dispatch over the same batches
    chain = bench.make_unrolled_chain(jax, step_fn, unroll)
    flat = [x for (p, ts, valid) in batches
            for x in (p["k"], p["v"], ts, valid)]
    st_ch, n_ch, v_ch = chain(state0, *flat)

    assert int(n_ch) == n_ref
    np.testing.assert_allclose(float(v_ch), v_ref, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(st_ch)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # ~8s compile: bench-harness validation, nightly leg
# (wfverify-round headroom pass)
def test_unrolled_chain_continues_across_dispatches():
    """Chained dispatches thread state exactly like 2*unroll sequential
    steps (the timing loop calls the chain repeatedly)."""
    unroll = 2
    step_fn, state0 = _mk_step()
    rng = np.random.default_rng(8)
    batches = _mk_batches(2 * unroll, rng)

    step = jax.jit(step_fn)
    st = state0
    n_ref = 0
    for payload, ts, valid in batches:
        st, out, out_valid, _ = step(st, payload, ts, valid)
        n_ref += int(jnp.sum(out_valid))

    chain = bench.make_unrolled_chain(jax, step_fn, unroll)
    st_ch = state0
    n_ch = 0
    for d in range(2):
        flat = [x for (p, ts, valid) in batches[d * unroll:(d + 1) * unroll]
                for x in (p["k"], p["v"], ts, valid)]
        st_ch, n, _ = chain(st_ch, *flat)
        n_ch += int(n)

    assert n_ch == n_ref
    for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(st_ch)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
