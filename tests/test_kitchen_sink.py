"""One DAG exercising the widest feature surface together (the integration
the reference spreads over graph/merge/split/kafka/rocksdb test binaries):

Kafka source (2 replicas, event time) → stateful FilterTPU (keyed running
count drops every 3rd occurrence) → split by key parity:
  branch 0: MapTPU ⊕ FilterTPU chained → TB FfatWindowsTPU → columnar Sink
  branch 1: host Map (broadcast ×2 monitor taps) → persistent P_Sink
with closing functions on both sinks and exact oracles for every output.
"""

import os

import numpy as np
import pytest

import windflow_tpu as wf
from windflow_tpu.kafka import InMemoryBroker, KafkaSource_Builder

N_KEYS = 4
LENGTH = 480
TWIN, TSLIDE = 12_000, 4_000


def fill(broker):
    broker.create_topic("ev", 3)
    prod = broker.producer()
    for i in range(LENGTH):
        prod.produce("ev", {"key": i % N_KEYS, "v": i, "ts": i * 1000},
                     key=str(i % N_KEYS).encode(),
                     timestamp_usec=i * 1000)
    prod.flush()


def surviving():
    """Stateful filter: per key, drop every 3rd arrival (count % 3 == 2)."""
    cnt = {}
    out = []
    for i in range(LENGTH):
        k = i % N_KEYS
        c = cnt.get(k, 0)
        if c % 3 != 2:
            out.append({"key": k, "v": i, "ts": i * 1000})
        cnt[k] = c + 1
    return out


def test_kitchen_sink(tmp_path):
    broker = InMemoryBroker()
    fill(broker)

    import jax.numpy as jnp

    src = (KafkaSource_Builder(
            lambda msg, shipper: shipper.pushWithTimestamp(
                msg.value, msg.timestamp_usec)
            if msg is not None else False)
           .withBrokers(broker).withTopics("ev").withGroupID("ks")
           .withIdleness(1000).withParallelism(2)
           .withOutputBatchSize(32).build())

    # keyed stateful filter on device: drop every 3rd occurrence per key
    sf = (wf.FilterTPU_Builder(
            lambda t, s: ((s % 3) != 2, s + 1))
          .withInitialState(jnp.zeros((), jnp.int32))
          .withKeyBy(lambda t: t["key"]).withNumKeySlots(N_KEYS)
          .withDenseKeys().build())

    win_cols = {}
    sink_closed = []

    def on_cols(c, ctx=None):
        if c is None:
            return
        for k, w, v in zip(c.cols["key"], c.cols["wid"], c.cols["value"]):
            win_cols[(int(k), int(w))] = int(v)

    tpu_map = (wf.MapTPU_Builder(
        lambda t: {"key": t["key"], "v": t["v"] * 2}).build())
    tpu_flt = wf.FilterTPU_Builder(lambda t: (t["v"] % 10) != 6).build()
    win = (wf.Ffat_WindowsTPU_Builder(lambda t: t["v"], lambda a, b: a + b)
           .withTBWindows(TWIN, TSLIDE).withKeyBy(lambda t: t["key"])
           .withMaxKeys(N_KEYS).build())
    col_sink = (wf.Sink_Builder(on_cols).withColumnarSink()
                .withClosingFunction(lambda: sink_closed.append("cols"))
                .build())

    taps = []
    tap = (wf.Map_Builder(lambda t, ctx: taps.append(ctx.replica_index) or t)
           .withParallelism(2).withBroadcast().build())
    db_path = str(tmp_path / "ks_kv")
    psink = (wf.P_Sink_Builder(lambda t, s: None)
             .withDBPath(db_path).withKeepDb(True)
             .withClosingFunction(lambda: sink_closed.append("p"))
             .build())

    g = wf.PipeGraph("kitchen_sink", wf.ExecutionMode.DEFAULT,
                     wf.TimePolicy.EVENT)
    mp = g.add_source(src)
    mp.add(sf)
    mp.split(lambda t: t["key"] % 2, 2)
    b0 = mp.select(0)
    b0.add(tpu_map)
    b0.chain(tpu_flt)
    b0.add(win).add_sink(col_sink)
    b1 = mp.select(1)
    b1.add(tap)
    b1.add_sink(psink)
    g.run()

    # oracle: branch 0 = even keys, v*2, drop v%10==6, TB windows sum
    keep = surviving()
    per_key = {}
    for t in keep:
        if t["key"] % 2 == 0:
            v = t["v"] * 2
            if v % 10 != 6:
                per_key.setdefault(t["key"], []).append((t["ts"], v))
    from conftest import tb_window_sums
    exp_w = tb_window_sums(per_key, TWIN, TSLIDE)
    assert win_cols == exp_w

    # branch 1: odd keys, broadcast delivered to BOTH tap replicas
    n_odd = sum(1 for t in keep if t["key"] % 2 == 1)
    assert sorted(set(taps)) == [0, 1]
    assert len(taps) == 2 * n_odd

    # closers ran once per sink
    assert sorted(sink_closed) == ["cols", "p"]
    # the persistent sink's store survived on disk (withKeepDb; private
    # handles suffix the path with the replica index, db_handle.py:41-42)
    assert os.path.exists(db_path + "_r0")
