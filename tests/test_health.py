"""Health-plane contracts (docs/OBSERVABILITY.md "Health plane"): the
watchdog state machine over a healthy run, seeded-stall detection with
root-cause attribution (in stats, the raised error, and the OpenMetrics
exposition), crash-path FAILED attribution + END_APP delivery, postmortem
bundles round-tripping through wf_doctor --check, and the
watchdog-disabled off-path budget."""

import dataclasses
import json
import os
import subprocess
import sys
import time

import pytest

import windflow_tpu as wf
from windflow_tpu.basic import default_config
from windflow_tpu.monitoring.health import (BACKPRESSURED, FAILED, OK,
                                            STALLED, HealthPlane)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _graph(cfg, n=3000, cap=256, name="health_app", sink_fn=None):
    src = (wf.Source_Builder(
        lambda: iter({"key": i % 8, "v": float(i)} for i in range(n)))
        .withName("src").withOutputBatchSize(cap).build())
    m = (wf.MapTPU_Builder(lambda t: {"key": t["key"], "v": t["v"] * 2.0})
         .withName("mtpu").build())
    snk = (wf.Sink_Builder(sink_fn or (lambda t, ctx=None: None))
           .withName("snk").build())
    g = wf.PipeGraph(name, wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(src).add(m).add_sink(snk)
    return g, snk


def _cfg(tmp_path=None, **kw):
    if tmp_path is not None:
        kw.setdefault("log_dir", str(tmp_path))
    return dataclasses.replace(default_config, **kw)


# ---------------------------------------------------------------------------
# healthy run: all OK, zero stalls
# ---------------------------------------------------------------------------

def test_healthy_run_reports_all_ok(tmp_path):
    g, _ = _graph(_cfg(tmp_path))
    g.run()
    h = g.stats()["Health"]
    assert h["enabled"] is True
    assert h["graph_state"] == OK
    assert {v["state"] for v in h["verdicts"].values()} == {OK}
    assert h["stall_events"] == 0
    assert h["last_stall"] is None
    assert h["samples_taken"] > 0
    # JSON-clean: the section ships in every NEW_REPORT payload
    json.dumps(h)


def test_health_disabled_off_path(tmp_path):
    g, _ = _graph(_cfg(tmp_path, health_watchdog=False))
    g.run()
    assert g._health is None
    assert g.stats()["Health"] == {"enabled": False}
    # off-path budget (mirrors test_recorder_overhead_within_budget's
    # stance): the disabled tick is ONE attribute check — micro-assert
    # it stays orders of magnitude under a sampling tick
    t0 = time.perf_counter()
    for _ in range(10_000):
        g.health_tick()
    per_call = (time.perf_counter() - t0) / 10_000
    assert per_call < 5e-6, \
        f"disabled health_tick costs {per_call * 1e6:.2f}us/call"


# ---------------------------------------------------------------------------
# seeded stall: detection, attribution, enriched error, postmortem
# ---------------------------------------------------------------------------

def test_seeded_stall_attributed_to_wedged_sink(tmp_path):
    """A sink that stops draining stalls the graph: the error must name
    it (regression for the bare "routing bug?" message), stats()["Health"]
    must show the STALLED verdict, and the bundle must validate."""
    g, snk = _graph(_cfg(tmp_path, health_stall_grace_usec=50_000),
                    name="stall_app")
    g.start()
    snk.replicas[0].drain = lambda limit=0: False   # wedged: never drains
    with pytest.raises(wf.WindFlowError) as ei:
        g.wait_end()
    msg = str(ei.value)
    assert "routing bug?" not in msg
    assert "root cause 'snk'" in msg
    assert "queue" in msg and "message(s) pending" in msg
    # the same diagnosis in stats: STALLED latched on the root cause
    h = g.stats()["Health"]
    assert h["graph_state"] == STALLED
    assert h["verdicts"]["snk"]["state"] == STALLED
    assert h["verdicts"]["snk"]["queue_depth"] > 0
    assert h["verdicts"]["src"]["state"] == OK
    assert h["stall_events"] == 1    # exactly one: no double count
    assert h["last_stall"]["root_cause"] == "snk"
    # a state-change timeline entry recorded the degradation
    assert any("snk" in e["changes"] for e in h["timeline"])


def test_stall_exposes_nonzero_stall_counter_in_openmetrics(tmp_path):
    from windflow_tpu.monitoring.openmetrics import (parse_exposition,
                                                     render_openmetrics)
    g, snk = _graph(_cfg(tmp_path, health_stall_grace_usec=50_000))
    g.start()
    snk.replicas[0].drain = lambda limit=0: False
    with pytest.raises(wf.WindFlowError):
        g.wait_end()
    fams = parse_exposition(render_openmetrics(g.stats()))
    stalls = fams["wf_stall_events_total"]["samples"]
    assert stalls and stalls[0][2] >= 1
    # enum gauge: exactly one active state per operator, snk on stalled
    by_op = {}
    for name, labels, value in fams["wf_operator_health"]["samples"]:
        if value == 1:
            assert labels["operator"] not in by_op
            by_op[labels["operator"]] = labels["state"]
    assert by_op["snk"] == "stalled"
    assert by_op["src"] == "ok"


def test_stall_postmortem_roundtrips_wf_doctor(tmp_path):
    g, snk = _graph(_cfg(tmp_path, health_stall_grace_usec=50_000),
                    name="pm_app")
    g.start()
    snk.replicas[0].drain = lambda limit=0: False
    with pytest.raises(wf.WindFlowError) as ei:
        g.wait_end()
    bundle = g._postmortem_dir
    assert bundle is not None and os.path.isdir(bundle)
    assert bundle in str(ei.value)     # the error points at the bundle
    with open(os.path.join(bundle, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["schema"] == "wf-postmortem/1"
    assert manifest["app"] == "pm_app"
    assert set(manifest["files"]) >= {"stats.json", "health.json",
                                      "events.json", "jit.json"}
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_doctor.py"),
         "--check", bundle], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout
    # the human render names the root cause
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_doctor.py"),
         bundle], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "ROOT CAUSE: 'snk'" in r.stdout


def test_wf_doctor_check_rejects_corrupt_bundle(tmp_path):
    g, snk = _graph(_cfg(tmp_path, health_stall_grace_usec=50_000))
    g.start()
    snk.replicas[0].drain = lambda limit=0: False
    with pytest.raises(wf.WindFlowError):
        g.wait_end()
    bundle = g._postmortem_dir
    hp = os.path.join(bundle, "health.json")
    with open(hp) as f:
        h = json.load(f)
    h["verdicts"]["snk"]["state"] = "ZOMBIE"      # illegal state
    with open(hp, "w") as f:
        json.dump(h, f)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_doctor.py"),
         "--check", bundle], capture_output=True, text=True)
    assert r.returncode == 1
    assert "illegal state" in r.stderr


def test_manual_postmortem_on_healthy_graph(tmp_path):
    g, _ = _graph(_cfg(tmp_path))
    g.run()
    bundle = g.dump_postmortem(str(tmp_path / "pm"), reason="manual")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "wf_doctor.py"),
         "--check", bundle], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


# ---------------------------------------------------------------------------
# crash path: FAILED attribution + abnormal-termination telemetry
# ---------------------------------------------------------------------------

def test_operator_crash_marked_failed_with_attribution(tmp_path):
    def boom(t):
        if t["v"] > 500:
            raise ValueError("seeded operator crash")
    cfg = _cfg(tmp_path)
    src = (wf.Source_Builder(
        lambda: iter({"key": i % 8, "v": float(i)} for i in range(3000)))
        .withName("src").withOutputBatchSize(256).build())
    bad = wf.Map_Builder(boom).withName("bad_map").build()
    snk = wf.Sink_Builder(lambda t, ctx=None: None).withName("snk").build()
    g = wf.PipeGraph("crash_app", wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(src).add(bad).add_sink(snk)
    with pytest.raises(ValueError, match="seeded operator crash"):
        g.run()
    h = g.stats()["Health"]
    assert h["verdicts"]["bad_map"]["state"] == FAILED
    assert "ValueError" in h["verdicts"]["bad_map"]["failure"]
    assert h["graph_state"] == FAILED
    # crash postmortem written BEFORE finalize tore the graph down
    assert g._postmortem_dir is not None
    with open(os.path.join(g._postmortem_dir, "manifest.json")) as f:
        assert json.load(f)["reason"].startswith("crash: ValueError")


def test_monitor_sends_end_app_on_crash(tmp_path):
    """Satellite regression: abnormal termination must still deliver a
    final report + END_APP (the dashboard used to show crashed apps live
    forever), with the Aborted marker set."""
    from test_monitoring import StubDashboard
    stub = StubDashboard()
    stub.start()
    cfg = _cfg(tmp_path, tracing_enabled=True,
               dashboard_host="127.0.0.1", dashboard_port=stub.port,
               health_stall_grace_usec=50_000)
    g, snk = _graph(cfg, name="crash_monitored")
    g.start()
    snk.replicas[0].drain = lambda limit=0: False
    with pytest.raises(wf.WindFlowError):
        g.wait_end()
    stub.join(timeout=5)
    assert stub.messages, "dashboard never contacted"
    mtype, ident, payload = stub.messages[-1]
    assert mtype == 2, "END_APP missing on the crash path"
    report = json.loads(payload.rstrip(b"\0"))
    assert report.get("Aborted") is True
    assert report["Health"]["verdicts"]["snk"]["state"] == STALLED


# ---------------------------------------------------------------------------
# state machine unit behavior
# ---------------------------------------------------------------------------

def test_backpressure_verdict_on_deep_queue(tmp_path):
    """An operator holding a deep backlog (but inside the stall grace) is
    BACKPRESSURED, and recovers to OK once the backlog drains."""
    cfg = _cfg(tmp_path, health_backpressure_depth=2,
               health_stall_grace_usec=60_000_000)
    g, snk = _graph(cfg, n=4000, cap=128)
    g.start()
    rep = snk.replicas[0]
    real = type(rep).drain
    rep.drain = lambda limit=0: False       # hold the backlog briefly
    for _ in range(40):
        if len(rep.inbox) >= 2:
            break
        g.step()
    assert len(rep.inbox) >= 2, "backlog never built"
    assert g._health.sample()["snk"]["state"] == BACKPRESSURED
    del rep.drain                           # un-wedge (restore the method)
    assert rep.drain.__func__ is real
    g.wait_end()
    assert g._health.sample()["snk"]["state"] == OK


def test_stall_latch_clears_on_progress(tmp_path):
    g, snk = _graph(_cfg(tmp_path, health_stall_grace_usec=50_000))
    g.start()
    rep = snk.replicas[0]
    real = type(rep).drain
    rep.drain = lambda limit=0: False
    with pytest.raises(wf.WindFlowError):
        g.wait_end()
    assert g._health.sample()["snk"]["state"] == STALLED  # latched
    # un-wedge: restore the real drain and let the backlog clear
    rep.drain = lambda limit=0: real(rep, limit)
    while rep.inbox:
        rep.drain(0)
    assert g._health.sample()["snk"]["state"] == OK
    g._finalize(dump=False)


def test_watchdog_then_hard_stall_counts_one_event(tmp_path):
    """A cadence tick that detects the stall first (grace elapsed) and
    the subsequent wait_end hard-stall confirmation are ONE stall, not
    two — the latch carries the 'already counted' fact between them."""
    g, snk = _graph(_cfg(tmp_path, health_stall_grace_usec=20_000))
    g.start()
    snk.replicas[0].drain = lambda limit=0: False
    for _ in range(20):
        g.step()                    # build a pending backlog
    g._health.sample()              # baseline progress observation
    time.sleep(0.05)                # let the grace window elapse
    v = g._health.sample()          # cadence detection: counts the stall
    assert v["snk"]["state"] == STALLED
    assert g._health.stall_events == 1
    with pytest.raises(wf.WindFlowError):
        g.wait_end()                # hard-stall confirmation: no recount
    assert g._health.stall_events == 1
    # the hard stall re-dumped a FRESH frame over the watchdog bundle
    with open(os.path.join(g._postmortem_dir, "manifest.json")) as f:
        assert json.load(f)["reason"] == "stall"


def test_crash_after_manual_snapshot_still_bundles(tmp_path):
    """A routine mid-run dump_postmortem must not suppress the crash
    bundle: the on-disk reason must be the crash, not the snapshot."""
    def boom(t):
        if t["v"] > 500:
            raise ValueError("late crash")
    cfg = _cfg(tmp_path)
    src = (wf.Source_Builder(
        lambda: iter({"key": i % 8, "v": float(i)} for i in range(3000)))
        .withName("src").withOutputBatchSize(256).build())
    bad = wf.Map_Builder(boom).withName("bad_map").build()
    snk = wf.Sink_Builder(lambda t, ctx=None: None).withName("snk").build()
    g = wf.PipeGraph("snap_app", wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(src).add(bad).add_sink(snk)
    g.start()
    g.dump_postmortem(str(tmp_path / "snap"), reason="manual snapshot")
    with pytest.raises(ValueError):
        g.wait_end()
    assert g._postmortem_dir != str(tmp_path / "snap")
    with open(os.path.join(g._postmortem_dir, "manifest.json")) as f:
        assert json.load(f)["reason"].startswith("crash: ValueError")


def test_postmortem_during_unbundled_stall_does_not_deadlock(tmp_path):
    """Regression: dump_postmortem holds the bundle lock while its stats
    section re-samples the watchdog; an operator newly past the grace
    window used to fire the cadence auto-bundle from inside that sample
    and re-enter the non-reentrant lock on the same thread."""
    g, snk = _graph(_cfg(tmp_path, health_stall_grace_usec=20_000))
    g.start()
    snk.replicas[0].drain = lambda limit=0: False
    for _ in range(20):
        g.step()                    # pending backlog, no health tick yet
    g._health.sample()              # baseline observation
    time.sleep(0.05)                # grace elapses with NO cadence tick
    done = {}

    def dump():
        done["dir"] = g.dump_postmortem(str(tmp_path / "pm"))
    import threading
    t = threading.Thread(target=dump, daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive(), "dump_postmortem deadlocked"
    assert os.path.isdir(done["dir"])
    g._finalize(dump=False)


def test_compile_storm_baselined_per_graph(tmp_path):
    """The jit registry is process-global: a prior graph's recompiles
    must not flag a fresh graph's same-named operator; recompiles during
    THIS run past the threshold must."""
    from windflow_tpu.monitoring.jit_registry import default_registry
    entry = default_registry().entry("mtpu")
    before = entry.recompiles
    try:
        entry.recompiles = before + 10          # a prior graph's storm
        entry.compiles = max(entry.compiles, 1)  # keep snapshot() visible
        g, _ = _graph(_cfg(tmp_path, health_recompile_storm=4))
        g.start()
        v = g._health.sample()
        assert v["mtpu"]["compile_storm"] is False   # baselined away
        entry.recompiles += 4                   # storm DURING this run
        v = g._health.sample()
        assert v["mtpu"]["compile_storm"] is True
        assert v["mtpu"]["state"] == BACKPRESSURED
        g.wait_end()
    finally:
        entry.recompiles = before


def test_manual_snapshot_does_not_consume_stall_auto_bundle(tmp_path):
    """A routine dump_postmortem must not use up the watchdog's
    once-per-graph stall auto-bundle (streaming deployments never reach
    wait_end's hard-stall dump)."""
    g, snk = _graph(_cfg(tmp_path, health_stall_grace_usec=20_000))
    g.start()
    g.dump_postmortem(str(tmp_path / "snap"), reason="manual snapshot")
    snk.replicas[0].drain = lambda limit=0: False
    for _ in range(20):
        g.step()
    g._health.sample()              # baseline
    time.sleep(0.05)                # grace elapses
    g._health.sample()              # cadence stall: auto-bundle fires
    assert g._health.stall_events == 1
    assert g._postmortem_dir != str(tmp_path / "snap")
    with open(os.path.join(g._postmortem_dir, "manifest.json")) as f:
        assert json.load(f)["reason"].startswith("watchdog: stalled")
    g._finalize(dump=False)


def test_format_diagnosis_no_root_cause():
    msg = HealthPlane.format_diagnosis({"root_cause": None, "verdicts": {
        "src": {"state": OK, "queue_depth": 0,
                "last_advance_age_usec": 0}}})
    assert "source starvation" in msg
