"""DETERMINISTIC-path scaling: the OrderingCollector's k-way merge must stay
linear on long streams (reference uses priority queues,
``ordering_collector.hpp:51-``; the naive per-tuple min-scan + list.pop(0)
was quadratic)."""

import random
import time

import pytest

from windflow_tpu.batch import HostBatch
from windflow_tpu.parallel.collectors import OrderingCollector

import windflow_tpu as wf


def test_collector_merge_100k_linear():
    C, N = 4, 100_000
    rnd = random.Random(7)
    # C per-channel ordered streams with interleaved timestamps
    streams = [[] for _ in range(C)]
    for ts in range(N):
        streams[rnd.randrange(C)].append(ts)

    col = OrderingCollector(C)
    out = []
    t0 = time.perf_counter()
    # feed in batches of 64 round-robin across channels
    pos = [0] * C
    while any(pos[c] < len(streams[c]) for c in range(C)):
        for c in range(C):
            lo, hi = pos[c], min(pos[c] + 64, len(streams[c]))
            if lo < hi:
                chunk = streams[c][lo:hi]
                out.extend(col.on_message(
                    c, HostBatch(list(chunk), list(chunk), chunk[-1])))
                pos[c] = hi
    for c in range(C):
        out.extend(col.on_channel_eos(c))
    elapsed = time.perf_counter() - t0

    released = [ts for b in out for ts in b.tss]
    assert released == sorted(released)
    assert len(released) == N
    assert elapsed < 5.0, f"ordering merge took {elapsed:.1f}s for {N} tuples"


@pytest.mark.slow  # ~19s: 100k-scale variant; the collector-level
# linearity test above pins the same contract at tier-1 speed
def test_deterministic_graph_100k():
    n = 100_000
    total = {"v": 0, "c": 0}

    def sink(x):
        if x is not None:
            total["v"] += x
            total["c"] += 1

    g = wf.PipeGraph("det_perf", wf.ExecutionMode.DETERMINISTIC)
    src = wf.Source_Builder(lambda: iter(range(n))) \
        .withParallelism(4).withOutputBatchSize(64).build()
    snk = wf.Sink_Builder(sink).build()
    t0 = time.perf_counter()
    g.add_source(src).add(wf.Map(lambda x: x * 2)).add_sink(snk)
    g.run()
    elapsed = time.perf_counter() - t0

    assert total["c"] == 4 * n   # each of the 4 source replicas runs the gen
    assert total["v"] == 4 * sum(2 * i for i in range(n))
    assert elapsed < 30.0, f"DETERMINISTIC graph took {elapsed:.1f}s"


def test_kslack_release_batches_runs():
    """KSlackCollector ships each release run as ONE HostBatch (not
    per-tuple singletons), preserving release order and the drop count."""
    from windflow_tpu.parallel.collectors import KSlackCollector

    rnd = random.Random(3)
    col = KSlackCollector(1)
    out = []
    N = 10_000
    # mildly out-of-order stream: ts jittered by up to 8
    stream = [max(0, i + rnd.randint(-8, 8)) for i in range(N)]
    for lo in range(0, N, 64):
        chunk = stream[lo:lo + 64]
        out.extend(col.on_message(
            0, HostBatch(list(chunk), list(chunk), max(chunk))))
    out.extend(col.on_channel_eos(0))

    released = [ts for b in out for ts in b.tss]
    assert released == sorted(released)      # K-slack order
    assert len(released) + col.num_dropped == N
    # batching actually happened: far fewer batches than tuples
    assert len(out) < len(released) / 4, (len(out), len(released))


@pytest.mark.slow  # ~17s: 100k-scale variant (see DETERMINISTIC twin)
def test_probabilistic_graph_100k_linear():
    """PROBABILISTIC analogue of the DETERMINISTIC linearity test: a
    100k-tuple K-slack pipeline with parallel sources completes in linear
    time now that release runs ship as batches."""
    n = 100_000
    total = {"v": 0, "c": 0}

    def sink(x):
        if x is not None:
            total["v"] += x
            total["c"] += 1

    g = wf.PipeGraph("kslack_perf", wf.ExecutionMode.PROBABILISTIC)
    src = wf.Source_Builder(lambda: iter(range(n))) \
        .withParallelism(4).withOutputBatchSize(64).build()
    snk = wf.Sink_Builder(sink).build()
    t0 = time.perf_counter()
    g.add_source(src).add(wf.Map(lambda x: x * 2)).add_sink(snk)
    g.run()
    elapsed = time.perf_counter() - t0

    # in-order per-source streams: K stays 0, nothing drops
    assert total["c"] == 4 * n
    assert total["v"] == 4 * sum(2 * i for i in range(n))
    assert elapsed < 30.0, f"PROBABILISTIC graph took {elapsed:.1f}s"


def test_kslack_release_splits_on_shared_boundary():
    """A release run containing both multicast (shared) and private tuples
    splits on the flag boundary: a single shared tuple must not force
    copy-on-write over the whole run downstream."""
    from windflow_tpu.parallel.collectors import KSlackCollector

    col = KSlackCollector(1)
    # out-of-order warmup grows K so tuples buffer across both messages
    out = list(col.on_message(
        0, HostBatch([100, 90], [100, 90], 100)))
    out += col.on_message(0, HostBatch(list(range(0, 8)),
                                       [110 + t for t in range(0, 8)], 117))
    out += col.on_message(0, HostBatch(list(range(8, 12)),
                                       [118 + t - 8 for t in range(8, 12)],
                                       121, shared=True))
    out += col.on_channel_eos(0)
    released = [(b.shared, list(b.items)) for b in out]
    # all tuples out, order kept, flags exact per sub-batch
    flat = [it for _, its in released for it in its]
    assert flat == [90, 100] + list(range(12))
    for sh, its in released:
        assert all((isinstance(it, int) and 8 <= it < 12) == sh
                   for it in its)
