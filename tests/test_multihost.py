"""Multi-host mesh layer tests on the virtual 8-device CPU mesh: emulated
host groups must place host boundaries along the key axis, and the sharded
keyed programs from parallel/mesh.py must run unchanged on such meshes."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import windflow_tpu  # noqa: F401  (jax config)
from windflow_tpu.basic import WindFlowError
from windflow_tpu.batch import HostBatch
from windflow_tpu.parallel import mesh as meshmod
from windflow_tpu.parallel.multihost import (initialize, make_multihost_mesh,
                                             stage_local)


def test_initialize_single_process_noop():
    initialize()  # must not raise or try to contact a coordinator
    assert jax.process_count() == 1


def test_mesh_host_boundaries_on_key_axis():
    mesh = make_multihost_mesh(local_data=2, emulate_hosts=2)
    assert mesh.shape == {"data": 2, "key": 4}
    devs = list(jax.devices())
    arr = mesh.devices
    # host 0's devices occupy key columns [0, 2), host 1's [2, 4): the
    # data-axis all_gather stays inside one host group
    host0 = set(devs[:4])
    assert set(arr[:, :2].ravel()) == host0
    assert set(arr[:, 2:].ravel()) == set(devs[4:])


def test_mesh_uneven_groups_rejected():
    with pytest.raises(WindFlowError):
        make_multihost_mesh(local_data=3, emulate_hosts=2)


def test_keyed_reduce_on_multihost_mesh():
    mesh = make_multihost_mesh(local_data=2, emulate_hosts=2)
    K, CAP = 16, 256
    rng = np.random.default_rng(5)
    keys = rng.integers(0, K, CAP)
    vals = rng.random(CAP)
    hb = HostBatch([{"k": int(k), "v": float(v)}
                    for k, v in zip(keys, vals)],
                   list(range(CAP)), 0)
    db = stage_local(hb, CAP, mesh)
    fn = meshmod.make_sharded_keyed_reduce(
        mesh, CAP, K, lambda a, b: {"k": a["k"], "v": a["v"] + b["v"]},
        key_fn=lambda t: t["k"], use_psum=False)
    table, has = fn(db.payload, db.valid)
    expected = np.zeros(K)
    for k, v in zip(keys, vals):
        expected[k] += v
    np.testing.assert_allclose(np.asarray(table["v"]), expected, rtol=1e-6)
    assert bool(np.asarray(has).all())


def test_ffat_on_multihost_mesh():
    """Key-sharded FFAT state across emulated hosts: results identical to a
    single-chip run."""
    mesh = make_multihost_mesh(local_data=2, emulate_hosts=2)
    K, CAP, P_, R, D = 8, 64, 4, 4, 1
    lift = lambda t: t["v"]
    comb = lambda a, b: a + b
    step = meshmod.make_sharded_ffat_step(mesh, CAP, K, P_, R, D,
                                          lift, comb, lambda t: t["k"])
    from windflow_tpu.windows.ffat_kernels import (make_ffat_state,
                                                   make_ffat_step)
    ref_step = jax.jit(make_ffat_step(CAP, K, P_, R, D, lift, comb,
                                      lambda t: t["k"]))
    state = meshmod.make_sharded_ffat_state(jnp.zeros(()), K, R, mesh)
    ref_state = make_ffat_state(jnp.zeros(()), K, R)
    rng = np.random.default_rng(7)
    got, exp = {}, {}
    for it in range(6):
        payload = {"k": jnp.asarray(rng.integers(0, K, CAP), jnp.int32),
                   "v": jnp.asarray(rng.random(CAP, dtype=np.float32))}
        ts = jnp.arange(CAP, dtype=jnp.int64)
        valid = jnp.ones(CAP, bool)
        state, out, fired, _ = step(state, payload, ts, valid)
        ref_state, rout, rfired, _ = ref_step(ref_state, payload, ts, valid)
        for o, f, dst in ((out, fired, got), (rout, rfired, exp)):
            fm = np.asarray(f)
            ok_ = {k: np.asarray(v) for k, v in o.items()}
            for i in np.nonzero(fm)[0]:
                dst[(int(ok_["key"][i]), int(ok_["wid"][i]))] = \
                    float(ok_["value"][i])
    assert got.keys() == exp.keys() and len(got) > 0
    for kk in exp:
        assert abs(got[kk] - exp[kk]) < 1e-4


def test_ffat_flat_ingest_layout():
    """ingest="flat" (the multi-process staging layout): batches fully
    sharded over (data, key) must produce results identical to the
    single-chip step on the same logical lane order — i.e. the key-then-
    data gather reconstructs the logical P((data, key)) order exactly."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_multihost_mesh(local_data=2, emulate_hosts=2)
    K, CAP, P_, R, D = 8, 64, 4, 4, 1
    lift = lambda t: t["v"]
    comb = lambda a, b: a + b
    step = meshmod.make_sharded_ffat_step(mesh, CAP, K, P_, R, D,
                                          lift, comb, lambda t: t["k"],
                                          ingest="flat")
    from windflow_tpu.windows.ffat_kernels import (make_ffat_state,
                                                   make_ffat_step)
    ref_step = jax.jit(make_ffat_step(CAP, K, P_, R, D, lift, comb,
                                      lambda t: t["k"]))
    state = meshmod.make_sharded_ffat_state(jnp.zeros(()), K, R, mesh)
    ref_state = make_ffat_state(jnp.zeros(()), K, R)
    sh = NamedSharding(mesh, P((meshmod.DATA_AXIS, meshmod.KEY_AXIS)))
    rng = np.random.default_rng(11)
    got, exp = {}, {}
    for _ in range(5):
        k_np = rng.integers(0, K, CAP).astype(np.int32)
        v_np = rng.integers(0, 100, CAP).astype(np.float32)
        payload = {"k": jax.device_put(jnp.asarray(k_np), sh),
                   "v": jax.device_put(jnp.asarray(v_np), sh)}
        ts = jax.device_put(jnp.arange(CAP, dtype=jnp.int64), sh)
        ok = jax.device_put(jnp.ones(CAP, bool), sh)
        state, out, fired, _ = step(state, payload, ts, ok)
        ref_state, rout, rfired, _ = ref_step(
            ref_state, {"k": jnp.asarray(k_np), "v": jnp.asarray(v_np)},
            jnp.arange(CAP, dtype=jnp.int64), jnp.ones(CAP, bool))
        for o, f, dst in ((out, fired, got), (rout, rfired, exp)):
            fm = np.asarray(f)
            cols = {kk_: np.asarray(v) for kk_, v in o.items()}
            for i in np.nonzero(fm)[0]:
                dst[(int(cols["key"][i]), int(cols["wid"][i]))] = \
                    float(cols["value"][i])
    assert len(exp) > 0 and got == exp


@pytest.mark.slow  # ~37s: spawns two OS processes + a TCP coordinator;
# the in-process multihost mesh tests above keep tier-1 coverage
def test_two_process_dcn_reduce_and_ffat():
    """REAL multi-process validation (VERDICT r3 item 5): two OS processes
    join one jax.distributed job over a TCP coordinator with Gloo CPU
    collectives (the CPU stand-in for DCN), build the multi-host mesh, and
    run a keyed reduce (each process staging only its own ingested lanes)
    plus a key-sharded FFAT window step spanning the process boundary.
    Every process checks the full results against a local oracle."""
    import socket
    import subprocess
    import sys as _sys

    with socket.socket() as s:       # free TCP port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker = str(__import__("pathlib").Path(__file__).with_name(
        "_multihost_worker.py"))
    import os as _os
    env = {k: v for k, v in _os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    repo = str(__import__("pathlib").Path(__file__).resolve().parents[1])
    env["PYTHONPATH"] = repo + (_os.pathsep + env["PYTHONPATH"]
                                if env.get("PYTHONPATH") else "")
    procs = [subprocess.Popen(
                [_sys.executable, worker, str(i), "2", str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env)
             for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        # harvest whatever the killed workers managed to print — the
        # whole point of this message is debuggability on a hang
        for p in procs:
            try:
                out, _ = p.communicate(timeout=10)
                outs.append(out or "")
            except Exception:
                outs.append("<no output harvested>")
        raise AssertionError("two-process DCN run hung:\n" +
                             "\n".join(outs))
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0 and "DCN_WORKER_OK" in out, \
            f"worker {i} failed (rc={p.returncode}):\n{out[-3000:]}"
