"""Durable-state suite (windflow_tpu/durability, docs/DURABILITY.md):
watermark-aligned checkpoint/restore, exactly-once sinks, and the
failure-injection (chaos) A/B family — kill a replica mid-window /
mid-epoch / mid-sink-flush under seeded schedules, restore, and diff
the sunk output record-for-record against the uninterrupted run.

The fast gate runs one chaos cell per mechanism (aligned barrier,
fenced Kafka dedupe, stateful-table restore, atomic-rename file sink)
plus the protocol/observability unit tests; the full family x kill
point x fusion matrix is the ``slow``-marked soak (CI_NIGHTLY leg)."""

import dataclasses
import json
import os
import subprocess
import sys
import time

import pytest

import windflow_tpu as wf
from windflow_tpu.basic import WindFlowError
from windflow_tpu.durability import chaos
from windflow_tpu.durability.checkpoint import (load_checkpoint,
                                                topology_signature)
from windflow_tpu.durability.sinks import EpochFileSink
from windflow_tpu.kafka.client import InMemoryBroker
from windflow_tpu.kafka.kafka_sink import KafkaSink, KafkaSinkMessage

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cell_pair(tmp_path, family, *, fusion=True, n=4096):
    base = chaos.make_cell(family, str(tmp_path / "ck_a"), fusion=fusion,
                           out_dir=str(tmp_path / "out_a"), n=n)
    chal = chaos.make_cell(family, str(tmp_path / "ck_b"), fusion=fusion,
                           out_dir=str(tmp_path / "out_b"), n=n)
    return base, chal


def _run_cell(tmp_path, family, point, *, fusion=True, n=4096,
              spec=None):
    base, chal = _cell_pair(tmp_path, family, fusion=fusion, n=n)
    v = chaos.run_ab(base["factory"], chal["factory"],
                     spec or chaos.default_kill(family, point),
                     base["read"], chal["read"])
    assert v["diff"] is None, \
        f"{family}/{point}/fusion={fusion}: {v['diff']}"
    assert v["restored_epoch"] is not None
    assert v["records"] > 0
    return v


# ---------------------------------------------------------------------------
# chaos A/B: one fast cell per mechanism
# ---------------------------------------------------------------------------

def test_chaos_window_mid_epoch_fused(tmp_path):
    """Kill between checkpoints on the fused map->CB-window chain: the
    FFAT ring + frontier restore mid-stream, the Kafka source seeks
    back, and the resumed output matches record for record."""
    _run_cell(tmp_path, "window_cb", "mid_epoch", fusion=True)


def test_chaos_window_mid_sink_flush_dedupes(tmp_path):
    """Kill in the torn two-phase window (sink epoch committed, manifest
    never written): the replay re-commits the epoch and the broker-side
    fence dedupes every already-published message — the exactly-once
    case plain flush cannot survive.  Fusion OFF covers the unfused
    sweep in the fast gate (the slow matrix crosses both)."""
    v = _run_cell(tmp_path, "window_cb", "mid_sink_flush", fusion=False)
    assert v["dedupe_hits"] > 0


def test_chaos_stateful_mid_window(tmp_path):
    """Kill the dense-key stateful operator mid-batch: the slot table +
    per-key running sums restore to the barrier and replay continues
    them without double counting."""
    _run_cell(tmp_path, "stateful", "mid_window")


def test_chaos_reduce_mid_epoch(tmp_path):
    """Host keyed Reduce: per-replica rolling state dicts restore."""
    _run_cell(tmp_path, "reduce", "mid_epoch")


def test_chaos_file_sink_mid_sink_flush(tmp_path):
    """EpochFileSink stage-then-rename: kill after the rename but before
    the manifest — the replayed epoch overwrites the file idempotently
    and the committed concatenation stays the exact record sequence."""
    _run_cell(tmp_path, "stateless_chain", "mid_sink_flush")
    out = EpochFileSink.read_committed(str(tmp_path / "out_b"))
    assert out and not os.path.exists(
        str(tmp_path / "out_b" / ".staging" / "open.jsonl"))


@pytest.mark.slow
@pytest.mark.parametrize("fusion", [True, False])
@pytest.mark.parametrize("point", chaos.KILL_POINTS)
@pytest.mark.parametrize("family", chaos.FAMILIES)
def test_chaos_matrix_soak(tmp_path, family, point, fusion):
    """The full acceptance matrix: every seeded kill point across every
    graph family, fusion ON and OFF — 30 cells of kill -> restore ->
    record-for-record diff (nightly leg; tools/wf_chaos.py runs the
    same cells standalone)."""
    n = 4096 if family != "window_tb" else 6558
    v = _run_cell(tmp_path, family, point, fusion=fusion, n=n)
    if point == "mid_sink_flush" and family != "stateless_chain":
        assert v["dedupe_hits"] > 0


# ---------------------------------------------------------------------------
# kill-a-shard / restore-on-N±1 (rescale-on-restore)
# ---------------------------------------------------------------------------

def test_rescale_restore_reduce_fewer_and_more_shards(tmp_path):
    """Kill the keyed host Reduce at 3 shards, restore at 2 AND at 4:
    the per-replica per-key state dicts re-bucket through the new
    placement (durability/rebucket.py) and the output stays per-key
    record-for-record exact — chip failure and capacity change become
    'restore on N±1'."""
    for restore_p in (2, 4):
        v = chaos.run_rescale_ab(
            "reduce", "mid_epoch", str(tmp_path), shards_kill=3,
            shards_restore=restore_p, n=4096)
        assert v["diff"] is None, f"3->{restore_p}: {v['diff']}"
        assert v["restored_epoch"] is not None
        assert v["records"] == 4096


def test_rescale_restore_window_cb_replicas(tmp_path):
    """Keyed CB FFAT at parallelism 2 killed mid-epoch, restored at 3:
    the shared pane-ring table is replica-independent (per-key clock
    lanes), so the rescale is pure routing re-bucketing — fired windows
    stay per-key exact."""
    v = chaos.run_rescale_ab(
        "window_cb", "mid_epoch", str(tmp_path), shards_kill=2,
        shards_restore=3, n=4096)
    assert v["diff"] is None, v["diff"]
    assert v["restored_epoch"] is not None


def test_rescale_restore_mesh_cb_fewer_chips(tmp_path):
    """Multi-chip durable state: CB FFAT sharded over a 4-chip (virtual)
    mesh, killed mid-epoch, restored onto a 2-chip mesh — the dense
    key-sharded state re-places under the new key axis and every fired
    window matches the uninterrupted 4-chip run per key.  (This is the
    cell the old checkpoint.py mesh raise made impossible.)"""
    from windflow_tpu.parallel.mesh import make_mesh
    v = chaos.run_rescale_ab(
        "window_cb", "mid_epoch", str(tmp_path), shards_kill=1,
        shards_restore=1, mesh_kill=make_mesh(4),
        mesh_restore=make_mesh(2), n=4096)
    assert v["diff"] is None, v["diff"]
    assert v["mesh"] == "1x4->1x2"


@pytest.mark.slow
@pytest.mark.parametrize("family,point,kill,restore", [
    ("reduce", "mid_window", 3, 2),
    ("reduce", "mid_window", 3, 4),
    ("stateful", "mid_epoch", 2, 3),
    ("stateful", "mid_window", 3, 2),
    ("window_cb", "mid_window", 2, 3),
    ("window_tb", "mid_epoch", 2, 3),
    ("window_tb", "mid_epoch", 3, 2),
])
def test_rescale_matrix_replicas_soak(tmp_path, family, point, kill,
                                      restore):
    """The replica-rescale soak: every rescale family across kill
    points and both directions (nightly leg; tools/wf_chaos.py
    --rescale runs the same cells standalone).  window_tb exercises the
    per-replica TB ring-clock agreement path."""
    n = 4096 if family != "window_tb" else 6558
    v = chaos.run_rescale_ab(family, point, str(tmp_path),
                             shards_kill=kill, shards_restore=restore,
                             n=n)
    assert v["diff"] is None, v["diff"]


@pytest.mark.slow
@pytest.mark.parametrize("family,kk_kill,kk_restore", [
    ("window_cb", 2, 4),
    ("window_tb", 4, 2),
    ("window_tb", 2, 4),
])
def test_rescale_matrix_mesh_soak(tmp_path, family, kk_kill, kk_restore):
    """Mesh-shape rescale soak: CB and TB FFAT killed on one mesh and
    restored on another (N±1 chips), TB covering the per-shard
    scalar-clock-lane merge (durability/rebucket.py)."""
    from windflow_tpu.parallel.mesh import make_mesh
    n = 4096 if family != "window_tb" else 6558
    v = chaos.run_rescale_ab(family, "mid_epoch", str(tmp_path),
                             shards_kill=1, shards_restore=1,
                             mesh_kill=make_mesh(kk_kill),
                             mesh_restore=make_mesh(kk_restore), n=n)
    assert v["diff"] is None, v["diff"]


def test_rescale_refuses_torn_sink_fence_then_reconciles(tmp_path):
    """The shard-count-changing exactly-once hole (the satellite
    bugfix): a kill in the torn two-phase window leaves the broker
    fence one epoch AHEAD of the manifest.  The fence dedupes by
    replica-lifetime sequence — exact only while the replayed record
    order matches, which a rescale breaks — so a shape-changing restore
    must REFUSE with the reconciliation recipe, while the same-shape
    restore reconciles through the seq dedupe exactly as before."""
    cell = chaos.make_cell("reduce", str(tmp_path / "ck"), n=4096,
                           parallelism=3)
    with pytest.raises(WindFlowError, match="WF605.*fence"):
        chaos.run_killed_and_restored(
            cell["factory"],
            chaos.default_kill("reduce", "mid_sink_flush"),
            restore_factory=lambda: cell["factory"](parallelism=2))
    # same cell, same-shape restore: the documented reconciliation
    cell2 = chaos.make_cell("reduce", str(tmp_path / "ck2"), n=4096,
                            parallelism=3)
    g = chaos.run_killed_and_restored(
        cell2["factory"], chaos.default_kill("reduce", "mid_sink_flush"))
    assert g.stats()["Durability"]["dedupe_hits"] > 0


def test_epoch_file_sink_rescale_overwrite_reconciles(tmp_path):
    """EpochFileSink under a rescale restore: the idempotent
    os.replace commit makes the file sink self-healing — a torn epoch
    file is simply overwritten by the (re-interleaved) replay and the
    committed concatenation stays per-key exact across the shard-count
    change."""
    import windflow_tpu as wf

    from windflow_tpu.kafka.kafka_source import KafkaSource

    def build(out_dir, ckpt, parallelism):
        sink = EpochFileSink(out_dir)
        broker = InMemoryBroker()
        broker.create_topic("in", 1)
        p = broker.producer()
        for i in range(4096):
            p.produce("in", {"key": i % 8, "value": float(i)},
                      timestamp_usec=1_000 + i * 7)
        p.produce("in", "EOS", timestamp_usec=1_000 + 4096 * 7)

        def deser(msg, shipper):
            if msg is None:
                return True
            if msg.value == "EOS":
                return False
            shipper.pushWithTimestamp(dict(msg.value),
                                      msg.timestamp_usec)
            return True

        def factory(parallelism=parallelism):
            cfg = dataclasses.replace(wf.default_config)
            cfg.durability = ckpt
            cfg.durability_epoch_sweeps = 3
            cfg.punctuation_interval_usec = 10 ** 12
            cfg.health_postmortem_on_crash = False

            def red_fn(item, state):
                state["key"] = item["key"]
                state["n"] = state.get("n", 0) + 1

            g = wf.PipeGraph("fsr", config=cfg)
            src = KafkaSource(deser, broker, ["in"], group_id="fsr",
                              name="ksrc", output_batch_size=256)
            pipe = g.add_source(src)
            pipe.add(wf.Reduce_Builder(red_fn, dict)
                     .withKeyBy(lambda t: t["key"])
                     .withParallelism(parallelism)
                     .withName("red").build())
            pipe.add_sink(wf.Sink_Builder(sink).withName("fs").build())
            return g
        return factory

    fb = build(str(tmp_path / "out_a"), str(tmp_path / "ck_a"), 3)
    chaos.run_baseline(fb)
    fc = build(str(tmp_path / "out_b"), str(tmp_path / "ck_b"), 3)
    chaos.run_killed_and_restored(
        fc, chaos.KillSpec("mid_sink_flush", after=2),
        restore_factory=lambda: fc(parallelism=2))
    base = EpochFileSink.read_committed(str(tmp_path / "out_a"))
    resc = EpochFileSink.read_committed(str(tmp_path / "out_b"))
    assert chaos.diff_keyed_records([base], [resc]) is None


def test_manifest_records_mesh_shape_and_placements(tmp_path):
    """The checkpoint manifest pins the shard shape a rescale restores
    against: mesh (None on a single chip) and the per-op override
    placement summary."""
    cell = chaos.make_cell("reduce", str(tmp_path / "ck"), n=2048,
                           parallelism=2)
    chaos.run_baseline(cell["factory"])
    pending = load_checkpoint(str(tmp_path / "ck"))
    assert "mesh" in pending["manifest"]
    assert pending["manifest"]["mesh"] is None
    assert "placements" in pending["manifest"]
    assert pending["placements"] == {}


def test_wf605_unrebucketable_state_refuses_rescale(tmp_path):
    """A keyed operator checkpointing state of a kind the re-bucketer
    does not know refuses a shape-changing restore with WF605 naming
    the operator (static half of the rescale contract)."""
    from windflow_tpu.analysis.preflight import manifest_rescale_plan

    cell = chaos.make_cell("reduce", str(tmp_path / "ck"), n=2048,
                           parallelism=3)
    g = cell["factory"]()
    # same composed graph, manifest claiming a different parallelism
    ops = g._topo_operators()
    red = [op for op in ops if op.name == "red"][0]
    manifest = {"topology": [dict(s) for s in topology_signature(ops)],
                "mesh": None}
    manifest["topology"][ops.index(red)]["parallelism"] = 5
    diags, rescaled = manifest_rescale_plan(g, manifest)
    assert rescaled and not diags     # Reduce re-buckets: allowed

    # an op whose class overrides snapshot_state with an unknown state
    # kind has no re-bucketing rule — WF605, named.  (Manifest rebuilt
    # after the swap: the type matches, only the parallelism differs.)
    class _Custom(type(red)):
        def snapshot_state(self):
            return {"kind": "custom"}
    red.__class__ = _Custom
    manifest = {"topology": [dict(s) for s in topology_signature(ops)],
                "mesh": None}
    manifest["topology"][ops.index(red)]["parallelism"] = 5
    diags, rescaled = manifest_rescale_plan(g, manifest)
    assert rescaled
    assert any(d.code == "WF605" for d in diags), diags


def test_preflight_wf604_unrebucketable_keyed_op_on_mesh(tmp_path):
    """Preflight names rescale-incompatible operators up front: a keyed
    operator on a MESH checkpointing state of an unknown kind warns
    WF604 at check() — before any restore ever trips over WF605."""
    import windflow_tpu as wf
    from windflow_tpu.ops.reduce_op import Reduce
    from windflow_tpu.parallel.mesh import make_mesh

    class _CustomReduce(Reduce):
        def snapshot_state(self):
            return {"kind": "custom"}

    cfg = dataclasses.replace(wf.default_config)
    cfg.durability = str(tmp_path / "ck")
    cfg.mesh = make_mesh(2)
    g = wf.PipeGraph("wf604", config=cfg)
    src = wf.Source_Builder(
        lambda: iter([{"key": i % 4, "value": 1.0} for i in range(64)])
    ).withOutputBatchSize(32).build()
    red = (wf.Reduce_Builder(lambda i, s: None, dict)
           .withKeyBy(lambda t: t["key"]).withName("red").build())
    red.__class__ = _CustomReduce
    g.add_source(src).add(red).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    diags = g.check()
    assert any(d.code == "WF604" and "red" in d.message
               for d in diags), [str(d) for d in diags]


def test_rebucket_tb_clock_disagreement_raises():
    """Dynamic half of the rescale contract: TB pane rings whose
    per-shard clocks disagree at the barrier cannot merge — the
    re-bucketer refuses with the reconciliation recipe instead of
    re-firing or skipping windows."""
    import numpy as np

    from windflow_tpu.durability.rebucket import (RescaleError,
                                                  rebucket_blob)

    class _FakeTB:
        name = "w"
        max_keys = 8
        is_tb = True
        key_extractor = staticmethod(lambda t: t["key"])

    def st(base):
        return {"cells": np.zeros((8, 4), np.float32),
                "cell_valid": np.zeros((8, 4), bool),
                "horizon": np.full(8, -(1 << 60), np.int64),
                "base": np.asarray(base, np.int64),
                "win_next": np.asarray(0, np.int64),
                "max_seen": np.asarray(0, np.int64),
                "n_late": np.asarray(0, np.int64),
                "n_evicted": np.asarray(0, np.int64),
                "n_win_dropped": np.asarray(0, np.int64)}

    blob = {"kind": "ffat_tpu", "states": {0: st(3), 1: st(7)},
            "compactor": None}
    with pytest.raises(RescaleError, match="clocks disagree"):
        rebucket_blob(_FakeTB(), blob, 2, 3, None, None)


def test_rebucket_compacted_override_translates_keys_to_slots():
    """A live executor override is keyed by USER key (the domain the
    emitters route by); a compacted ring's rows are SLOTS.  The
    re-bucketer must translate through the checkpointed key→slot remap
    so the overridden key's pane rows land on the shard its tuples
    route to — not on ``slot % n``."""
    import numpy as np

    from windflow_tpu.durability.rebucket import rebucket_blob

    class _FakeTB:
        name = "w"
        max_keys = 8
        is_tb = True
        key_extractor = staticmethod(lambda t: t["key"])

    def st(mark_row=None):
        cells = np.zeros((8, 4), np.float32)
        valid = np.zeros((8, 4), bool)
        if mark_row is not None:
            cells[mark_row, 0] = 42.0
            valid[mark_row, 0] = True
        return {"cells": cells, "cell_valid": valid,
                "horizon": np.full(8, -(1 << 60), np.int64),
                "base": np.asarray(5, np.int64),
                "win_next": np.asarray(2, np.int64),
                "max_seen": np.asarray(9, np.int64),
                "n_late": np.asarray(0, np.int64),
                "n_evicted": np.asarray(0, np.int64),
                "n_win_dropped": np.asarray(0, np.int64)}

    # user key 100 compacts to slot 3; the executor had moved it to
    # shard 2 pre-kill (its ring rows live there), then the graph
    # rescales 3 → 4 shards with the override re-installed
    blob = {"kind": "ffat_tpu",
            "states": {0: st(), 1: st(), 2: st(mark_row=3)},
            "compactor": {"key_slot": {100: 3}}}
    out = rebucket_blob(_FakeTB(), blob, 3, 4, None, None,
                        override={100: 2})
    # without translation the override (user key 100) would never match
    # row 3 and the rows would re-bucket to slot 3 % 4 == shard 3 —
    # away from where the re-installed override routes the tuples
    assert bool(out["states"][2]["cell_valid"][3, 0])
    assert float(out["states"][2]["cells"][3, 0]) == 42.0
    assert not bool(out["states"][3]["cell_valid"][3, 0])


# ---------------------------------------------------------------------------
# checkpoint protocol units
# ---------------------------------------------------------------------------

def test_checkpoint_store_layout_and_gc(tmp_path):
    """Epoch-versioned entries land in the LogKV, the manifest is the
    commit marker, and GC tombstones epochs beyond durability_keep."""
    cell = chaos.make_cell("window_cb", str(tmp_path / "ck"), n=4096,
                           epoch_sweeps=2)
    g = cell["factory"]()
    g.run()
    sec = g.stats()["Durability"]
    assert sec["enabled"] and sec["epochs_committed"] >= 3
    assert sec["last_checkpoint_bytes"] > 0
    assert sec["checkpoint_ms_total"] >= sec["last_checkpoint_ms"]
    pending = load_checkpoint(str(tmp_path / "ck"))
    last = sec["epochs_committed"] - 1
    assert pending["epoch"] == last
    assert pending["manifest"]["topology"] == topology_signature(
        g._operators)
    # retention: with durability_keep=2, epoch 0's records are gone
    from windflow_tpu.persistent.kv import LogKV
    kv = LogKV(str(tmp_path / "ck" / "checkpoint.kv"))
    try:
        eps = {int(k.split(b"/", 2)[1]) for k in kv.keys()
               if k.startswith(b"ep/")}
        assert 0 not in eps and last in eps
        assert len(eps) <= g.config.durability_keep
    finally:
        kv.close()


def test_restore_into_mismatched_graph_errors_named_diff(tmp_path):
    """WF602: restoring into a graph whose topology/record specs differ
    from the manifest fails with a diff naming the operator and field —
    never a silent wrong-state restore."""
    cell = chaos.make_cell("window_cb", str(tmp_path / "ck"), n=2048)
    cell["factory"]().run()

    cfg = dataclasses.replace(wf.default_config)
    cfg.durability = str(tmp_path / "ck")
    wrong = wf.PipeGraph("chaos", config=cfg)
    src = (wf.Source_Builder(lambda: iter(()))
           .withName("ksrc").withOutputBatchSize(256).build())
    pipe = wrong.add_source(src)
    pipe.add(wf.MapTPU_Builder(lambda t: t).withName("m").build())
    pipe.add_sink(wf.Sink_Builder(lambda r: None).withName("snk").build())
    with pytest.raises(WindFlowError) as ei:
        wrong.restore()
    msg = str(ei.value)
    assert "WF602" in msg and "checkpoint has" in msg
    assert not wrong._started

    # same shape, different operator type: the diff names the field
    wrong2 = cell["factory"]()
    wrong2._topo_operators()[1].name = "renamed"
    with pytest.raises(WindFlowError) as ei2:
        wrong2.restore(str(tmp_path / "ck"))
    assert "WF602" in str(ei2.value) and "renamed" in str(ei2.value)


def test_restore_does_not_mutate_shared_config(tmp_path):
    """restore(dir) must not write the checkpoint directory through a
    shared Config instance (PipeGraph holds passed configs by
    reference): a sibling graph built from the same Config would
    silently open the same store and collide on sink fences."""
    cell = chaos.make_cell("window_cb", str(tmp_path / "ck"), n=4096)
    cell["factory"]().run()
    shared = dataclasses.replace(wf.default_config)
    assert shared.durability == ""
    g = cell["factory"]()
    g.config = shared                  # composed graph on a shared config
    g.restore(str(tmp_path / "ck"))
    g.wait_end()
    assert shared.durability == ""     # untouched
    assert g.config.durability == str(tmp_path / "ck")


def test_restore_needs_a_complete_epoch(tmp_path):
    cell = chaos.make_cell("window_cb", str(tmp_path / "empty"), n=2048)
    g = cell["factory"]()
    with pytest.raises(WindFlowError, match="nothing to restore"):
        g.restore()


def test_epoch_file_sink_rejects_parallelism(tmp_path):
    """A shared EpochFileSink object under sink parallelism > 1 would
    race its staging handle across pooled replicas — the plane rejects
    the composition loudly at build."""
    import windflow_tpu as wf
    cfg = dataclasses.replace(wf.default_config)
    cfg.durability = str(tmp_path / "ck")
    cfg.preflight = "off"
    g = wf.PipeGraph("par", config=cfg)
    src = (wf.Source_Builder(lambda: iter([{"v": 1}]))
           .withOutputBatchSize(8).build())
    g.add_source(src).add_sink(
        wf.Sink_Builder(EpochFileSink(str(tmp_path / "out")))
        .withParallelism(2).build())
    with pytest.raises(WindFlowError, match="parallelism == 1"):
        g.start()
    g._finalize(dump=False)


def test_epoch_file_sink_cold_restart_discards_stale_staging(tmp_path):
    """A cold restart (no restore — e.g. the crash predated the first
    checkpoint) constructs a fresh EpochFileSink over the same dir: the
    dead run's staged-but-uncommitted records must not leak into the new
    run's first committed epoch."""
    d = str(tmp_path / "out")
    dead = EpochFileSink(d)
    dead({"ghost": 1})
    dead._f.flush()                       # crashed before any commit
    fresh = EpochFileSink(d)
    fresh({"real": 1})
    fresh.commit_epoch(0)
    assert EpochFileSink.read_committed(d) == [{"real": 1}]


def test_unpicklable_state_errors_name_the_operator(tmp_path):
    """An unpicklable user state object fails the checkpoint with an
    error naming the operator, not a raw PicklingError out of step()."""
    cell = chaos.make_cell("window_cb", str(tmp_path / "ck"), n=4096)
    g = cell["factory"]()
    g.start()
    g._operators[0].snapshot_state = lambda: {"bad": lambda: None}
    with pytest.raises(WindFlowError, match="not.*picklable"):
        g._durability.checkpoint()
    assert "ksrc" in str(
        pytest.raises(WindFlowError, g._durability.checkpoint).value)
    g._finalize(dump=False)


# ---------------------------------------------------------------------------
# exactly-once Kafka sink mechanics
# ---------------------------------------------------------------------------

def test_kafka_sink_eos_flush_and_fence():
    """Satellite fix: on_eos flushes AND fences — a straggler tuple
    after the EOS flush raises loudly instead of racing the producer
    teardown into a silent drop."""
    broker = InMemoryBroker()
    broker.create_topic("out", 1)
    snk = KafkaSink(lambda r: KafkaSinkMessage("out", r), broker,
                    name="ks")
    snk.build_replicas(wf.ExecutionMode.DEFAULT, wf.TimePolicy.INGRESS)
    rep = snk.replicas[0]
    rep.process_single({"v": 1}, 10, 10)
    rep.on_eos()
    assert rep._fenced
    assert broker.topic_size("out") == 1     # flushed, not dropped
    with pytest.raises(WindFlowError, match="flush-and-fence"):
        rep.process_single({"v": 2}, 11, 11)


def test_kafka_part_max_restores_group_level(tmp_path):
    """Per-partition event-time frontiers are group-level state: after a
    restore, EVERY source replica seeds the merged _part_max map (the
    rebalance may hand a partition to a different replica index than the
    one that checkpointed it); the first poll prunes foreign entries."""
    from windflow_tpu.kafka.kafka_source import KafkaSource
    broker = InMemoryBroker()
    broker.create_topic("in", 2)
    p = broker.producer()
    for i in range(3000):
        p.produce("in", {"key": i % 4, "value": float(i)},
                  partition=i % 2, timestamp_usec=1_000 + i)

    def deser(msg, shipper):
        if msg is None:
            return True
        shipper.pushWithTimestamp(dict(msg.value), msg.timestamp_usec)
        return True

    def factory():
        cfg = dataclasses.replace(wf.default_config)
        cfg.durability = str(tmp_path / "ck")
        cfg.durability_epoch_sweeps = 2
        cfg.punctuation_interval_usec = 10 ** 12
        cfg.health_postmortem_on_crash = False
        src = KafkaSource(deser, broker, ["in"], group_id="gp",
                          name="ksrc", parallelism=2,
                          output_batch_size=128)
        g = wf.PipeGraph("pmax", config=cfg)
        g.add_source(src).add_sink(
            wf.Sink_Builder(lambda r: None).build())
        return g

    g = factory()
    g.start()
    arm_spec = chaos.KillSpec("mid_epoch", after=5)
    chaos.arm(g, arm_spec)
    with pytest.raises(chaos.ChaosKill):
        g.wait_end()
    chaos.abandon(g)
    # both partitions were heard pre-kill, by whichever replica owned
    # them — the merged checkpoint map must cover both
    g2 = factory()
    g2.restore()
    src_op = g2._topo_operators()[0]
    merged = src_op._restore_part_max
    assert set(merged) == {("in", 0), ("in", 1)}
    for rep in src_op.replicas:
        # every replica seeded the full map; pruning to its own
        # assignment happens at its first poll
        for tp, ts in merged.items():
            assert rep._part_max.get(tp) == ts
    g2._finalize(dump=False)
    chaos.abandon(g2)


def test_broker_fence_dedupes_on_lifetime_seq():
    """fenced_commit is atomic + idempotent: replayed seqs skip, new
    seqs append, the fence tracks the frontier."""
    broker = InMemoryBroker()
    broker.create_topic("t", 1)
    msgs = [(s, "t", f"m{s}", None, None, 1000 + s) for s in (1, 2, 3)]
    appended, deduped = broker.fenced_commit("f", 0, msgs)
    assert (appended, deduped) == (3, 0)
    # replay epoch 0's tail + epoch 1's fresh messages in one commit
    replay = msgs[1:] + [(4, "t", "m4", None, None, 1004)]
    appended, deduped = broker.fenced_commit("f", 1, replay)
    assert (appended, deduped) == (1, 2)
    assert broker.fence("f") == (1, 4)
    assert broker.topic_size("t") == 4


# ---------------------------------------------------------------------------
# preflight WF6xx
# ---------------------------------------------------------------------------

def _durable_cfg(tmp_path):
    cfg = dataclasses.replace(wf.default_config)
    cfg.durability = str(tmp_path / "ck")
    return cfg


def test_preflight_wf601_non_replayable_source(tmp_path):
    g = wf.PipeGraph("p", config=_durable_cfg(tmp_path))
    src = (wf.Source_Builder(lambda: iter([{"v": 1}]))
           .withOutputBatchSize(8).build())
    g.add_source(src).add(
        wf.MapTPU_Builder(lambda t: t).build()).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    codes = [d.code for d in g.check()]
    assert "WF601" in codes
    # same graph without durability: silent
    g2 = wf.PipeGraph("p2")
    src2 = (wf.Source_Builder(lambda: iter([{"v": 1}]))
            .withOutputBatchSize(8).build())
    g2.add_source(src2).add(
        wf.MapTPU_Builder(lambda t: t).build()).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    assert "WF601" not in [d.code for d in g2.check()]


def test_preflight_wf603_opaque_state_operator(tmp_path):
    g = wf.PipeGraph("p", config=_durable_cfg(tmp_path))
    src = (wf.Source_Builder(lambda: iter([{"k": 0, "v": 1}]))
           .withTimestampExtractor(lambda t: t["v"]).build())
    win = (wf.Keyed_Windows_Builder(lambda items: len(items))
           .withTBWindows(10, 10).withKeyBy(lambda t: t["k"]).build())
    g.add_source(src).add(win).add_sink(
        wf.Sink_Builder(lambda r: None).build())
    diags = [d for d in g.check() if d.code == "WF603"]
    assert diags and diags[0].severity == "warning"


# ---------------------------------------------------------------------------
# kill switch / off-path budget + observability surfaces
# ---------------------------------------------------------------------------

def test_durability_off_path_budget():
    """Config.durability unset: no plane, stats section {enabled: False},
    and the sweep hook is ONE `is None` check (mirrors the health/ledger
    off-path micro-asserts)."""
    src = (wf.Source_Builder(lambda: iter(
        {"k": i, "v": float(i)} for i in range(64)))
        .withOutputBatchSize(32).build())
    g = wf.PipeGraph("off")
    g.add_source(src).add_sink(wf.Sink_Builder(lambda r: None).build())
    g.run()
    assert g._durability is None
    assert g.stats()["Durability"] == {"enabled": False}
    t0 = time.perf_counter()
    for _ in range(10_000):
        if g._durability is not None:    # the sweep hook's whole cost
            g._durability.on_sweep()
    per_call = (time.perf_counter() - t0) / 10_000
    assert per_call < 5e-6, \
        f"disabled durability check costs {per_call * 1e6:.2f}us/call"


def test_stats_openmetrics_and_postmortem_doctor(tmp_path):
    """The plane's read surfaces: stats()["Durability"], wf_durability_*
    OpenMetrics families (strict-parser clean), postmortem
    durability.json rendered + validated by wf_doctor jax-free, and a
    corrupted section rejected."""
    from windflow_tpu.monitoring.openmetrics import (parse_exposition,
                                                     render_openmetrics)
    cell = chaos.make_cell("window_cb", str(tmp_path / "ck"), n=2048,
                           epoch_sweeps=2)
    g = cell["factory"]()
    g.run()
    stats = g.stats()
    sec = stats["Durability"]
    assert sec["epochs_committed"] >= 1 and sec["restored_epoch"] is None
    text = render_openmetrics(stats)
    assert "wf_durability_epochs_committed_total" in text
    assert "wf_durability_checkpoint_bytes" in text
    parse_exposition(text)       # strict: raises on format violations

    d = g.dump_postmortem(str(tmp_path / "pm"), reason="test")
    dur = json.load(open(os.path.join(d, "durability.json")))
    assert dur["enabled"] and dur["epochs_committed"] >= 1
    doctor = os.path.join(REPO, "tools", "wf_doctor.py")
    r = subprocess.run([sys.executable, doctor, "--check", d],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    out = subprocess.run([sys.executable, doctor, d],
                         capture_output=True, text=True)
    assert "durability:" in out.stdout and "epoch(s) committed" \
        in out.stdout
    # corrupt the section: --check must reject
    dur["epochs_committed"] = -3
    json.dump(dur, open(os.path.join(d, "durability.json"), "w"))
    r2 = subprocess.run([sys.executable, doctor, "--check", d],
                        capture_output=True, text=True)
    assert r2.returncode == 1 and "epochs_committed" in r2.stderr


def test_restored_graph_reports_restore_in_stats(tmp_path):
    """After a kill+restore, stats()["Durability"] carries the restored
    epoch and restore_ms, and the OpenMetrics restored gauge flips."""
    from windflow_tpu.monitoring.openmetrics import render_openmetrics
    cell = chaos.make_cell("window_cb", str(tmp_path / "ck"), n=4096)
    g2 = chaos.run_killed_and_restored(
        cell["factory"], chaos.default_kill("window_cb", "mid_epoch"))
    sec = g2.stats()["Durability"]
    assert sec["restored_epoch"] is not None
    assert sec["restore_ms"] is not None and sec["restore_ms"] >= 0
    assert 'wf_durability_restored' in render_openmetrics(g2.stats())
