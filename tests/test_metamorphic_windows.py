"""Randomized metamorphic sweep at the reference's breadth (VERDICT r2
item 7; reference oracle pattern ``tests/graph_tests/test_graph_1.cpp:84-100,
194-206`` and the ``test_win_*_{cb,tb}.cpp`` matrix): every window family ×
{CB, TB} × execution mode, swept over random parallelism [1, 4] and batch
size [1, 257].  Run 0 of each cell is the oracle; every other random
configuration must reproduce its sink accumulation exactly.  A final DAG
combines merge AND split with a TPU window stage.

This sweep is the regression net that would have caught the round-2 TB
firing bug (watermarks never reaching the device path): any configuration
that under-fires changes the accumulated (count, total) pair.
"""

import random
import zlib

import pytest

import windflow_tpu as wf

N_KEYS = 4
LENGTH = 400
WIN, SLIDE = 16, 4            # count windows
TWIN, TSLIDE = 16_000, 4_000  # time windows (µs)


def stream():
    return [{"key": i % N_KEYS, "value": i, "ts": i * 1000}
            for i in range(LENGTH)]


def _win_builder(family, wt, rnd):
    lift = lambda t: t["value"]
    comb = lambda a, b: a + b
    nonin = lambda items: sum(t["value"] for t in items)
    par = rnd.randint(1, 4)
    if family == "keyed":
        b = wf.Keyed_Windows_Builder(nonin).withParallelism(par)
    elif family == "parallel":
        b = wf.Parallel_Windows_Builder(nonin).withParallelism(par)
    elif family == "paned":
        b = wf.Paned_Windows_Builder(
            nonin, lambda panes: sum(panes)).withParallelisms(
                par, rnd.randint(1, 4))
    elif family == "mapreduce":
        b = wf.MapReduce_Windows_Builder(
            nonin, lambda partials: sum(partials)).withParallelisms(
                par, rnd.randint(1, 4))
    elif family == "ffat_host":
        b = wf.Ffat_Windows_Builder(lift, comb).withParallelism(par)
    elif family == "ffat_tpu":
        b = wf.Ffat_WindowsTPU_Builder(lift, comb) \
            .withMaxKeys(N_KEYS).withParallelism(par)
    else:
        raise AssertionError(family)
    if wt == "cb":
        b = b.withCBWindows(WIN, SLIDE)
    else:
        b = b.withTBWindows(TWIN, TSLIDE)
    return b.withKeyBy(lambda t: t["key"])


def _run(family, wt, mode, rnd):
    acc = {"count": 0, "total": 0}

    def on_result(r):
        if r is None:
            return
        acc["count"] += 1
        v = r["value"] if isinstance(r, dict) else getattr(r, "value", r)
        acc["total"] += int(v)

    batch = rnd.randint(1, 257)
    src = (wf.Source_Builder(lambda: iter(stream()))
           .withTimestampExtractor(lambda t: t["ts"])
           .withOutputBatchSize(batch).build())
    op = _win_builder(family, wt, rnd).build()
    snk = (wf.Sink_Builder(on_result)
           .withParallelism(rnd.randint(1, 3)).build())
    # whole-chain fusion is a CONFIG dimension (windflow_tpu/fusion):
    # fused and unfused sweeps must reproduce the oracle exactly — and
    # so are the Pallas kernels (windflow_tpu/kernels): kernel-backed
    # and lax builds of the same window programs must too — and so is
    # the megastep executor (windflow_tpu/megastep): forced-K sweeps of
    # the same spec must match the oracle through the K-granular pacing
    # and downgrade paths (the fold A/B lives in tests/test_megastep.py)
    cfg = wf.Config(whole_chain_fusion=rnd.random() < 0.7,
                    pallas_kernels="auto" if rnd.random() < 0.7
                    else "0",
                    megastep_sweeps="auto" if rnd.random() < 0.7
                    else 4)
    g = wf.PipeGraph(f"meta_{family}_{wt}", mode, wf.TimePolicy.EVENT,
                     config=cfg)
    g.add_source(src).add(op).add_sink(snk)
    g.run()
    return acc["count"], acc["total"]


@pytest.mark.parametrize("wt", ["cb", "tb"])
# the ffat_tpu cells are the two slowest of the sweep (~4-5s each: four
# full device runs apiece); they ride the nightly leg (calibration-round
# headroom pass) — tier-1 keeps the device operator covered against the
# oracle in test_ffat_spec_sweep and record-for-record in test_windows
@pytest.mark.parametrize("family", ["keyed", "parallel", "paned",
                                    "mapreduce", "ffat_host",
                                    pytest.param("ffat_tpu",
                                                 marks=pytest.mark.slow)])
def test_window_sweep(family, wt):
    # Device operators are DEFAULT-mode only, exactly as the reference's
    # GPU builders reject non-DEFAULT modes (SURVEY.md §2.5 invariants).
    modes = [wf.ExecutionMode.DEFAULT]
    if family != "ffat_tpu":
        modes.append(wf.ExecutionMode.DETERMINISTIC)
    rnd = random.Random(zlib.crc32(f"{family}/{wt}".encode()))
    oracle = None
    for mode in modes:
        for _ in range(2):
            got = _run(family, wt, mode, rnd)
            assert got[0] > 0
            if oracle is None:
                oracle = got
            else:
                assert got == oracle, (family, wt, mode, got, oracle)


@pytest.mark.slow   # 3 full merge+split DAG runs (~6s): nightly leg
def test_merge_and_split_with_tpu_window_stage():
    """One DAG combining graph-level MERGE and SPLIT with a device window
    stage: two sources merge, a MapTPU transforms, a split sends even keys
    to FfatWindowsTPU (CB) and odd keys to a host Ffat_Windows (TB); both
    sinks' accumulations must be configuration-independent."""
    def run(rnd):
        accs = [{"count": 0, "total": 0}, {"count": 0, "total": 0}]

        def mk_sink(i):
            def on_result(r):
                if r is None:
                    return
                accs[i]["count"] += 1
                v = r["value"] if isinstance(r, dict) \
                    else getattr(r, "value", r)
                accs[i]["total"] += int(v)
            return on_result

        # one staging capacity: a device operator requires a fixed
        # upstream batch capacity across all its feeding edges
        b1 = b2 = rnd.randint(1, 129)
        half = LENGTH // 2
        s1 = (wf.Source_Builder(lambda: iter(stream()[:half]))
              .withTimestampExtractor(lambda t: t["ts"])
              .withOutputBatchSize(b1).build())
        s2 = (wf.Source_Builder(lambda: iter(stream()[half:]))
              .withTimestampExtractor(lambda t: t["ts"])
              .withOutputBatchSize(b2).build())
        g = wf.PipeGraph("merge_split_tpuwin", wf.ExecutionMode.DEFAULT,
                         wf.TimePolicy.EVENT,
                         config=wf.Config(
                             whole_chain_fusion=rnd.random() < 0.7))
        p1 = g.add_source(s1)
        p2 = g.add_source(s2)
        merged = p1.merge(p2)
        merged.add(wf.MapTPU_Builder(
            lambda t: {"key": t["key"], "value": t["value"] * 2,
                       "ts": t["ts"]}).build())
        branches = merged.split(lambda t: t["key"] % 2, 2)
        even = branches.select(0)
        even.add(wf.Ffat_WindowsTPU_Builder(
            lambda t: t["value"], lambda a, b: a + b)
            .withCBWindows(WIN, SLIDE).withKeyBy(lambda t: t["key"])
            .withMaxKeys(N_KEYS).build())
        even.add_sink(wf.Sink_Builder(mk_sink(0)).build())
        odd = branches.select(1)
        odd.add(wf.Ffat_Windows_Builder(
            lambda t: t["value"], lambda a, b: a + b)
            .withTBWindows(TWIN, TSLIDE).withKeyBy(lambda t: t["key"])
            .withParallelism(rnd.randint(1, 3)).build())
        odd.add_sink(wf.Sink_Builder(mk_sink(1)).build())
        g.run()
        return [(a["count"], a["total"]) for a in accs]

    rnd = random.Random(77)
    oracle = run(rnd)
    assert oracle[0][0] > 0 and oracle[1][0] > 0
    for _ in range(2):
        assert run(rnd) == oracle
