"""Host worker pool (Config.host_worker_threads): the reference runs one OS
thread per replica (``basic_operator.hpp:54-235``), so host-operator
pipelines scale across cores; here a worker pool drains host replicas
concurrently each sweep.  These tests pin the correctness contract: pooled
execution must produce exactly the single-thread results (per-replica
processing stays serial; keyed routing still pins each key to one replica),
for host-only graphs, mixed host/TPU graphs, and shared-DB persistent
operators (which must stay on the driver thread)."""

import threading

import pytest

import windflow_tpu as wf


def _host_graph(workers: int):
    """Source -> keyed FlatMap(4) -> KeyedWindows(4) -> Sink(2), all host.

    Each key flows through ONE channel end to end (keyed routing + a
    single source replica), so CB window contents are scheduling-
    independent — cross-channel interleave would make them arrival-order
    dependent in DEFAULT mode, with or without the pool (same as the
    reference's thread-per-replica runtime)."""
    results = []
    results_lock = threading.Lock()
    n, keys = 4000, 16

    def gen():
        for i in range(n):
            yield {"k": i % keys, "v": float(i)}

    def expand(t, shipper):
        shipper.push({"k": t["k"], "v": t["v"]})
        if t["k"] % 2 == 0:
            shipper.push({"k": t["k"], "v": -t["v"]})

    def win(t, acc):
        return (acc or 0.0) + t["v"]

    def sink(r):
        if r is not None:
            with results_lock:  # sink replicas may run on pool threads
                results.append((int(r.key), int(r.wid), float(r.value)))

    cfg = wf.Config(host_worker_threads=workers)
    g = wf.PipeGraph("host_pool", wf.ExecutionMode.DEFAULT, config=cfg)
    src = wf.Source_Builder(gen).withOutputBatchSize(64).build()
    fm = (wf.FlatMap_Builder(expand).withKeyBy(lambda t: t["k"])
          .withParallelism(4).build())
    kw = (wf.Keyed_Windows_Builder(win).withCBWindows(8, 4)
          .withKeyBy(lambda t: t["k"]).withParallelism(4).build())
    snk = wf.Sink_Builder(sink).withParallelism(2).build()
    g.add_source(src).add(fm).add(kw).add_sink(snk)
    g.run()
    return sorted(results)


def test_pool_matches_single_thread_host_graph():
    assert _host_graph(4) == _host_graph(0)


def test_pool_matches_single_thread_mixed_tpu_graph():
    """Host stages around a TPU reduce: the pooled host replicas stage
    device batches concurrently (inflight counter is lock-guarded)."""

    def run(workers):
        acc = {}

        def sink(t):
            if t is not None:
                k = int(t["k"])
                acc[k] = acc.get(k, 0.0) + float(t["v"])

        cfg = wf.Config(host_worker_threads=workers)
        g = wf.PipeGraph("pool_mixed", wf.ExecutionMode.DEFAULT, config=cfg)
        src = (wf.Source_Builder(
                lambda: iter({"k": i % 8, "v": float(i)}
                             for i in range(4096)))
               .withOutputBatchSize(256).build())
        m = (wf.Map_Builder(lambda t: {"k": t["k"], "v": t["v"] * 2})
             .withParallelism(3).withOutputBatchSize(256).build())
        red = (wf.ReduceTPU_Builder(
                lambda a, b: {"k": a["k"], "v": a["v"] + b["v"]})
               .withKeyBy(lambda t: t["k"]).build())
        snk = wf.Sink_Builder(sink).build()
        g.add_source(src).add(m).add(red).add_sink(snk)
        g.run()
        return acc

    assert run(4) == run(0)


def test_pool_shared_db_stays_on_driver_thread():
    """Shared-DB persistent replicas are not pool-safe; the graph still
    runs correctly with the pool on, and the partition excludes them."""
    import tempfile

    from windflow_tpu.persistent import P_Map_Builder

    with tempfile.TemporaryDirectory() as d:
        seen = []
        seen_lock = threading.Lock()

        def fn(t, state):
            state["sum"] += t["v"]
            return {"k": t["k"], "v": state["sum"]}

        cfg = wf.Config(host_worker_threads=4)
        g = wf.PipeGraph("pool_pdb", wf.ExecutionMode.DEFAULT, config=cfg)
        src = (wf.Source_Builder(
                lambda: iter({"k": i % 4, "v": 1.0} for i in range(64)))
               .withOutputBatchSize(16).build())
        pm = (P_Map_Builder(fn).withDbPath(f"{d}/kv").withSharedDb()
              .withInitialState({"sum": 0.0})
              .withKeyBy(lambda t: t["k"]).withParallelism(2).build())
        snk = wf.Sink_Builder(
            lambda t: seen.append((t["k"], t["v"]))
            if t is not None else None).build()
        g.add_source(src).add(pm).add_sink(snk)
        g.run()
        assert pm.replicas[0] in g._main_replicas
        assert pm.replicas[0] not in g._pool_replicas
        # every key counted to 16 (per-key serialization held)
        finals = {}
        for k, v in seen:
            finals[k] = max(finals.get(k, 0.0), v)
        assert finals == {k: 16.0 for k in range(4)}


def test_pool_deterministic_mode_matches():
    """DETERMINISTIC ordering is a collector property, not a scheduling
    property — pooled drains must not change the released sequence."""

    def run(workers):
        out = []
        cfg = wf.Config(host_worker_threads=workers)
        g = wf.PipeGraph("pool_det", wf.ExecutionMode.DETERMINISTIC,
                         config=cfg)
        src = (wf.Source_Builder(lambda: iter(range(2000)))
               .withParallelism(3).withOutputBatchSize(32).build())
        m = wf.Map_Builder(lambda x: x * 2).withParallelism(2).build()
        snk = wf.Sink_Builder(
            lambda x: out.append(x) if x is not None else None).build()
        g.add_source(src).add(m).add_sink(snk)
        g.run()
        return out

    assert run(4) == run(0)


def test_operator_error_propagates_and_releases_pool():
    """An operator exception mid-run must propagate as the root cause —
    not be masked by an error-path stats dump — and must release the
    worker pool and monitor."""
    import tempfile

    class Boom(RuntimeError):
        pass

    def bad(t):
        if t >= 64:
            raise Boom("user fn failed")
        return t

    with tempfile.TemporaryDirectory() as d:
        cfg = wf.Config(host_worker_threads=2, tracing_enabled=True,
                        log_dir=d)
        g = wf.PipeGraph("err_path", wf.ExecutionMode.DEFAULT, config=cfg)
        g.add_source(wf.Source_Builder(lambda: iter(range(256)))
                     .withOutputBatchSize(32).build()) \
         .add(wf.Map(bad)) \
         .add_sink(wf.Sink_Builder(lambda t: None).build())
        with pytest.raises(Boom):
            g.run()
        assert g._pool is None
        assert g._monitor is None


def test_source_start_failure_releases_pool():
    """start() failing AFTER the worker pool exists (a source generator
    factory raising) must shut the non-daemon pool down, not leak its
    threads (advisor r4)."""
    class BootBoom(RuntimeError):
        pass

    def bad_gen():
        raise BootBoom("generator factory failed")

    cfg = wf.Config(host_worker_threads=2)
    g = wf.PipeGraph("start_err", wf.ExecutionMode.DEFAULT, config=cfg)
    g.add_source(wf.Source_Builder(bad_gen)
                 .withOutputBatchSize(32).build()) \
     .add(wf.Map(lambda t: t)) \
     .add_sink(wf.Sink_Builder(lambda t: None).build())
    with pytest.raises(BootBoom):
        g.run()
    assert g._pool is None
    alive = [t.name for t in threading.enumerate()
             if t.name.startswith("wf-start_err")]
    assert not alive, alive


def _slow_fast_graph(workers: int, n: int = 240, sleep_s: float = 0.002):
    """Keyed Map with 2 replicas whose tuples stall for different times (a
    GIL-releasing stall, like blocking IO or native compute): key 0 routes
    to the SLOW replica (sleep_s per tuple), key 1 to a half-as-slow one."""
    import time as _time

    out = []
    lock = threading.Lock()

    def gen():
        for i in range(n):
            yield {"k": i % 2, "v": i}

    def fn(t):
        _time.sleep(sleep_s if t["k"] == 0 else sleep_s / 2)
        return t

    def sink(t):
        if t is not None:
            with lock:
                out.append((t["k"], t["v"]))

    # interval punctuation off: a wall-clock punctuation mid-run flushes
    # the emitter's open batches, after which the two destinations receive
    # on ALTERNATING sweeps — each sweep then has only one busy replica
    # and the overlap this test measures disappears by phase accident,
    # not by pool behavior (the flake mode: pass/fail depended on startup
    # wall-clock alignment)
    cfg = wf.Config(host_worker_threads=workers,
                    punctuation_interval_usec=1 << 50)
    g = wf.PipeGraph("slow_replica", wf.ExecutionMode.DEFAULT, config=cfg)
    src = wf.Source_Builder(gen).withOutputBatchSize(32).build()
    m = (wf.Map_Builder(fn).withKeyBy(lambda t: t["k"])
         .withParallelism(2).build())
    snk = wf.Sink_Builder(sink).build()
    g.add_source(src).add(m).add_sink(snk)
    import time as _t
    t0 = _t.perf_counter()
    g.run()
    return sorted(out), _t.perf_counter() - t0


def test_pool_slow_replica_does_not_starve_siblings():
    """VERDICT r4 weak #4: one deliberately slow replica must not idle its
    sibling for the whole run — with the pool, the fast replica's work
    overlaps the slow replica's stalls, so wall time approaches the slow
    replica's own service time instead of the serial sum.  The sweep
    barrier bounds the overlap granularity (sweep_drain_limit messages),
    not the total:  both runs process identical data; only wall differs.

    sleep() releases the GIL, so the overlap is observable even on the
    one-core CI host (the pool's scaling claim for GIL-holding pure-
    Python work is separately documented as multicore-only)."""
    # the process's FIRST pooled graph pays a one-off ~0.15 s machinery
    # warmup (thread spawn + first-use imports on pool workers); discard
    # it so the comparison measures the pool, not process warmup
    _slow_fast_graph(2, n=16)
    serial_out, serial_wall = _slow_fast_graph(0)
    pooled_out, pooled_wall = _slow_fast_graph(2)
    assert pooled_out == serial_out            # identical results
    # serial: slow and half-slow stalls add up (n/2 * 1.5 * sleep);
    # pooled: the half-slow replica's stalls ride inside the slow
    # replica's (ideal wall = n/2 * sleep, a 1.5x win).  Demand a solid
    # margin, not the ideal, to stay robust on a noisy one-core host.
    assert pooled_wall < serial_wall * 0.85, (pooled_wall, serial_wall)
