"""Example-application tests: each model runs end-to-end and matches a
pure-Python oracle (and, metamorphically, itself under different
parallelism — the reference's oracle style applied to whole applications)."""

import random

import pytest

from windflow_tpu.models import ffat_analytics, spike_detection, wordcount
from windflow_tpu.models.spike_detection import Reading


TEXT = """the quick brown fox jumps over the lazy dog
the dog barks and the fox runs
pack my box with five dozen liquor jugs
the five boxing wizards jump quickly""".splitlines()


def test_wordcount_matches_oracle():
    counts = wordcount.run(TEXT * 10, counter_parallelism=3)
    oracle = {}
    for line in TEXT * 10:
        for w in line.split():
            oracle[w.lower()] = oracle.get(w.lower(), 0) + 1
    assert counts == oracle


def test_wordcount_metamorphic():
    ref = wordcount.run(TEXT * 5)
    for par in [(2, 2, 1), (1, 3, 4)]:
        got = wordcount.run(TEXT * 5, source_parallelism=1,
                            splitter_parallelism=par[1],
                            counter_parallelism=par[2], batch=3)
        assert got == ref


def make_readings(n, devices=4, spike_every=50):
    rnd = random.Random(9)
    out = []
    for i in range(n):
        base = 10.0 + rnd.random()
        # spike injected per device (i // devices counts that device's
        # readings), so every device sees spikes
        if (i // devices) % spike_every == spike_every - 1:
            base *= 3.0
        out.append(Reading(device=i % devices, value=base))
    return out


def test_spike_detection_finds_injected_spikes():
    readings = make_readings(800)
    spikes = spike_detection.run(readings, win_len=16, slide=1,
                                 threshold=1.5)
    assert spikes, "no spikes detected"
    # every detection's window average stays below the spike magnitude
    # (~31); EOS-flushed partial windows can push the average above the
    # steady-state ~12 but a flagged window can never be spike-dominated
    assert all(s.average < 25.0 for s in spikes)
    # detections exist for every device
    assert {s.device for s in spikes} == {0, 1, 2, 3}


def test_ffat_analytics_matches_oracle():
    n, keys = 6000, 8
    rnd = random.Random(11)
    records = [{"k": i % keys, "v": rnd.random()} for i in range(n)]
    win, slide = 64, 16
    results = ffat_analytics.run(
        records, win_len=win, slide=slide, max_keys=keys, batch=512)
    # oracle: transform, filter, per-key sliding sums over surviving tuples
    per_key = {k: [] for k in range(keys)}
    for r in records:
        v = r["v"] * 1.5 + 1.0
        if (r["k"] & 7) != 7:
            per_key[r["k"]].append(v)
    expected = {}
    for k, vals in per_key.items():
        w = 0
        while w * slide + win <= len(vals):
            expected[(k, w)] = sum(vals[w * slide: w * slide + win])
            w += 1
    got = {(r["key"], r["wid"]): r["value"] for r in results
           if (r["key"], r["wid"]) in expected}
    assert set(got) == set(expected)
    for kk in expected:
        assert abs(got[kk] - expected[kk]) < 1e-3 * max(1, abs(expected[kk]))


def test_telemetry_frames_model():
    """The zero-per-tuple pipeline: binary frames in, TB window columns
    out, exact vs a python oracle."""
    import numpy as np
    from windflow_tpu.models import telemetry_frames

    n, n_keys = 2000, 4
    rec = np.empty(n, dtype=[("k", "<i8"), ("t", "<i8"), ("v", "<f8")])
    rec["k"] = np.arange(n) % n_keys
    rec["t"] = np.arange(n) * 10_000          # 10 ms apart
    rec["v"] = np.arange(n, dtype=np.float64)
    blob = rec.tobytes()

    got = {}

    def on_windows(cols):
        for k, w, v in zip(cols.cols["key"], cols.cols["wid"],
                           cols.cols["value"]):
            got[(int(k), int(w))] = float(v)

    g = telemetry_frames.build(
        lambda: iter([blob[i:i + 7777] for i in range(0, len(blob), 7777)]),
        on_windows, win_usec=1_000_000, slide_usec=250_000,
        max_keys=n_keys, batch=256, lateness_usec=0)
    g.run()

    exp = {}
    per_key = {}
    for i in range(n):
        per_key.setdefault(i % n_keys, []).append((i * 10_000, float(i)))
    for k, pts in per_key.items():
        wids = set()
        for ts, _ in pts:
            last = ts // 250_000
            first = max(0, -(-(ts - 1_000_000 + 1) // 250_000))
            wids.update(range(first, last + 1))
        for w in wids:
            vals = [v for ts, v in pts
                    if w * 250_000 <= ts < w * 250_000 + 1_000_000]
            if vals:
                exp[(k, w)] = sum(vals)
    assert set(got) == set(exp)
    for kk in exp:
        assert abs(got[kk] - exp[kk]) < 1e-3


def test_ad_analytics_matches_oracle():
    """The YSB-shaped pipeline: filter by event type, join ad→campaign via a
    device table gather, per-campaign tumbling TB counts — exact vs a
    python oracle."""
    from windflow_tpu.models import ad_analytics

    rnd = random.Random(17)
    n_ads, n_campaigns, n = 40, 10, 5000
    ad_to_campaign = [rnd.randrange(n_campaigns) for _ in range(n_ads)]
    events = [{"ad_id": rnd.randrange(n_ads),
               "etype": rnd.randrange(3),
               "ts": i * 2_500} for i in range(n)]

    win = slide = 1_000_000  # 1 s tumbling
    got = ad_analytics.run(events, ad_to_campaign,
                           win_usec=win, slide_usec=slide, batch=256,
                           view_type=1)
    exp = {}
    for e in events:
        if e["etype"] == 1:
            key = (ad_to_campaign[e["ad_id"]], e["ts"] // slide)
            exp[key] = exp.get(key, 0) + 1
    assert got == exp


def test_mesh_analytics_matches_oracle():
    """The multi-chip example app on the virtual 8-device mesh: sharded
    chained stages + key-sharded windows reproduce the python oracle."""
    from windflow_tpu.models import mesh_analytics

    n, keys = 4096, 16
    rnd = random.Random(23)
    records = [{"k": i % keys, "v": float(rnd.randint(-40, 100))}
               for i in range(n)]
    win, slide = 16, 8
    got = mesh_analytics.run(records, n_devices=8, data_axis=2,
                             win_len=win, slide=slide, max_keys=keys,
                             batch=512)
    per_key = {}
    for r in records:
        if r["v"] * 1.5 >= 0.0:     # the clip filter really drops lanes
            per_key.setdefault(r["k"], []).append(r["v"] * 1.5)
    exp = {}
    for k, vals in per_key.items():
        w = 0
        while w * slide < len(vals):
            exp[(k, w)] = sum(vals[w * slide: w * slide + win])
            w += 1
    gmap = {(k, w): v for k, w, v in got}
    assert set(gmap) == set(exp)
    for kk in exp:
        assert abs(gmap[kk] - exp[kk]) < 1e-3 * max(1.0, abs(exp[kk]))


def test_market_ticker_high_low_matches_oracle():
    """MarketTicker: one declared-max FFAT op computes per-symbol sliding
    high AND low (lo = -max(-p)); prices strictly negative-free but the
    lift's negated leaf is all-negative, so a zero-identity bug in the
    monoid path would corrupt every low."""
    from windflow_tpu.models import market_ticker
    n, syms, win, slide = 5000, 6, 32, 8
    rnd = random.Random(21)
    ticks = [{"sym": i % syms, "price": 10.0 + rnd.random() * 90.0}
             for i in range(n)]
    rows = market_ticker.run(ticks, win_len=win, slide=slide,
                             max_symbols=syms, batch=512)
    per_sym = {s: [] for s in range(syms)}
    for t in ticks:
        per_sym[t["sym"]].append(t["price"])
    exp = {}
    for s, ps in per_sym.items():
        w = 0
        while w * slide + win <= len(ps):
            seg = ps[w * slide: w * slide + win]
            exp[(s, w)] = (max(seg), min(seg))
            w += 1
    got = {(r["sym"], r["wid"]): (r["high"], r["low"]) for r in rows
           if (r["sym"], r["wid"]) in exp}
    assert set(got) == set(exp)
    for kk, (hi, lo) in exp.items():
        ghi, glo = got[kk]
        assert abs(ghi - hi) < 1e-4 and abs(glo - lo) < 1e-4, kk
    # EOS partials may add trailing windows beyond the oracle's full ones
    assert len(rows) >= len(exp)


def test_fraud_detection_matches_oracle():
    """FraudDetection: keyed device state (previous transaction type per
    card) drives a Markov transition score; flagged alerts must match a
    sequential python oracle exactly — any cross-batch state carryover
    bug changes which transitions get flagged."""
    from windflow_tpu.models import fraud_detection
    n, cards, types = 4000, 12, 4
    rnd = random.Random(31)
    # a chain-shaped matrix: staying or stepping forward is likely,
    # jumping back is rare (the fraud signal)
    trans = [[0.0] * types for _ in range(types)]
    for i in range(types):
        for j in range(types):
            trans[i][j] = 0.45 if j in (i, (i + 1) % types) else 0.05
    txs = [{"card": i % cards, "etype": rnd.randrange(types)}
           for i in range(n)]
    alerts = fraud_detection.run(txs, trans, max_cards=cards,
                                 threshold=0.1, batch=256)
    prev = {}
    exp = []
    for t in txs:
        c, e = t["card"], t["etype"]
        score = 1.0 if c not in prev else trans[prev[c]][e]
        if score < 0.1:
            exp.append((c, e))
        prev[c] = e
    assert [(a["card"], a["etype"]) for a in alerts] == exp
    assert len(exp) > 100   # the stream must actually flag things
